"""Benchmark: ResNet-50 ImageNet-shape training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline: the reference's strongest published single-device number —
ResNet-50 training, batch 32, P100: 181.53 img/s (BASELINE.md,
docs/how_to/perf.md:132-139).  vs_baseline = ours / 181.53.

Also reports MFU = achieved model FLOP/s over the chip's peak bf16 FLOP/s
(peak looked up from the device_kind; "mfu": null when the kind is unknown).

Failure behaviour (this is what round 1 lacked): backend init runs under a
watchdog — if jax can't produce a device within BENCH_INIT_TIMEOUT_S
(default 240s, the axon plugin can hang indefinitely), or anything else
raises, the bench emits a JSON line with an "error" field instead of dying
with a raw traceback or a silent timeout.  BENCH_DEVICE_CHECK=1 makes it
probe the backend, print the device line, and exit without benchmarking.

The run uses the FusedTrainer fast path (whole train step = one XLA
computation, buffer donation, bf16 compute with fp32 master weights —
the TPU-native equivalent of the reference's fp32 cuDNN path).
"""
import json
import os
import sys
import threading
import time

import numpy as np

BASELINE_IMG_S = 181.53  # P100 ResNet-50 train b32 (docs/how_to/perf.md:132-139)

# ResNet-50 @ 224x224: ~4.089 GFLOP forward per image (2 FLOPs/MAC);
# training step ~= 3x forward (fwd + 2x in bwd).
TRAIN_FLOPS_PER_IMG = 3 * 4.089e9

# The device-kind -> peak FLOP/s table lives in the telemetry perf
# plane (mxnet_tpu/telemetry/perf.py:PEAK_TFLOPS, round 22) — ONE
# table, so bench MFU and the live program_mfu gauge can never
# disagree.  _peak_flops below delegates to it.


def _emit(payload):
    print(json.dumps(payload), flush=True)


def _fallback_streak():
    """Consecutive most-recent bench rounds (committed BENCH_r*.json)
    that ended in a backend-init fallback.  The r03–r05 pattern — three
    rounds silently embedding the same committed artifact — must read
    as a harness bug, not a footnote: the CURRENT failure makes the
    streak one longer."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in glob.glob(os.path.join(glob.escape(here),
                                       "BENCH_r[0-9]*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                parsed = json.load(f).get("parsed") or {}
        except Exception:  # noqa: BLE001 — unreadable round: not a fallback
            parsed = {}
        err = str(parsed.get("error") or "")
        fell = "last_measured" in parsed or "backend init" in err
        rounds.append((int(m.group(1)), fell))
    rounds.sort(reverse=True)
    streak = 1  # the failure being emitted right now
    for _, fell in rounds:
        if not fell:
            break
        streak += 1
    return streak


def _bench_trend_check(current_fallback=None):
    """Run the committed-trajectory regression sentinel
    (tools/bench_trend.py) and surface its table on stderr; returns its
    exit code (0 clean, 1 regression/fallback, negative = the sentinel
    itself failed).  ``current_fallback`` marks the round being captured
    RIGHT NOW as an artifact fallback, so a non-live round is loud in
    its own log instead of a footnote discovered rounds later."""
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_trend.py")
    cmd = [sys.executable, script]
    if current_fallback:
        cmd += ["--current-fallback", str(current_fallback)[:200]]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=120)
        text = (r.stdout or "") + (r.stderr or "")
        if text.strip():
            print("[bench_trend] " + text.strip().replace(
                "\n", "\n[bench_trend] "), file=sys.stderr, flush=True)
        return r.returncode
    except Exception as exc:  # noqa: BLE001 — the sentinel must not kill the bench
        print("[bench_trend] sentinel failed: %r" % (exc,),
              file=sys.stderr, flush=True)
        return -1


def _fail(msg, metric="resnet50_train_imgs_per_sec_per_chip"):
    payload = {"metric": metric, "value": 0.0, "unit": "img/s",
               "vs_baseline": 0.0, "error": msg}
    # regression sentinel, loud-on-fallback: the failing round reports
    # the committed trajectory AND its own non-liveness on stderr
    payload["bench_trend_rc"] = _bench_trend_check(current_fallback=msg)
    if "backend init" in msg:
        streak = _fallback_streak()
        payload["fallback_streak"] = streak
        if streak >= 3:
            # ROADMAP item 3 honesty gate: a third consecutive
            # backend-init fallback is a HARD harness failure — no
            # committed artifact is embedded (stale numbers reading as
            # live ones is exactly the r03–r05 failure mode), the
            # nonzero exit stands, and the error says to fix the
            # harness, not the footnote
            payload["error"] = (
                f"HARD FAILURE: {streak} consecutive backend-init "
                f"fallbacks — fix the bench harness/backend before "
                f"trusting any committed artifact ({msg})")
            _emit(payload)
            return
    # a backend outage at bench time should not erase the round's real
    # measurement: embed the committed artifact (captured by
    # tools/tpu_watch.sh during an earlier backend window) so the error
    # line still carries the hardware numbers and where they came from
    try:
        import glob

        here = os.path.dirname(os.path.abspath(__file__))
        # newest committed capture wins (bench_r05_* once a round-5
        # window lands, else the r04 artifact); newest-first with
        # fallback, because the newest file may be a PARTIAL write from
        # the very outage that routed us into _fail
        cands = sorted(glob.glob(os.path.join(
            glob.escape(here), "docs", "measured",
            "bench_r[0-9][0-9]_tpu*.json")), reverse=True)
        for art in cands:
            try:
                with open(art) as f:
                    measured = json.load(f)
            except Exception:  # noqa: BLE001 — truncated capture
                continue
            rel = os.path.relpath(art, here)
            # artifacts carry their own capture date; never guess from
            # file mtime (that's the checkout time on a fresh clone).
            # nested under "error" context so automated extra-key
            # scanners can't mistake the stale artifact for live numbers.
            # fallback_reason makes the artifact substitution EXPLICIT in
            # the emitted json — rounds r03–r05 fell back silently and
            # their reports read stale numbers as live ones
            stamp = measured.get("captured_utc", "date unrecorded")
            payload["last_measured"] = {
                "note": "NOT a live capture; committed artifact embedded "
                        "because this run errored",
                "fallback_reason": msg,
                "source": "%s (captured %s)" % (rel, stamp),
                "data": measured,
            }
            break
    except Exception:  # noqa: BLE001 — the artifact is best-effort
        pass
    _emit(payload)


def _peak_flops(device_kind):
    """Peak FLOP/s for a device kind — the telemetry perf plane's
    shared table (None on a miss; callers record a
    ``peak_flops_unknown`` note instead of guessing)."""
    from mxnet_tpu.telemetry import perf as _perf

    return _perf.peak_flops(device_kind)


def _init_backend(timeout_s, retry_timeout_s, notes):
    """Initialize the jax backend under a two-window watchdog; returns
    ``(devices, attempts)`` where attempts counts jax.devices() calls
    (1 = clean first try) — recorded in the JSON next to init_notes so
    the r03–r05 "fell back to committed artifacts" pattern is
    diagnosable from the artifact alone.

    The accelerator plugin's init can hang with ~0 CPU forever (observed
    in round 1: BENCH_r01 rc=1 / probe >500s), and rounds r03–r05 showed
    a SECOND failure mode: init that completes just past the first
    timeout.  jax backend init is not interruptible from Python, so the
    watchdog cannot re-run it — instead it retries by EXTENDING the
    deadline once (``BENCH_INIT_RETRY_TIMEOUT_S``, default 2x the first
    window) before hard-exiting with the diagnostic JSON line the driver
    can parse.  An init that *raises* is genuinely retried once.  Every
    attempt lands in ``notes`` (emitted as ``init_notes`` in the bench
    JSON), so a slow-but-successful init is visible instead of silent.

    Round 21 adds PHASE attribution: init walks three phases — ``import``
    (the jax import itself), ``device enumeration`` (``jax.devices()``,
    where the plugin handshake lives), ``first compile`` (a 1-element
    jitted add, the first XLA client round-trip) — and the watchdog
    stamps the in-flight phase into every timeout note, so a hung
    artifact says WHICH phase wedged instead of just "init timed out".
    """
    state = {"done": False, "phase": "import"}
    deadline = {"at": time.monotonic() + timeout_s, "extended": False}

    def watchdog():
        while not state["done"]:
            now = time.monotonic()
            if now >= deadline["at"]:
                if not deadline["extended"]:
                    deadline["extended"] = True
                    deadline["at"] = now + retry_timeout_s
                    notes.append(
                        "backend init exceeded the %ds window during "
                        "phase '%s'; watchdog extended once for a %ds "
                        "retry window"
                        % (timeout_s, state["phase"], retry_timeout_s))
                else:
                    _fail("backend init timed out after retry "
                          "(%ds + %ds windows) during phase '%s': %s"
                          % (timeout_s, retry_timeout_s, state["phase"],
                             "; ".join(notes)))
                    os._exit(2)
            time.sleep(1.0)

    threading.Thread(target=watchdog, daemon=True).start()
    tic = time.monotonic()
    attempts = 0
    try:
        import jax

        state["phase"] = "device enumeration"
        try:
            attempts += 1
            devices = jax.devices()
        except Exception as exc:  # noqa: BLE001 — plugin flake: retry once
            notes.append("device enumeration raised %r; retrying once"
                         % (exc,))
            time.sleep(2.0)
            attempts += 1
            devices = jax.devices()
        state["phase"] = "first compile"
        import jax.numpy as jnp

        jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.zeros((1,))))
        init_s = time.monotonic() - tic
        if init_s > min(timeout_s, 60):
            notes.append("backend init took %.1fs (last phase: %s)"
                         % (init_s, state["phase"]))
        return devices, attempts
    finally:
        state["done"] = True  # disarm even when init raises


def main():
    if "--shard-micro" in sys.argv:
        # subprocess mode for _shard_micro on single-device hosts: the
        # parent owns the accelerator, this process runs the virtual
        # CPU mesh and prints ONE json line
        _emit(_shard_micro_body())
        return 0
    timeout_s = int(os.environ.get("BENCH_INIT_TIMEOUT_S", "240"))
    retry_s = int(os.environ.get("BENCH_INIT_RETRY_TIMEOUT_S",
                                 str(2 * timeout_s)))
    init_notes = []
    try:
        devices, init_attempts = _init_backend(timeout_s, retry_s, init_notes)
    except Exception as exc:  # noqa: BLE001 — diagnostic JSON is the contract
        _fail("backend init failed after retry: %r (%s)"
              % (exc, "; ".join(init_notes) or "first attempt"))
        return 2
    if not devices:
        _fail("backend initialized but exposed no devices")
        return 2
    dev = devices[0]
    kind = getattr(dev, "device_kind", str(dev))

    if os.environ.get("BENCH_DEVICE_CHECK"):
        _emit({"metric": "device_check", "value": 1, "unit": "devices",
               "vs_baseline": 0.0, "platform": dev.platform,
               "device_kind": kind, "n_devices": len(devices),
               "init_attempts": init_attempts,
               **({"init_notes": init_notes} if init_notes else {})})
        return 0

    try:
        return _bench(dev, kind, init_notes, init_attempts)
    except Exception as exc:  # noqa: BLE001
        _fail("bench failed on %s: %r" % (kind, exc))
        return 2


def _dispatch_micro():
    """Executor hot-path micro-bench (round 6): Python-overhead-per-step
    of the Module-path train step and recompiles across re-binds.

    Times 100 fused train-step dispatches on a tiny (near-no-op) graph —
    the graph computes nothing worth measuring, so the per-step cost IS
    the host-side overhead (input gather, jit cache lookup, dispatch).
    Then re-binds the same symbol structure across 3 bucket shapes twice:
    with the program cache on, the second sweep must hit the cache and
    the `recompiles` delta should be 0.
    """
    import jax

    from mxnet_tpu import sym, telemetry as tm
    from mxnet_tpu.context import default_accelerator_context
    from mxnet_tpu.telemetry import perf as _perf

    was_enabled = tm.enabled()
    perf_was = _perf.enabled()
    tm.enable()
    try:
        ctx = default_accelerator_context()
        net = sym.SoftmaxOutput(
            sym.FullyConnected(sym.Variable("data"), num_hidden=8,
                               name="bench_fc"),
            name="softmax")
        shapes = [(8, 16), (8, 32), (8, 64)]
        compile_ctr = tm.get_registry().get("executor_compile_total")

        def sweep():
            last = None
            for shp in shapes:
                last = net.simple_bind(ctx, data=shp)
                last.forward(is_train=True)
                last.backward()
            return last

        ex = sweep()                      # warm: one trace per shape
        before = compile_ctr.total()
        ex = sweep()                      # re-bind the same 3 structures
        recompiles = compile_ctr.total() - before

        # arm the perf plane only AFTER the recompile sweep: the
        # one-time cost capture re-traces the program for lower(), and
        # that bookkeeping trace must not read as a cache miss above
        _perf.enable()
        ex.forward(is_train=True)
        ex.backward()                     # warm + one-time cost capture
        jax.block_until_ready(ex.outputs[0]._read())
        _perf.reset(costs=False)          # keep cost rows, drop warmup wall
        n = 100
        tic = time.perf_counter()
        for _ in range(n):
            ex.forward(is_train=True)
            ex.backward()
        jax.block_until_ready(ex.outputs[0]._read())
        dt = time.perf_counter() - tic
        out = {"dispatch_us_per_step": round(dt / n * 1e6, 1),
               "recompiles": int(recompiles)}
        # agreement check (round 22): bench-side MFU (plane cost row
        # FLOPs over the loop's own wall) vs the plane's program_mfu
        # (same FLOPs over the wall its dispatch sites accumulated) —
        # the two denominators measure the same loop, so the values
        # must track each other
        prof = _perf.profile_payload(topn=0)
        row = next((p for p in prof["programs"]
                    if p["program"] == getattr(ex, "_program_label", None)),
                   None)
        if row and row.get("flops") and prof.get("peak_flops") and dt > 0:
            out["dispatch_bench_mfu"] = round(
                row["flops"] * n / (dt * prof["peak_flops"]), 6)
            if row.get("mfu") is not None:
                out["dispatch_program_mfu"] = round(row["mfu"], 6)
        return out
    finally:
        if not was_enabled:
            tm.disable()
        if not perf_was:
            _perf.disable()
            _perf.reset()


def _kv_update_micro():
    """KVStore update-path micro-bench (round 7): eager per-key push/pull
    vs the bucketed jit-fused engine (kvstore_fused.py) on a ~100-param
    model.

    Each timed step is the Module-path kvstore half: one batched
    ``push(keys, grads)`` (reduce + optimizer update) + one batched
    ``pull(keys, outs)``.  Eager pays ~6 tiny dispatches per key; fused
    pays one compiled program per bucket — the reported ratio is the
    per-step dispatch-overhead win.  ``kv_buckets`` records the fused
    plan size under the default MXTPU_KV_BUCKET_MB.
    """
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rng = np.random.RandomState(7)
    # ~100 keys, conv/bias-shaped mix (~1.7MB total) like a small convnet
    shapes = ([(128, 32), (32,), (64, 64), (64,)] * 25)
    weights = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
    grads = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
    keys = list(range(len(shapes)))

    def run(fused):
        prev = os.environ.get("MXTPU_FUSED_UPDATE")
        os.environ["MXTPU_FUSED_UPDATE"] = "1" if fused else "0"
        try:
            kv = mx.kv.create("local")
            kv.set_optimizer(mx.optimizer.create(
                "sgd", learning_rate=0.05, momentum=0.9,
                rescale_grad=1.0 / 32))
            kv.init(keys, [nd.array(w) for w in weights])
            gnds = [[nd.array(g)] for g in grads]
            outs = [nd.zeros(s) for s in shapes]

            def step():
                kv.push(keys, gnds)
                kv.pull(keys, outs)

            for _ in range(3):  # warmup: plan build + bucket compiles
                step()
            jax.block_until_ready([o._read() for o in outs])
            n = 30
            tic = time.perf_counter()
            for _ in range(n):
                step()
            jax.block_until_ready([o._read() for o in outs])
            dt = (time.perf_counter() - tic) / n
            nbuckets = kv._fused.num_buckets if kv._fused is not None else 0
            return dt, nbuckets
        finally:
            if prev is None:
                os.environ.pop("MXTPU_FUSED_UPDATE", None)
            else:
                os.environ["MXTPU_FUSED_UPDATE"] = prev

    eager_dt, _ = run(False)
    fused_dt, nbuckets = run(True)
    return {"kv_update_us_per_step": round(fused_dt * 1e6, 1),
            "kv_update_us_per_step_eager": round(eager_dt * 1e6, 1),
            "kv_update_speedup": round(eager_dt / max(fused_dt, 1e-9), 1),
            "kv_buckets": nbuckets}


def _pipeline_micro():
    """Async-pipeline micro-bench (round 8): the Module-fit hot loop with
    device-resident fused metrics + the bounded in-flight window
    (MXTPU_ASYNC_DEPTH) vs the eager per-batch-sync loop, and step_multi
    vs single-step dispatch on the same workload — the regression
    tracker for the round-5 finding that step_multi came out SLOWER than
    single dispatch once its host stacking tax was counted.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import engine, sym, telemetry as tm

    was_enabled = tm.enabled()
    tm.enable()
    prevs = {k: os.environ.get(k)
             for k in ("MXTPU_FUSED_METRICS", "MXTPU_ASYNC_DEPTH")}
    try:
        data = sym.Variable("data")
        net = sym.SoftmaxOutput(
            sym.FullyConnected(data, name="pipe_fc", num_hidden=64),
            name="softmax")
        rs = np.random.RandomState(3)
        nsteps, b = 16, 64
        x = rs.uniform(-1, 1, (b * nsteps, 128)).astype(np.float32)
        y = rs.randint(0, 64, b * nsteps).astype(np.float32)

        def run_loop(fused, depth, epochs=3):
            os.environ["MXTPU_FUSED_METRICS"] = "1" if fused else "0"
            os.environ["MXTPU_ASYNC_DEPTH"] = str(depth)
            it = mx.io.NDArrayIter(x, y, batch_size=b)
            mod = mx.mod.Module(net)
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label)
            mod.init_params()
            mod.init_optimizer(optimizer="sgd", optimizer_params=(
                ("learning_rate", 0.05),))
            metric = mx.metric.create("acc")

            def epoch():
                # fit's steady-state body: dispatch, enqueue metric,
                # bound the window; values only read at the boundary
                it.reset()
                metric.reset()
                window = engine.AsyncWindow()
                for batch in it:
                    mod.forward_backward(batch)
                    mod.update()
                    mod.update_metric(metric, batch.label)
                    window.push(mod._output_handles())
                window.drain()
                metric.get_global_name_value()

            epoch()  # warm: compiles + metric kernels
            reg = tm.get_registry()
            stall = reg.get("trainer_host_stall_seconds")
            syncs = reg.get("metric_host_sync_total")
            s0 = stall.sum(site="window") if stall is not None else 0.0
            c0 = syncs.total() if syncs is not None else 0.0
            tic = time.perf_counter()
            for _ in range(epochs):
                epoch()
            dt = time.perf_counter() - tic
            stall_us = ((stall.sum(site="window") - s0) / (epochs * nsteps)
                        * 1e6 if stall is not None else 0.0)
            sync_per_epoch = ((syncs.total() - c0) / epochs
                              if syncs is not None else 0.0)
            return (dt / (epochs * nsteps) * 1e6, stall_us, sync_per_epoch)

        eager_us, _, eager_syncs = run_loop(fused=False, depth=1)
        fused_d1_us, _, _ = run_loop(fused=True, depth=1)
        fused_us, stall_us, fused_syncs = run_loop(fused=True, depth=2)

        # --- step_multi vs single-step dispatch, same workload ---------
        from mxnet_tpu.trainer import FusedTrainer

        k = 8
        tr = FusedTrainer(net, optimizer="sgd",
                          optimizer_params={"lr": 0.05,
                                            "rescale_grad": 1.0 / b})
        tr.init(data=(b, 128))
        xb = jax.device_put(x[:b])
        yb = jax.device_put(y[:b])

        def barrier():
            name = sorted(tr.params)[0]
            return float(np.asarray(tr.params[name]).ravel()[0])

        tr.step(data=xb, softmax_label=yb)  # compile
        barrier()
        iters = 48
        tic = time.perf_counter()
        for _ in range(iters):
            tr.step(data=xb, softmax_label=yb)
        barrier()
        single_us = (time.perf_counter() - tic) / iters * 1e6

        stacked = {"data": jnp.stack([xb] * k),
                   "softmax_label": jnp.stack([yb] * k)}
        tr.step_multi(**stacked)  # compile (pre-stacked, non-donated)
        barrier()
        calls = max(iters // k, 1)
        tic = time.perf_counter()
        for _ in range(calls):
            tr.step_multi(**stacked)
        barrier()
        multi_us = (time.perf_counter() - tic) / (calls * k) * 1e6

        return {
            "pipeline_us_per_step": round(fused_us, 1),
            "pipeline_us_per_step_fused_d1": round(fused_d1_us, 1),
            "pipeline_us_per_step_eager": round(eager_us, 1),
            "pipeline_fused_speedup": round(eager_us / max(fused_us, 1e-9), 2),
            "host_stall_us_per_step": round(stall_us, 1),
            "metric_sync_per_epoch": round(fused_syncs, 1),
            "metric_sync_per_epoch_eager": round(eager_syncs, 1),
            "step_single_us_per_step": round(single_us, 1),
            "step_multi_us_per_step": round(multi_us, 1),
            "steps_per_call_speedup": round(
                single_us / max(multi_us, 1e-9), 2),
        }
    finally:
        for k_, v_ in prevs.items():
            if v_ is None:
                os.environ.pop(k_, None)
            else:
                os.environ[k_] = v_
        if not was_enabled:
            tm.disable()


def _survival_micro():
    """Survival-layer micro-bench (round 15): what checkpointing costs
    the training loop.  ckpt_capture_us_per_step is the HOT-LOOP tax —
    the async device-copy dispatch at a snapshot step (the fetch + file
    IO run on the writer thread and must not appear here);
    ckpt_write_ms is the background writer's wall time for the full
    state (fetch + fsync + atomic publish); ckpt_resume_ms is
    checksum-validated restore."""
    import tempfile

    import numpy as np

    from mxnet_tpu import checkpoint as ck
    from mxnet_tpu import sym
    from mxnet_tpu.trainer import FusedTrainer

    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=256,
                           name="surv_fc"), name="softmax")
    rs = np.random.RandomState(11)
    b = 64
    x = rs.uniform(-1, 1, (b, 512)).astype(np.float32)
    y = rs.randint(0, 256, b).astype(np.float32)
    tr = FusedTrainer(net, optimizer="adam",
                      optimizer_params={"lr": 0.05,
                                        "rescale_grad": 1.0 / b})
    tr.init(data=(b, 512))
    tr.step(data=x, softmax_label=y)  # compile
    name = sorted(tr.params)[0]
    float(np.asarray(tr.params[name]).ravel()[0])  # barrier

    n = 40
    tic = time.perf_counter()
    for _ in range(n):
        tr.step(data=x, softmax_label=y)
    float(np.asarray(tr.params[name]).ravel()[0])
    plain_us = (time.perf_counter() - tic) / n * 1e6

    out = {}
    with tempfile.TemporaryDirectory() as d:
        writes = []
        tic = time.perf_counter()
        for i in range(n):
            tr.step(data=x, softmax_label=y)
            if i % 10 == 0:  # capture WITHOUT draining: dispatch only
                writes.append(tr.save_state(d, background=True))
        float(np.asarray(tr.params[name]).ravel()[0])
        armed_us = (time.perf_counter() - tic) / n * 1e6
        for w in writes:
            w.wait()
        tic = time.perf_counter()
        tr.save_state(d, background=False)
        write_ms = (time.perf_counter() - tic) * 1e3
        tic = time.perf_counter()
        tr.restore_state(d)
        resume_ms = (time.perf_counter() - tic) * 1e3
        state_bytes = sum(
            int(v.size) * np.dtype(v.dtype).itemsize
            for v in tr._checkpoint_arrays().values())
    out["ckpt_step_us_plain"] = round(plain_us, 1)
    out["ckpt_step_us_armed"] = round(armed_us, 1)
    out["ckpt_capture_us_per_step"] = round(armed_us - plain_us, 1)
    out["ckpt_write_ms"] = round(write_ms, 2)
    out["ckpt_resume_ms"] = round(resume_ms, 2)
    out["ckpt_state_bytes"] = int(state_bytes)
    return out


def _health_micro():
    """Health-layer micro-bench (round 9): the fused training hot loop
    with MXTPU_SENTINEL off vs on (the in-program isfinite+norm
    accumulator; <3% overhead target — the sentinel adds one tiny
    reduction to an already-compiled step and ZERO host syncs), and the
    flight recorder's per-record host cost (a bounded ring append).
    """
    import numpy as np

    from mxnet_tpu import telemetry as tm
    from mxnet_tpu.telemetry import health
    from mxnet_tpu.trainer import FusedTrainer
    from mxnet_tpu import sym

    was_enabled = tm.enabled()
    tm.enable()
    prev = os.environ.get("MXTPU_SENTINEL")
    try:
        net = sym.SoftmaxOutput(
            sym.FullyConnected(sym.Variable("data"), num_hidden=64,
                               name="health_fc"),
            name="softmax")
        rs = np.random.RandomState(9)
        b = 64
        x = rs.uniform(-1, 1, (b, 128)).astype(np.float32)
        y = rs.randint(0, 64, b).astype(np.float32)

        def run(sentinel):
            os.environ["MXTPU_SENTINEL"] = "1" if sentinel else "0"
            tr = FusedTrainer(net, optimizer="sgd",
                              optimizer_params={"lr": 0.05,
                                                "rescale_grad": 1.0 / b})
            tr.init(data=(b, 128))
            tr.step(data=x, softmax_label=y)  # compile
            health.sentinel_check()
            name = sorted(tr.params)[0]
            float(np.asarray(tr.params[name]).ravel()[0])  # barrier
            n = 60
            tic = time.perf_counter()
            for _ in range(n):
                tr.step(data=x, softmax_label=y)
            health.sentinel_check()
            float(np.asarray(tr.params[name]).ravel()[0])
            return (time.perf_counter() - tic) / n * 1e6

        off_us = run(False)
        on_us = run(True)

        # flight-recorder record cost: the pure host-side ring append
        # the fit loops pay per step
        n = 20000
        tic = time.perf_counter()
        for i in range(n):
            health.record_step(loop="bench", step=i, depth=2,
                               dispatch_s=0.0)
        rec_us = (time.perf_counter() - tic) / n * 1e6
        return {
            "health_sentinel_us_per_step": round(on_us, 1),
            "health_sentinel_us_per_step_off": round(off_us, 1),
            "health_sentinel_overhead_pct": round(
                (on_us - off_us) / max(off_us, 1e-9) * 100.0, 2),
            "flight_record_us": round(rec_us, 3),
        }
    finally:
        if prev is None:
            os.environ.pop("MXTPU_SENTINEL", None)
        else:
            os.environ["MXTPU_SENTINEL"] = prev
        if not was_enabled:
            tm.disable()


def _shard_micro_body():
    """Sharded-update micro-bench (round 11): the fused kvstore bucket
    step with the cross-replica sharded update (MXTPU_SHARD_UPDATE=1,
    arXiv:2004.13336) vs the replicated per-key bucket programs, on the
    process mesh.  Reports the per-step dispatch cost of each, the
    optimizer-state bytes per replica (the 1/N residency win), and the
    logical collective payload per sharded step."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, telemetry as tm
    from mxnet_tpu.parallel.mesh import global_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    was_enabled = tm.enabled()
    tm.enable()
    prev = os.environ.get("MXTPU_SHARD_UPDATE")
    prev_cap = os.environ.get("MXTPU_KV_BUCKET_MB")
    try:
        mesh = global_mesh()
        repl = NamedSharding(mesh, P())
        rng = np.random.RandomState(11)
        # deliberately small keys + a tiny bucket cap: the section
        # measures DISPATCH/RESIDENCY structure (sharded vs replicated,
        # bytes per replica, collective payload), and virtual-CPU rigs
        # serialize every mesh collective through the host cores —
        # MB-scale buckets there turn one step into seconds of
        # rendezvous without changing any reported ratio
        os.environ.setdefault("MXTPU_KV_BUCKET_MB", "0.05")
        shapes = [(64, 37), (37,), (128, 16), (19,)] * 6
        weights = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
        grads = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
        keys = list(range(len(shapes)))

        def run(shard):
            os.environ["MXTPU_SHARD_UPDATE"] = "1" if shard else "0"
            kv = mx.kv.create("local")
            kv.set_optimizer(mx.optimizer.create(
                "adam", learning_rate=1e-3, rescale_grad=1.0 / 64))
            kv.init(keys, [nd.array(w) for w in weights])
            gnds = [[nd.NDArray(jax.device_put(g, repl))] for g in grads]
            outs = [nd.zeros(s) for s in shapes]

            def step():
                kv.push(keys, gnds)
                kv.pull(keys, outs)

            for _ in range(3):  # warmup: plan build + bucket compiles
                step()
            jax.block_until_ready([o._read() for o in outs])
            coll = tm.get_registry().get("executor_collective_bytes_total")
            c0 = coll.total() if coll is not None else 0
            n = 20
            tic = time.perf_counter()
            for _ in range(n):
                step()
            jax.block_until_ready([o._read() for o in outs])
            dt = (time.perf_counter() - tic) / n
            cps = ((coll.total() - c0) / n) if coll is not None else 0
            return dt, kv._fused.state_memory(), cps

        repl_dt, repl_mem, _ = run(False)
        shard_dt, shard_mem, coll_per_step = run(True)
        return {
            "shard_update_us_per_step": round(shard_dt * 1e6, 1),
            "shard_update_us_per_step_replicated": round(repl_dt * 1e6, 1),
            "optimizer_state_bytes_per_replica": int(
                shard_mem["per_replica_bytes"]),
            "optimizer_state_bytes_per_replica_replicated": int(
                repl_mem["per_replica_bytes"]),
            "collective_bytes_per_step": int(coll_per_step),
            "shard_replicas": int(shard_mem["replicas"]),
            "shard_buckets": int(shard_mem["sharded_buckets"]),
        }
    finally:
        if prev is None:
            os.environ.pop("MXTPU_SHARD_UPDATE", None)
        else:
            os.environ["MXTPU_SHARD_UPDATE"] = prev
        if prev_cap is None:
            os.environ.pop("MXTPU_KV_BUCKET_MB", None)
        else:
            os.environ["MXTPU_KV_BUCKET_MB"] = prev_cap
        if not was_enabled:
            tm.disable()


def _shard_micro():
    """Run the sharded-update micro on this process's mesh when it has
    >= 2 devices (the MULTICHIP path), else in a fresh subprocess on an
    8-virtual-CPU mesh (the backend is already owned by this process,
    so a single-chip host cannot re-init it for a second mesh)."""
    import jax

    if len(jax.devices()) >= 2:
        return _shard_micro_body()
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXTPU_PLATFORM="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"))
    r = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--shard-micro"],
                       capture_output=True, text=True, timeout=600, env=env)
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
        except json.JSONDecodeError:
            continue
        payload["shard_mesh"] = "8-virtual-cpu-subprocess"
        return payload
    return {"shard_error": "subprocess rc=%d: %s"
            % (r.returncode, (r.stderr or r.stdout)[-300:])}


_DIST_PS_WORKER = r'''
import os, sys, time
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_sync")
keys = list(range(8))
shapes = [(256, 64)] * 8
kv.init(keys, [mx.nd.ones(s) for s in shapes])
kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.01,
                                     rescale_grad=1.0))
grads = [[mx.nd.ones(s)] for s in shapes]
outs = [mx.nd.zeros(s) for s in shapes]
kv.push(keys, grads); kv.pull(keys, outs)  # warm
kv.barrier()
n = 20
tic = time.perf_counter()
for _ in range(n):
    kv.push(keys, grads)
    kv.pull(keys, outs)
us = (time.perf_counter() - tic) / n * 1e6
if kv.rank == 0:
    print('{"dist_ps_us": %f}' % us, flush=True)
kv.barrier()
'''

_DIST_ELASTIC_WORKER = r'''
import os, time
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2")
slot = int(os.environ["MXTPU_ELASTIC_SLOT"])
gen = int(os.environ["MXTPU_DIST_GENERATION"])
if slot == 1 and gen == 0:
    os.environ["MXTPU_FAULT_PLAN"] = "host_crash:crash_after:6"
os.environ["MXTPU_ASYNC_DEPTH"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import io as mx_io, sym
from mxnet_tpu.parallel import dist
from mxnet_tpu.parallel.mesh import create_mesh
from mxnet_tpu.trainer import FusedTrainer

OUT = os.environ["DIST_MICRO_OUT"]
net = sym.SoftmaxOutput(
    sym.FullyConnected(sym.Variable("data"), num_hidden=32, name="fc"),
    sym.Variable("softmax_label"), name="softmax")
rs = np.random.RandomState(5)
X = rs.uniform(-1, 1, (160, 16)).astype(np.float32)
Y = rs.randint(0, 10, 160).astype(np.float32)


def main():
    np.random.seed(0)
    mx.random.seed(0)
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.05},
                      mesh=create_mesh((2,), ("data",)))
    train = mx_io.NDArrayIter(X, Y, batch_size=8)
    marked = []

    def cb(param):
        if not marked:
            marked.append(1)
            with open(os.path.join(OUT, "gen%d_first_step_%d"
                                   % (gen, slot)), "w") as f:
                f.write(repr(time.time()))

    tr.fit(train, num_epoch=30, resume=True, batch_end_callback=cb)


dist.elastic_main(main)
'''


def _dist_micro():
    """Multi-host runtime micro (round 17, docs/multihost.md): the
    per-step kvstore cost of the collective dist_sync path (fused
    bucketed dispatch — the cross-host all-reduce is in-trace) vs the
    PS transport (per-key RPCs over the 2-worker/1-server local rig),
    plus generation_failover_ms — the end-to-end wall time from a
    SIGKILL-shaped host death to the shrunk generation's first resumed
    training step under the elastic launcher (detect via lease expiry
    + relaunch + checkpoint resume + re-bind)."""
    import re
    import subprocess
    import sys
    import tempfile
    from datetime import datetime

    import numpy as np

    import mxnet_tpu as mx

    out = {}
    # collective transport, in-process: batched push/pull through the
    # fused bucket engine (same math a pod runs over DCN)
    kv = mx.kv.create("dist_sync")
    keys = list(range(8))
    shapes = [(256, 64)] * 8
    kv.init(keys, [mx.nd.ones(s) for s in shapes])
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.01,
                                         rescale_grad=1.0))
    grads = [[mx.nd.ones(s)] for s in shapes]
    outs_ = [mx.nd.zeros(s) for s in shapes]
    kv.push(keys, grads)
    kv.pull(keys, outs_)
    outs_[0].asnumpy()
    n = 20
    tic = time.perf_counter()
    for _ in range(n):
        kv.push(keys, grads)
        kv.pull(keys, outs_)
    outs_[0].asnumpy()
    out["dist_step_us_per_step_collective"] = round(
        (time.perf_counter() - tic) / n * 1e6, 1)

    repo = os.path.dirname(os.path.abspath(__file__))
    launch = os.path.join(repo, "tools", "launch.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_PLATFORM="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    with tempfile.TemporaryDirectory() as d:
        # PS transport: real worker+server processes on localhost
        ps_path = os.path.join(d, "ps_worker.py")
        with open(ps_path, "w") as f:
            f.write(_DIST_PS_WORKER)
        r = subprocess.run(
            [sys.executable, launch, "-n", "2", "-s", "1",
             "--launcher", "local", sys.executable, ps_path],
            capture_output=True, text=True, timeout=300, env=env)
        m = re.search(r'\{"dist_ps_us": ([0-9.]+)\}', r.stdout)
        if m:
            out["dist_step_us_per_step_ps"] = round(float(m.group(1)), 1)
        else:
            out["dist_ps_error"] = "rc=%d: %s" % (
                r.returncode, (r.stderr or r.stdout)[-200:])

        # elastic failover: kill one of two hosts mid-epoch, measure
        # death-observed -> first resumed step of the shrunk generation
        ew_path = os.path.join(d, "elastic_worker.py")
        with open(ew_path, "w") as f:
            f.write(_DIST_ELASTIC_WORKER)
        eenv = dict(env, DIST_MICRO_OUT=d, MXTPU_CKPT_DIR=os.path.join(
            d, "ckpt"), MXTPU_CKPT_EVERY="2", MXTPU_COORD_LEASE_S="1.0",
            MXTPU_DIST_BARRIER_TIMEOUT_S="8", XLA_FLAGS="")
        r = subprocess.run(
            [sys.executable, launch, "-n", "2", "--max-restarts", "1",
             "--launcher", "elastic", "--rejoin-progress", "3",
             "--exit-grace", "60", sys.executable, ew_path],
            capture_output=True, text=True, timeout=420, env=eenv)
        log = r.stdout + r.stderr
        crash = re.search(
            r"^([0-9-]+ [0-9:,]+) launch\.py slot 1 crashed", log, re.M)
        marker = os.path.join(d, "gen1_first_step_0")
        if crash and os.path.exists(marker):
            t_crash = datetime.strptime(
                crash.group(1), "%Y-%m-%d %H:%M:%S,%f").timestamp()
            with open(marker) as f:
                t_resume = float(f.read())
            out["generation_failover_ms"] = round(
                (t_resume - t_crash) * 1e3, 1)
            out["dist_generations"] = len(re.findall(
                r"launch\.py generation \d+: world=", log))
        else:
            out["dist_failover_error"] = "rc=%d: %s" % (
                r.returncode, log[-200:])
    return out


def _fleet_micro():
    """Fleet observability micro (round 18, docs/multihost.md): the
    coordinator-side federation + straggler plane on an in-process
    2-member rig — fleet_scrape_ms (one /metrics.json federation sweep
    over both members' HTTP endpoints), straggler_detect_ms (first
    inflated heartbeat to the coordinator naming the slow host), and
    merge_trace_ms (two synthetic per-host flight dumps folded into one
    chrome trace by tools/fleetstat.py merge-trace)."""
    import importlib.util
    import tempfile

    from mxnet_tpu import telemetry as tm
    from mxnet_tpu.parallel.coordinator import CoordinatorService

    out = {}
    was_enabled = tm.enabled()
    tm.enable()
    servers = []
    svc = None
    try:
        # two per-"host" registries behind real HTTP = a 2-member fleet
        # in one process (the same shape a pod runs, minus the DCN)
        for i in range(2):
            reg = tm.Registry()
            reg.get_or_create(tm.Counter, "trainer_samples_total",
                              "samples", ("loop",)).inc(64 * (i + 1),
                                                        loop="fused")
            servers.append(tm.start_http_server(0, registry=reg))
        svc = CoordinatorService(port=0, lease_s=1.0).start()
        for i, srv in enumerate(servers):
            svc.join("h%d" % i, host="h%d" % i, rank=i,
                     telemetry_addr="127.0.0.1:%d"
                                    % srv.server_address[1])
        tic = time.perf_counter()
        snap = svc.scraper.scrape_once()
        out["fleet_scrape_ms"] = round(
            (time.perf_counter() - tic) * 1e3, 2)
        if not all(s.get("ok") for s in snap.values()):
            out["fleet_scrape_error"] = "scrape failed: %r" % (snap,)

        # injected slow host: h1's heartbeats carry a 10x step wall;
        # measure first slow report -> the coordinator naming it
        tic = time.perf_counter()
        named = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            svc.heartbeat("h0", steps={"count": 32, "step_wall_s": 0.01,
                                       "dispatch_s": 0.002})
            svc.heartbeat("h1", steps={"count": 32, "step_wall_s": 0.10,
                                       "dispatch_s": 0.002})
            named = svc.cluster().get("straggler")
            if named:
                break
            time.sleep(0.05)
        if named and named.get("member") == "h1":
            out["straggler_detect_ms"] = round(
                (time.perf_counter() - tic) * 1e3, 1)
        else:
            out["straggler_error"] = "straggler never flagged: %r" % (
                named,)
    finally:
        for srv in servers:
            srv.shutdown()
        if svc is not None:
            svc.stop()
        if not was_enabled:
            tm.disable()

    # merge-trace over synthetic two-host dumps (h1's clock runs 2.5s
    # behind, its dump carries the matching offset estimate)
    spec = importlib.util.spec_from_file_location(
        "mxtpu_fleetstat",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools", "fleetstat.py"))
    fleetstat = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleetstat)
    with tempfile.TemporaryDirectory() as d:
        paths = []
        for i in range(2):
            skew = 0.0 if i == 0 else -2.5
            ring = [{"seq": s, "step": s, "loop": "fused",
                     "t": 1000.0 + 0.01 * s + skew,
                     "wall_s": 0.01, "dispatch_s": 0.004}
                    for s in range(256)]
            dump = {"version": 2, "ring": ring,
                    "identity": {"host": "h%d" % i, "rank": i,
                                 "generation": 0,
                                 "clock": {"offset_s": -skew}}}
            p = os.path.join(d, "flight_h%d.json" % i)
            with open(p, "w") as f:
                json.dump(dump, f)
            paths.append(p)
        tic = time.perf_counter()
        fleetstat.merge_trace(paths, os.path.join(d, "trace.json"))
        out["merge_trace_ms"] = round((time.perf_counter() - tic) * 1e3, 2)
    return out


def _serve_micro():
    """Serving micro-bench (round 10): the continuous-batching decode
    scheduler (mxnet_tpu/serving/) under a synthetic Poisson arrival
    load — served tokens/s, p50/p99 time-to-first-token, and mean slot
    occupancy.  Drives the SlotScheduler directly (the HTTP layer adds
    ~connection overhead, not decode behavior); prompts span several
    prefill buckets so admission exercises the bucketed-length programs
    the way mixed traffic would.
    """
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import models, telemetry as tm
    from mxnet_tpu.models.decode import KVDecoder
    from mxnet_tpu.serving import SlotScheduler

    was_enabled = tm.enabled()
    tm.enable()
    sched = None
    try:
        L_, H_, D_, T_, V_ = 2, 4, 128, 128, 512
        net = models.transformer.transformer_lm(
            num_layers=L_, num_heads=H_, d_model=D_, seq_len=T_,
            vocab_size=V_)
        ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                             data=(1, T_), softmax_label=(1, T_))
        rs = np.random.RandomState(11)
        params = {}
        for name, arr in ex.arg_dict.items():
            if name in ("data", "softmax_label"):
                continue
            arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
            params[name] = arr
        dec = KVDecoder(params, num_layers=L_, num_heads=H_, max_len=T_)
        sched = SlotScheduler(dec, num_slots=4, queue_size=64,
                              default_deadline_ms=120000)
        # warm every program mixed traffic will hit: one request per
        # prefill bucket + the shared step/adopt programs
        for plen in (5, 12, 30):
            sched.generate(rs.randint(0, V_, plen), max_new_tokens=2,
                           timeout=120)
        n_req, max_new = 24, 12
        reqs = []
        tic = time.perf_counter()
        ticks0 = sched.stats["ticks"]
        slot_ticks0 = sched.stats["slot_ticks"]
        for _ in range(n_req):
            time.sleep(float(rs.exponential(0.01)))  # Poisson arrivals
            reqs.append(sched.submit(
                rs.randint(0, V_, int(rs.randint(4, 32))),
                max_new_tokens=max_new))
        for r in reqs:
            r.wait(300)
        dt = time.perf_counter() - tic
        toks = sum(len(r.tokens) for r in reqs)
        ttfts = sorted(r.ttft for r in reqs if r.ttft is not None)
        ticks = sched.stats["ticks"] - ticks0
        slot_ticks = sched.stats["slot_ticks"] - slot_ticks0
        pct = lambda q: ttfts[min(int(q * len(ttfts)), len(ttfts) - 1)]
        return {
            "serve_tokens_per_sec": round(toks / dt, 1),
            "serve_ttft_p50_ms": round(pct(0.50) * 1e3, 1),
            "serve_ttft_p99_ms": round(pct(0.99) * 1e3, 1),
            "serve_slot_occupancy_mean": round(
                slot_ticks / max(ticks, 1), 2),
            "serve_outcomes_ok": sum(1 for r in reqs
                                     if r.outcome == "ok"),
            "serve_requests": n_req,
        }
    finally:
        if sched is not None:
            sched.close()
        if not was_enabled:
            tm.disable()


def _router_micro():
    """Serving-fleet micro-bench (round 19, ISSUE 15).  Two parts:

    (1) a Poisson soak through the replica router
    (serving/router.py) against a 2-replica in-process fleet sharing
    one decoder — fleet-wide served tokens/s, p50/p99 TTFT through the
    router, and the retry counter (0 on a healthy fleet);

    (2) paged-vs-contiguous co-batching at EQUAL slot count: a mixed
    long/short workload where the long requests share an 80-token
    system prefix.  The contiguous backend prefills every long prompt
    at its full bucket; the paged backend (MXTPU_KV_BLOCK-style pages
    + prefix cache) computes the shared prefix once and prefills only
    the tails — the acceptance ratio
    ``paged_vs_contiguous_tokens_per_sec`` (>= 1.2 on this rig).
    """
    import json as _json
    import threading
    import urllib.request

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import models, telemetry as tm
    from mxnet_tpu.models.decode import KVDecoder
    from mxnet_tpu.serving import (ReplicaRouter, SlotScheduler,
                                   serve_decoder, start_router)

    was_enabled = tm.enabled()
    tm.enable()
    out = {}
    servers, scheds = [], []
    rsrv = router = None
    try:
        L_, H_, D_, T_, V_ = 2, 4, 128, 128, 512
        net = models.transformer.transformer_lm(
            num_layers=L_, num_heads=H_, d_model=D_, seq_len=T_,
            vocab_size=V_)
        ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                             data=(1, T_), softmax_label=(1, T_))
        rs = np.random.RandomState(19)
        params = {}
        for name, arr in ex.arg_dict.items():
            if name in ("data", "softmax_label"):
                continue
            arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
            params[name] = arr
        dec = KVDecoder(params, num_layers=L_, num_heads=H_, max_len=T_)

        # ---- (1) routed Poisson soak over a 2-replica fleet ----------
        for _ in range(2):
            s, sch = serve_decoder(dec, port=0, num_slots=4,
                                   queue_size=64,
                                   default_deadline_ms=120000)
            servers.append(s)
            scheds.append(sch)
        addrs = ["127.0.0.1:%d" % s.server_address[1] for s in servers]
        router = ReplicaRouter(replicas=addrs, scrape_s=0.2, retries=2)
        rsrv = start_router(router, port=0)
        rport = rsrv.server_address[1]

        def post(body):
            req = urllib.request.Request(
                "http://127.0.0.1:%d/generate" % rport,
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as r:
                return r.status, _json.loads(r.read())

        # warm every replica's programs (each bucket mixed traffic hits)
        for sch in scheds:
            for plen in (5, 12, 30):
                sch.generate(rs.randint(0, V_, plen), max_new_tokens=2,
                             timeout=300)
        retries0 = tm.get_registry().get("router_retries_total").total()
        n_req, max_new = 24, 12
        results, errors = [], []

        def client(i):
            try:
                prompt = rs.randint(0, V_, int(rs.randint(4, 32)))
                results.append(post({"prompt": prompt.tolist(),
                                     "max_tokens": max_new}))
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        tic = time.perf_counter()
        threads = []
        for i in range(n_req):
            time.sleep(float(rs.exponential(0.01)))  # Poisson arrivals
            t = threading.Thread(target=client, args=(i,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - tic
        if errors:
            raise errors[0]
        toks = sum(o["n_tokens"] for _, o in results)
        ttfts = sorted(o["ttft_ms"] for _, o in results
                       if o.get("ttft_ms") is not None)
        pct = lambda q: ttfts[min(int(q * len(ttfts)), len(ttfts) - 1)]
        out["serve_fleet_tokens_per_sec"] = round(toks / dt, 1)
        out["serve_fleet_ttft_p50_ms"] = round(pct(0.50), 1)
        out["serve_fleet_ttft_p99_ms"] = round(pct(0.99), 1)
        out["serve_fleet_ok"] = sum(1 for st, o in results
                                    if st == 200 and o["outcome"] == "ok")
        out["serve_fleet_requests"] = n_req
        out["serve_fleet_replicas"] = len(addrs)
        out["router_retry_total"] = int(
            tm.get_registry().get("router_retries_total").total()
            - retries0)

        # ---- (2) paged vs contiguous co-batching, equal slot count ---
        prefix = rs.randint(0, V_, 80)       # the shared system prompt

        def mixed_workload(seed):
            w = []
            r2 = np.random.RandomState(seed)
            for i in range(20):
                if i % 4 == 3:               # short, prefix-free
                    w.append(r2.randint(0, V_, int(r2.randint(4, 16))))
                else:                        # long, shared prefix
                    w.append(np.concatenate(
                        [prefix,
                         r2.randint(0, V_, int(r2.randint(4, 16)))]))
            return w

        def soak(sched, seed):
            # warm the buckets THIS traffic hits with a disjoint prefix
            # (the measured run still pays its one shared-prefix fill)
            warm = np.concatenate(
                [rs.randint(0, V_, 80), rs.randint(0, V_, 8)])
            sched.generate(warm, max_new_tokens=2, timeout=300)
            sched.generate(rs.randint(0, V_, 6), max_new_tokens=2,
                           timeout=300)
            sched.generate(rs.randint(0, V_, 12), max_new_tokens=2,
                           timeout=300)
            reqs = []
            r3 = np.random.RandomState(seed + 1)
            tic = time.perf_counter()
            for p in mixed_workload(seed):
                time.sleep(float(r3.exponential(0.002)))
                reqs.append(sched.submit(p, max_new_tokens=8))
            for r in reqs:
                r.wait(300)
            dt = time.perf_counter() - tic
            assert all(r.outcome == "ok" for r in reqs), \
                [r.outcome for r in reqs]
            return sum(len(r.tokens) for r in reqs) / dt

        cont = SlotScheduler(dec, num_slots=4, queue_size=64,
                             default_deadline_ms=120000, paged=False)
        try:
            cont_tps = soak(cont, 77)
        finally:
            cont.close()
        paged = SlotScheduler(dec, num_slots=4, queue_size=64,
                              default_deadline_ms=120000, paged=True,
                              kv_block=16)
        try:
            paged_tps = soak(paged, 77)
            pstats = paged.paged_stats()
        finally:
            paged.close()
        out["serve_paged_tokens_per_sec"] = round(paged_tps, 1)
        out["serve_contiguous_tokens_per_sec"] = round(cont_tps, 1)
        out["paged_vs_contiguous_tokens_per_sec"] = round(
            paged_tps / cont_tps, 3)
        out["serve_prefix_pages"] = pstats["prefix_pages"]
        return out
    finally:
        if rsrv is not None:
            rsrv.shutdown()
        if router is not None:
            router.stop()
        for s in servers:
            s.shutdown()
        for sch in scheds:
            sch.close()
        if not was_enabled:
            tm.disable()


def _trace_micro():
    """Request-tracing overhead micro-bench (round 20, ISSUE 16).

    The SAME routed Poisson workload as ``_router_micro``'s soak — a
    2-replica in-process fleet behind the replica router — run three
    ways: tracing OFF, tracing ON at sample rate 1.0, and sampled at
    25%.  Span recording is pure host-side dict/ring writes (never a
    device sync — tools/lint.py proves the tick-path callers), so the
    acceptance gate is ``trace_overhead_pct`` <= 2 on this rig.  The
    on-run's SLO plane numbers ride along (every routed request feeds
    the router's burn-rate windows).
    """
    import json as _json
    import threading
    import urllib.request

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import models, telemetry as tm
    from mxnet_tpu.models.decode import KVDecoder
    from mxnet_tpu.serving import (ReplicaRouter, serve_decoder,
                                   start_router)
    from mxnet_tpu.telemetry import tracing

    was_enabled = tm.enabled()
    was_tracing = tracing.trace_on()
    sample0 = os.environ.get("MXTPU_TRACE_SAMPLE")
    tm.enable()
    out = {}
    servers, scheds = [], []
    rsrv = router = None
    try:
        L_, H_, D_, T_, V_ = 2, 4, 128, 128, 512
        net = models.transformer.transformer_lm(
            num_layers=L_, num_heads=H_, d_model=D_, seq_len=T_,
            vocab_size=V_)
        ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                             data=(1, T_), softmax_label=(1, T_))
        rs = np.random.RandomState(20)
        params = {}
        for name, arr in ex.arg_dict.items():
            if name in ("data", "softmax_label"):
                continue
            arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
            params[name] = arr
        dec = KVDecoder(params, num_layers=L_, num_heads=H_, max_len=T_)

        for _ in range(2):
            s, sch = serve_decoder(dec, port=0, num_slots=4,
                                   queue_size=64,
                                   default_deadline_ms=120000)
            servers.append(s)
            scheds.append(sch)
        addrs = ["127.0.0.1:%d" % s.server_address[1] for s in servers]
        router = ReplicaRouter(replicas=addrs, scrape_s=0.2, retries=2)
        rsrv = start_router(router, port=0)
        rport = rsrv.server_address[1]

        def post(body):
            req = urllib.request.Request(
                "http://127.0.0.1:%d/generate" % rport,
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as r:
                return r.status, _json.loads(r.read())

        for sch in scheds:      # warm every bucket the traffic hits
            for plen in (5, 12, 30):
                sch.generate(rs.randint(0, V_, plen), max_new_tokens=2,
                             timeout=300)
        n_req, max_new = 24, 12

        def soak(seed):
            rs2 = np.random.RandomState(seed)
            prompts = [rs2.randint(0, V_, int(rs2.randint(4, 32)))
                       for _ in range(n_req)]
            results, errors = [], []

            def client(p):
                try:
                    results.append(post({"prompt": p.tolist(),
                                         "max_tokens": max_new}))
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            tic = time.perf_counter()
            threads = []
            for p in prompts:
                time.sleep(float(rs2.exponential(0.01)))
                t = threading.Thread(target=client, args=(p,))
                t.start()
                threads.append(t)
            for t in threads:
                t.join(300)
            dt = time.perf_counter() - tic
            if errors:
                raise errors[0]
            return sum(o["n_tokens"] for _, o in results) / dt

        # identical workload (same seed) three ways: A/B the span path.
        # One unmeasured soak settles threads/caches, then each arm of
        # the off/on comparison takes its best of two runs — the soak
        # is Poisson-arrival threaded HTTP, whose run-to-run scheduling
        # jitter would otherwise swamp a <=2% span-recording overhead.
        tracing.enable_tracing(False)
        soak(100)
        off_tps = max(soak(101) for _ in range(2))
        tracing.clear_spans()
        os.environ["MXTPU_TRACE_SAMPLE"] = "1"
        tracing.enable_tracing(True)
        on_tps = max(soak(101) for _ in range(2))
        n_spans = len(tracing.spans())
        tracing.clear_spans()
        os.environ["MXTPU_TRACE_SAMPLE"] = "0.25"
        sampled_tps = soak(101)
        out["serve_trace_off_tokens_per_sec"] = round(off_tps, 1)
        out["serve_trace_on_tokens_per_sec"] = round(on_tps, 1)
        out["serve_trace_sampled_tokens_per_sec"] = round(sampled_tps, 1)
        out["trace_overhead_pct"] = round(
            (off_tps - on_tps) / off_tps * 100.0, 2)
        out["serve_trace_spans"] = n_spans
        slo = router.slo.snapshot()
        out["slo_burn_rate_availability_60s"] = \
            slo["windows"]["60s"]["burn_rate"]["availability"]
        out["slo_violations_availability"] = \
            slo["violations_total"]["availability"]
        return out
    finally:
        tracing.enable_tracing(was_tracing)
        tracing.clear_spans()
        if sample0 is None:
            os.environ.pop("MXTPU_TRACE_SAMPLE", None)
        else:
            os.environ["MXTPU_TRACE_SAMPLE"] = sample0
        if rsrv is not None:
            rsrv.shutdown()
        if router is not None:
            router.stop()
        for s in servers:
            s.shutdown()
        for sch in scheds:
            sch.close()
        if not was_enabled:
            tm.disable()


def _autotune_micro():
    """Autotune micro-bench (round 21, ISSUE 18).  Four numbers:

    - ``paged_attn_{gather,kernel}_us_per_step``: one full decode step
      (all layers) over the paged pool through the PR-15 gather
      materialization vs the tuned paged-attention schedule the
      autotuner picks for this rig — plus the ratio as
      ``paged_attn_kernel_speedup`` (higher is better; the acceptance
      gate is >= 1.2x);
    - ``autotune_search_ms``: wall cost of the bounded first search
      (``MXTPU_AUTOTUNE_TRIALS`` candidates, warmup + best-of-k each);
    - ``autotune_cache_hit``: a SECOND in-process run against the file
      the first search persisted — 1 iff it reused the winner with
      zero new trials (the whole point of the on-disk cache);
    - ``epilogue_tuned_vs_default_us``: the residual epilogue's tuned
      ``block_rows`` vs the static default, same jitted kernel timing
      ``tune()`` used (negative = the tuned block is faster).

    Runs against a private temp ``MXTPU_SCHEDULE_CACHE`` in search mode
    and restores the caller's autotune state on the way out.
    """
    import functools
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from mxnet_tpu import autotune as at, telemetry as tm
    from mxnet_tpu.autotune import search as at_search
    from mxnet_tpu.ops import paged_attention as pa
    from mxnet_tpu.ops import residual_epilogue as repi

    was_enabled = tm.enabled()
    tm.enable()
    cache0 = os.environ.get("MXTPU_SCHEDULE_CACHE")
    tmpd = tempfile.mkdtemp(prefix="mxtpu_autotune_bench_")
    os.environ["MXTPU_SCHEDULE_CACHE"] = \
        "search:" + os.path.join(tmpd, "schedules.json")
    at.reset()
    out = {}
    try:
        # serving-shaped decode step: B slots, M pages/slot (a 512-token
        # context window), half-full ragged cursors (make_bench_fn's
        # honest steady-state mix) — the regime where the gather path
        # materializes every page and a liveness-bounded walk does not
        B, H, M, block, dh, L = 4, 8, 32, 16, 64, 2
        dtype = jnp.float32
        platform = jax.default_backend()
        sig = pa.keysig(B, H, M, block, dh, dtype)
        default = pa.default_schedule(platform, block, dh, dtype)
        cands = pa.candidate_schedules(platform, block, dh, M, dtype)
        bench = functools.partial(pa.make_bench_fn, B=B, H=H, M=M,
                                  block=block, dh=dh, L=L, dtype=dtype)
        tic = time.perf_counter()
        winner = at.ensure("paged_attention", sig, default, cands, bench,
                           warmup=1, best_of=3)
        out["autotune_search_ms"] = round(
            (time.perf_counter() - tic) * 1e3, 1)
        gather_us = at.measure(bench({"impl": "gather"}),
                               warmup=1, best_of=5)
        kernel_us = at.measure(bench(winner), warmup=1, best_of=5)
        out["paged_attn_gather_us_per_step"] = round(gather_us, 1)
        out["paged_attn_kernel_us_per_step"] = round(kernel_us, 1)
        out["paged_attn_kernel_impl"] = winner.get("impl", "gather")
        out["paged_attn_kernel_speedup"] = round(gather_us / kernel_us, 2)
        # second in-process run: forget the memo (NOT the file), re-ensure
        trials0 = at_search._TM_TRIALS.total()
        hits0 = at_search._TM_CACHE.value(result="hit")
        at.reset()
        again = at.ensure("paged_attention", sig, default, cands, bench,
                          warmup=1, best_of=3)
        hit = (again == winner
               and at_search._TM_TRIALS.total() == trials0
               and at_search._TM_CACHE.value(result="hit") > hits0)
        out["autotune_cache_hit"] = int(hit)
        # epilogue knob: ResNet-tail shape, interpret timing on a
        # CPU rig (exactly what tune() itself measures)
        rows, channels = 2048, 256
        interp = jax.default_backend() != "tpu"
        tuned = repi.tune(rows, channels, interpret=interp)
        rs = np.random.RandomState(0)
        x2 = jnp.asarray(rs.normal(size=(rows, channels)).astype(np.float32))
        s2 = jnp.asarray(rs.normal(size=(rows, channels)).astype(np.float32))
        sc = jnp.asarray(rs.normal(size=(channels,)).astype(np.float32))
        b_ = jnp.asarray(rs.normal(size=(channels,)).astype(np.float32))

        def _epi_us(br):
            fn = jax.jit(functools.partial(
                repi._pallas_fwd, interpret=interp, block_rows=br))
            return at.measure(lambda: fn(x2, s2, sc, b_),
                              warmup=1, best_of=3)

        default_us = _epi_us(repi._default_block_rows(rows))
        tuned_us = _epi_us(int(tuned["block_rows"]))
        out["epilogue_tuned_block_rows"] = int(tuned["block_rows"])
        out["epilogue_tuned_vs_default_us"] = round(
            tuned_us - default_us, 1)
        return out
    finally:
        if cache0 is None:
            os.environ.pop("MXTPU_SCHEDULE_CACHE", None)
        else:
            os.environ["MXTPU_SCHEDULE_CACHE"] = cache0
        at.reset()
        shutil.rmtree(tmpd, ignore_errors=True)
        if not was_enabled:
            tm.disable()


def _sparse_micro():
    """Row-sparse embedding-update micro-bench (round 13): the fused
    sparse bucket (touched-rows-only jitted update, kvstore_fused +
    sparse.py) vs the dense-gradient path on a table whose row count
    dwarfs one batch's lookups — the regime where the dense scatter
    plus full-table optimizer sweep is the step bottleneck.

    Both sides run the same Module-path kvstore step (one batched push)
    with the same Adam state; the dense side is fed ``todense()`` of
    the identical row-sparse gradient, so the arithmetic being timed is
    equivalent.  Emits the ISSUE-9 acceptance ratio
    (``sparse_update_speedup`` >= 3 on this table), the touched-row
    fraction, and sustained touched-rows-per-second through the sparse
    path."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, sparse

    rows = int(os.environ.get("BENCH_SPARSE_ROWS", "300000"))
    dim = int(os.environ.get("BENCH_SPARSE_DIM", "64"))
    lookups = int(os.environ.get("BENCH_SPARSE_LOOKUPS", "4096"))
    rng = np.random.RandomState(11)
    table = rng.uniform(-1, 1, (rows, dim)).astype(np.float32)
    idx_steps = [rng.randint(0, rows, lookups).astype(np.int32)
                 for _ in range(8)]
    val_steps = [rng.uniform(-1, 1, (lookups, dim)).astype(np.float32)
                 for _ in range(8)]
    uniq = np.mean([np.unique(i).size for i in idx_steps])

    def run(sparse_grads):
        kv = mx.kv.create("local")
        kv.set_optimizer(mx.optimizer.create(
            "adam", learning_rate=0.05, rescale_grad=1.0 / lookups))
        init = sparse.full_row_sparse(nd.array(table)) if sparse_grads \
            else nd.array(table)
        kv.init(0, init)
        grads = []
        for i, v in zip(idx_steps, val_steps):
            g = sparse.RowSparseNDArray(nd.NDArray(i), nd.NDArray(v),
                                        (rows, dim))
            grads.append([g] if sparse_grads else [g.todense()])

        def step(n):
            kv.push([0], grads[n % len(grads)])

        for w in range(3):
            step(w)
        jax.block_until_ready(kv._store[0]._read())
        n = 20
        tic = time.perf_counter()
        for s in range(n):
            step(s)
        jax.block_until_ready(kv._store[0]._read())
        return (time.perf_counter() - tic) / n

    dense_dt = run(False)
    sparse_dt = run(True)
    return {
        "sparse_update_us_per_step": round(sparse_dt * 1e6, 1),
        "sparse_update_us_per_step_dense": round(dense_dt * 1e6, 1),
        "sparse_update_speedup": round(dense_dt / max(sparse_dt, 1e-9), 1),
        "sparse_touched_row_fraction": round(float(uniq) / rows, 5),
        "embedding_rows_per_sec": round(uniq / max(sparse_dt, 1e-9)),
        "sparse_table_rows": rows,
    }


def _amp_micro():
    """AMP micro-bench (round 14, docs/amp.md): ResNet-50 training
    through the Module/Executor/KVStore path with MXTPU_AMP=bf16 +
    dynamic loss scaling vs plain fp32 — img/s per chip and MFU both
    ways (the ROADMAP >= 0.35 target's measurement), the loss-scale
    ladder's final state, and the fused residual-epilogue kernel's
    per-block time vs XLA's unfused elementwise chain.

    On the CPU fallback rig the model drops to the cifar-style
    resnet-8 at a small batch (recorded in ``amp_model``): the section
    then measures dispatch/machinery structure, not chip throughput.
    On a >=2-device host the Module binds across the process mesh, so
    the fused update runs the SHARDED bucket programs and the reported
    ``amp_master_bytes_per_replica`` is the 1/N master residency."""
    import jax
    import numpy as np

    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import amp, models, nd
    from mxnet_tpu import executor as ex_mod
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.module import Module

    devs = jax.devices()
    on_cpu = devs[0].platform == "cpu"
    if on_cpu:
        layers, img, batch, iters = 8, 32, 8, 8
    else:
        layers, img = 50, 224
        batch = int(os.environ.get("BENCH_AMP_BATCH", "256"))
        iters = int(os.environ.get("BENCH_AMP_ITERS", "12"))
    nclass = 100 if on_cpu else 1000
    net = models.get_symbol(f"resnet-{layers}", num_classes=nclass,
                            image_shape=(3, img, img))
    rng = np.random.RandomState(11)
    data = rng.uniform(0, 1, (batch, 3, img, img)).astype(np.float32)
    labels = rng.randint(0, nclass, batch).astype(np.float32)
    mk_ctx = mx.cpu if on_cpu else mx.tpu
    contexts = [mk_ctx(i) for i in range(len(devs))] if len(devs) > 1 \
        else [mk_ctx(0)]

    def run(amp_on):
        for k, v in (("MXTPU_AMP", "bf16"),
                     ("MXTPU_LOSS_SCALE", "dynamic")):
            if amp_on:
                os.environ[k] = v
            else:
                os.environ.pop(k, None)
        amp.reset_scaler()
        ex_mod.program_cache_clear()
        mod = Module(net, context=contexts)
        mod.bind(data_shapes=[("data", data.shape)],
                 label_shapes=[("softmax_label", labels.shape)])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(kvstore="local", optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05,
                                             "momentum": 0.9})
        batch_nd = DataBatch(data=[nd.array(data)],
                             label=[nd.array(labels)])

        def step():
            mod.forward(batch_nd, is_train=True)
            mod.backward()
            mod.update()

        for _ in range(2):  # compile + settle
            step()
        ex = mod._exec_group.execs[0]
        pname = sorted(ex.arg_dict)[-1]
        jax.block_until_ready(ex.arg_dict[pname]._read())
        tic = time.perf_counter()
        for _ in range(iters):
            step()
        jax.block_until_ready(ex.arg_dict[pname]._read())
        dt = time.perf_counter() - tic
        mem = mod._kvstore._fused.state_memory() \
            if mod._kvstore is not None and mod._kvstore._fused else {}
        rep = amp.global_scaler().report() if amp_on else {}
        return batch * iters / dt, mem, rep

    fp32_rate, _, _ = run(False)
    amp_rate, mem, rep = run(True)

    # sharded fp32 masters (the MULTICHIP payload): bf16-STORED params
    # through the fused kvstore on the process mesh — masters ride the
    # sharded flat state at 1/N bytes per replica.  (The Module run
    # above keeps params f32 — there the params ARE the masters.)
    if len(devs) > 1:
        try:
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from mxnet_tpu.parallel.mesh import global_mesh

            os.environ["MXTPU_AMP"] = "bf16"
            repl = NamedSharding(global_mesh(), P())
            kvm = mx.kv.create("local")
            kvm.set_optimizer(mx.optimizer.create(
                "sgd", learning_rate=0.05, momentum=0.9))
            mshapes = [(256, 64), (64,), (128, 32)]
            kvm.init(list(range(len(mshapes))),
                     [nd.array(rng.uniform(-1, 1, s).astype(
                         np.float32)).astype(jnp.bfloat16)
                      for s in mshapes])
            mgrads = [[nd.NDArray(_jax.device_put(rng.uniform(
                -0.1, 0.1, s).astype(np.float32), repl))]
                for s in mshapes]
            for _ in range(3):
                kvm.push(list(range(len(mshapes))), mgrads)
            mem = kvm._fused.state_memory()
        except Exception:  # noqa: BLE001 — payload stays Module-only
            pass
    os.environ.pop("MXTPU_AMP", None)
    os.environ.pop("MXTPU_LOSS_SCALE", None)
    amp.reset_scaler()

    out = {
        "amp_model": f"resnet-{layers}_b{batch}_{img}px"
                     + ("_cpu" if on_cpu else ""),
        "amp_imgs_per_sec": round(amp_rate, 1),
        "amp_imgs_per_sec_fp32": round(fp32_rate, 1),
        "amp_speedup": round(amp_rate / max(fp32_rate, 1e-9), 3),
        "amp_loss_scale_final": rep.get("scale"),
        "amp_overflows": rep.get("overflow_total"),
        "amp_skipped_steps": rep.get("skipped_steps_total"),
        "amp_master_bytes_per_replica": mem.get(
            "master_bytes_per_replica", 0),
        "amp_shard_replicas": mem.get("replicas", 1),
    }
    if not on_cpu:
        peak = _peak_flops(devs[0].device_kind)
        if peak and layers == 50:
            per_chip = amp_rate / len(devs)
            out["amp_mfu"] = round(
                per_chip * TRAIN_FLOPS_PER_IMG / peak, 4)
            out["amp_mfu_fp32"] = round(
                (fp32_rate / len(devs)) * TRAIN_FLOPS_PER_IMG / peak, 4)

    # --- fused residual-epilogue kernel vs XLA's unfused chain --------
    from mxnet_tpu.ops import residual_epilogue as re_mod

    n, h, w, c = (8, 14, 14, 256) if on_cpu else (64, 56, 56, 256)
    x = jnp.asarray(rng.uniform(-1, 1, (n, h, w, c)).astype(np.float32))
    s = jnp.asarray(rng.uniform(-1, 1, (n, h, w, c)).astype(np.float32))
    sc = jnp.asarray(rng.uniform(0.5, 1.5, (c,)).astype(np.float32))
    b = jnp.asarray(rng.uniform(-0.5, 0.5, (c,)).astype(np.float32))
    impl = "auto" if not on_cpu else "lax"

    fused = jax.jit(lambda x_, s_: re_mod.residual_epilogue(
        x_, s_, sc, b, channel_axis=-1, impl=impl,
        platform=devs[0].platform))
    unfused = jax.jit(lambda x_, s_: jnp.maximum(
        (x_ + s_) * sc.reshape(1, 1, 1, -1) + b.reshape(1, 1, 1, -1),
        0.0))

    def time_fn(fn):
        jax.block_until_ready(fn(x, s))
        reps = 30
        tic = time.perf_counter()
        for _ in range(reps):
            out_ = fn(x, s)
        jax.block_until_ready(out_)
        return (time.perf_counter() - tic) / reps * 1e6

    out["epilogue_us_per_block"] = round(time_fn(fused), 1)
    out["epilogue_us_per_block_xla"] = round(time_fn(unfused), 1)
    out["epilogue_block"] = f"{n}x{h}x{w}x{c}"
    return out


def _passes_micro():
    """Graph-rewrite pipeline micro-bench (round 12): bind/trace cost
    and node count with MXTPU_GRAPH_PASSES off vs on, per-pass node
    deltas, and the predict-path throughput with Conv+BN folding on vs
    off (the pass the serving path rides).

    The subject net is a conv+BN stack with residual elemwise chains
    and a constant subgraph — small enough for the CPU fallback rig,
    shaped so every pass has something to do.
    """
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import passes, sym
    from mxnet_tpu import executor as ex_mod
    from mxnet_tpu.context import default_accelerator_context
    from mxnet_tpu.predict import Predictor

    ctx = default_accelerator_context()
    shape = (8, 3, 32, 32)

    def build():
        d = sym.Variable("data")
        x = d
        for i, nf in enumerate((16, 16, 32, 32)):
            c = sym.Convolution(x, num_filter=nf, kernel=(3, 3), pad=(1, 1),
                                stride=(2, 2) if i == 2 else (1, 1),
                                no_bias=(i % 2 == 0), name=f"pm_c{i}")
            b = sym.BatchNorm(c, fix_gamma=False, name=f"pm_b{i}")
            a = sym.Activation(b, act_type="relu", name=f"pm_r{i}")
            # elemwise chain + duplicated subexpression per block
            x = sym.exp(sym.tanh(a * 0.5)) + sym.exp(sym.tanh(a * 0.5))
        x = sym.broadcast_add(x, sym.ones((1, 32, 1, 1)) * 0.125)
        fc = sym.FullyConnected(sym.Flatten(x), num_hidden=10, name="pm_fc")
        return sym.SoftmaxOutput(fc, label=sym.Variable("softmax_label"),
                                 name="softmax")

    net = build()

    def timed_bind(env_val):
        prior = os.environ.get("MXTPU_GRAPH_PASSES")
        os.environ["MXTPU_GRAPH_PASSES"] = env_val
        try:
            ex_mod.program_cache_clear()
            tic = time.perf_counter()
            ex = net.simple_bind(ctx, grad_req="null", data=shape)
            out = ex.forward(is_train=False)[0]
            jax.block_until_ready(out._read())
            return (time.perf_counter() - tic) * 1e3
        finally:
            if prior is None:
                os.environ.pop("MXTPU_GRAPH_PASSES", None)
            else:
                os.environ["MXTPU_GRAPH_PASSES"] = prior
    trace_ms_before = round(timed_bind("off"), 1)
    trace_ms_after = round(timed_bind("default"), 1)

    report = passes.pipeline_report(net)
    nodes_before = report[0]["nodes_before"] if report else None
    nodes_after = report[-1]["nodes_after"] if report else None

    # predict path: BN-fold on vs off, same checkpoint values
    rs = np.random.RandomState(0)
    probe = net.simple_bind(ctx, grad_req="null", data=shape)
    args, auxs = {}, {}
    for k_, v_ in probe.arg_dict.items():
        if k_ in ("data", "softmax_label"):
            continue
        args[k_] = mx.nd.array(
            rs.uniform(-0.25, 0.25, v_.shape).astype(np.float32))
    for k_, v_ in probe.aux_dict.items():
        lo, hi = (0.5, 1.5) if "var" in k_ else (-0.1, 0.1)
        auxs[k_] = mx.nd.array(
            rs.uniform(lo, hi, v_.shape).astype(np.float32))
    x = rs.uniform(-1, 1, shape).astype(np.float32)

    def infer_rate(env_val):
        prior = os.environ.get("MXTPU_GRAPH_PASSES")
        os.environ["MXTPU_GRAPH_PASSES"] = env_val
        try:
            ex_mod.program_cache_clear()
            p = Predictor(symbol=net, arg_params=dict(args),
                          aux_params=dict(auxs),
                          input_shapes={"data": shape})
            p.forward(data=x)
            p.get_output(0)  # compile + settle
            n = 30
            tic = time.perf_counter()
            for _ in range(n):
                p.forward(data=x)
                p.get_output(0)
            dt = time.perf_counter() - tic
            return shape[0] * n / dt, p._n_bn_folded
        finally:
            if prior is None:
                os.environ.pop("MXTPU_GRAPH_PASSES", None)
            else:
                os.environ["MXTPU_GRAPH_PASSES"] = prior

    rate_nofold, _ = infer_rate("0")
    rate_fold, n_folded = infer_rate("default")

    out = {
        "passes_trace_ms_before": trace_ms_before,
        "passes_trace_ms_after": trace_ms_after,
        "passes_nodes_before": nodes_before,
        "passes_nodes_after": nodes_after,
        "passes_convbn_folded": int(n_folded),
        "passes_infer_img_s_nofold": round(rate_nofold, 1),
        "passes_infer_img_s_bnfold": round(rate_fold, 1),
        "passes_bnfold_speedup": round(rate_fold / max(rate_nofold, 1e-9), 3),
    }
    for row in report:
        out[f"passes_nodes_after_{row['pass']}"] = row["nodes_after"]
    return out


def _bench(dev, kind, init_notes=(), init_attempts=1):
    import jax
    import jax.numpy as jnp

    import mxnet_tpu  # noqa: F401 (sets matmul precision policy)
    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    # BENCH_EXPLAIN (round 22): arm the perf-attribution plane for the
    # whole bench so a profile document (ranked programs, cost rows,
    # MFU) can be written next to the headline number
    explain = os.environ.get("BENCH_EXPLAIN", "").strip()
    if explain:
        from mxnet_tpu.telemetry import perf as _perf

        _perf.enable()
    net = models.get_symbol("resnet-50", num_classes=1000)
    dtype = jnp.bfloat16 if os.environ.get("BENCH_DTYPE", "bf16") == "bf16" else jnp.float32

    tr = FusedTrainer(
        net,
        optimizer="sgd",
        optimizer_params={"lr": 0.1, "momentum": 0.9, "rescale_grad": 1.0 / batch},
        dtype=dtype,
    )
    tr.init(data=(batch, 3, 224, 224))

    # Synthetic batches staged on device BEFORE the timed loop.  This
    # measures the training step, not the host link: the bench chip sits
    # behind a ~200MB/s tunnel, while a production TPU host feeds via local
    # DMA with the input pipeline overlapped (docs/how_to/perf.md).  A few
    # distinct batches rotate so no per-step caching can help.
    rs = np.random.RandomState(0)
    staged = []
    for i in range(4):
        data = rs.uniform(0, 1, (batch, 3, 224, 224)).astype(np.float32)
        label = rs.randint(0, 1000, batch).astype(np.float32)
        staged.append({"data": jax.device_put(data),
                       "softmax_label": jax.device_put(label)})

    def fetch_barrier():
        # block_until_ready can ack at dispatch on tunneled backends;
        # pulling real bytes is the only barrier that can't lie
        name = sorted(tr.params)[0]
        return float(np.asarray(tr.params[name]).ravel()[0])

    for i in range(8):  # compile + settle
        tr.step(**staged[i % len(staged)])
    fetch_barrier()

    iters = int(os.environ.get("BENCH_ITERS", "60"))
    # steps-per-call: k steps fused into one dispatch (FusedTrainer.
    # step_multi, a lax.scan over the step body).  Per-call dispatch is
    # the dominant cost of small-batch steps on this tunneled rig
    # (tools/probe_gap.py: 82% of a b32 step), and amortizing it is a
    # framework feature, not a bench trick — the training math is
    # step-for-step identical (tests/test_train.py::
    # test_step_multi_matches_sequential_steps).
    spc_env = os.environ.get("BENCH_STEPS_PER_CALL", "auto")
    spc = (8 if batch <= 64 else 1) if spc_env == "auto" else max(1, int(spc_env))
    if spc > 1:
        stacked = {
            k_: jnp.stack([staged[i % len(staged)][k_] for i in range(spc)])
            for k_ in ("data", "softmax_label")
        }
        tr.step_multi(**stacked)  # compile
        fetch_barrier()
        tr.step_multi(**stacked)  # settle
        fetch_barrier()
        calls = max(iters // spc, 1)
        tic = time.perf_counter()
        for _ in range(calls):
            tr.step_multi(**stacked)
        fetch_barrier()
        dt = time.perf_counter() - tic
        img_s = batch * spc * calls / dt
    else:
        tic = time.perf_counter()
        for i in range(iters):
            tr.step(**staged[i % len(staged)])
        fetch_barrier()
        dt = time.perf_counter() - tic
        img_s = batch * iters / dt
    peak = _peak_flops(kind)
    mfu = (img_s * TRAIN_FLOPS_PER_IMG / peak) if peak else None
    payload = {
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "captured_utc": time.strftime("%Y-%m-%d", time.gmtime()),
        "device_kind": kind,
        "batch": batch,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "model_tflops_per_sec": round(img_s * TRAIN_FLOPS_PER_IMG / 1e12, 2),
        "steps_per_call": spc,
    }
    if peak is None:
        # an unknown device kind must leave a note, not a bare null MFU
        payload["peak_flops_unknown"] = (
            "device_kind %r has no telemetry/perf.py:PEAK_TFLOPS entry"
            % kind)
    payload["init_attempts"] = int(init_attempts)
    if init_notes:
        # a slow/retried backend init is a datapoint, not a silent event
        payload["init_notes"] = list(init_notes)
    if explain:
        # write the perf plane's full profile document (tools/explain.py
        # renders it); BENCH_EXPLAIN=1 picks a default path
        from mxnet_tpu.telemetry import perf as _perf

        out_path = explain if explain.lower() not in ("1", "true") \
            else "BENCH_EXPLAIN.json"
        try:
            with open(out_path, "w") as f:
                json.dump(_perf.profile_payload(topn=0), f, indent=1)
            payload["explain_path"] = out_path
        except OSError as exc:
            payload["explain_error"] = repr(exc)

    if os.environ.get("BENCH_EXTRAS", "1") == "1":
        # secondary datapoint (inference b32; P100 baseline 713.17 img/s)
        # under a watchdog: if its extra compile hangs, the ALREADY
        # MEASURED training number must still reach stdout — losing the
        # primary metric to an optional extra would repeat round 1's
        # silent-timeout failure
        # exactly-one-emit: whichever of (main thread, watchdog) claims
        # the flag first emits; the loser stays silent — otherwise a
        # score() finishing inside the watchdog's final window could
        # print the metric line twice
        lock = threading.Lock()
        state = {"emitted": False}

        def claim():
            with lock:
                if state["emitted"]:
                    return False
                state["emitted"] = True
                return True

        def extras_watchdog():
            deadline = time.monotonic() + float(
                os.environ.get("BENCH_EXTRAS_TIMEOUT_S", "480"))
            while time.monotonic() < deadline:
                if state["emitted"]:
                    return
                time.sleep(1.0)
            if claim():
                payload["extras_error"] = "inference extras timed out"
                _emit(payload)
                os._exit(0)

        threading.Thread(target=extras_watchdog, daemon=True).start()
        deadline = time.monotonic() + float(
            os.environ.get("BENCH_EXTRAS_TIMEOUT_S", "480")) - 20.0

        class _Extras(dict):
            """Every recorded extra lands in the payload IMMEDIATELY
            (under the emit lock) so a watchdog timeout in a LATER block
            cannot discard minutes of already-measured numbers."""

            def __setitem__(self, k, v):
                super().__setitem__(k, v)
                with lock:
                    if not state["emitted"]:
                        payload[k] = v

            def setdefault(self, k, v):
                if k not in self:
                    self[k] = v
                return self[k]

        extras = _Extras()

        def _time_steps(step_fn, barrier, iters):
            """warmup already done by caller; barrier -> timed loop ->
            barrier (the one copy of the measurement scaffold the
            single-batch blocks share)."""
            barrier()
            tic_ = time.perf_counter()
            for _ in range(iters):
                step_fn()
            barrier()
            return time.perf_counter() - tic_
        try:
            # inference: reuse the ALREADY-COMPILED trainer's params with
            # its eval graph — one forward-only compile, no separate
            # predictor build (round-2 extras timed out rebuilding one)
            infer_iters = 30
            warm = tr.eval(data=staged[0]["data"])  # compile
            # barrier on the warmup's OWN output: params have no data
            # dependency on an eval, so fetch_barrier() would let the
            # warmup execution bleed into the timed window
            float(np.asarray(warm[0]).ravel()[0])
            itic = time.perf_counter()
            for i in range(infer_iters):
                out = tr.eval(data=staged[i % len(staged)]["data"])
            float(np.asarray(out[0]).ravel()[0])
            idt = time.perf_counter() - itic
            inf = batch * infer_iters / idt
            extras["resnet50_infer_b32_imgs_per_sec"] = round(inf, 1)
            # methodology: the train symbol's eval forward reusing staged
            # train batches, NOT the predictor ABI path earlier rounds'
            # benchmark_score measured — keyed distinctly so round-over-
            # round ratios aren't misread as apples-to-apples
            extras["eval_forward_vs_p100_infer_baseline"] = round(
                inf / 713.17, 2)
        except Exception as exc:  # noqa: BLE001
            extras["extras_error"] = repr(exc)
        try:
            # large-batch train: the chip's best-case throughput (the b32
            # headline stays baseline-comparable; this shows the ceiling).
            # Needs a fresh compile for the new shape — only start it when
            # enough budget remains for compile (~60s) + measurement.
            big = int(os.environ.get("BENCH_LARGE_BATCH", "256"))
            if big > batch and time.monotonic() < deadline - 120:
                big_tr = FusedTrainer(
                    net, optimizer="sgd",
                    optimizer_params={"lr": 0.1, "momentum": 0.9,
                                      "rescale_grad": 1.0 / big},
                    dtype=dtype)
                big_tr.init(data=(big, 3, 224, 224))
                bdata = {"data": jax.device_put(rs.uniform(
                    0, 1, (big, 3, 224, 224)).astype(np.float32)),
                    "softmax_label": jax.device_put(
                        rs.randint(0, 1000, big).astype(np.float32))}
                big_tr.step(**bdata)  # compile
                bname = sorted(big_tr.params)[0]
                bbarrier = lambda: float(
                    np.asarray(big_tr.params[bname]).ravel()[0])
                bbarrier()
                big_tr.step(**bdata)  # settle
                biters = 12
                bdt = _time_steps(lambda: big_tr.step(**bdata),
                                  bbarrier, biters)
                big_img_s = big * biters / bdt
                extras["resnet50_train_b%d_imgs_per_sec" % big] = round(
                    big_img_s, 1)
                if peak:
                    extras["mfu_b%d" % big] = round(
                        big_img_s * TRAIN_FLOPS_PER_IMG / peak, 4)
            elif big > batch:
                extras["large_batch_skipped"] = "insufficient extras budget"
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # transformer-LM train + KV-cache decode: the beyond-parity
            # model family's own numbers, when budget remains
            if time.monotonic() < deadline - 150 and os.environ.get(
                    "BENCH_LM", "1") == "1":
                L_, H_, D_, T_, V_ = 4, 8, 512, 512, 8192
                lm = models.transformer.transformer_lm(
                    num_layers=L_, num_heads=H_, d_model=D_, seq_len=T_,
                    vocab_size=V_)
                lm_tr = FusedTrainer(
                    lm, optimizer="adam", optimizer_params={"lr": 1e-3},
                    dtype=dtype)
                bsz = 8
                lm_tr.init(data=(bsz, T_), softmax_label=(bsz, T_))
                toks = jax.device_put(rs.randint(
                    0, V_, (bsz, T_)).astype(np.float32))
                labs = jax.device_put(rs.randint(
                    0, V_, (bsz, T_)).astype(np.float32))
                lm_tr.step(data=toks, softmax_label=labs)  # compile
                lname = sorted(lm_tr.params)[0]
                lbarrier = lambda: float(
                    np.asarray(lm_tr.params[lname]).ravel()[0])
                lm_iters = 15
                ldt = _time_steps(
                    lambda: lm_tr.step(data=toks, softmax_label=labs),
                    lbarrier, lm_iters)
                extras["transformer_lm_train_tokens_per_sec"] = round(
                    bsz * T_ * lm_iters / ldt, 0)

                from mxnet_tpu.models.decode import KVDecoder

                dec = KVDecoder(lm_tr.params, num_layers=L_,
                                num_heads=H_, max_len=T_, dtype=dtype)
                dstate, dlog = dec.prefill(np.zeros((bsz, 32), np.int64))
                tok = np.asarray(dlog[:, -1]).argmax(-1)
                dstate, dwarm = dec.step(dstate, tok)   # compile
                float(np.asarray(dwarm).ravel()[0])     # warmup barrier
                dn = 40
                dtic = time.perf_counter()
                for _ in range(dn):
                    dstate, dlog2 = dec.step(dstate, tok)
                float(np.asarray(dlog2).ravel()[0])
                ddt = time.perf_counter() - dtic
                extras["kv_decode_tokens_per_sec"] = round(
                    bsz * dn / ddt, 1)
                # fused decode: the WHOLE n-token loop in one dispatch
                # (generate_scan) — decode's analog of steps-per-call.
                # The timed window includes the 8-token prefill dispatch
                # generate_scan performs internally, so the reported
                # rate (still counting only the 64 generated tokens) is
                # a conservative lower bound on the scan loop itself
                fn_tok = 64
                dec.generate_scan(np.zeros((bsz, 8), np.int64),
                                  fn_tok)           # compile
                ftic = time.perf_counter()
                dec.generate_scan(np.zeros((bsz, 8), np.int64), fn_tok)
                fdt = time.perf_counter() - ftic
                extras["kv_decode_fused_tokens_per_sec"] = round(
                    bsz * fn_tok / fdt, 1)
            elif os.environ.get("BENCH_LM", "1") == "1":
                extras["lm_skipped"] = "insufficient extras budget"
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # executor hot-path: dispatch_us_per_step (Python overhead of
            # a fused train-step) + recompiles across bucket-shape
            # re-binds (program cache regression tracker, ISSUE 2)
            if os.environ.get("BENCH_DISPATCH", "1") == "1":
                # per-key sets (dict.update bypasses _Extras.__setitem__,
                # which is what lands keys in the payload immediately)
                for k_, v_ in _dispatch_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # kvstore update hot-path: eager per-key push/pull vs the
            # bucketed jit-fused engine on a ~100-param model (ISSUE 3)
            if os.environ.get("BENCH_KV", "1") == "1":
                for k_, v_ in _kv_update_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # async-pipeline hot loop: fused device metrics + bounded
            # window vs the eager per-batch-sync loop, and the fixed
            # step_multi vs single dispatch (ISSUE 4)
            if os.environ.get("BENCH_PIPELINE", "1") == "1":
                for k_, v_ in _pipeline_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # health layer: sentinel-on vs sentinel-off fused-loop
            # overhead (<3% target) + flight-recorder per-record cost
            # (ISSUE 5)
            if os.environ.get("BENCH_HEALTH", "1") == "1":
                for k_, v_ in _health_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # survival layer: async-checkpoint capture tax on the hot
            # loop + writer wall time + validated-resume time (ISSUE 11)
            if os.environ.get("BENCH_CKPT", "1") == "1":
                for k_, v_ in _survival_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # mesh-sharded update path: sharded vs replicated bucket
            # step, optimizer-state bytes per replica, collective
            # payload — the MULTICHIP runs' primary section (ISSUE 7)
            if os.environ.get("BENCH_SHARD", "1") == "1":
                for k_, v_ in _shard_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # elastic multi-host runtime: collective-vs-PS kvstore step
            # cost + the generation failover wall time on the
            # multi-process CPU rig (ISSUE 13)
            if os.environ.get("BENCH_DIST", "1") == "1":
                for k_, v_ in _dist_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # fleet observability plane: federation scrape, straggler
            # detection latency, merge-trace cost (ISSUE 14)
            if os.environ.get("BENCH_FLEET", "1") == "1":
                for k_, v_ in _fleet_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # serving hot path: continuous-batching scheduler under a
            # Poisson arrival load — served tok/s, TTFT tail, slot
            # occupancy (ISSUE 6)
            if os.environ.get("BENCH_SERVE", "1") == "1":
                for k_, v_ in _serve_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # serving fleet: Poisson soak through the replica router +
            # paged-vs-contiguous co-batching at equal slots (ISSUE 15)
            if os.environ.get("BENCH_ROUTER", "1") == "1":
                for k_, v_ in _router_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # request tracing + SLO plane: the routed soak with span
            # recording off/on/sampled — trace_overhead_pct is the
            # host-side cost of the per-request lens (ISSUE 16)
            if os.environ.get("BENCH_TRACE", "1") == "1":
                for k_, v_ in _trace_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # schedule autotuner: paged-attention kernel vs gather per
            # decode step, search cost, persisted-cache reuse, and the
            # epilogue's tuned block_rows vs its default (ISSUE 18)
            if os.environ.get("BENCH_AUTOTUNE", "1") == "1":
                for k_, v_ in _autotune_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # graph-rewrite pipeline: bind/trace cost + node counts
            # passes-off vs on, and the Conv+BN-folded predict path
            # (ISSUE 8)
            if os.environ.get("BENCH_PASSES", "1") == "1":
                for k_, v_ in _passes_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # row-sparse embedding update: touched-rows-only fused
            # bucket vs the dense-gradient scatter path (ISSUE 9)
            if os.environ.get("BENCH_SPARSE", "1") == "1":
                for k_, v_ in _sparse_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        try:
            # first-class AMP: bf16 Module training vs fp32 (MFU toward
            # the ROADMAP >= 0.35 target), loss-scale ladder state, and
            # the fused residual-epilogue kernel vs XLA's chain; on a
            # multi-device host the Module spans the mesh, so masters
            # run SHARDED (1/N bytes per replica) — ISSUE 10
            if os.environ.get("BENCH_AMP", "1") == "1":
                for k_, v_ in _amp_micro().items():
                    extras[k_] = v_
        except Exception as exc:  # noqa: BLE001
            extras.setdefault("extras_error", repr(exc))
        # the MFU config is the bench's biggest resident (560M params:
        # ~7.8 GB of masters + Adam slots + bf16 cache on a 16 GB chip):
        # drop every earlier section's device state first, or their live
        # buffers + compiled-executable scratch tip it into
        # RESOURCE_EXHAUSTED (observed once the fused-decode extra
        # joined the lineup)
        import gc

        # (plain del per name: locals() is a snapshot in CPython, so
        # dynamic deletion would silently do nothing; the barrier
        # lambdas close over their trainers and must go too.  One guarded
        # del PER NAME — a grouped `del a, b, c` aborts at the first
        # unbound name, leaving the rest of a partially-initialized
        # section alive and defeating this cleanup's purpose)
        try:
            del big_tr
        except NameError:
            pass
        try:
            del bdata
        except NameError:
            pass
        try:
            del bbarrier
        except NameError:
            pass
        try:
            del lm_tr
        except NameError:
            pass
        try:
            del toks
        except NameError:
            pass
        try:
            del labs
        except NameError:
            pass
        try:
            del lbarrier
        except NameError:
            pass
        try:
            del dec
        except NameError:
            pass
        try:
            del dstate
        except NameError:
            pass
        try:
            del dlog
        except NameError:
            pass
        try:
            del dlog2
        except NameError:
            pass
        try:
            del dwarm
        except NameError:
            pass
        try:
            del tok
        except NameError:
            pass
        try:
            del tr
        except NameError:
            pass
        try:
            del staged
        except NameError:
            pass
        try:
            del fetch_barrier
        except NameError:
            pass
        gc.collect()
        try:
            # compute-bound MFU headline: a ~220M-param LM config where
            # the MXU is actually fed (ResNet-50-with-BN is HBM-roofline-
            # bound at ~0.175 on v5e; tools/probe_lm_mfu.py sweeps this
            # family with the SAME shared config + FLOP rule)
            if peak and time.monotonic() < deadline - 180 and \
                    os.environ.get("BENCH_LM_MFU", "1") == "1":
                from mxnet_tpu.models.transformer import (
                    MFU_HEADLINE_CONFIG, lm_train_flops_per_token)

                cfg = MFU_HEADLINE_CONFIG
                Tm, Vm = cfg["seq_len"], cfg["vocab_size"]
                Bm = int(os.environ.get("BENCH_LM_MFU_BATCH", "8"))
                # flash-attention tile size from the same sweep (read at
                # trace time); restored after the trainer is built
                blk = os.environ.get("BENCH_LM_MFU_BLOCK", "256x256")
                old_blk = (os.environ.get("MXTPU_FLASH_BLOCK_Q"),
                           os.environ.get("MXTPU_FLASH_BLOCK_K"))
                bq, bk = blk.split("x")
                os.environ["MXTPU_FLASH_BLOCK_Q"] = bq
                os.environ["MXTPU_FLASH_BLOCK_K"] = bk
                try:
                    big_lm = models.transformer.transformer_lm(**cfg)
                    mtr = FusedTrainer(big_lm, optimizer="adam",
                                       optimizer_params={"lr": 1e-4},
                                       dtype=dtype)
                    mtr.init(data=(Bm, Tm), softmax_label=(Bm, Tm))
                    mtoks = jax.device_put(rs.randint(
                        0, Vm, (Bm, Tm)).astype(np.float32))
                    mlabs = jax.device_put(rs.randint(
                        0, Vm, (Bm, Tm)).astype(np.float32))
                    mtr.step(data=mtoks, softmax_label=mlabs)  # compile
                    mname = sorted(mtr.params)[0]
                    mbarrier = lambda: float(
                        np.asarray(mtr.params[mname]).ravel()[0])
                    mbarrier()
                    mdt = _time_steps(
                        lambda: mtr.step(data=mtoks, softmax_label=mlabs),
                        mbarrier, 10)
                finally:
                    for env_k, env_v in zip(("MXTPU_FLASH_BLOCK_Q",
                                             "MXTPU_FLASH_BLOCK_K"),
                                            old_blk):
                        if env_v is None:
                            os.environ.pop(env_k, None)
                        else:
                            os.environ[env_k] = env_v
                mtok_s = Bm * Tm * 10 / mdt
                fpt = lm_train_flops_per_token(
                    cfg["num_layers"], cfg["d_model"], cfg["d_ff"], Tm, Vm)
                extras["transformer_lm_mfu"] = round(
                    mtok_s * fpt / peak, 4)
                extras["transformer_lm_mfu_tokens_per_sec"] = round(
                    mtok_s, 0)
                extras["transformer_lm_mfu_config"] = (
                    "L%d D%d ff%d T%d V%d b%d blk%s %s" % (
                        cfg["num_layers"], cfg["d_model"], cfg["d_ff"],
                        Tm, Vm, Bm, blk, jnp.dtype(dtype).name))
        except Exception as exc:  # noqa: BLE001
            extras["lm_mfu_error"] = repr(exc)  # the headline must not
            #                                     vanish behind an earlier
            #                                     block's unrelated error
        # postamble: the regression sentinel judges the COMMITTED
        # trajectory (this round's numbers land in it next commit); its
        # table goes to stderr, its verdict rides the payload
        extras["bench_trend_rc"] = _bench_trend_check()
        if not claim():
            return 0  # the watchdog already emitted the primary payload
        payload.update(extras)

    _emit(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
