"""Benchmark: ResNet-50 ImageNet-shape training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference's strongest published single-device number —
ResNet-50 training, batch 32, P100: 181.53 img/s (BASELINE.md,
docs/how_to/perf.md:132-139).  vs_baseline = ours / 181.53.

The run uses the FusedTrainer fast path (whole train step = one XLA
computation, buffer donation, bf16 compute with fp32 master weights —
the TPU-native equivalent of the reference's fp32 cuDNN path).
"""
import json
import os
import time

import numpy as np

BASELINE_IMG_S = 181.53  # P100 ResNet-50 train b32 (docs/how_to/perf.md:132-139)


def main():
    import jax
    import jax.numpy as jnp

    import mxnet_tpu  # noqa: F401 (sets matmul precision policy)
    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    net = models.get_symbol("resnet-50", num_classes=1000)
    dtype = jnp.bfloat16 if os.environ.get("BENCH_DTYPE", "bf16") == "bf16" else jnp.float32

    tr = FusedTrainer(
        net,
        optimizer="sgd",
        optimizer_params={"lr": 0.1, "momentum": 0.9, "rescale_grad": 1.0 / batch},
        dtype=dtype,
    )
    tr.init(data=(batch, 3, 224, 224))

    rs = np.random.RandomState(0)
    data = rs.uniform(0, 1, (batch, 3, 224, 224)).astype(np.float32)
    label = rs.randint(0, 1000, batch).astype(np.float32)

    # warmup / compile
    for _ in range(3):
        outs = tr.step(data=data, softmax_label=label)
    jax.block_until_ready(outs)
    jax.block_until_ready(jax.tree_util.tree_leaves(tr.params))

    iters = int(os.environ.get("BENCH_ITERS", "30"))
    tic = time.perf_counter()
    for _ in range(iters):
        outs = tr.step(data=data, softmax_label=label)
    jax.block_until_ready(outs)
    jax.block_until_ready(jax.tree_util.tree_leaves(tr.params))
    dt = time.perf_counter() - tic

    img_s = batch * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
