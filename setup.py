#!/usr/bin/env python
"""Package build for mxnet_tpu (parity: tools/pip_package in the
reference — the C ABI libraries ship as package data, like the
reference wheel bundles libmxnet.so).

    python setup.py bdist_wheel      # wheel incl. native libs
    python setup.py sdist            # source dist

The native libraries are rebuilt from src/ with `make -C src` when
absent; the wheel simply packages whatever is in mxnet_tpu/lib/.
"""
import glob
import os
import subprocess

from setuptools import find_packages, setup

HERE = os.path.dirname(os.path.abspath(__file__))


def _ensure_native_libs():
    """Build the C ABI libraries when absent (fresh clone: mxnet_tpu/lib
    is generated, not tracked)."""
    libdir = os.path.join(HERE, "mxnet_tpu", "lib")
    if glob.glob(os.path.join(libdir, "*.so")):
        return
    makefile = os.path.join(HERE, "src", "Makefile")
    if os.path.exists(makefile):
        subprocess.run(["make", "-C", os.path.join(HERE, "src")],
                       check=True)
    if not glob.glob(os.path.join(libdir, "*.so")):
        raise RuntimeError(
            "mxnet_tpu/lib/*.so missing and `make -C src` did not produce "
            "them; build the native runtime before packaging")


_ensure_native_libs()


def _readme():
    path = os.path.join(HERE, "README.md")
    if os.path.exists(path):
        with open(path) as f:
            return f.read()
    return ""


setup(
    name="mxnet-tpu",
    version="0.9.4",  # tracks the reference surface this package mirrors
    description="TPU-native deep learning framework with the MXNet "
                "v0.9 API surface (JAX/XLA/Pallas compute, C++ runtime)",
    long_description=_readme(),
    long_description_content_type="text/markdown",
    packages=find_packages(include=["mxnet_tpu", "mxnet_tpu.*"]),
    package_data={"mxnet_tpu": ["lib/*.so"]},
    include_package_data=True,
    python_requires=">=3.10",
    install_requires=["numpy", "jax"],
    extras_require={
        "io": ["pillow"],
        "viz": ["graphviz"],
    },
)
