#!/usr/bin/env python
"""Package build for mxnet_tpu (parity: tools/pip_package in the
reference — the C ABI libraries ship as package data, like the
reference wheel bundles libmxnet.so).

    python setup.py bdist_wheel      # platform wheel incl. native libs
    python setup.py sdist            # source dist (native SOURCES only)

Binary commands (bdist_wheel / install / develop) rebuild any missing
native library from src/ first; metadata-only commands (sdist, egg_info,
--help) need no toolchain.
"""
import glob
import os
import subprocess
import sys

from setuptools import find_packages, setup
from setuptools.dist import Distribution

HERE = os.path.dirname(os.path.abspath(__file__))

_CORE_LIBS = ("libmxtpu.so", "libmxtpu_capi.so", "libmxtpu_predict.so")
_BINARY_CMDS = {"bdist_wheel", "bdist", "install", "develop", "build",
                "build_ext"}


def _ensure_native_libs():
    """Build any missing C ABI library (fresh clone: mxnet_tpu/lib is
    generated, not tracked; the Makefile's default target covers only
    the host engine, so name the capi/predict targets explicitly)."""
    libdir = os.path.join(HERE, "mxnet_tpu", "lib")
    if not all(os.path.exists(os.path.join(libdir, lib))
               for lib in _CORE_LIBS):
        subprocess.run(
            ["make", "-C", os.path.join(HERE, "src"),
             "all", "capi", "predict"], check=True)
    missing = [lib for lib in _CORE_LIBS
               if not os.path.exists(os.path.join(libdir, lib))]
    if missing:
        raise RuntimeError(
            f"native libraries {missing} missing after `make -C src`; "
            "build the runtime before packaging")


if _BINARY_CMDS.intersection(sys.argv[1:]):
    _ensure_native_libs()


class _BinaryDistribution(Distribution):
    """The wheel carries platform-specific .so files — force a platform
    tag so pip never installs an x86-64 Linux wheel elsewhere."""

    def has_ext_modules(self):
        return True


def _readme():
    path = os.path.join(HERE, "README.md")
    if os.path.exists(path):
        with open(path) as f:
            return f.read()
    return ""


setup(
    name="mxnet-tpu",
    version="0.9.4",  # tracks the reference surface this package mirrors
    description="TPU-native deep learning framework with the MXNet "
                "v0.9 API surface (JAX/XLA/Pallas compute, C++ runtime)",
    long_description=_readme(),
    long_description_content_type="text/markdown",
    packages=find_packages(include=["mxnet_tpu", "mxnet_tpu.*"]),
    package_data={"mxnet_tpu": ["lib/*.so"]},
    include_package_data=True,
    distclass=_BinaryDistribution,
    python_requires=">=3.10",
    install_requires=["numpy", "jax"],
    extras_require={
        "io": ["pillow"],
        "viz": ["graphviz"],
    },
)
