package MXNetTPU;

# Perl predict-only frontend over the C ABI (parity model: the
# reference's matlab/+mxnet/model.m — load a checkpoint, feed inputs,
# read outputs; everything heavier stays in the core runtime).
#
#   my $p = MXNetTPU::Predictor->new(
#       symbol_file => "m-symbol.json", params_file => "m-0000.params",
#       input_key => "data", input_shape => [4, 8]);
#   my $out = $p->predict([ @flat_row_major_floats ]);   # array ref
#   my $shape = $p->output_shape;                        # array ref

use strict;
use warnings;

our $VERSION = '0.1';

# RTLD_GLOBAL: libmxtpu_predict.so embeds CPython; the interpreter's own
# extension modules (math, _struct, ...) resolve libpython symbols from
# the global namespace, so the chain must be loaded globally.  Defining
# dl_load_flags makes XSLoader delegate to DynaLoader, which honors it.
sub dl_load_flags { 0x01 }

require XSLoader;
XSLoader::load('MXNetTPU', $VERSION);

package MXNetTPU::Predictor;

use strict;
use warnings;
use Carp ();

sub new {
    my ($class, %args) = @_;
    for my $k (qw(symbol_file params_file input_key input_shape)) {
        Carp::croak("MXNetTPU::Predictor->new: missing $k")
            unless defined $args{$k};
    }
    my $sym    = _slurp($args{symbol_file});
    my $params = _slurp($args{params_file});
    my $handle = MXNetTPU::_create($sym, $params, $args{input_key},
                                   $args{input_shape});
    return bless {
        handle => $handle,
        key    => $args{input_key},
    }, $class;
}

sub predict {
    my ($self, $data) = @_;
    MXNetTPU::_set_input($self->{handle}, $self->{key}, $data);
    MXNetTPU::_forward($self->{handle});
    my $shape = $self->output_shape(0);
    my $total = 1;
    $total *= $_ for @$shape;
    return MXNetTPU::_output($self->{handle}, 0, $total);
}

sub output_shape {
    my ($self, $index) = @_;
    return MXNetTPU::_output_shape($self->{handle}, $index // 0);
}

sub DESTROY {
    my ($self) = @_;
    MXNetTPU::_free($self->{handle}) if $self->{handle};
    $self->{handle} = 0;
}

sub _slurp {
    my ($path) = @_;
    open my $fh, '<:raw', $path
        or Carp::croak("MXNetTPU: cannot read $path: $!");
    local $/;
    my $data = <$fh>;
    close $fh;
    return $data;
}

1;
