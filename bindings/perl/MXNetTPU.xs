/* Perl XS glue for the C predict ABI (libmxtpu_predict.so).
 *
 * Parity model: the reference's language bindings are thin wrappers over
 * the same C API (SURVEY.md Appendix B — R-package/src glue, matlab
 * model.m, amalgamation/jni/predictor.cc).  This is the predict-only
 * binding in the one extra interpreter this image ships (perl): XS calls
 * MXPredCreate/SetInput/Forward/GetOutputShape/GetOutput/Free directly.
 */
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include "mxtpu.h"

MODULE = MXNetTPU  PACKAGE = MXNetTPU

PROTOTYPES: DISABLE

IV
_create(sym_json, params_sv, key, shape_ref)
        const char *sym_json
        SV *params_sv
        const char *key
        SV *shape_ref
    CODE:
        STRLEN plen;
        const char *pbytes = SvPVbyte(params_sv, plen);
        AV *shape_av = (AV *)SvRV(shape_ref);
        int ndim = (int)av_len(shape_av) + 1;
        if (ndim <= 0 || ndim > 8)
            croak("MXNetTPU: input shape must have 1..8 dims");
        unsigned indptr[2] = {0, (unsigned)ndim};
        unsigned shape[8];
        int i;
        for (i = 0; i < ndim; i++)
            shape[i] = (unsigned)SvUV(*av_fetch(shape_av, i, 0));
        const char *keys[1];
        keys[0] = key;
        void *h = NULL;
        if (MXPredCreate(sym_json, pbytes, (int)plen, 1, 0, 1, keys,
                         indptr, shape, &h) != 0)
            croak("MXPredCreate: %s", MXPredGetLastError());
        RETVAL = PTR2IV(h);
    OUTPUT:
        RETVAL

void
_set_input(handle, key, data_ref)
        IV handle
        const char *key
        SV *data_ref
    CODE:
        AV *av = (AV *)SvRV(data_ref);
        unsigned n = (unsigned)av_len(av) + 1;
        float *buf = (float *)malloc(n * sizeof(float));
        unsigned i;
        for (i = 0; i < n; i++)
            buf[i] = (float)SvNV(*av_fetch(av, i, 0));
        int rc = MXPredSetInput(INT2PTR(void *, handle), key, buf, n);
        free(buf);
        if (rc != 0)
            croak("MXPredSetInput: %s", MXPredGetLastError());

void
_forward(handle)
        IV handle
    CODE:
        if (MXPredForward(INT2PTR(void *, handle)) != 0)
            croak("MXPredForward: %s", MXPredGetLastError());

SV *
_output_shape(handle, index)
        IV handle
        UV index
    CODE:
        unsigned *shape = NULL;
        unsigned ndim = 0;
        if (MXPredGetOutputShape(INT2PTR(void *, handle),
                                 (uint32_t)index, &shape, &ndim) != 0)
            croak("MXPredGetOutputShape: %s", MXPredGetLastError());
        AV *av = newAV();
        unsigned i;
        for (i = 0; i < ndim; i++)
            av_push(av, newSVuv(shape[i]));
        RETVAL = newRV_noinc((SV *)av);
    OUTPUT:
        RETVAL

SV *
_output(handle, index, total)
        IV handle
        UV index
        UV total
    CODE:
        float *buf = (float *)malloc(total * sizeof(float));
        if (MXPredGetOutput(INT2PTR(void *, handle), (uint32_t)index,
                            buf, (uint32_t)total) != 0) {
            free(buf);
            croak("MXPredGetOutput: %s", MXPredGetLastError());
        }
        AV *av = newAV();
        UV i;
        for (i = 0; i < total; i++)
            av_push(av, newSVnv((double)buf[i]));
        free(buf);
        RETVAL = newRV_noinc((SV *)av);
    OUTPUT:
        RETVAL

void
_free(handle)
        IV handle
    CODE:
        MXPredFree(INT2PTR(void *, handle));
