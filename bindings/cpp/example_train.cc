/*
 * C++ frontend demo: compose an MLP, bind, and train with SGD via the
 * kvstore updater — pure C++ user code on libmxtpu_capi.so, the analogue
 * of the reference's R/Scala training loops over the C ABI.
 *
 * Build/run: see tests/test_cpp_binding.py (compiled by the test suite).
 */
#include <cmath>
#include <cstdio>
#include <random>

#include "mxtpu.hpp"

using mxtpu::Device;
using mxtpu::Executor;
using mxtpu::KVStore;
using mxtpu::NDArray;
using mxtpu::Symbol;

constexpr int kBatch = 16;
constexpr int kIn = 12;
constexpr int kClasses = 4;
constexpr float kLR = 0.2f / kBatch;

static void SgdUpdater(int key, NDArrayHandle recv, NDArrayHandle local,
                       void *) {
  auto g = NDArray::FromHandle(recv);
  auto w = NDArray::FromHandle(local);
  auto gv = g.CopyTo();
  auto wv = w.CopyTo();
  for (size_t i = 0; i < wv.size(); ++i) wv[i] -= kLR * gv[i];
  w.CopyFrom(wv);
  (void)key;
  /* recv/local are borrowed during the callback: release, don't free */
  g.release();
  w.release();
}

int main() {
  auto data = Symbol::Variable("data");
  auto label = Symbol::Variable("softmax_label");
  auto fc1 = Symbol::Op("FullyConnected", "fc1", {&data},
                        {{"num_hidden", "32"}});
  auto act = Symbol::Op("Activation", "relu1", {&fc1},
                        {{"act_type", "relu"}});
  auto fc2 = Symbol::Op("FullyConnected", "fc2", {&act},
                        {{"num_hidden", "4"}});
  auto net = Symbol::Op("SoftmaxOutput", "softmax", {&fc2, &label}, {});

  // JSON round-trip proves serialization interop with the Python side
  auto json = net.ToJSON();
  auto reloaded = Symbol::FromJSON(json);

  Executor exec(reloaded, Device::kCPU, "write",
                {{"data", {kBatch, kIn}}, {"softmax_label", {kBatch}}});

  std::mt19937 rng(7);
  std::uniform_real_distribution<float> ud(-0.15f, 0.15f);
  KVStore kv("local");
  kv.SetUpdater(SgdUpdater, nullptr);
  std::vector<std::string> pnames;
  int key = 0;
  for (auto &name : reloaded.ListArguments()) {
    if (name == "data" || name == "softmax_label") continue;
    auto w = exec.Arg(name);
    std::vector<float> init(w.Size());
    for (auto &v : init) v = ud(rng);
    w.CopyFrom(init);
    kv.Init(key++, w);
    pnames.push_back(name);
  }

  // learnable synthetic task: class = argmax over 4 disjoint input bands
  std::vector<float> x(kBatch * kIn), y(kBatch);
  for (int i = 0; i < kBatch; ++i) {
    int cls = i % kClasses;
    y[i] = static_cast<float>(cls);
    for (int j = 0; j < kIn; ++j)
      x[i * kIn + j] = ud(rng) + (j % kClasses == cls ? 0.9f : 0.0f);
  }
  exec.Arg("data").CopyFrom(x);
  exec.Arg("softmax_label").CopyFrom(y);

  float first = 0, last = 0;
  for (int step = 0; step < 30; ++step) {
    exec.Forward(true);
    exec.Backward();
    for (size_t k = 0; k < pnames.size(); ++k) {
      kv.Push(static_cast<int>(k), exec.Grad(pnames[k]),
              -static_cast<int>(k));
      auto w = exec.Arg(pnames[k]);
      kv.Pull(static_cast<int>(k), &w, -static_cast<int>(k));
    }
    auto probs = exec.Output(0).CopyTo();
    float loss = 0;
    for (int i = 0; i < kBatch; ++i) {
      float p = probs[i * kClasses + static_cast<int>(y[i])];
      loss += -std::log(p > 1e-8f ? p : 1e-8f);
    }
    loss /= kBatch;
    if (step == 0) first = loss;
    last = loss;
  }
  std::printf("first %.4f last %.4f\n", first, last);
  if (!(last < first * 0.5f)) {
    std::fprintf(stderr, "loss did not decrease enough\n");
    return 2;
  }
  std::printf("CPP TRAIN OK\n");
  return 0;
}
