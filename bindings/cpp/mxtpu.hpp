/*
 * mxtpu.hpp — header-only C++ frontend over the general C ABI
 * (src/mxtpu_capi.h).
 *
 * Parity role: the reference's language bindings (R-package, scala JNI,
 * cpp usage of c_api.h) all sit on the C ABI; this wrapper is the C++
 * consumer demonstrating the same contract with RAII lifetime handling:
 * Symbol composition, shape inference, executor training and kvstore
 * updates without a line of Python in user code.
 *
 * Error model: throws mxtpu::Error carrying MXGetLastError().
 */
#ifndef MXTPU_HPP_
#define MXTPU_HPP_

#include <cstdint>
#include <map>
#include <numeric>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxtpu_capi.h"

namespace mxtpu {

struct Error : std::runtime_error {
  explicit Error(const std::string &where)
      : std::runtime_error(where + ": " + MXGetLastError()) {}
};

inline void check(int rc, const char *where) {
  if (rc != 0) throw Error(where);
}

enum class Device : int { kCPU = 1, kAccelerator = 2 };

class NDArray {
 public:
  NDArray() = default;
  NDArray(const std::vector<uint32_t> &shape, Device dev = Device::kCPU) {
    check(MXNDArrayCreate(shape.data(),
                          static_cast<uint32_t>(shape.size()),
                          static_cast<int>(dev), 0, &h_),
          "NDArrayCreate");
    owned_ = true;
  }
  /* wrap a handle returned by executor lookups (owned: caller frees) */
  static NDArray FromHandle(NDArrayHandle h) {
    NDArray a;
    a.h_ = h;
    a.owned_ = true;
    return a;
  }
  ~NDArray() { reset(); }
  NDArray(NDArray &&o) noexcept : h_(o.h_), owned_(o.owned_) {
    o.h_ = nullptr;
    o.owned_ = false;
  }
  NDArray &operator=(NDArray &&o) noexcept {
    reset();
    h_ = o.h_;
    owned_ = o.owned_;
    o.h_ = nullptr;
    o.owned_ = false;
    return *this;
  }
  NDArray(const NDArray &) = delete;
  NDArray &operator=(const NDArray &) = delete;

  std::vector<uint32_t> Shape() const {
    std::vector<uint32_t> buf(8);
    uint32_t ndim = 0;
    check(MXNDArrayGetShape(h_, &ndim,  buf.data(),
                            static_cast<uint32_t>(buf.size())),
          "GetShape");
    if (ndim > buf.size()) {  // rank exceeded the guess: fetch again
      buf.resize(ndim);
      check(MXNDArrayGetShape(h_, &ndim, buf.data(),
                              static_cast<uint32_t>(buf.size())),
            "GetShape");
    }
    buf.resize(ndim);
    return buf;
  }
  uint64_t Size() const {
    auto s = Shape();
    return std::accumulate(s.begin(), s.end(), uint64_t{1},
                           std::multiplies<uint64_t>());
  }
  void CopyFrom(const std::vector<float> &data) {
    check(MXNDArraySyncCopyFromCPU(h_, data.data(), data.size()),
          "SyncCopyFromCPU");
  }
  std::vector<float> CopyTo() const {
    std::vector<float> out(Size());
    check(MXNDArraySyncCopyToCPU(h_, out.data(), out.size()),
          "SyncCopyToCPU");
    return out;
  }
  NDArrayHandle handle() const { return h_; }
  /* detach without freeing — for handles borrowed inside callbacks */
  void release() {
    h_ = nullptr;
    owned_ = false;
  }

 private:
  void reset() {
    if (owned_ && h_) MXNDArrayFree(h_);
    h_ = nullptr;
  }
  NDArrayHandle h_ = nullptr;
  bool owned_ = false;
};

class Symbol {
 public:
  static Symbol Variable(const std::string &name) {
    SymbolHandle h = nullptr;
    check(MXSymbolCreateVariable(name.c_str(), &h), "CreateVariable");
    return Symbol(h);
  }
  /* op + attrs; inputs applied immediately (Compose) */
  static Symbol Op(const std::string &op, const std::string &name,
                   const std::vector<Symbol *> &inputs,
                   const std::map<std::string, std::string> &attrs = {}) {
    std::vector<const char *> keys, vals;
    for (auto &kv : attrs) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    SymbolHandle h = nullptr;
    check(MXSymbolCreateAtomicSymbol(op.c_str(),
                                     static_cast<uint32_t>(keys.size()),
                                     keys.data(), vals.data(), &h),
          "CreateAtomicSymbol");
    std::vector<SymbolHandle> args;
    for (auto *s : inputs) args.push_back(s->h_);
    check(MXSymbolCompose(h, name.c_str(),
                          static_cast<uint32_t>(args.size()), nullptr,
                          args.data()),
          "Compose");
    return Symbol(h);
  }
  static Symbol FromJSON(const std::string &json) {
    SymbolHandle h = nullptr;
    check(MXSymbolCreateFromJSON(json.c_str(), &h), "CreateFromJSON");
    return Symbol(h);
  }
  std::string ToJSON() const {
    const char *out = nullptr;
    check(MXSymbolSaveToJSON(h_, &out), "SaveToJSON");
    return out;
  }
  std::vector<std::string> ListArguments() const {
    uint32_t n = 0;
    const char **names = nullptr;
    check(MXSymbolListArguments(h_, &n, &names), "ListArguments");
    return {names, names + n};
  }
  ~Symbol() {
    if (h_) MXSymbolFree(h_);
  }
  Symbol(Symbol &&o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  Symbol &operator=(Symbol &&o) noexcept {
    if (h_) MXSymbolFree(h_);
    h_ = o.h_;
    o.h_ = nullptr;
    return *this;
  }
  Symbol(const Symbol &) = delete;
  Symbol &operator=(const Symbol &) = delete;
  SymbolHandle handle() const { return h_; }

 private:
  explicit Symbol(SymbolHandle h) : h_(h) {}
  SymbolHandle h_ = nullptr;
};

class Executor {
 public:
  Executor(const Symbol &net, Device dev, const std::string &grad_req,
           const std::map<std::string, std::vector<uint32_t>> &shapes) {
    std::vector<const char *> keys;
    std::vector<uint32_t> ind{0};
    std::vector<uint32_t> data;
    for (auto &kv : shapes) {
      keys.push_back(kv.first.c_str());
      data.insert(data.end(), kv.second.begin(), kv.second.end());
      ind.push_back(static_cast<uint32_t>(data.size()));
    }
    check(MXExecutorSimpleBind(net.handle(), static_cast<int>(dev), 0,
                               grad_req.c_str(),
                               static_cast<uint32_t>(keys.size()),
                               keys.data(), ind.data(), data.data(), &h_),
          "SimpleBind");
  }
  ~Executor() {
    if (h_) MXExecutorFree(h_);
  }
  Executor(const Executor &) = delete;
  Executor &operator=(const Executor &) = delete;

  void Forward(bool is_train) {
    check(MXExecutorForward(h_, is_train ? 1 : 0), "Forward");
  }
  void Backward() { check(MXExecutorBackward(h_), "Backward"); }
  NDArray Output(uint32_t i) const {
    NDArrayHandle out = nullptr;
    check(MXExecutorOutput(h_, i, &out), "Output");
    return NDArray::FromHandle(out);
  }
  NDArray Arg(const std::string &name) const {
    NDArrayHandle out = nullptr;
    check(MXExecutorArgArray(h_, name.c_str(), &out), "ArgArray");
    return NDArray::FromHandle(out);
  }
  NDArray Grad(const std::string &name) const {
    NDArrayHandle out = nullptr;
    check(MXExecutorGradArray(h_, name.c_str(), &out), "GradArray");
    return NDArray::FromHandle(out);
  }

 private:
  ExecutorHandle h_ = nullptr;
};

class KVStore {
 public:
  explicit KVStore(const std::string &type = "local") {
    check(MXKVStoreCreate(type.c_str(), &h_), "KVStoreCreate");
  }
  ~KVStore() {
    if (h_) MXKVStoreFree(h_);
  }
  KVStore(const KVStore &) = delete;
  KVStore &operator=(const KVStore &) = delete;

  void Init(int key, const NDArray &v) {
    NDArrayHandle h = v.handle();
    check(MXKVStoreInit(h_, 1, &key, &h), "KVStoreInit");
  }
  void Push(int key, const NDArray &v, int priority = 0) {
    NDArrayHandle h = v.handle();
    check(MXKVStorePush(h_, 1, &key, &h, priority), "KVStorePush");
  }
  void Pull(int key, NDArray *out, int priority = 0) {
    NDArrayHandle h = out->handle();
    check(MXKVStorePull(h_, 1, &key, &h, priority), "KVStorePull");
  }
  void SetUpdater(MXKVStoreUpdater fn, void *state) {
    check(MXKVStoreSetUpdater(h_, fn, state), "SetUpdater");
  }

 private:
  KVStoreHandle h_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXTPU_HPP_
