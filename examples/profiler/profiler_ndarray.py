#!/usr/bin/env python
"""Profile a sweep of imperative NDArray ops (parity:
example/profiler/profiler_ndarray.py — the reference runs a broad
imperative op sweep under the profiler; events appear per op under
mode='all').

Each op family below is exercised under the running profiler and the
dumped chrome-trace must contain an event for every call.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def sweep(n):
    rs = np.random.RandomState(0)
    a = nd.array(rs.rand(n, n).astype(np.float32))
    b = nd.array(rs.rand(n, n).astype(np.float32))
    ops_run = []

    def run(name, fn):
        out = fn()
        if isinstance(out, tuple):
            out = out[0]
        out.wait_to_read()
        ops_run.append(name)

    run("broadcast_add", lambda: nd.broadcast_add(a, b))
    run("elemwise_mul", lambda: a * b)
    run("dot", lambda: nd.dot(a, b))
    run("sum", lambda: nd.sum(a))
    run("transpose", lambda: nd.transpose(a))
    run("slice_axis", lambda: nd.slice_axis(a, axis=0, begin=0, end=n // 2))
    run("relu", lambda: nd.relu(a - 0.5))
    run("concat", lambda: nd.concat(a, b, dim=1))
    run("argmax", lambda: nd.argmax(a, axis=1))
    run("exp", lambda: nd.exp(a * 0.01))
    return ops_run


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--filename", default="/tmp/profile_ndarray.json")
    args = ap.parse_args()

    sweep(16)  # compile everything outside the trace
    mx.profiler.profiler_set_config(mode="all", filename=args.filename)
    mx.profiler.profiler_set_state("run")
    sweep(args.n)
    mx.profiler.profiler_set_state("stop")
    mx.profiler.dump_profile()

    with open(args.filename) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events if e["cat"] == "imperative"}
    print(f"{len(events)} events; imperative ops seen: {sorted(names)}")
    # every sweep family must have produced at least one event (the
    # arithmetic sugar lowers to registered ops, so check count instead
    # of exact names for those)
    assert len(names) >= 8, names
    assert "dot" in names and "transpose" in names, names
    print("PROF OK")


if __name__ == "__main__":
    main()
