#!/usr/bin/env python
"""Profile the image-record pipeline (parity:
example/profiler/profiler_imageiter.py — the reference runs
ImageRecordIter under the profiler so batch production shows up in the
trace).

Writes a small synthetic .rec, iterates it with the profiler running,
and asserts the data-io events are in the dump.
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, pack_img  # noqa: E402


def write_rec(prefix, n, side):
    rs = np.random.RandomState(0)
    w = MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = (rs.rand(side, side, 3) * 255).astype(np.uint8)
        w.write_idx(i, pack_img(IRHeader(0, float(i % 10), i, 0), img,
                                quality=90))
    w.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--filename", default="/tmp/profile_imageiter.json")
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "toy")
        write_rec(prefix, args.images, 32)
        it = mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
            data_shape=(3, 28, 28), batch_size=args.batch_size,
            rand_crop=True, shuffle=True, preprocess_threads=2)

        mx.profiler.profiler_set_config(mode="all",
                                        filename=args.filename)
        mx.profiler.profiler_set_state("run")
        batches = 0
        for batch in it:
            batch.data[0].wait_to_read()
            batches += 1
        mx.profiler.profiler_set_state("stop")
        mx.profiler.dump_profile()

    with open(args.filename) as f:
        events = json.load(f)["traceEvents"]
    io_events = [e for e in events if e["cat"] == "data-io"]
    total = sum(e["dur"] for e in io_events) / 1e3
    print(f"{batches} batches, {len(io_events)} data-io events, "
          f"{total:.1f} ms in the pipeline")
    assert len(io_events) == batches > 0, (len(io_events), batches)
    print("PROF OK")


if __name__ == "__main__":
    main()
