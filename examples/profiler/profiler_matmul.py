#!/usr/bin/env python
"""Profile imperative matmuls (parity: example/profiler/profiler_matmul.py
— the reference times a loop of nd.dot calls under the profiler and
dumps chrome-trace JSON)."""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--iterations", type=int, default=20)
    ap.add_argument("--filename", default="/tmp/profile_matmul.json")
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    a = nd.array(rs.rand(args.n, args.n).astype(np.float32))
    b = nd.array(rs.rand(args.n, args.n).astype(np.float32))
    nd.dot(a, b).wait_to_read()  # compile outside the trace

    mx.profiler.profiler_set_config(mode="all", filename=args.filename)
    mx.profiler.profiler_set_state("run")
    c = None
    for _ in range(args.iterations):
        c = nd.dot(a, b)
    c.wait_to_read()
    mx.profiler.profiler_set_state("stop")
    mx.profiler.dump_profile()

    with open(args.filename) as f:
        events = json.load(f)["traceEvents"]
    dots = [e for e in events if e["name"] == "dot"]
    total = sum(e["dur"] for e in dots) / 1e3
    print(f"{len(dots)} dot events, {total:.2f} ms total "
          f"-> open {args.filename} in chrome://tracing")
    assert len(dots) == args.iterations, len(dots)
    print("PROF OK")


if __name__ == "__main__":
    main()
