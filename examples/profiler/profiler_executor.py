#!/usr/bin/env python
"""Op-level profiling to chrome://tracing JSON (parity:
example/profiler/profiler_executor.py): run a bound executor with the
profiler on, dump profile.json, open in chrome://tracing or Perfetto."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="lenet")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--iterations", type=int, default=5)
    ap.add_argument("--filename", default="profile_executor.json")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = models.get_symbol(args.network, num_classes=10,
                            image_shape=(1, 28, 28))
    ex = net.simple_bind(ctx=None, data=(args.batch_size, 1, 28, 28))
    init = mx.init.Xavier()
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(name, arr)
    ex.arg_dict["data"][:] = np.random.uniform(
        size=(args.batch_size, 1, 28, 28)).astype(np.float32)

    mx.profiler.profiler_set_config(mode="all", filename=args.filename)
    mx.profiler.profiler_set_state("run")
    for _ in range(args.iterations):
        ex.forward(is_train=True)
        ex.backward()
    ex.outputs[0].wait_to_read()
    mx.profiler.profiler_set_state("stop")
    mx.profiler.dump_profile()
    logging.info("wrote %s — open in chrome://tracing", args.filename)
