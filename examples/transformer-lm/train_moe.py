#!/usr/bin/env python
"""Mixture-of-experts LM training — expert parallelism as a WORKLOAD.

Each transformer block's FFN is an expert-parallel MoE layer
(parallel/moe.py): tokens route to their top-k experts via gate logits,
ride two all_to_all collectives to the expert's device and back, and the
load-balancing loss keeps experts busy.  Attention/LayerNorm stay dense.
top_k=1 is Switch; --top-k 2 is the GShard/Mixtral configuration.

Run on the virtual mesh (no hardware needed):
  XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python train_moe.py [--top-k 2]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax

if os.environ.get("MXTPU_LC_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from common import (attention_block_params, causal_attention, glorot,  # noqa: E402
                    layer_norm as _ln, zeros)
from mxnet_tpu.parallel import moe as moe_mod  # noqa: E402
from mxnet_tpu.parallel.mesh import create_mesh  # noqa: E402


def init_params(rs, n_layers, D, n_experts, vocab):
    blocks = []
    for _ in range(n_layers):
        b = attention_block_params(rs, D)
        b.update({
            "ln2_g": jnp.ones(D), "ln2_b": zeros(D),
            # expert-parallel FFN (one expert slice per device)
            "gate_w": glorot(rs, D, n_experts),
            "w_in": glorot(rs, n_experts, D, 4 * D),
            "w_out": glorot(rs, n_experts, 4 * D, D)})
        blocks.append(b)
    return {"embed": glorot(rs, vocab, D), "head": glorot(rs, D, vocab),
            "blocks": blocks}


def forward(params, X, n_heads, mesh, top_k):
    B, T = X.shape
    h = params["embed"][X]
    D = h.shape[-1]
    aux_total = 0.0

    for p in params["blocks"]:
        x = _ln(h, p["ln1_g"], p["ln1_b"])
        att = causal_attention(x @ p["q_w"].T, x @ p["k_w"].T,
                               x @ p["v_w"].T, n_heads)
        h = h + att @ p["proj_w"].T + p["proj_b"]

        x = _ln(h, p["ln2_g"], p["ln2_b"])
        moe_params = {"gate_w": p["gate_w"], "w_in": p["w_in"],
                      "w_out": p["w_out"]}
        y, aux = moe_mod.moe_ffn(moe_params, x.reshape(B * T, D), mesh,
                                 "expert", top_k=top_k,
                                 activation=jax.nn.gelu)
        aux_total = aux_total + aux
        h = h + y.reshape(B, T, D)
    return h @ params["head"], aux_total


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-experts", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--aux-weight", type=float, default=0.01)
    args = ap.parse_args(argv)

    if args.d_model % args.heads:
        ap.error("--d-model must divide by --heads")
    if (args.batch * args.seq_len) % args.n_experts:
        ap.error("--batch * --seq-len must divide by --n-experts "
                 "(tokens shard over the expert mesh)")
    platform = os.environ.get("MXTPU_LC_PLATFORM", "cpu")
    mesh = create_mesh((args.n_experts,), ("expert",),
                       devices=jax.devices(platform)[:args.n_experts])
    rs = np.random.RandomState(0)
    params = init_params(rs, args.layers, args.d_model, args.n_experts,
                         args.vocab)
    X = jnp.asarray(rs.randint(0, args.vocab,
                               (args.batch, args.seq_len)).astype(np.int32))
    Y = (X * 5 + 3) % args.vocab

    def loss_fn(p):
        logits, aux = forward(p, X, args.heads, mesh, args.top_k)
        lp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(lp, Y[..., None], axis=-1).mean()
        return nll + args.aux_weight * aux, (nll, aux)

    step = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
    first = None
    for i in range(args.steps):
        (loss, (nll, aux)), grads = step(params)
        params = jax.tree_util.tree_map(lambda w, d: w - args.lr * d,
                                        params, grads)
        if first is None:
            first = float(nll)
        if i % 5 == 0 or i == args.steps - 1:
            print("step %3d  nll %.4f  balance_aux %.4f  (top-%d of %d "
                  "experts)" % (i, float(nll), float(aux), args.top_k,
                                args.n_experts))
    if args.steps > 1:
        assert float(nll) < first, (first, float(nll))
    print("converged: nll %.3f -> %.3f with %d-expert MoE FFNs"
          % (first, float(nll), args.n_experts))


if __name__ == "__main__":
    main()
