#!/usr/bin/env python
"""Transformer LM trained with 1F1B pipeline parallelism — the WHOLE
model (embedding, transformer blocks with their 4x-wide FFNs, final
norm + LM head) lives inside the pipeline as per-stage parameter trees.

Beyond-reference: the reference approximates pipelining with ctx_group
placement + engine overlap on an equal-width LSTM
(docs/how_to/model_parallel_lstm.md); this is a scheduled-microbatch
1F1B pipeline in one XLA program (parallel/pipeline.py:
make_pipeline_train_step), composable with data parallelism via --dp.

Memory: activation stash is O(stages), flat in the number of
microbatches — `python tools/pipeline_memory.py` prints the measured
GPipe-vs-1F1B table.

Run (8 virtual CPU devices via tests/conftest-style env):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/transformer-lm/train_pp.py            # 4-stage pp
  ... train_pp.py --dp 2                                   # dp x pp
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax

if os.environ.get("MXTPU_LC_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mxnet_tpu.parallel import pipeline as pp  # noqa: E402
from mxnet_tpu.parallel.mesh import create_mesh  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import glorot, layer_norm, token_nll, zeros  # noqa: E402


def block(p, h, n_heads):
    """Pre-LN attention + 4x GELU FFN block on [mb, T, D]."""
    B, T, D = h.shape
    dh = D // n_heads
    x = layer_norm(h, p["ln1_g"], p["ln1_b"])
    q, k, v = x @ p["q_w"], x @ p["k_w"], x @ p["v_w"]
    sh = lambda a: a.reshape(B, T, n_heads, dh).transpose(0, 2, 1, 3)
    s = (sh(q) @ sh(k).transpose(0, 1, 3, 2)) / np.sqrt(dh)
    s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e9)
    att = (jax.nn.softmax(s, -1) @ sh(v)).transpose(0, 2, 1, 3)
    h = h + att.reshape(B, T, D) @ p["proj_w"] + p["proj_b"]
    x = layer_norm(h, p["ln2_g"], p["ln2_b"])
    f = jax.nn.gelu(x @ p["fi_w"] + p["fi_b"])
    return h + f @ p["fo_w"] + p["fo_b"]


def block_params(rs, D):
    return {"ln1_g": jnp.ones(D), "ln1_b": zeros(D),
            "q_w": glorot(rs, D, D), "k_w": glorot(rs, D, D),
            "v_w": glorot(rs, D, D),
            "proj_w": glorot(rs, D, D), "proj_b": zeros(D),
            "ln2_g": jnp.ones(D), "ln2_b": zeros(D),
            "fi_w": glorot(rs, D, 4 * D), "fi_b": zeros(4 * D),
            "fo_w": glorot(rs, 4 * D, D), "fo_b": zeros(D)}


def make_stages(rs, n_stages, blocks_per_stage, D, vocab, n_heads):
    """Per-stage trees: embed on stage 0, final-norm + head on the last,
    `blocks_per_stage` blocks everywhere."""

    def trunk(bp, h):
        return jax.lax.scan(lambda h, b: (block(b, h, n_heads), None),
                            h, bp)[0]

    fns, trees = [], []
    for s in range(n_stages):
        one = [block_params(rs, D) for _ in range(blocks_per_stage)]
        tree = {"blocks": {k: jnp.stack([b[k] for b in one])
                           for k in one[0]}}
        if s == 0:
            tree["embed"] = glorot(rs, vocab, D, scale=0.1)
            fns.append(lambda p, ids: trunk(
                p["blocks"], p["embed"][ids.astype(jnp.int32)]))
        elif s == n_stages - 1:
            tree["lnf_g"] = jnp.ones(D)
            tree["lnf_b"] = zeros(D)
            tree["head"] = glorot(rs, D, vocab, scale=0.1)
            fns.append(lambda p, h: layer_norm(
                trunk(p["blocks"], h), p["lnf_g"], p["lnf_b"]) @ p["head"])
        else:
            fns.append(lambda p, h: trunk(p["blocks"], h))
        trees.append(tree)
    return fns, trees


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--dp", type=int, default=1,
                    help="data-parallel factor (mesh = dp x stages)")
    ap.add_argument("--blocks-per-stage", type=int, default=1)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--micro", type=int, default=4,
                    help="microbatches per step")
    ap.add_argument("--micro-batch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.3)
    args = ap.parse_args(argv)

    platform = os.environ.get("MXTPU_LC_PLATFORM", "cpu")
    n_dev = args.dp * args.stages
    if len(jax.devices(platform)) < n_dev:
        ap.error(f"need {n_dev} devices (set "
                 "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    if args.dp > 1:
        mesh = create_mesh((args.dp, args.stages), ("data", "pipe"),
                           devices=jax.devices(platform)[:n_dev])
        data_axis = "data"
    else:
        mesh = create_mesh((args.stages,), ("pipe",),
                           devices=jax.devices(platform)[:args.stages])
        data_axis = None

    rs = np.random.RandomState(0)
    fns, trees = make_stages(rs, args.stages, args.blocks_per_stage,
                             args.d_model, args.vocab, args.heads)
    stacked, meta = pp.union_stack(trees, mesh)
    step = pp.make_pipeline_train_step(fns, token_nll, meta, mesh,
                                       data_axis=data_axis)

    # affine-map toy language: y = 5x + 3 (mod vocab) — learnable by the
    # head alone, so convergence proves grads reach every stage
    M, mb = args.micro, args.micro_batch
    X = rs.randint(0, args.vocab, (M, mb, args.seq_len))
    Y = (X * 5 + 3) % args.vocab
    xs = jnp.asarray(X, jnp.float32)
    ys = jnp.asarray(Y, jnp.float32)

    first = None
    for i in range(args.steps):
        loss, grads = step(stacked, xs, ys)
        # grads are pipe-sharded like the params: the SGD update runs
        # sharded too (no gather)
        stacked = jax.tree_util.tree_map(
            lambda w, g: w - args.lr * g, stacked, grads)
        if first is None:
            first = float(loss)
        if i % 5 == 0 or i == args.steps - 1:
            print("step %3d  nll %.4f   (%d stages%s, %d micro x %d)"
                  % (i, float(loss), args.stages,
                     f" x dp{args.dp}" if args.dp > 1 else "", M, mb))
    assert float(loss) < first, (first, float(loss))
    print("converged: nll %.3f -> %.3f through the 1F1B pipeline"
          % (first, float(loss)))


if __name__ == "__main__":
    main()
