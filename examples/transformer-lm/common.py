"""Shared functional transformer pieces for the parallel-LM examples
(train_long_context.py, train_moe.py): LayerNorm, dense causal
attention, and weight-init helpers — one copy so numerics fixes reach
every workload."""
import jax
import jax.numpy as jnp
import numpy as np


def layer_norm(x, g, b, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * g + b


def causal_attention(q, k, v, n_heads):
    """Causal attention on [B, T, D] projections via the package's
    attention dispatcher (flash kernels where eligible, lax fallback —
    the same path train_long_context.py's dense oracle uses)."""
    from mxnet_tpu.parallel.ring_attention import attention

    B, T, D = q.shape
    dh = D // n_heads
    sh = lambda a: a.reshape(B, T, n_heads, dh).transpose(0, 2, 1, 3)
    out = attention(sh(q), sh(k), sh(v), causal=True)
    return out.transpose(0, 2, 1, 3).reshape(B, T, D)


def glorot(rs, *shape, scale=0.08):
    return jnp.asarray(rs.normal(0, scale, shape).astype(np.float32))


def zeros(*shape):
    return jnp.zeros(shape, jnp.float32)


from mxnet_tpu.ops.loss import token_nll  # noqa: F401 — shared LM loss


def attention_block_params(rs, D, scale=0.08):
    """ln + q/k/v + out-projection parameter set for one block."""
    return {"ln1_g": jnp.ones(D), "ln1_b": zeros(D),
            "q_w": glorot(rs, D, D, scale=scale),
            "k_w": glorot(rs, D, D, scale=scale),
            "v_w": glorot(rs, D, D, scale=scale),
            "proj_w": glorot(rs, D, D, scale=scale), "proj_b": zeros(D)}
