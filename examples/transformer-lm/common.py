"""Shared functional transformer pieces for the parallel-LM examples
(train_long_context.py, train_moe.py): LayerNorm, dense causal
attention, and weight-init helpers — one copy so numerics fixes reach
every workload."""
import jax
import jax.numpy as jnp
import numpy as np


def layer_norm(x, g, b, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * g + b


def causal_attention(q, k, v, n_heads):
    """Dense causal attention on [B, T, D] projections."""
    B, T, D = q.shape
    dh = D // n_heads
    sh = lambda a: a.reshape(B, T, n_heads, dh).transpose(0, 2, 1, 3)
    qh, kh, vh = sh(q), sh(k), sh(v)
    scores = (qh @ kh.transpose(0, 1, 3, 2)) / np.sqrt(dh)
    scores = jnp.where(jnp.tril(jnp.ones((T, T), bool)), scores, -1e9)
    out = jax.nn.softmax(scores, -1) @ vh
    return out.transpose(0, 2, 1, 3).reshape(B, T, D)


def glorot(rs, *shape, scale=0.08):
    return jnp.asarray(rs.normal(0, scale, shape).astype(np.float32))


def zeros(*shape):
    return jnp.zeros(shape, jnp.float32)


def attention_block_params(rs, D, scale=0.08):
    """ln + q/k/v + out-projection parameter set for one block."""
    return {"ln1_g": jnp.ones(D), "ln1_b": zeros(D),
            "q_w": glorot(rs, D, D, scale=scale),
            "k_w": glorot(rs, D, D, scale=scale),
            "v_w": glorot(rs, D, D, scale=scale),
            "proj_w": glorot(rs, D, D, scale=scale), "proj_b": zeros(D)}
