#!/usr/bin/env python
"""Bucketed variable-length transformer LM — the reference's bucketing
machinery (docs/how_to/bucketing.md, BucketSentenceIter) driving the
modern model family.

Sentences bin into per-length buckets; BucketingModule generates one
symbol per bucket from sym_gen, shares parameters by name (the
positional table is sized to the LONGEST bucket and sliced per bucket),
and with compile_buckets=True pads every bucket to the default so the
whole run costs ONE XLA compile.  ignore_label masks the padding out of
loss and gradient, so the padded compile is numerically exact.

Run:  MXTPU_PLATFORM=cpu python train_bucketing.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.models.transformer import transformer_lm  # noqa: E402


def synthetic_corpus(n, vocab, seed=0):
    """Variable-length 'sentences' with a learnable next-token rule."""
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        length = int(rs.choice([6, 10, 14, 18]) + rs.randint(0, 3))
        toks = [int(rs.randint(2, vocab))]
        for _ in range(length - 1):
            toks.append((toks[-1] * 3 + 1) % (vocab - 2) + 2)
        out.append(toks)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-heads", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--no-compile-sharing", action="store_true")
    args = ap.parse_args(argv)

    buckets = [8, 12, 16, 20]
    sentences = synthetic_corpus(256, args.vocab)
    train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                      buckets=buckets, invalid_label=0)
    max_len = max(buckets)

    def sym_gen(seq_len):
        symbol = transformer_lm(num_layers=args.num_layers,
                                num_heads=args.num_heads,
                                d_model=args.d_model, seq_len=seq_len,
                                vocab_size=args.vocab, ignore_label=0,
                                max_len=max_len)
        return symbol, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=train.default_bucket_key,
        compile_buckets=not args.no_compile_sharing)
    metric = mx.metric.Perplexity(ignore_label=0)
    mod.fit(train, eval_metric=metric,
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))
    name, ppl = metric.get_global()
    print("final train %s: %.2f" % (name, ppl))
    assert ppl < float(args.vocab), "no learning happened"
    print("bucketed transformer OK (buckets %s, one pos_embed of %d)"
          % (buckets, max_len))


if __name__ == "__main__":
    main()
