#!/usr/bin/env python
"""Decoder-only Transformer LM with causal FlashAttention
(beyond-reference: the reference's sequence modeling tops out at bucketed
LSTMs — this is the long-context model family the TPU stack is built
for).

Trains next-character prediction on a text file (or a synthetic grammar)
through the FusedTrainer fast path, then samples.  For sequences beyond
one chip, the same attention runs ring-sharded over a mesh
(docs/how_to/multi_devices.md, parallel/ring_attention.py)."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402
from mxnet_tpu.trainer import FusedTrainer  # noqa: E402


def synthetic_text(n=40000, seed=0):
    rs = np.random.RandomState(seed)
    words = ["abc", "acba", "bca", "cab"]
    out = []
    while sum(len(w) + 1 for w in out) < n:
        out.append(words[rs.randint(len(words))])
    return " ".join(out)


def main():
    ap = argparse.ArgumentParser(description="transformer char-LM")
    ap.add_argument("--text", type=str, default=None)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-heads", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--sample-len", type=int, default=120)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    text = open(args.text).read() if args.text else synthetic_text()
    chars = sorted(set(text))
    vocab = {c: i for i, c in enumerate(chars)}
    inv = {i: c for c, i in vocab.items()}
    ids = np.array([vocab[c] for c in text], dtype=np.float32)
    logging.info("corpus %d chars, vocab %d", len(ids), len(vocab))

    net = models.transformer.transformer_lm(
        num_layers=args.num_layers, num_heads=args.num_heads,
        d_model=args.d_model, seq_len=args.seq_len, vocab_size=len(vocab))
    tr = FusedTrainer(net, optimizer="adam",
                      optimizer_params={"lr": args.lr})
    tr.init(data=(args.batch_size, args.seq_len),
            softmax_label=(args.batch_size, args.seq_len))

    rs = np.random.RandomState(0)
    n_win = len(ids) - args.seq_len - 1
    for step in range(args.steps):
        starts = rs.randint(0, n_win, args.batch_size)
        toks = np.stack([ids[s:s + args.seq_len] for s in starts])
        labs = np.stack([ids[s + 1:s + 1 + args.seq_len] for s in starts])
        out = tr.step(data=toks, softmax_label=labs)
        if step % 50 == 0 or step == args.steps - 1:
            pred = np.asarray(out[0]).reshape(args.batch_size,
                                              args.seq_len, -1).argmax(-1)
            logging.info("step %d: next-char acc %.3f", step,
                         float((pred == labs).mean()))

    # sampling through the KV-cache decoder (models/decode.py): prefill
    # the prompt once, then ONE jitted O(seq_len) step per token — the
    # old sliding-window eval re-ran the full O(T^2) forward per token
    import time

    from mxnet_tpu.models.decode import KVDecoder

    dec = KVDecoder(tr.params, num_layers=args.num_layers,
                    num_heads=args.num_heads, max_len=args.seq_len)
    n_prompt = max(1, min(8, args.seq_len // 2))
    n_sample = min(args.sample_len, args.seq_len - n_prompt)
    if n_sample < args.sample_len:
        print(f"note: sampling {n_sample} tokens (seq_len {args.seq_len} "
              f"bounds prompt+sample; train with a longer --seq-len for "
              "longer samples)")
    prompt = ids[:n_prompt].astype(int)[None, :]
    tic = time.perf_counter()
    sampled = dec.generate(prompt, n_sample, temperature=1.0, rng=rs)
    dt = time.perf_counter() - tic
    print("sample:", "".join(inv[int(t)] for t in sampled[0]))
    print(f"decode: {n_sample / dt:.1f} tok/s (KV cache, prefill "
          f"{n_prompt} + {n_sample} steps)")


if __name__ == "__main__":
    main()
