#!/usr/bin/env python
"""Long-context LM training with ring attention — sequence parallelism
as a WORKLOAD, not just an op.

The reference's long-sequence story tops out at bucketed LSTMs
(SURVEY.md §5.7); here the full training step runs with activations
sharded over a 'seq' mesh axis: every matmul/LayerNorm/FFN operates on
its local sequence shard, and attention is exact sequence-parallel
attention (parallel/ring_attention.py).  --impl ring (default): K/V
shards rotate via ppermute while each device streams its online-softmax
accumulation, so the (T, T) score matrix never materializes and max
context scales linearly with the number of devices.  --impl ulysses:
head/sequence all-to-alls — each device attends over the FULL sequence
for H/n heads (scores materialize per device; cheaper collectives,
requires heads % n == 0).

Run on the virtual mesh (no hardware needed):
  XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python train_long_context.py [--self-test]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax

if os.environ.get("MXTPU_LC_PLATFORM", "cpu") == "cpu":
    # virtual-mesh mode (default: runs anywhere); set MXTPU_LC_PLATFORM=tpu
    # on a real pod to shard the same workload over ICI
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from common import layer_norm as _ln  # noqa: E402
from mxnet_tpu.parallel.mesh import create_mesh  # noqa: E402
from mxnet_tpu.parallel.ring_attention import (  # noqa: E402
    ring_attention, ulysses_attention)


def init_params(rs, n_layers, D, H, vocab):
    from common import attention_block_params, glorot, zeros

    blocks = []
    for _ in range(n_layers):
        b = attention_block_params(rs, D, scale=0.06)
        b.update({"ln2_g": jnp.ones(D), "ln2_b": zeros(D),
                  "fi_w": glorot(rs, 4 * D, D, scale=0.06),
                  "fi_b": zeros(4 * D),
                  "fo_w": glorot(rs, D, 4 * D, scale=0.06),
                  "fo_b": zeros(D)})
        blocks.append(b)
    return {"embed": glorot(rs, vocab, D, scale=0.06),
            "head": glorot(rs, D, vocab, scale=0.06),
            "blocks": jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *blocks)}


def forward(params, X, n_heads, mesh=None, impl="ring"):
    """[B, T] ids -> [B, T, vocab] logits.  With a mesh, attention runs
    sequence-sharded over 'seq' (impl: ring | ulysses); everything else
    is local to the shard."""
    B, T = X.shape
    h = params["embed"][X]
    D = h.shape[-1]
    dh = D // n_heads

    def attend(q, k, v):
        sh = lambda a: a.reshape(B, T, n_heads, dh).transpose(0, 2, 1, 3)
        q, k, v = sh(q), sh(k), sh(v)
        if mesh is not None:
            sp = ring_attention if impl == "ring" else ulysses_attention
            o = sp(q, k, v, mesh, "seq", causal=True)
        else:
            from mxnet_tpu.parallel.ring_attention import attention

            o = attention(q, k, v, causal=True)
        return o.transpose(0, 2, 1, 3).reshape(B, T, D)

    def block(h, p):
        x = _ln(h, p["ln1_g"], p["ln1_b"])
        att = attend(x @ p["q_w"].T, x @ p["k_w"].T, x @ p["v_w"].T)
        h = h + att @ p["proj_w"].T + p["proj_b"]
        x = _ln(h, p["ln2_g"], p["ln2_b"])
        f = jax.nn.gelu(x @ p["fi_w"].T + p["fi_b"])
        return h + f @ p["fo_w"].T + p["fo_b"], None

    h, _ = jax.lax.scan(block, h, params["blocks"])
    return h @ params["head"]


def make_loss(n_heads, mesh=None, impl="ring"):
    def loss_fn(params, X, Y):
        logits = forward(params, X, n_heads, mesh, impl)
        lp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(lp, Y[..., None], axis=-1).mean()

    return loss_fn


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-devices", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=512,
                    help="context length, sharded over the seq mesh")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--impl", choices=("ring", "ulysses"), default="ring",
                    help="sequence-parallel attention strategy")
    ap.add_argument("--self-test", action="store_true",
                    help="check sharded grads == dense oracle at T=64")
    args = ap.parse_args(argv)

    if args.seq_len % args.n_devices:
        ap.error("--seq-len must divide by --n-devices")
    if args.self_test and 64 % args.n_devices:
        ap.error("--self-test shards T=64: --n-devices must divide 64")
    if args.impl == "ulysses" and args.heads % args.n_devices:
        ap.error("--impl ulysses needs --heads divisible by --n-devices")
    if args.d_model % args.heads:
        ap.error("--d-model must divide by --heads")
    platform = os.environ.get("MXTPU_LC_PLATFORM", "cpu")
    mesh = create_mesh((args.n_devices,), ("seq",),
                       devices=jax.devices(platform)[:args.n_devices])
    rs = np.random.RandomState(0)
    params = init_params(rs, args.layers, args.d_model, args.heads,
                         args.vocab)
    seq_sharded = NamedSharding(mesh, P(None, "seq"))

    def batch(T):
        X = rs.randint(0, args.vocab, (args.batch, T)).astype(np.int32)
        Y = ((X * 5 + 3) % args.vocab).astype(np.int32)
        return (jax.device_put(X, seq_sharded),
                jax.device_put(Y, seq_sharded))

    if args.self_test:
        Xs, Ys = batch(64)
        l_ring, g_ring = jax.jit(jax.value_and_grad(
            make_loss(args.heads, mesh, args.impl)))(params, Xs, Ys)
        l_ref, g_ref = jax.jit(jax.value_and_grad(
            make_loss(args.heads, None)))(params, np.asarray(Xs),
                                          np.asarray(Ys))
        np.testing.assert_allclose(float(l_ring), float(l_ref), rtol=1e-5)
        ref_flat = dict(jax.tree_util.tree_leaves_with_path(g_ref))
        for path, leaf in jax.tree_util.tree_leaves_with_path(g_ring):
            np.testing.assert_allclose(np.asarray(leaf),
                                       np.asarray(ref_flat[path]),
                                       rtol=2e-4, atol=1e-5,
                                       err_msg=str(path))
        print("self-test: %s-sharded grads == dense oracle" % args.impl)

    step = jax.jit(jax.value_and_grad(make_loss(args.heads, mesh,
                                                args.impl)))
    X, Y = batch(args.seq_len)
    first = None
    for i in range(args.steps):
        loss, grads = step(params, X, Y)
        params = jax.tree_util.tree_map(lambda w, d: w - args.lr * d,
                                        params, grads)
        if first is None:
            first = float(loss)
        if i % 5 == 0 or i == args.steps - 1:
            shard_note = ("per-device KV: T/%d = %d" % (
                args.n_devices, args.seq_len // args.n_devices)
                if args.impl == "ring" else
                "per-device heads: H/%d = %d, full-T KV" % (
                    args.n_devices, args.heads // args.n_devices))
            print("step %3d  T=%d  loss %.4f  (%s)"
                  % (i, args.seq_len, float(loss), shard_note))
    if args.steps > 1:
        assert float(loss) < first, (first, float(loss))
    print("converged: %.3f -> %.3f at context %d over %d devices"
          % (first, float(loss), args.seq_len, args.n_devices))


if __name__ == "__main__":
    main()
