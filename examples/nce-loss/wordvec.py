#!/usr/bin/env python
"""Skip-gram word vectors with NCE (parity: example/nce-loss/wordvec.py
— word2vec-style embeddings trained with sampled negatives instead of
the full-vocabulary softmax).

Synthetic corpus with known topical structure: the vocabulary is split
into C topics and every sentence stays inside one topic, so skip-gram
co-occurrence is purely intra-topic.  After training, embeddings must
recover that structure: mean intra-topic cosine similarity has to beat
inter-topic by a clear margin.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

import nce  # noqa: E402

VOCAB, EMBED, K, TOPICS = 240, 24, 6, 8


def make_pairs(rs, n_pairs):
    """Skip-gram (center, context) pairs, both from the same topic."""
    words_per = VOCAB // TOPICS
    topic = rs.randint(0, TOPICS, n_pairs)
    center = topic * words_per + rs.randint(0, words_per, n_pairs)
    context = topic * words_per + rs.randint(0, words_per, n_pairs)
    return center.astype(np.float32), context.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=500)
    ap.add_argument("--min-margin", type=float, default=0.2)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    mx.random.seed(0)

    data = sym.Variable("data")
    cand = sym.Variable("cand")
    nce_label = sym.Variable("nce_label")
    hidden = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                           name="in_embed")
    net = nce.nce_output(hidden, cand, nce_label, args.batch, K, VOCAB,
                         EMBED)
    ex = net.simple_bind(ctx=mx.context.default_accelerator_context(),
                         grad_req="write", data=(args.batch,),
                         cand=(args.batch, K + 1),
                         nce_label=(args.batch, K + 1))
    params, update = nce.init_and_updater(ex, lr=0.02)
    labels = nce.nce_labels(args.batch, K)
    sampler = nce.UnigramSampler(np.ones(VOCAB), seed=1)  # uniform corpus

    for step in range(args.steps):
        center, context = make_pairs(rs, args.batch)
        negs = sampler.draw((args.batch, K))
        candv = np.concatenate([context[:, None], negs], axis=1)
        ex.forward(is_train=True, data=center, cand=candv,
                   nce_label=labels)
        ex.backward()
        update()

    w = ex.arg_dict["in_embed_weight"].asnumpy()
    w = w / np.maximum(np.linalg.norm(w, axis=1, keepdims=True), 1e-8)
    sim = w @ w.T
    words_per = VOCAB // TOPICS
    topic_of = np.arange(VOCAB) // words_per
    same = topic_of[:, None] == topic_of[None, :]
    np.fill_diagonal(same, False)
    intra = float(sim[same].mean())
    inter = float(sim[~same & ~np.eye(VOCAB, dtype=bool)].mean())
    margin = intra - inter
    print(f"intra-topic cos {intra:.3f}  inter-topic {inter:.3f}  "
          f"margin {margin:.3f}")
    assert margin >= args.min_margin, (intra, inter)
    print("WORDVEC OK margin %.3f" % margin)


if __name__ == "__main__":
    main()
