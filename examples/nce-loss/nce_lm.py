#!/usr/bin/env python
"""NCE loss for large-vocabulary softmax (parity: example/nce-loss/).

The reference trains word models where a full softmax is too wide:
noise-contrastive estimation scores the true class plus k sampled noise
classes with a shared embedding + bias, using LogisticRegressionOutput
over the k+1 logits (nce.py NceOutput).  Same construction here: the
loader samples negatives by unigram frequency; the graph embeds
(label ∪ negatives), dots with the hidden state, and trains binary
targets [1, 0...0].
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

VOCAB, EMBED, K = 500, 32, 8  # k = negatives per positive


def build(batch):
    data = sym.Variable("data")            # (N,) context word id
    cand = sym.Variable("cand")            # (N, K+1) [target, negatives]
    nce_label = sym.Variable("nce_label")  # (N, K+1) [1, 0, ...]
    in_embed = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                             name="in_embed")         # (N, EMBED)
    out_embed = sym.Embedding(cand, input_dim=VOCAB, output_dim=EMBED,
                              name="out_embed")       # (N, K+1, EMBED)
    out_bias = sym.Embedding(cand, input_dim=VOCAB, output_dim=1,
                             name="out_bias")         # (N, K+1, 1)
    h = sym.Reshape(in_embed, shape=(batch, 1, EMBED))
    logits = sym.batch_dot(out_embed, h, transpose_b=True)  # (N, K+1, 1)
    logits = sym.Reshape(logits + out_bias, shape=(batch, K + 1))
    return sym.LogisticRegressionOutput(logits, nce_label, name="nce")


def synth_corpus(rs, n):
    """Skip-gram pairs from a Zipf corpus with strong co-occurrence."""
    ctx = rs.zipf(1.5, n).clip(1, VOCAB - 1)
    tgt = (ctx * 7 + 1) % VOCAB  # deterministic association to learn
    return ctx.astype(np.float32), tgt.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=150)
    args = ap.parse_args()
    rs = np.random.RandomState(0)

    net = build(args.batch)
    ex = net.simple_bind(ctx=mx.context.default_accelerator_context(),
                         grad_req="write", data=(args.batch,),
                         cand=(args.batch, K + 1),
                         nce_label=(args.batch, K + 1))
    init = mx.init.Xavier()
    params = {}
    for name, arr in ex.arg_dict.items():
        if name.endswith(("weight",)):
            init(name, arr)
            params[name] = arr
    opt = mx.optimizer.create("adam", learning_rate=0.01)
    updater = mx.optimizer.get_updater(opt)
    labels = np.zeros((args.batch, K + 1), np.float32)
    labels[:, 0] = 1.0

    first = last = None
    for step in range(args.steps):
        ctx, tgt = synth_corpus(rs, args.batch)
        negs = rs.randint(1, VOCAB, (args.batch, K)).astype(np.float32)
        cand = np.concatenate([tgt[:, None], negs], axis=1)
        ex.forward(is_train=True, data=ctx, cand=cand, nce_label=labels)
        ex.backward()
        for i, (name, arr) in enumerate(sorted(params.items())):
            updater(i, ex.grad_dict[name], arr)
        p = ex.outputs[0].asnumpy()
        loss = -(labels * np.log(np.maximum(p, 1e-8))
                 + (1 - labels) * np.log(np.maximum(1 - p, 1e-8))).mean()
        if step == 0:
            first = loss
        last = loss
        if step % 50 == 0:
            print(f"step {step}: nce loss {loss:.4f}")
    print(f"first {first:.4f} last {last:.4f}")
    assert last < first * 0.7
    print("TRAIN OK")


if __name__ == "__main__":
    main()
