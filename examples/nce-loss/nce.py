"""Shared NCE building blocks (parity: example/nce-loss/nce.py — the
reference's NceOutput construction reused by its toy/wordvec/LSTM
scripts).

Noise-contrastive estimation trains a large-vocabulary output layer by
scoring the true class against k sampled noise classes: the graph
embeds (target ∪ negatives) through the OUTPUT embedding + bias, dots
with the hidden vector, and trains binary targets [1, 0, ..., 0] with
LogisticRegressionOutput — O(k) per example instead of O(V).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

from mxnet_tpu import sym  # noqa: E402


def nce_output(hidden, cand, nce_label, batch, k, vocab, embed,
               prefix="out"):
    """Score `hidden` (N, embed) against k+1 candidate classes.

    cand (N, k+1) carries [target, negatives]; returns the sigmoid
    probabilities symbol (N, k+1) trained against nce_label."""
    out_embed = sym.Embedding(cand, input_dim=vocab, output_dim=embed,
                              name=f"{prefix}_embed")   # (N, k+1, E)
    out_bias = sym.Embedding(cand, input_dim=vocab, output_dim=1,
                             name=f"{prefix}_bias")     # (N, k+1, 1)
    h = sym.Reshape(hidden, shape=(batch, 1, embed))
    logits = sym.batch_dot(out_embed, h, transpose_b=True)
    logits = sym.Reshape(logits + out_bias, shape=(batch, k + 1))
    return sym.LogisticRegressionOutput(logits, nce_label,
                                        name=f"{prefix}_nce")


def nce_labels(batch, k):
    """The fixed binary targets: column 0 (the true class) is 1."""
    labels = np.zeros((batch, k + 1), np.float32)
    labels[:, 0] = 1.0
    return labels


class UnigramSampler:
    """Negative sampler over the word2vec-standard unigram^0.75
    distribution (parity: the reference's frequency-weighted negative
    table in nce.py's data layers)."""

    def __init__(self, counts, power=0.75, seed=0):
        p = np.asarray(counts, np.float64) ** power
        self._p = p / p.sum()
        self._rs = np.random.RandomState(seed)
        self._n = len(counts)

    def draw(self, shape):
        return self._rs.choice(self._n, size=shape,
                               p=self._p).astype(np.float32)


def init_and_updater(ex, lr, seed=None):
    """Shared trainer plumbing for the example scripts: Xavier-init all
    *_weight args of a bound executor and return (params, update_fn)
    where update_fn() applies the adam step over them in sorted order."""
    import mxnet_tpu as mx

    init = mx.init.Xavier()
    params = {}
    for name, arr in ex.arg_dict.items():
        if name.endswith("weight"):
            init(name, arr)
            params[name] = arr
    opt = mx.optimizer.create("adam", learning_rate=lr)
    updater = mx.optimizer.get_updater(opt)
    ordered = sorted(params.items())

    def update():
        for i, (name, arr) in enumerate(ordered):
            updater(i, ex.grad_dict[name], arr)

    return params, update


def full_vocab_accuracy(ctx_ids, tgt_ids, in_w, out_w, out_b):
    """Eval an NCE-trained model the honest way: score ALL classes with
    the learned output embedding and take the argmax."""
    h = in_w[ctx_ids.astype(int)]                      # (N, E)
    logits = h @ out_w.T + out_b[:, 0][None, :]        # (N, V)
    return float((logits.argmax(1) == tgt_ids.astype(int)).mean())
