#!/usr/bin/env python
"""NCE on the toy association task (parity: example/nce-loss/toy_nce.py
— identical task to toy_softmax.py, but the V-way softmax is replaced
by noise-contrastive estimation over k=8 unigram^0.75-sampled
negatives, O(k) instead of O(V) per example).

Self-asserting the approximation claim: evaluated by FULL-vocabulary
scoring (nce.full_vocab_accuracy), the NCE-trained embeddings must
reach accuracy comparable to the exact-softmax twin.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

import nce  # noqa: E402
from toy_softmax import VOCAB, EMBED, synth_corpus  # noqa: E402

K = 8  # negatives per positive


def build(batch):
    data = sym.Variable("data")            # (N,) context word id
    cand = sym.Variable("cand")            # (N, K+1) [target, negatives]
    nce_label = sym.Variable("nce_label")  # (N, K+1) [1, 0, ...]
    hidden = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                           name="in_embed")           # (N, EMBED)
    return nce.nce_output(hidden, cand, nce_label, batch, K, VOCAB,
                          EMBED)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--min-acc", type=float, default=0.85)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    mx.random.seed(0)

    net = build(args.batch)
    ex = net.simple_bind(ctx=mx.context.default_accelerator_context(),
                         grad_req="write", data=(args.batch,),
                         cand=(args.batch, K + 1),
                         nce_label=(args.batch, K + 1))
    params, update = nce.init_and_updater(ex, lr=0.01)
    labels = nce.nce_labels(args.batch, K)

    # negatives by unigram^0.75 over the Zipf corpus frequencies
    big_ctx, _ = synth_corpus(rs, 20000)
    counts = np.bincount(big_ctx.astype(int), minlength=VOCAB) + 1
    sampler = nce.UnigramSampler(counts, seed=1)

    first = last = None
    for step in range(args.steps):
        ctx, tgt = synth_corpus(rs, args.batch)
        negs = sampler.draw((args.batch, K))
        cand = np.concatenate([tgt[:, None], negs], axis=1)
        ex.forward(is_train=True, data=ctx, cand=cand, nce_label=labels)
        ex.backward()
        update()
        p = ex.outputs[0].asnumpy()
        loss = -(labels * np.log(np.maximum(p, 1e-8))
                 + (1 - labels) * np.log(np.maximum(1 - p, 1e-8))).mean()
        first = loss if first is None else first
        last = loss
        if step % 100 == 0:
            print(f"step {step}: nce loss {loss:.4f}")
    assert last < first * 0.7, (first, last)

    # honest eval: score the FULL vocabulary with the learned tables
    ctx, tgt = synth_corpus(rs, 512)
    acc = nce.full_vocab_accuracy(
        ctx, tgt,
        ex.arg_dict["in_embed_weight"].asnumpy(),
        ex.arg_dict["out_embed_weight"].asnumpy(),
        ex.arg_dict["out_bias_weight"].asnumpy())
    assert acc >= args.min_acc, acc
    print("NCE OK acc %.3f (k=%d vs V=%d)" % (acc, K, VOCAB))


if __name__ == "__main__":
    main()
