#!/usr/bin/env python
"""Full-softmax baseline for the toy association task (parity:
example/nce-loss/toy_softmax.py — the reference pairs every NCE script
with its exact-softmax twin so the approximation quality is visible).

The task: learn tgt = (ctx * 7 + 1) mod V from (ctx, tgt) pairs drawn
with Zipf-distributed contexts.  toy_nce.py trains the same task with
k=8 sampled negatives instead of the V-way softmax; run both and
compare the printed accuracies.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

VOCAB, EMBED = 500, 32


def synth_corpus(rs, n):
    """Skip-gram pairs from a Zipf corpus with strong co-occurrence."""
    ctx = rs.zipf(1.5, n).clip(1, VOCAB - 1)
    tgt = (ctx * 7 + 1) % VOCAB  # deterministic association to learn
    return ctx.astype(np.float32), tgt.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--min-acc", type=float, default=0.9)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    mx.random.seed(0)

    data = sym.Variable("data")
    net = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                        name="in_embed")
    net = sym.FullyConnected(net, num_hidden=VOCAB, name="out")
    net = sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (args.batch,))],
             label_shapes=[("softmax_label", (args.batch,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.Accuracy()
    for step in range(args.steps):
        ctx, tgt = synth_corpus(rs, args.batch)
        batch = mx.io.DataBatch([mx.nd.array(ctx)], [mx.nd.array(tgt)])
        mod.forward(batch, is_train=True)
        mod.update_metric(metric, batch.label)
        mod.backward()
        mod.update()
        if step % 100 == 0:
            print(f"step {step}: train acc {metric.get()[1]:.3f}")
            metric.reset()

    ctx, tgt = synth_corpus(rs, 512)
    correct = n_eval = 0
    # full batches only: the Module is bound to a fixed batch shape
    for i in range(0, 512 - args.batch + 1, args.batch):
        b = mx.io.DataBatch([mx.nd.array(ctx[i:i + args.batch])],
                            [mx.nd.array(tgt[i:i + args.batch])])
        mod.forward(b, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(1)
        correct += int((pred == tgt[i:i + args.batch]).sum())
        n_eval += args.batch
    acc = correct / float(n_eval)
    assert acc >= args.min_acc, acc
    print("SOFTMAX OK acc %.3f" % acc)


if __name__ == "__main__":
    main()
