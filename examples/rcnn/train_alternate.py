#!/usr/bin/env python
"""4-phase alternating Faster R-CNN training (parity:
example/rcnn/train_alternate.py — the original paper's optimization:
train RPN, train the detector on its frozen proposals, then fine-tune
each with the shared trunk frozen so both heads end up on ONE backbone).

  phase 1: backbone + RPN heads train (detector head dormant)
  phase 2: detector head trains on phase-1 proposals; backbone + RPN frozen
  phase 3: RPN heads re-train; backbone frozen (now shared with the head)
  phase 4: detector head re-trains on phase-3 proposals; all else frozen
  final:   joint eval graph -> VOC07 mAP

Data flows through the REAL VOCdevkit path: by default a synthetic
devkit (JPEG + XML annotations) is written and parsed back with
rcnn.dataset.PascalVOC; point --devkit at a real VOC2007 tree (with
--classes to name the 20-class list) to train on it.

Run:  MXTPU_PLATFORM=cpu python train_alternate.py --assert-map 0.5
(measured at the defaults: VOC07 mAP ~0.86 on the synthetic devkit —
above the end-to-end script's ~0.53, matching the paper's observation
that the staged schedule trades wall-clock for detector quality)
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from rcnn import config as cfg_mod  # noqa: E402
from rcnn.dataset import CLASSES, PascalVOC, write_synth_devkit  # noqa: E402
from rcnn.detect import eval_map  # noqa: E402
from rcnn.loader import AnchorLoader  # noqa: E402
from rcnn.metric import (RCNNAccuracy, RCNNLogLoss, RPNAccuracy,  # noqa: E402
                         RPNLogLoss)
from rcnn.targets import sample_rois  # noqa: E402
from rcnn.train_utils import build_executors, current_proposals  # noqa: E402

RPN_PARAMS = ("rpn_conv", "rpn_cls_score", "rpn_bbox_pred")
HEAD_PARAMS = ("fc6", "cls_score", "bbox_pred")


def trainable_names(params, phase):
    """The per-phase update sets (reference train_alternate.py's four
    jobs, expressed as which parameters the updater touches)."""
    def of(prefixes):
        return [n for n in params if n.startswith(prefixes)]

    backbone = [n for n in params
                if not n.startswith(RPN_PARAMS + HEAD_PARAMS)]
    return {
        1: backbone + of(RPN_PARAMS),
        2: of(HEAD_PARAMS),
        3: of(RPN_PARAMS),
        4: of(HEAD_PARAMS),
    }[phase]


def run_phase(phase, steps, ex, eval_ex, loader, params, cfg, lr, rs,
              log_interval):
    b = loader.batch_size
    names = trainable_names(params, phase)
    opt = mx.optimizer.create("sgd", learning_rate=lr, momentum=0.9,
                              rescale_grad=1.0 / b)
    updater = mx.optimizer.get_updater(opt)
    rpn_phase = phase in (1, 3)
    metrics = [RPNAccuracy(), RPNLogLoss()] if rpn_phase else \
        [RCNNAccuracy(), RCNNLogLoss()]
    R = cfg.rcnn_batch_rois
    step, tic = 0, time.perf_counter()
    while step < steps:
        loader.reset()
        for batch in loader:
            if step >= steps:
                break
            lab, bt4, bw4 = batch.label
            if rpn_phase:
                # head dormant: ignore-labeled rois + zero bbox weights
                # make both head losses identically zero, so nothing
                # leaks into the (frozen or not) trunk through the head
                rois = np.zeros((b * R, 5), np.float32)
                rois[:, 0] = np.repeat(np.arange(b), R)
                roi_label = np.full((b * R,), -1.0, np.float32)
                bbox_t = np.zeros((b * R, 4 * cfg.num_classes), np.float32)
                bbox_w = np.zeros_like(bbox_t)
            else:
                # proposals from the CURRENT RPN (frozen this phase)
                proposals = current_proposals(eval_ex, batch, cfg)
                rois, roi_label, bbox_t, bbox_w = sample_rois(
                    proposals, batch.gt, cfg, rs=rs)
                lab = np.full_like(lab, -1.0)  # RPN losses dormant
                bt4, bw4 = np.zeros_like(bt4), np.zeros_like(bw4)
            ex.forward(is_train=True, data=batch.data[0], rpn_label=lab,
                       rpn_bbox_target=bt4, rpn_bbox_weight=bw4,
                       rois=rois, roi_label=roi_label,
                       bbox_target=bbox_t, bbox_weight=bbox_w)
            ex.backward()
            for i, name in enumerate(sorted(names)):
                updater(i, ex.grad_dict[name], params[name])
            if rpn_phase:
                out = ex.outputs[0].asnumpy().reshape(b, 2, -1)
                for m in metrics:
                    m.update([lab], [out])
            else:
                out = ex.outputs[2].asnumpy()
                for m in metrics:
                    m.update([roi_label], [out])
            step += 1
            if step % log_interval == 0:
                vals = "  ".join("%s=%.4f" % m.get() for m in metrics)
                rate = log_interval * b / (time.perf_counter() - tic)
                print(f"phase {phase} step {step}  {vals}  "
                      f"({rate:.1f} img/s)", flush=True)
                for m in metrics:
                    m.reset()
                tic = time.perf_counter()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devkit", help="VOCdevkit path (default: write+parse "
                                     "a synthetic one)")
    ap.add_argument("--classes", nargs="+", default=list(CLASSES))
    ap.add_argument("--year", default="2007")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=150,
                    help="steps per phase (phases 3/4 run half)")
    ap.add_argument("--images", type=int, default=160)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--log-interval", type=int, default=10)
    ap.add_argument("--assert-map", type=float, default=None)
    args = ap.parse_args()
    # the class list drives the head width (a real 21-class VOC run must
    # not inherit the synthetic config's 3)
    cfg = cfg_mod.Config(cfg_mod.default,
                         num_classes=len(args.classes))
    rs = np.random.RandomState(0)
    np.random.seed(0)

    devkit = args.devkit
    if devkit is None:
        # count-keyed so a rerun with a different --images regenerates
        devkit = f"/tmp/rcnn_vocdevkit_{args.images}"
        if not os.path.isdir(os.path.join(devkit, f"VOC{args.year}")):
            write_synth_devkit(devkit, cfg, args.images, year=args.year)
    train_set = PascalVOC(devkit, "trainval", args.year,
                          tuple(args.classes), cfg)
    test_set = PascalVOC(devkit, "test", args.year, tuple(args.classes), cfg)
    images, gt = train_set.load()
    loader = AnchorLoader(cfg, batch_size=args.batch, images=images, gt=gt)

    b = args.batch
    ctx = mx.context.default_accelerator_context()
    ex, eval_ex, params = build_executors(cfg, b, ctx, loader)

    for phase, steps, lr in ((1, args.steps, args.lr),
                             (2, args.steps, args.lr),
                             (3, args.steps // 2, args.lr / 5),
                             (4, args.steps // 2, args.lr / 5)):
        print(f"=== phase {phase}: training {len(trainable_names(params, phase))} "
              f"param tensors, {steps} steps, lr {lr}", flush=True)
        run_phase(phase, steps, ex, eval_ex, loader, params, cfg, lr, rs,
                  args.log_interval)

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "ssd"))
    from eval_metric import VOC07MApMetric

    test_images, test_gt = test_set.load()
    heldout = AnchorLoader(cfg, batch_size=b, images=test_images,
                           gt=test_gt, shuffle=False)
    mAP = eval_map(eval_ex, heldout, cfg, VOC07MApMetric())
    print("VOC07_mAP: %.4f" % mAP)
    if args.assert_map is not None:
        assert mAP > args.assert_map, \
            f"mAP {mAP:.4f} below floor {args.assert_map}"
        print("MAP_FLOOR_OK")


if __name__ == "__main__":
    main()
