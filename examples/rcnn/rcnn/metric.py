"""Training metrics (parity: example/rcnn/rcnn/core/metric.py —
RPNAccMetric, RPNLogLossMetric, RCNNAccMetric, RCNNLogLossMetric; the
fit log prints all four so RPN and head learning are visible
separately)."""
import numpy as np

from mxnet_tpu.metric import EvalMetric


class RPNAccuracy(EvalMetric):
    def __init__(self):
        super().__init__("RPNAcc")

    def update(self, labels, preds):
        label = np.asarray(labels[0])            # (N, A*F*F), -1 ignored
        prob = np.asarray(preds[0])              # (N, 2, A*F*F)
        pred = prob.argmax(axis=1)
        mask = label != -1
        self.sum_metric += float((pred[mask] == label[mask]).sum())
        self.num_inst += int(mask.sum())


class RPNLogLoss(EvalMetric):
    def __init__(self):
        super().__init__("RPNLogLoss")

    def update(self, labels, preds):
        label = np.asarray(labels[0])
        prob = np.asarray(preds[0])
        mask = label != -1
        lab = np.clip(label, 0, 1).astype(int)
        picked = np.take_along_axis(prob, lab[:, None, :], axis=1)[:, 0]
        self.sum_metric += float(
            -np.log(np.maximum(picked[mask], 1e-12)).sum())
        self.num_inst += int(mask.sum())


class RCNNAccuracy(EvalMetric):
    def __init__(self):
        super().__init__("RCNNAcc")

    def update(self, labels, preds):
        label = np.asarray(labels[0]).astype(int)   # (N*R,)
        prob = np.asarray(preds[0])                 # (N*R, C)
        self.sum_metric += float((prob.argmax(1) == label).sum())
        self.num_inst += label.size


class RCNNLogLoss(EvalMetric):
    def __init__(self):
        super().__init__("RCNNLogLoss")

    def update(self, labels, preds):
        label = np.asarray(labels[0]).astype(int)
        prob = np.asarray(preds[0])
        picked = prob[np.arange(label.size), label]
        self.sum_metric += float(-np.log(np.maximum(picked, 1e-12)).sum())
        self.num_inst += label.size
