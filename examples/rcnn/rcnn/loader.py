"""AnchorLoader (parity: example/rcnn/rcnn/io/rpn.py AnchorLoader +
the synthetic stand-in for the VOC roidb): a DataIter that yields
images WITH their RPN anchor targets already assigned, so the compiled
graph never sees dynamic target shapes."""
import numpy as np

from mxnet_tpu.io import DataBatch, DataIter

from .anchors import grid_anchors
from .targets import assign_anchor, rpn_targets_to_feature_layout


def synth_image_set(cfg, n_images, seed=0):
    """Deterministic synthetic-VOC set: bright axis-aligned rectangles
    on noise; class = aspect category (1 wide, 2 tall)."""
    rs = np.random.RandomState(seed)
    im = cfg.im_size
    images = np.zeros((n_images, 3, im, im), np.float32)
    gt = []
    for i in range(n_images):
        x = rs.rand(3, im, im).astype(np.float32) * 0.2
        boxes = []
        for _ in range(rs.randint(1, 3)):
            wide = rs.randint(2)
            w, h = (rs.randint(20, 32), rs.randint(8, 14)) if wide else \
                   (rs.randint(8, 14), rs.randint(20, 32))
            x1 = rs.randint(0, im - w)
            y1 = rs.randint(0, im - h)
            x[:, y1:y1 + h, x1:x1 + w] += 0.8
            boxes.append([x1, y1, x1 + w - 1, y1 + h - 1, 1 + wide])
        images[i] = np.clip(x, 0, 1)
        gt.append(np.array(boxes, np.float32))
    return images, gt


class AnchorLoader(DataIter):
    """Yields DataBatch(data=[data, im_info],
    label=[rpn_label, rpn_bbox_target, rpn_bbox_weight]); the batch's
    gt boxes ride on ``batch.gt`` for the proposal_target stage (the
    reference passes them through the roidb the same way)."""

    def __init__(self, cfg, n_images=64, batch_size=8, seed=0,
                 shuffle=True, images=None, gt=None):
        super().__init__()
        self.cfg = cfg
        self.batch_size = batch_size
        if images is not None:
            # preloaded set (e.g. dataset.PascalVOC.load())
            self.images, self.gt = images, gt
            n_images = len(images)
        else:
            self.images, self.gt = synth_image_set(cfg, n_images, seed)
        self.anchors = grid_anchors(cfg)
        self._rs = np.random.RandomState(seed + 1)
        self._shuffle = shuffle
        self._order = np.arange(n_images)
        self._cur = 0
        self.reset()

    @property
    def provide_data(self):
        im = self.cfg.im_size
        return [("data", (self.batch_size, 3, im, im)),
                ("im_info", (self.batch_size, 3))]

    @property
    def provide_label(self):
        from .config import feat_size, num_anchors

        f, a0 = feat_size(self.cfg), num_anchors(self.cfg)
        return [("rpn_label", (self.batch_size, a0 * f * f)),
                ("rpn_bbox_target", (self.batch_size, 4 * a0, f, f)),
                ("rpn_bbox_weight", (self.batch_size, 4 * a0, f, f))]

    def reset(self):
        self._cur = 0
        if self._shuffle:
            self._rs.shuffle(self._order)

    def next(self):
        if self._cur + self.batch_size > len(self.images):
            raise StopIteration
        idx = self._order[self._cur:self._cur + self.batch_size]
        self._cur += self.batch_size
        x = self.images[idx]
        gt = [self.gt[i] for i in idx]
        labels, bt, bw = assign_anchor(gt, self.anchors, self.cfg,
                                       rs=self._rs)
        lab, bt4, bw4 = rpn_targets_to_feature_layout(labels, bt, bw,
                                                      self.cfg)
        im = self.cfg.im_size
        im_info = np.array([[im, im, 1.0]] * self.batch_size, np.float32)
        batch = DataBatch([x, im_info], [lab, bt4, bw4])
        batch.gt = gt
        return batch
