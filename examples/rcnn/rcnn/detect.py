"""Test-time detection (parity: example/rcnn/rcnn/core/tester.py
im_detect + pred_eval): per-class bbox decoding from the head's
regression branch, per-class NMS, and VOC07 mAP over a held-out set."""
import numpy as np

from .anchors import bbox_pred, clip_boxes, nms


def im_detect(outputs, cfg, batch):
    """Decode one eval forward into per-image detections.

    outputs: [rpn_cls_prob, _, cls_prob (N*R, C),
              bbox_pred (N*R, 4C), rois (N*R, 5)].
    Returns dets (batch, max_per_image, 6) rows
    (cls, score, x1, y1, x2, y2), -1 padded.
    """
    def to_np(a):
        # NDArray lacks __array__ by design; np.asarray would fall back
        # to element-wise iteration (one device sync per element)
        return a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)

    C = cfg.num_classes
    stds = np.asarray(cfg.rcnn_bbox_stds, np.float32)
    probs = to_np(outputs[2])
    deltas = to_np(outputs[3])
    rois = to_np(outputs[4])
    out = np.full((batch, cfg.test_max_per_image, 6), -1.0, np.float32)
    for i in range(batch):
        mine = rois[:, 0] == i
        boxes_i = rois[mine][:, 1:5]
        probs_i = probs[mine]
        deltas_i = deltas[mine]
        dets_i = []
        for c in range(1, C):  # skip background
            col = slice(4 * c, 4 * c + 4)
            decoded = bbox_pred(boxes_i, deltas_i[:, col] * stds)
            decoded = clip_boxes(decoded, cfg.im_size)
            scores = probs_i[:, c]
            keep = scores > cfg.test_score_thresh
            if not keep.any():
                continue
            cand = np.concatenate(
                [decoded[keep], scores[keep, None]], axis=1)
            for k in nms(cand, cfg.test_nms_thresh):
                dets_i.append([c, cand[k, 4], *cand[k, :4]])
        dets_i.sort(key=lambda d: -d[1])
        for j, d in enumerate(dets_i[:cfg.test_max_per_image]):
            out[i, j] = d
    return out


def eval_map(eval_ex, loader, cfg, metric):
    """Run detection over the loader's epoch and fold into the mAP
    metric; zero-filled targets feed the unused loss inputs."""
    from .config import feat_size, num_anchors

    b = loader.batch_size
    f, a0 = feat_size(cfg), num_anchors(cfg)
    zeros = dict(
        rpn_label=np.zeros((b, a0 * f * f), np.float32),
        rpn_bbox_target=np.zeros((b, 4 * a0, f, f), np.float32),
        rpn_bbox_weight=np.zeros((b, 4 * a0, f, f), np.float32),
        roi_label=np.zeros((b * cfg.rpn_post_nms_top_n,), np.float32))
    loader.reset()
    for batch in loader:
        eval_ex.forward(is_train=False, data=batch.data[0],
                        im_info=batch.data[1], **zeros)
        dets = im_detect(eval_ex.outputs, cfg, b)
        max_gt = max((len(g) for g in batch.gt), default=1)
        labels = np.full((b, max(max_gt, 1), 5), -1.0, np.float32)
        for i, g in enumerate(batch.gt):
            for j, row in enumerate(g):
                labels[i, j] = [row[4], row[0], row[1], row[2], row[3]]
        metric.update([labels], [dets])
    return metric.get()[1]
