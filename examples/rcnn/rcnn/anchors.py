"""Anchor/box geometry (parity: example/rcnn/rcnn/processing/
bbox_transform.py + generate_anchor.py): grid anchors, IoU, the
delta encode/decode pair, clipping and greedy NMS — pure numpy, used
by the host-side target assignment exactly as the reference computes
targets in its data loader."""
import numpy as np

from mxnet_tpu.ops.vision import _generate_anchors


def grid_anchors(cfg):
    """All anchors of the feature grid, (A*FH*FW, 4) in image coords."""
    from .config import feat_size

    f = feat_size(cfg)
    base = _generate_anchors(cfg.feature_stride, cfg.anchor_scales,
                             cfg.anchor_ratios)
    sx, sy = np.meshgrid(np.arange(f) * cfg.feature_stride,
                         np.arange(f) * cfg.feature_stride)
    shifts = np.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()], 1)
    return (shifts[:, None].astype(np.float32) + base[None]).reshape(-1, 4)


def np_iou(a, b):
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    iw = np.maximum(ix2 - ix1 + 1, 0)
    ih = np.maximum(iy2 - iy1 + 1, 0)
    inter = iw * ih
    ua = ((a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1))[:, None] + \
         ((b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1))[None] - inter
    return inter / np.maximum(ua, 1e-6)


def bbox_transform(boxes, gt):
    """Boxes -> regression deltas to their matched gt (parity:
    bbox_transform.py nonlinear_transform)."""
    bw = boxes[:, 2] - boxes[:, 0] + 1
    bh = boxes[:, 3] - boxes[:, 1] + 1
    bcx = boxes[:, 0] + 0.5 * (bw - 1)
    bcy = boxes[:, 1] + 0.5 * (bh - 1)
    gw = gt[:, 2] - gt[:, 0] + 1
    gh = gt[:, 3] - gt[:, 1] + 1
    gcx = gt[:, 0] + 0.5 * (gw - 1)
    gcy = gt[:, 1] + 0.5 * (gh - 1)
    return np.stack([(gcx - bcx) / bw, (gcy - bcy) / bh,
                     np.log(gw / bw), np.log(gh / bh)], axis=1)


def bbox_pred(boxes, deltas):
    """Apply deltas to boxes (inverse of bbox_transform; parity:
    nonlinear_pred) — deltas is (N, 4) for one class column."""
    bw = boxes[:, 2] - boxes[:, 0] + 1
    bh = boxes[:, 3] - boxes[:, 1] + 1
    bcx = boxes[:, 0] + 0.5 * (bw - 1)
    bcy = boxes[:, 1] + 0.5 * (bh - 1)
    cx = deltas[:, 0] * bw + bcx
    cy = deltas[:, 1] * bh + bcy
    w = np.exp(np.clip(deltas[:, 2], -10, 10)) * bw
    h = np.exp(np.clip(deltas[:, 3], -10, 10)) * bh
    return np.stack([cx - 0.5 * (w - 1), cy - 0.5 * (h - 1),
                     cx + 0.5 * (w - 1), cy + 0.5 * (h - 1)], axis=1)


def clip_boxes(boxes, im_size):
    return np.stack([np.clip(boxes[:, 0], 0, im_size - 1),
                     np.clip(boxes[:, 1], 0, im_size - 1),
                     np.clip(boxes[:, 2], 0, im_size - 1),
                     np.clip(boxes[:, 3], 0, im_size - 1)], axis=1)


def nms(dets, thresh):
    """Greedy NMS over (N, 5) [x1 y1 x2 y2 score]; returns kept indices
    (parity: rcnn/processing/nms.py py_nms_wrapper)."""
    if len(dets) == 0:
        return []
    order = dets[:, 4].argsort()[::-1]
    keep = []
    while order.size:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        iou = np_iou(dets[i:i + 1, :4], dets[order[1:], :4])[0]
        order = order[1:][iou <= thresh]
    return keep
