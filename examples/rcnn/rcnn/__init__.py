"""Faster R-CNN as a modular training system (parity model:
example/rcnn/rcnn/ — config, anchor/proposal target assignment,
symbols, loader, metrics as separate concerns, not one script)."""
