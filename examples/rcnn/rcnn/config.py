"""Configuration (parity: example/rcnn/rcnn/config.py — an edict the
whole system reads; one place to retune the detector)."""


class Config(dict):
    """dict with attribute access, like the reference's EasyDict."""

    __getattr__ = dict.__getitem__

    def __setattr__(self, k, v):
        self[k] = v


default = Config(
    # synthetic-VOC world geometry
    im_size=64,
    feature_stride=4,            # two 2x2 pools in the backbone
    num_classes=3,               # background, wide, tall

    # anchors
    anchor_scales=(2, 4, 8),
    anchor_ratios=(0.5, 1, 2),

    # RPN target assignment (parity: rcnn/io/rpn.py assign_anchor)
    rpn_fg_overlap=0.5,
    rpn_bg_overlap=0.3,
    rpn_batch_rois=64,
    rpn_fg_fraction=0.5,

    # proposal generation (parity: rpn/proposal.py)
    rpn_pre_nms_top_n=64,
    rpn_post_nms_top_n=16,
    rpn_nms_thresh=0.7,
    rpn_min_size=4,

    # proposal->head sampling (parity: rcnn/rpn/proposal_target.py)
    rcnn_batch_rois=16,          # rois per image fed to the head
    rcnn_fg_fraction=0.25,
    rcnn_fg_overlap=0.5,
    rcnn_bbox_stds=(0.1, 0.1, 0.2, 0.2),

    # test-time detection
    test_nms_thresh=0.3,
    test_score_thresh=0.05,
    test_max_per_image=8,
)


def num_anchors(cfg):
    return len(cfg.anchor_scales) * len(cfg.anchor_ratios)


def feat_size(cfg):
    return cfg.im_size // cfg.feature_stride
