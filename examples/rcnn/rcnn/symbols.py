"""Network definitions (parity: example/rcnn/rcnn/symbol/symbol_vgg.py
— backbone, RPN heads, Proposal, ROI pooling, and the fast-rcnn head
WITH its per-class bbox regression branch)."""
from mxnet_tpu import sym

from .config import feat_size, num_anchors


def backbone(data):
    """Small conv trunk standing in for VGG (3 convs, 2 pools -> the
    configured feature stride)."""
    net = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                          name="conv1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=32,
                          name="conv2")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=32,
                          name="conv3")
    return sym.Activation(net, act_type="relu", name="feat")


def get_symbol(cfg, batch, train_rois=False):
    """Joint train/eval graph.

    train_rois=True: the head pools an externally supplied `rois`
    variable (the proposal_target flow — training rois are sampled
    host-side from the previous forward's proposals) and emits LOSSES
    for both head branches.  False: the head consumes the in-graph
    Proposal output and emits raw scores + deltas for detection.

    Outputs: [rpn_cls_prob, rpn_bbox_loss, cls_prob,
              bbox_loss (train) | bbox_pred (eval), rois]
    """
    a0 = num_anchors(cfg)
    f = feat_size(cfg)
    C = cfg.num_classes

    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    rpn_label = sym.Variable("rpn_label")
    rpn_bbox_target = sym.Variable("rpn_bbox_target")
    rpn_bbox_weight = sym.Variable("rpn_bbox_weight")
    roi_label = sym.Variable("roi_label")

    feat = backbone(data)

    # RPN
    rpn = sym.Convolution(feat, kernel=(3, 3), pad=(1, 1), num_filter=32,
                          name="rpn_conv")
    rpn = sym.Activation(rpn, act_type="relu")
    rpn_cls = sym.Convolution(rpn, kernel=(1, 1), num_filter=2 * a0,
                              name="rpn_cls_score")
    rpn_bbox = sym.Convolution(rpn, kernel=(1, 1), num_filter=4 * a0,
                               name="rpn_bbox_pred")
    rpn_cls_flat = sym.Reshape(rpn_cls, shape=(0, 2, -1),
                               name="rpn_cls_flat")
    rpn_cls_prob = sym.SoftmaxOutput(rpn_cls_flat, rpn_label,
                                     multi_output=True, use_ignore=True,
                                     ignore_label=-1,
                                     normalization="valid",
                                     name="rpn_cls_prob")
    rpn_bbox_loss = sym.smooth_l1(
        rpn_bbox_weight * (rpn_bbox - rpn_bbox_target), scalar=3.0)
    rpn_bbox_loss = sym.MakeLoss(sym.sum(rpn_bbox_loss) / batch,
                                 name="rpn_bbox_loss")

    # proposals (gradient-free, like the reference's Proposal op)
    rpn_cls_act = sym.SoftmaxActivation(rpn_cls_flat, mode="channel",
                                        name="rpn_cls_act")
    rpn_cls_act = sym.Reshape(rpn_cls_act, shape=(0, 2 * a0, f, f))
    if train_rois:
        rois = sym.BlockGrad(sym.Variable("rois"), name="rois")
    else:
        rois = sym.Proposal(
            sym.BlockGrad(rpn_cls_act), sym.BlockGrad(rpn_bbox), im_info,
            feature_stride=cfg.feature_stride, scales=cfg.anchor_scales,
            ratios=cfg.anchor_ratios,
            rpn_pre_nms_top_n=cfg.rpn_pre_nms_top_n,
            rpn_post_nms_top_n=cfg.rpn_post_nms_top_n,
            threshold=cfg.rpn_nms_thresh, rpn_min_size=cfg.rpn_min_size,
            name="rois")

    # fast-rcnn head: shared trunk, class scores AND per-class deltas
    pooled = sym.ROIPooling(feat, rois, pooled_size=(4, 4),
                            spatial_scale=1.0 / cfg.feature_stride,
                            name="roi_pool")
    head = sym.FullyConnected(sym.Flatten(pooled), num_hidden=64,
                              name="fc6")
    head = sym.Activation(head, act_type="relu")
    cls_score = sym.FullyConnected(head, num_hidden=C, name="cls_score")
    cls_prob = sym.SoftmaxOutput(cls_score, roi_label, use_ignore=True,
                                 ignore_label=-1, normalization="valid",
                                 name="cls_prob")
    bbox_pred = sym.FullyConnected(head, num_hidden=4 * C,
                                   name="bbox_pred")
    if train_rois:
        bbox_target = sym.Variable("bbox_target")
        bbox_weight = sym.Variable("bbox_weight")
        n_rois = batch * cfg.rcnn_batch_rois
        bbox_loss = sym.smooth_l1(
            bbox_weight * (bbox_pred - bbox_target), scalar=1.0)
        bbox_branch = sym.MakeLoss(sym.sum(bbox_loss) / n_rois,
                                   name="bbox_loss")
    else:
        bbox_branch = sym.BlockGrad(bbox_pred, name="bbox_pred_out")
    return sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_branch,
                      sym.BlockGrad(rois)])
