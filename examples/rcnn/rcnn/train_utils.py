"""Shared trainer plumbing for the Faster R-CNN scripts (parity:
example/rcnn/rcnn/core/module.py + tools/train_rpn.py scaffolding —
the executor setup, parameter collection, and proposal extraction the
reference's train_end2end/train_alternate both lean on)."""
import numpy as np

import mxnet_tpu as mx

from .symbols import get_symbol

# label/target variables that LOOK like parameters by suffix but must
# never be initialized or updated (the old substring filter
# '"rpn_bbox" not in name' also swallowed rpn_bbox_pred_weight/bias —
# leaving the RPN box regressor untrained at its bind-time zeros)
LABEL_VARS = frozenset((
    "rpn_label", "rpn_bbox_target", "rpn_bbox_weight",
    "rois", "roi_label", "bbox_target", "bbox_weight"))


def build_executors(cfg, batch, ctx, loader):
    """Bind the joint train graph + the proposal/eval graph sharing ONE
    set of parameter NDArrays; returns (train_ex, eval_ex, params)."""
    b, R = batch, cfg.rcnn_batch_rois
    train_net = get_symbol(cfg, b, train_rois=True)
    ex = train_net.simple_bind(
        ctx=ctx, grad_req="write",
        data=(b, 3, cfg.im_size, cfg.im_size),
        rpn_label=loader.provide_label[0][1],
        rpn_bbox_target=loader.provide_label[1][1],
        rpn_bbox_weight=loader.provide_label[2][1],
        rois=(b * R, 5), roi_label=(b * R,),
        bbox_target=(b * R, 4 * cfg.num_classes),
        bbox_weight=(b * R, 4 * cfg.num_classes))
    init = mx.init.Xavier()
    params = {}
    for name, arr in ex.arg_dict.items():
        if name in LABEL_VARS or name in ("data", "im_info"):
            continue
        if name.endswith(("weight", "bias")):
            init(name, arr)
            params[name] = arr

    eval_net = get_symbol(cfg, b, train_rois=False)
    eval_args = {}
    for name in eval_net.list_arguments():
        if name in ex.arg_dict:
            eval_args[name] = ex.arg_dict[name]  # shared: one update serves both
        else:
            shp = {"data": (b, 3, cfg.im_size, cfg.im_size),
                   "im_info": (b, 3)}.get(name)
            eval_args[name] = mx.nd.zeros(shp) if shp else mx.nd.zeros((1,))
    eval_ex = eval_net.bind(ctx=ctx, args=eval_args, args_grad=None,
                            grad_req="null")
    return ex, eval_ex, params


def current_proposals(eval_ex, batch, cfg):
    """Forward the proposal graph on a batch (zero-filled loss inputs)
    and return its rois (N*post_nms, 5) as numpy."""
    lab, bt4, bw4 = batch.label
    b = batch.data[0].shape[0]
    eval_ex.forward(
        is_train=False, data=batch.data[0], im_info=batch.data[1],
        rpn_label=np.zeros(lab.shape, np.float32),
        rpn_bbox_target=np.zeros(bt4.shape, np.float32),
        rpn_bbox_weight=np.zeros(bw4.shape, np.float32),
        roi_label=np.zeros((b * cfg.rpn_post_nms_top_n,), np.float32))
    return eval_ex.outputs[4].asnumpy()
