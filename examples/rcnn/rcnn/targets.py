"""Target assignment — the host-side half of Faster R-CNN training.

Parity: the reference computes BOTH target stages in the data path so
the compiled graph stays static —
  * RPN anchor targets in the loader (rcnn/io/rpn.py assign_anchor),
  * head targets by SAMPLING the previous forward's proposals
    (rcnn/rpn/proposal_target.py: fg/bg fractions, per-class bbox
    deltas, normalization stds).
Same split here, numpy end to end.
"""
import numpy as np

from .anchors import bbox_transform, np_iou


def assign_anchor(gt_list, anchors, cfg, rs=None):
    """RPN targets: fg iou>=rpn_fg_overlap, bg < rpn_bg_overlap, the
    rest ignored; a fixed-size anchor batch is sampled per image (up to
    rpn_fg_fraction foreground) — without sampling the ~100:1 bg:fg
    imbalance drowns the foreground gradient."""
    rs = rs or np.random
    n = len(gt_list)
    total = anchors.shape[0]
    labels = np.full((n, total), -1, np.float32)
    bbox_t = np.zeros((n, total, 4), np.float32)
    bbox_w = np.zeros((n, total, 4), np.float32)
    for i, gt in enumerate(gt_list):
        iou = np_iou(anchors, gt[:, :4])
        best = iou.max(axis=1)
        arg = iou.argmax(axis=1)
        labels[i, best < cfg.rpn_bg_overlap] = 0
        fg = best >= cfg.rpn_fg_overlap
        for j in range(gt.shape[0]):   # every gt keeps its best anchor
            fg[iou[:, j].argmax()] = True
        labels[i, fg] = 1
        fg_idx = np.where(labels[i] == 1)[0]
        n_fg = min(len(fg_idx), int(cfg.rpn_batch_rois * cfg.rpn_fg_fraction))
        if len(fg_idx) > n_fg:
            off = rs.choice(fg_idx, len(fg_idx) - n_fg, replace=False)
            labels[i, off] = -1
        bg_idx = np.where(labels[i] == 0)[0]
        n_bg = cfg.rpn_batch_rois - n_fg
        if len(bg_idx) > n_bg:
            off = rs.choice(bg_idx, len(bg_idx) - n_bg, replace=False)
            labels[i, off] = -1
        fg = labels[i] == 1
        bbox_t[i, fg] = bbox_transform(anchors[fg], gt[arg[fg], :4])
        bbox_w[i, fg] = 1.0
    return labels, bbox_t, bbox_w


def rpn_targets_to_feature_layout(labels, bbox_t, bbox_w, cfg):
    """(N, A*F*F[,4]) row-major anchor targets -> the channel-major
    layout the RPN heads emit ((N, A*F*F) labels, (N, 4A, F, F) boxes)."""
    from .config import feat_size, num_anchors

    f, a0 = feat_size(cfg), num_anchors(cfg)
    n = labels.shape[0]
    lab = labels.reshape(n, f, f, a0).transpose(0, 3, 1, 2).reshape(n, -1)
    bt = bbox_t.reshape(n, f, f, a0, 4).transpose(0, 3, 4, 1, 2) \
        .reshape(n, 4 * a0, f, f)
    bw = bbox_w.reshape(n, f, f, a0, 4).transpose(0, 3, 4, 1, 2) \
        .reshape(n, 4 * a0, f, f)
    return lab, bt, bw


def sample_rois(rois, gt_list, cfg, rs=None):
    """proposal_target: sample a fixed head batch from the proposals.

    Appends each image's gt boxes to its proposal list (the reference
    does the same so the head always sees true foreground), computes
    IoU, samples rcnn_fg_fraction foreground + background to
    rcnn_batch_rois per image, and emits per-class bbox deltas
    normalized by rcnn_bbox_stds.

    Returns (rois_out [N*R, 5], label [N*R], bbox_target [N*R, 4C],
    bbox_weight [N*R, 4C]).
    """
    rs = rs or np.random
    n_img = len(gt_list)
    R = cfg.rcnn_batch_rois
    C = cfg.num_classes
    stds = np.asarray(cfg.rcnn_bbox_stds, np.float32)
    out_rois = np.zeros((n_img * R, 5), np.float32)
    out_label = np.zeros((n_img * R,), np.float32)
    out_bt = np.zeros((n_img * R, 4 * C), np.float32)
    out_bw = np.zeros((n_img * R, 4 * C), np.float32)
    for i, gt in enumerate(gt_list):
        mine = rois[rois[:, 0] == i][:, 1:5]
        cand = np.concatenate([mine, gt[:, :4]], axis=0)
        iou = np_iou(cand, gt[:, :4])
        best = iou.max(axis=1)
        arg = iou.argmax(axis=1)
        fg_idx = np.where(best >= cfg.rcnn_fg_overlap)[0]
        bg_idx = np.where(best < cfg.rcnn_fg_overlap)[0]
        n_fg = int(min(len(fg_idx), round(R * cfg.rcnn_fg_fraction)))
        if len(fg_idx) > n_fg:
            fg_idx = rs.choice(fg_idx, n_fg, replace=False)
        n_bg = R - n_fg
        if len(bg_idx) >= n_bg:
            bg_idx = rs.choice(bg_idx, n_bg, replace=False)
        elif len(bg_idx) > 0:
            bg_idx = rs.choice(bg_idx, n_bg, replace=True)
        else:
            bg_idx = np.zeros((0,), int)
        keep = np.concatenate([fg_idx, bg_idx]).astype(int)
        # pad (rare: no bg candidates at all) by repeating the last roi
        while len(keep) < R:
            keep = np.concatenate([keep, keep[-1:]])
        sel = cand[keep]
        lab = np.zeros((R,), np.float32)
        lab[:len(fg_idx)] = gt[arg[fg_idx], 4] if len(fg_idx) else 0
        sl = slice(i * R, (i + 1) * R)
        out_rois[sl, 0] = i
        out_rois[sl, 1:5] = sel
        out_label[sl] = lab
        if len(fg_idx):
            deltas = bbox_transform(sel[:len(fg_idx)],
                                    gt[arg[fg_idx], :4]) / stds
            for k, cls in enumerate(lab[:len(fg_idx)].astype(int)):
                col = slice(4 * cls, 4 * cls + 4)
                out_bt[i * R + k, col] = deltas[k]
                out_bw[i * R + k, col] = 1.0
    return out_rois, out_label, out_bt, out_bw
