"""Pascal VOC dataset loading (parity:
example/rcnn/rcnn/dataset/pascal_voc.py — the reference parses a
VOCdevkit tree: ImageSets/Main lists, Annotations XML, JPEGImages —
into a roidb).  Same tree format here, parsed with ElementTree + PIL,
resized to the configured square input with boxes rescaled.

``write_synth_devkit`` emits a REAL VOCdevkit directory from the
synthetic rectangles task (JPEG images, XML annotations, image-set
lists), so the parse path is exercised out of the box and a real
VOC2007 devkit drops straight in.
"""
import os
import xml.etree.ElementTree as ET

import numpy as np

from .loader import synth_image_set

CLASSES = ("__background__", "wide", "tall")


def write_synth_devkit(path, cfg, n_images, seed=0, year="2007"):
    """Materialize the synthetic set as VOCdevkit/VOC<year>/..."""
    from PIL import Image

    root = os.path.join(path, f"VOC{year}")
    for d in ("Annotations", "JPEGImages", "ImageSets/Main"):
        os.makedirs(os.path.join(root, d), exist_ok=True)
    images, gt = synth_image_set(cfg, n_images, seed)
    ids = []
    for i, (im, boxes) in enumerate(zip(images, gt)):
        idx = f"{i:06d}"
        ids.append(idx)
        arr = (im.transpose(1, 2, 0) * 255).clip(0, 255).astype(np.uint8)
        Image.fromarray(arr).save(
            os.path.join(root, "JPEGImages", idx + ".jpg"), quality=95)
        ann = ET.Element("annotation")
        ET.SubElement(ann, "filename").text = idx + ".jpg"
        size = ET.SubElement(ann, "size")
        ET.SubElement(size, "width").text = str(im.shape[2])
        ET.SubElement(size, "height").text = str(im.shape[1])
        ET.SubElement(size, "depth").text = "3"
        for row in boxes:
            obj = ET.SubElement(ann, "object")
            ET.SubElement(obj, "name").text = CLASSES[int(row[4])]
            ET.SubElement(obj, "difficult").text = "0"
            bb = ET.SubElement(obj, "bndbox")
            # VOC convention: 1-based inclusive pixel coordinates
            ET.SubElement(bb, "xmin").text = str(int(row[0]) + 1)
            ET.SubElement(bb, "ymin").text = str(int(row[1]) + 1)
            ET.SubElement(bb, "xmax").text = str(int(row[2]) + 1)
            ET.SubElement(bb, "ymax").text = str(int(row[3]) + 1)
        ET.ElementTree(ann).write(
            os.path.join(root, "Annotations", idx + ".xml"))
    n_train = max(1, int(n_images * 0.8))
    with open(os.path.join(root, "ImageSets/Main/trainval.txt"), "w") as f:
        f.write("\n".join(ids[:n_train]) + "\n")
    with open(os.path.join(root, "ImageSets/Main/test.txt"), "w") as f:
        f.write("\n".join(ids[n_train:]) + "\n")
    return root


class PascalVOC:
    """Parse VOCdevkit/VOC<year> into (images, gt) arrays the
    AnchorLoader consumes; classes absent from ``classes`` are skipped
    (the reference filters the 20-class list the same way)."""

    def __init__(self, devkit_path, image_set="trainval", year="2007",
                 classes=CLASSES, cfg=None, skip_difficult=True):
        self.root = os.path.join(devkit_path, f"VOC{year}")
        if not os.path.isdir(self.root):
            raise FileNotFoundError(self.root)
        self.classes = tuple(classes)
        self._cls_index = {c: i for i, c in enumerate(self.classes)}
        self.cfg = cfg
        self.skip_difficult = skip_difficult
        with open(os.path.join(self.root, "ImageSets/Main",
                               image_set + ".txt")) as f:
            self.ids = [line.strip() for line in f if line.strip()]

    def _parse_annotation(self, idx, scale_x, scale_y):
        tree = ET.parse(os.path.join(self.root, "Annotations", idx + ".xml"))
        boxes = []
        for obj in tree.findall("object"):
            name = obj.find("name").text.strip()
            if name not in self._cls_index:
                continue
            diff = obj.find("difficult")
            if self.skip_difficult and diff is not None \
                    and int(diff.text) == 1:
                continue
            bb = obj.find("bndbox")
            x1 = (float(bb.find("xmin").text) - 1) * scale_x
            y1 = (float(bb.find("ymin").text) - 1) * scale_y
            x2 = (float(bb.find("xmax").text) - 1) * scale_x
            y2 = (float(bb.find("ymax").text) - 1) * scale_y
            boxes.append([x1, y1, x2, y2, self._cls_index[name]])
        return np.asarray(boxes, np.float32).reshape(-1, 5)

    def load(self):
        """-> (images (N,3,S,S) float32 in [0,1], [gt (k,5)]).

        Images left with ZERO usable boxes (all objects difficult or
        outside the class list) are dropped — anchor assignment and
        proposal sampling both need at least one gt box (the reference
        filters its roidb the same way, filter_roidb)."""
        from PIL import Image

        size = self.cfg.im_size
        images, gt, dropped = [], [], 0
        for idx in self.ids:
            img = Image.open(os.path.join(
                self.root, "JPEGImages", idx + ".jpg")).convert("RGB")
            w, h = img.size
            boxes = self._parse_annotation(
                idx, (size - 1) / max(w - 1, 1), (size - 1) / max(h - 1, 1))
            if len(boxes) == 0:
                dropped += 1
                continue
            arr = np.asarray(img.resize((size, size), Image.BILINEAR),
                             np.float32) / 255.0
            images.append(arr.transpose(2, 0, 1))
            gt.append(boxes)
        if dropped:
            print(f"PascalVOC: dropped {dropped} images with no usable "
                  "gt boxes")
        if not images:
            raise ValueError(f"{self.root}: no images with usable gt boxes")
        return np.stack(images), gt
