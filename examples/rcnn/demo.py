#!/usr/bin/env python
"""Detection demo (parity: example/rcnn/demo.py): load a checkpoint
saved by train_end2end.py --save-prefix, run the detector on fresh
synthetic images, and print each image's detections next to its ground
truth (plus an ASCII render so the localization is visible).

Run:  MXTPU_PLATFORM=cpu python train_end2end.py --steps 200 \
          --save-prefix /tmp/frcnn
      MXTPU_PLATFORM=cpu python demo.py --prefix /tmp/frcnn
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from rcnn import config as cfg_mod  # noqa: E402
from rcnn.detect import im_detect  # noqa: E402
from rcnn.loader import synth_image_set  # noqa: E402
from rcnn.symbols import get_symbol  # noqa: E402

CLASSES = ["bg", "wide", "tall"]


def ascii_render(img, dets, gt, cfg, cols=48):
    """Terminal sketch: '#' image intensity, box corners marked."""
    im = cfg.im_size
    scale = im / cols
    rows = cols // 2
    grid = [[" "] * cols for _ in range(rows)]
    lum = img.mean(0)
    for r in range(rows):
        for c in range(cols):
            y = int(r * im / rows)
            x = int(c * scale)
            if lum[y, x] > 0.5:
                grid[r][c] = "#"

    def mark(box, ch):
        x1, y1, x2, y2 = box
        for (bx, by) in ((x1, y1), (x2, y1), (x1, y2), (x2, y2)):
            c = min(int(bx / scale), cols - 1)
            r = min(int(by * rows / im), rows - 1)
            grid[r][c] = ch

    for g in gt:
        mark(g[:4], "G")
    for d in dets:
        if d[0] > 0:
            mark(d[2:6], "D")
    return "\n".join("".join(r) for r in grid)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--prefix", required=True)
    ap.add_argument("--images", type=int, default=2)
    ap.add_argument("--seed", type=int, default=1234)
    args = ap.parse_args()
    cfg = cfg_mod.default
    b = args.images

    symbol, arg_params, aux_params = mx.model.load_checkpoint(
        args.prefix, 0)
    net = get_symbol(cfg, b, train_rois=False)
    from rcnn.config import feat_size, num_anchors

    f, a0 = feat_size(cfg), num_anchors(cfg)
    ex = net.simple_bind(
        ctx=mx.context.default_accelerator_context(), grad_req="null",
        data=(b, 3, cfg.im_size, cfg.im_size), im_info=(b, 3),
        rpn_label=(b, a0 * f * f), rpn_bbox_target=(b, 4 * a0, f, f),
        rpn_bbox_weight=(b, 4 * a0, f, f),
        roi_label=(b * cfg.rpn_post_nms_top_n,))
    ex.copy_params_from({k: v for k, v in arg_params.items()},
                        aux_params, allow_extra_params=True)

    imgs, gt = synth_image_set(cfg, b, seed=args.seed)
    im = cfg.im_size
    ex.forward(is_train=False, data=imgs,
               im_info=np.array([[im, im, 1.0]] * b, np.float32),
               rpn_label=np.zeros((b, a0 * f * f), np.float32),
               rpn_bbox_target=np.zeros((b, 4 * a0, f, f), np.float32),
               rpn_bbox_weight=np.zeros((b, 4 * a0, f, f), np.float32),
               roi_label=np.zeros((b * cfg.rpn_post_nms_top_n,),
                                  np.float32))
    dets = im_detect(ex.outputs, cfg, b)
    for i in range(b):
        print(f"--- image {i} ---")
        for g in gt[i]:
            print(f"  gt : {CLASSES[int(g[4])]:>5} "
                  f"[{g[0]:.0f} {g[1]:.0f} {g[2]:.0f} {g[3]:.0f}]")
        for d in dets[i]:
            if d[0] > 0:
                print(f"  det: {CLASSES[int(d[0])]:>5} "
                      f"[{d[2]:.0f} {d[3]:.0f} {d[4]:.0f} {d[5]:.0f}] "
                      f"score {d[1]:.2f}")
        print(ascii_render(imgs[i], dets[i], gt[i], cfg))
    n_det = int((dets[:, :, 0] > 0).sum())
    print(f"DEMO OK: {n_det} detections over {b} images")


if __name__ == "__main__":
    main()
