#!/usr/bin/env python
"""Faster R-CNN on synthetic detection data (parity: example/rcnn/).

The reference's pipeline: conv backbone -> RPN (cls + bbox heads) ->
Proposal op -> ROIPooling -> fast-rcnn heads, with anchor/proposal targets
computed in the DATA LOADER (example/rcnn/rcnn/io/rpn.py AnchorLoader) —
the graph itself stays static.  Same split here: targets are assigned
host-side with numpy IoU; the compiled graph contains the backbone, both
RPN losses, the Proposal op, ROIPooling and the head losses.

Synthetic task: images contain 1-2 axis-aligned bright rectangles on
noise; classes = rectangle aspect category.  Loss must fall.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.ops.vision import _generate_anchors  # noqa: E402

IM, STRIDE, A0 = 64, 4, 9  # two 2x2 pools -> feature stride 4
FEAT = IM // STRIDE
POST = 16
NUM_CLASSES = 3  # background, wide, tall


def build_symbol(batch, train_rois=False):
    """train_rois=True: the head pools an externally supplied `rois`
    variable — the reference's proposal_target flow, where training rois
    are the RPN proposals WITH the gt boxes appended so the head always
    sees foreground samples (example/rcnn proposal_target.py).  False:
    the head consumes the in-graph Proposal output (inference/eval)."""
    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    rpn_label = sym.Variable("rpn_label")          # (N, A0*FH*FW)
    rpn_bbox_target = sym.Variable("rpn_bbox_target")  # (N, 4*A0, FH, FW)
    rpn_bbox_weight = sym.Variable("rpn_bbox_weight")
    roi_label = sym.Variable("roi_label")          # (N*POST,)

    # backbone
    net = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                          name="conv1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=32,
                          name="conv2")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=32,
                          name="conv3")
    feat = sym.Activation(net, act_type="relu", name="feat")

    # RPN heads
    rpn = sym.Convolution(feat, kernel=(3, 3), pad=(1, 1), num_filter=32,
                          name="rpn_conv")
    rpn = sym.Activation(rpn, act_type="relu")
    rpn_cls = sym.Convolution(rpn, kernel=(1, 1), num_filter=2 * A0,
                              name="rpn_cls_score")
    rpn_bbox = sym.Convolution(rpn, kernel=(1, 1), num_filter=4 * A0,
                               name="rpn_bbox_pred")

    # rpn classification loss over (bg, fg) per anchor; label -1 ignored
    rpn_cls_flat = sym.Reshape(rpn_cls, shape=(0, 2, -1), name="rpn_cls_flat")
    rpn_cls_prob = sym.SoftmaxOutput(rpn_cls_flat, rpn_label, multi_output=True,
                                     use_ignore=True, ignore_label=-1,
                                     normalization="valid", name="rpn_cls_prob")
    # rpn bbox smooth-l1, masked to fg anchors
    rpn_bbox_loss = sym.smooth_l1(rpn_bbox_weight * (rpn_bbox - rpn_bbox_target),
                                  scalar=3.0)
    rpn_bbox_loss = sym.MakeLoss(sym.sum(rpn_bbox_loss) / batch,
                                 name="rpn_bbox_loss")

    # proposals (gradient-free path, like the reference)
    rpn_cls_act = sym.SoftmaxActivation(rpn_cls_flat, mode="channel",
                                        name="rpn_cls_act")
    rpn_cls_act = sym.Reshape(rpn_cls_act, shape=(0, 2 * A0, FEAT, FEAT))
    if train_rois:
        rois = sym.BlockGrad(sym.Variable("rois"), name="rois")
    else:
        rois = sym.Proposal(sym.BlockGrad(rpn_cls_act),
                            sym.BlockGrad(rpn_bbox),
                            im_info, feature_stride=STRIDE,
                            scales=(2, 4, 8), ratios=(0.5, 1, 2),
                            rpn_pre_nms_top_n=64, rpn_post_nms_top_n=POST,
                            threshold=0.7, rpn_min_size=4, name="rois")

    # fast-rcnn head
    pooled = sym.ROIPooling(feat, rois, pooled_size=(4, 4),
                            spatial_scale=1.0 / STRIDE, name="roi_pool")
    head = sym.FullyConnected(sym.Flatten(pooled), num_hidden=64, name="fc6")
    head = sym.Activation(head, act_type="relu")
    cls_score = sym.FullyConnected(head, num_hidden=NUM_CLASSES,
                                   name="cls_score")
    cls_prob = sym.SoftmaxOutput(cls_score, roi_label, use_ignore=True,
                                 ignore_label=-1, normalization="valid",
                                 name="cls_prob")
    return sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, sym.BlockGrad(rois)])


def synth_batch(rs, batch):
    """Images with bright rectangles; returns data + gt boxes/classes."""
    x = rs.rand(batch, 3, IM, IM).astype(np.float32) * 0.2
    gt = []
    for i in range(batch):
        boxes = []
        for _ in range(rs.randint(1, 3)):
            wide = rs.randint(2)
            w, h = (rs.randint(20, 32), rs.randint(8, 14)) if wide else \
                   (rs.randint(8, 14), rs.randint(20, 32))
            x1 = rs.randint(0, IM - w)
            y1 = rs.randint(0, IM - h)
            x[i, :, y1:y1 + h, x1:x1 + w] += 0.8
            boxes.append([x1, y1, x1 + w - 1, y1 + h - 1, 1 + wide])
        gt.append(np.array(boxes, np.float32))
    return np.clip(x, 0, 1), gt


def np_iou(a, b):
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    iw = np.maximum(ix2 - ix1 + 1, 0)
    ih = np.maximum(iy2 - iy1 + 1, 0)
    inter = iw * ih
    ua = ((a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1))[:, None] + \
         ((b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1))[None] - inter
    return inter / np.maximum(ua, 1e-6)


def anchor_targets(gt_list, anchors, rpn_batch=64, fg_fraction=0.5,
                   rs=None):
    """RPN targets (parity: rcnn/io/rpn.py assign_anchor): fg iou>=0.5,
    bg iou<0.3, rest ignored; bbox deltas for fg anchors.  Like the
    reference, a fixed-size anchor batch is SAMPLED per image (up to
    fg_fraction foreground) and everything else ignored — without this
    the ~100:1 bg:fg imbalance drowns the foreground gradient and the
    RPN only ever learns the class prior."""
    rs = rs or np.random
    n = len(gt_list)
    total = anchors.shape[0]
    labels = np.full((n, total), -1, np.float32)
    bbox_t = np.zeros((n, total, 4), np.float32)
    bbox_w = np.zeros((n, total, 4), np.float32)
    for i, gt in enumerate(gt_list):
        iou = np_iou(anchors, gt[:, :4])
        best = iou.max(axis=1)
        arg = iou.argmax(axis=1)
        labels[i, best < 0.3] = 0
        fg = best >= 0.5
        # guarantee at least one fg per gt (reference does the same)
        for j in range(gt.shape[0]):
            fg[iou[:, j].argmax()] = True
        labels[i, fg] = 1
        # subsample the anchor batch (assign_anchor num_batch/fg_fraction)
        fg_idx = np.where(labels[i] == 1)[0]
        n_fg = min(len(fg_idx), int(rpn_batch * fg_fraction))
        if len(fg_idx) > n_fg:
            off = rs.choice(fg_idx, len(fg_idx) - n_fg, replace=False)
            labels[i, off] = -1
        bg_idx = np.where(labels[i] == 0)[0]
        n_bg = rpn_batch - n_fg
        if len(bg_idx) > n_bg:
            off = rs.choice(bg_idx, len(bg_idx) - n_bg, replace=False)
            labels[i, off] = -1
        fg = labels[i] == 1
        g = gt[arg[fg], :4]
        a = anchors[fg]
        aw = a[:, 2] - a[:, 0] + 1
        ah = a[:, 3] - a[:, 1] + 1
        acx = a[:, 0] + 0.5 * (aw - 1)
        acy = a[:, 1] + 0.5 * (ah - 1)
        gw = g[:, 2] - g[:, 0] + 1
        gh = g[:, 3] - g[:, 1] + 1
        gcx = g[:, 0] + 0.5 * (gw - 1)
        gcy = g[:, 1] + 0.5 * (gh - 1)
        bbox_t[i, fg] = np.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                                  np.log(gw / aw), np.log(gh / ah)], axis=1)
        bbox_w[i, fg] = 1.0
    return labels, bbox_t, bbox_w


def roi_targets(rois, gt_list):
    """Head classification targets for the proposals of the LAST forward
    (parity: proposal_target.py): class of best-iou gt if iou>=0.5 else 0."""
    labels = np.zeros((rois.shape[0],), np.float32)
    for r in range(rois.shape[0]):
        i = int(rois[r, 0])
        gt = gt_list[i]
        iou = np_iou(rois[r:r + 1, 1:5], gt[:, :4])[0]
        j = iou.argmax()
        labels[r] = gt[j, 4] if iou[j] >= 0.5 else 0.0
    return labels


def evaluate(ex, rs, args, im_info, n_batches=8):
    """Detection mAP over held-out synthetic batches (parity:
    example/rcnn test/eval flow): proposals are the boxes, the head's
    softmax picks class+score, the shared VOC metric ranks them."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "..", "ssd"))
    from eval_metric import VOC07MApMetric

    m = VOC07MApMetric()
    for _ in range(n_batches):
        x, gt = synth_batch(rs, args.batch)
        zero = np.zeros
        ex.forward(is_train=False, data=x, im_info=im_info,
                   rpn_label=zero((args.batch, A0 * FEAT * FEAT), np.float32),
                   rpn_bbox_target=zero((args.batch, 4 * A0, FEAT, FEAT),
                                        np.float32),
                   rpn_bbox_weight=zero((args.batch, 4 * A0, FEAT, FEAT),
                                        np.float32),
                   roi_label=zero((args.batch * POST,), np.float32))
        rois = ex.outputs[3].asnumpy()              # (B*POST, 5)
        probs = ex.outputs[2].asnumpy()             # (B*POST, C)
        cls = probs.argmax(1).astype(np.float32)
        score = probs.max(1)
        dets = np.full((args.batch, POST, 6), -1.0, np.float32)
        counts = [0] * args.batch
        for r in range(rois.shape[0]):
            b = int(rois[r, 0])
            if cls[r] == 0:                         # background
                continue
            dets[b, counts[b]] = [cls[r], score[r], *rois[r, 1:5]]
            counts[b] += 1
        labels = np.full((args.batch, 4, 5), -1.0, np.float32)
        for b, g in enumerate(gt):
            for j, row in enumerate(g):
                labels[b, j] = [row[4], row[0], row[1], row[2], row[3]]
        m.update([labels], [dets])
    return m.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--eval", action="store_true",
                    help="compute detection mAP after training")
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    np.random.seed(0)  # deterministic Xavier init (initializers use np.random)

    base = _generate_anchors(STRIDE, (2, 4, 8), (0.5, 1, 2))
    sx, sy = np.meshgrid(np.arange(FEAT) * STRIDE, np.arange(FEAT) * STRIDE)
    shifts = np.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()], 1)
    anchors = (shifts[:, None].astype(np.float32) + base[None]).reshape(-1, 4)

    # TRAIN graph: head pools host-supplied rois (proposals + gt boxes,
    # the reference's proposal_target flow).  PROPOSAL/EVAL graph: head
    # pools the in-graph Proposal output.  Both share the same parameter
    # NDArrays, so one update serves both.
    net = build_symbol(args.batch, train_rois=True)
    ex = net.simple_bind(
        ctx=mx.context.default_accelerator_context(), grad_req="write",
        data=(args.batch, 3, IM, IM),
        rpn_label=(args.batch, A0 * FEAT * FEAT),
        rpn_bbox_target=(args.batch, 4 * A0, FEAT, FEAT),
        rpn_bbox_weight=(args.batch, 4 * A0, FEAT, FEAT),
        rois=(args.batch * POST, 5),
        roi_label=(args.batch * POST,))
    init = mx.init.Xavier()
    params = {}
    for name, arr in ex.arg_dict.items():
        if name.endswith(("weight", "bias")) and "rpn_bbox_target" not in name:
            init(name, arr)
            params[name] = arr

    eval_net = build_symbol(args.batch, train_rois=False)
    eval_args = {}
    for name in eval_net.list_arguments():
        if name in ex.arg_dict:
            eval_args[name] = ex.arg_dict[name]  # SHARED NDArray
        else:
            shp = {"data": (args.batch, 3, IM, IM),
                   "im_info": (args.batch, 3)}.get(name)
            eval_args[name] = mx.nd.zeros(shp) if shp else mx.nd.zeros((1,))
    eval_ex = eval_net.bind(ctx=mx.context.default_accelerator_context(),
                            args=eval_args, args_grad=None, grad_req="null")
    opt = mx.optimizer.create("sgd", learning_rate=args.lr, momentum=0.9,
                              rescale_grad=1.0 / args.batch)
    updater = mx.optimizer.get_updater(opt)

    im_info = np.array([[IM, IM, 1.0]] * args.batch, np.float32)
    first = last = None
    for step in range(args.steps):
        x, gt = synth_batch(rs, args.batch)
        labels, bt, bw = anchor_targets(gt, anchors, rs=rs)
        # anchor layout in Proposal/loss: (H, W, A0) flattened; the rpn
        # label reshape (N, 2, A0*FH*FW) maps channel-major — match it
        lab = labels.reshape(args.batch, FEAT, FEAT, A0)
        lab = lab.transpose(0, 3, 1, 2).reshape(args.batch, -1)
        bt4 = bt.reshape(args.batch, FEAT, FEAT, A0, 4)
        bt4 = bt4.transpose(0, 3, 4, 1, 2).reshape(args.batch, 4 * A0, FEAT, FEAT)
        bw4 = bw.reshape(args.batch, FEAT, FEAT, A0, 4)
        bw4 = bw4.transpose(0, 3, 4, 1, 2).reshape(args.batch, 4 * A0, FEAT, FEAT)
        # proposal-target stage (parity: proposal_target.py): the eval
        # graph yields THIS batch's proposals; gt boxes are APPENDED
        # (overwriting the tail rows) so the head always sees foreground
        # samples, exactly as the reference's sampler guarantees
        eval_ex.forward(is_train=False, data=x, im_info=im_info,
                        rpn_label=lab, rpn_bbox_target=bt4,
                        rpn_bbox_weight=bw4,
                        roi_label=np.zeros((args.batch * POST,), np.float32))
        rois = eval_ex.outputs[3].asnumpy().copy()
        for i in range(args.batch):
            for j, g in enumerate(gt[i]):
                rois[i * POST + POST - 1 - j] = [i, g[0], g[1], g[2], g[3]]
        roi_labels = roi_targets(rois, gt)

        ex.forward(is_train=True, data=x, rpn_label=lab,
                   rpn_bbox_target=bt4, rpn_bbox_weight=bw4,
                   rois=rois, roi_label=roi_labels)
        ex.backward()
        for i, (name, arr) in enumerate(sorted(params.items())):
            updater(i, ex.grad_dict[name], arr)

        probs = ex.outputs[0].asnumpy().reshape(args.batch, 2, -1)
        mask = lab >= 0
        fg = np.where(lab > 0, 1, 0)
        picked = np.take_along_axis(probs, fg[:, None, :], axis=1)[:, 0]
        loss = -np.log(np.maximum(picked[mask], 1e-8)).mean()
        if step == 0:
            first = loss
        last = loss
        if step % 5 == 0:
            print(f"step {step}: rpn_cls_loss {loss:.4f}")
    print(f"first {first:.4f} last {last:.4f}")
    assert last < first, "rpn loss did not decrease"
    print("TRAIN OK")
    if args.eval:
        mAP = evaluate(eval_ex, rs, args, im_info)
        print(f"mAP: {mAP:.4f}")


if __name__ == "__main__":
    main()
