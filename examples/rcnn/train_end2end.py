#!/usr/bin/env python
"""End-to-end joint Faster R-CNN training (parity:
example/rcnn/train_end2end.py): AnchorLoader feeds RPN targets,
proposal_target samples the head batch from the previous forward's
proposals, all four losses (rpn cls, rpn bbox, rcnn cls, rcnn bbox)
train jointly, the four reference metrics log per interval, and eval
reports VOC07 mAP from per-class decoded + NMSed head detections.

Run:  MXTPU_PLATFORM=cpu python train_end2end.py --steps 150 \
          --assert-map 0.3
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from rcnn import config as cfg_mod  # noqa: E402
from rcnn.detect import eval_map  # noqa: E402
from rcnn.loader import AnchorLoader  # noqa: E402
from rcnn.metric import (RCNNAccuracy, RCNNLogLoss, RPNAccuracy,  # noqa: E402
                         RPNLogLoss)
from rcnn.train_utils import build_executors, current_proposals  # noqa: E402
from rcnn.targets import sample_rois  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--images", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--log-interval", type=int, default=10)
    ap.add_argument("--assert-map", type=float, default=None)
    ap.add_argument("--save-prefix", type=str, default=None,
                    help="save a Module-format checkpoint after training "
                         "(demo.py loads it)")
    args = ap.parse_args()
    cfg = cfg_mod.default
    rs = np.random.RandomState(0)
    np.random.seed(0)  # initializers draw from numpy's global RNG

    loader = AnchorLoader(cfg, n_images=args.images,
                          batch_size=args.batch)
    b = args.batch
    ctx = mx.context.default_accelerator_context()
    # shared plumbing (rcnn/train_utils.py) — note this also fixes the
    # old substring param filter that silently left rpn_bbox_pred's
    # weight/bias untrained at bind-time zeros
    ex, eval_ex, params = build_executors(cfg, b, ctx, loader)

    opt = mx.optimizer.create("sgd", learning_rate=args.lr, momentum=0.9,
                              rescale_grad=1.0 / b)
    updater = mx.optimizer.get_updater(opt)
    metrics = [RPNAccuracy(), RPNLogLoss(), RCNNAccuracy(), RCNNLogLoss()]

    step = 0
    tic = time.perf_counter()
    while step < args.steps:
        loader.reset()
        for batch in loader:
            if step >= args.steps:
                break
            lab, bt4, bw4 = batch.label
            # stage 1: this batch's proposals from the CURRENT weights
            proposals = current_proposals(eval_ex, batch, cfg)
            # stage 2: proposal_target sampling
            rois, roi_label, bbox_t, bbox_w = sample_rois(
                proposals, batch.gt, cfg, rs=rs)
            # stage 3: joint forward/backward on the sampled batch
            ex.forward(is_train=True, data=batch.data[0], rpn_label=lab,
                       rpn_bbox_target=bt4, rpn_bbox_weight=bw4,
                       rois=rois, roi_label=roi_label,
                       bbox_target=bbox_t, bbox_weight=bbox_w)
            ex.backward()
            for i, (name, arr) in enumerate(sorted(params.items())):
                updater(i, ex.grad_dict[name], arr)
            metrics[0].update([lab], [ex.outputs[0].asnumpy()
                                      .reshape(b, 2, -1)])
            metrics[1].update([lab], [ex.outputs[0].asnumpy()
                                      .reshape(b, 2, -1)])
            metrics[2].update([roi_label], [ex.outputs[2].asnumpy()])
            metrics[3].update([roi_label], [ex.outputs[2].asnumpy()])
            step += 1
            if step % args.log_interval == 0:
                vals = "  ".join("%s=%.4f" % m.get() for m in metrics)
                rate = args.log_interval * b / (time.perf_counter() - tic)
                print(f"step {step}  {vals}  ({rate:.1f} img/s)")
                for m in metrics:
                    m.reset()
                tic = time.perf_counter()

    # held-out eval: fresh images the detector never trained on
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "ssd"))
    from eval_metric import VOC07MApMetric

    heldout = AnchorLoader(cfg, n_images=32, batch_size=b, seed=99,
                           shuffle=False)
    mAP = eval_map(eval_ex, heldout, cfg, VOC07MApMetric())
    print("VOC07_mAP: %.4f" % mAP)
    if args.save_prefix:
        mx.model.save_checkpoint(
            args.save_prefix, 0, eval_ex.symbol,
            {k: v for k, v in params.items()}, {})
        print("saved %s-0000.params" % args.save_prefix)
    if args.assert_map is not None:
        assert mAP > args.assert_map, \
            f"mAP {mAP:.4f} below floor {args.assert_map}"
        print("MAP_FLOOR_OK")


if __name__ == "__main__":
    main()
