#!/usr/bin/env python
"""Bayesian learning via SGLD (parity: example/bayesian-methods/sgld.py):
stochastic gradient Langevin dynamics — SGD plus Gaussian gradient noise
— collects posterior weight samples whose averaged predictions beat any
single sample."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402


def build_net():
    data = sym.Variable("data")
    net = sym.FullyConnected(sym.Flatten(data), num_hidden=64, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--burn-in-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(4096, 512)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch_size)

    net = build_net()
    mod = mx.mod.Module(net)
    samples = []

    def collect(epoch, symbol, arg_params, aux_params):
        if epoch >= args.burn_in_epochs:
            samples.append({k: v.copy() for k, v in arg_params.items()})

    mod.fit(train, num_epoch=args.num_epochs, optimizer="sgld",
            optimizer_params={"learning_rate": args.lr, "wd": 1e-4},
            epoch_end_callback=collect,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 30))

    # posterior predictive = average softmax over weight samples
    probs = np.zeros((len(xte), 10), np.float32)
    scorer = mx.mod.Module(net)
    scorer.bind(data_shapes=[("data", (args.batch_size,) + xte.shape[1:])],
                for_training=False, label_shapes=None)
    for s in samples:
        scorer.set_params(s, {}, allow_missing=True)
        val.reset()
        preds = scorer.predict(val)
        probs += preds.asnumpy()[: len(xte)]
    ensemble_acc = float((probs.argmax(axis=1) == yte).mean())
    single_acc = mod.score(val, "acc")[0][1]
    logging.info("last-sample acc %.3f, posterior-averaged acc %.3f "
                 "(%d samples)", single_acc, ensemble_acc, len(samples))
