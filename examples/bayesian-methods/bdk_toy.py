#!/usr/bin/env python
"""Bayesian Dark Knowledge on the classic cubic-regression toy
(parity: example/bayesian-methods/bdk_demo.py + algos.py — there, an
SGLD teacher's posterior predictive is distilled into one student net
that carries the uncertainty; same system here, asserted not eyeballed).

Three framework features get exercised end to end:
  - the SGLD optimizer as a POSTERIOR SAMPLER (weight decay = gaussian
    prior, rescale_grad = full-data likelihood scaling, per-step
    gaussian noise), driven through the Module update loop,
  - posterior-predictive assembly from weight samples (mean + variance
    over an input grid),
  - a custom distillation loss via MakeLoss: the student outputs
    (mean, log-variance) and minimizes the gaussian NLL of the
    TEACHER'S predictive distribution — (mu_t - mu_s)^2 + var_t inside
    the quadratic term, the BDK objective.

Asserts: the teacher's predictive mean tracks y=x^3 inside the data;
its predictive std GROWS outside the data (the Bayesian claim); the
student reproduces both.

Run:  MXTPU_PLATFORM=cpu python bdk_toy.py
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

NOISE_STD = 0.1    # observation noise on the NORMALIZED scale
X_SCALE, Y_SCALE = 4.0, 30.0


def true_fn(x):
    return (X_SCALE * x) ** 3 / Y_SCALE


def make_data(rs, n):
    x = rs.uniform(-1.0, 1.0, n).astype(np.float32)          # x/4 in [-1,1]
    y = true_fn(x) + rs.normal(0, NOISE_STD, n).astype(np.float32)
    return x[:, None], y.astype(np.float32)


def teacher_symbol(hidden):
    data = sym.Variable("data")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=hidden,
                                          name="t_fc1"), act_type="relu")
    pred = sym.FullyConnected(h, num_hidden=1, name="t_fc2")
    return sym.LinearRegressionOutput(sym.Flatten(pred),
                                      sym.Variable("y_label"), name="reg")


def student_symbol(hidden):
    """Heteroscedastic student: outputs (mu, log var); MakeLoss carries
    the BDK objective  0.5*logvar + ((mu_t - mu)^2 + var_t)/(2*var)."""
    data = sym.Variable("data")
    mu_t = sym.Variable("mu_t")          # teacher predictive mean
    var_t = sym.Variable("var_t")        # teacher predictive variance
    h = sym.Activation(sym.FullyConnected(data, num_hidden=hidden,
                                          name="s_fc1"), act_type="relu")
    out = sym.FullyConnected(h, num_hidden=2, name="s_fc2")
    mu = sym.slice_axis(out, axis=1, begin=0, end=1)
    logv = sym.slice_axis(out, axis=1, begin=1, end=2)
    logv = sym.clip(logv, a_min=-8.0, a_max=4.0)
    nll = 0.5 * logv + (sym.square(mu - mx.sym.Reshape(mu_t, shape=(-1, 1)))
                        + mx.sym.Reshape(var_t, shape=(-1, 1))) \
        * sym.exp(-logv) * 0.5
    loss = sym.MakeLoss(sym.mean(nll), name="bdk_loss")
    # expose mu/logv for prediction alongside the loss head
    return sym.Group([loss, sym.BlockGrad(mu), sym.BlockGrad(logv)])


def fit_teacher_sgld(args, x, y, grid):
    """SGLD over the teacher posterior; returns predictive mean/var on
    the grid assembled from post-burn-in weight samples."""
    n = len(x)
    mod = mx.mod.Module(teacher_symbol(args.hidden), data_names=("data",),
                        label_names=("y_label",))
    it = mx.io.NDArrayIter({"data": x}, {"y_label": y},
                           batch_size=args.batch, shuffle=True)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    # SGLD hyperparameters ARE the Bayesian model: rescale_grad scales
    # the minibatch gradient to the full-data log-likelihood (N/batch
    # over the noise variance), wd is the gaussian prior precision
    mod.init_optimizer(optimizer="sgld", optimizer_params={
        "learning_rate": args.sgld_lr,
        "rescale_grad": n / args.batch / (NOISE_STD ** 2),
        "wd": 1.0})
    pred_mod = mx.mod.Module(teacher_symbol(args.hidden),
                             data_names=("data",), label_names=("y_label",))
    pred_mod.bind(data_shapes=[("data", (len(grid), 1))],
                  label_shapes=[("y_label", (len(grid),))],
                  for_training=False, shared_module=mod)
    moments = np.zeros((2, len(grid)), np.float64)
    count, step = 0, 0
    while count < args.samples:
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            step += 1
            if step > args.burn_in and step % args.thin == 0:
                pred_mod.forward(mx.io.DataBatch(
                    [mx.nd.array(grid[:, None])],
                    [mx.nd.zeros((len(grid),))]), is_train=False)
                p = pred_mod.get_outputs()[0].asnumpy().ravel()
                moments[0] += p
                moments[1] += p * p
                count += 1
                if count >= args.samples:
                    break
    mean = moments[0] / count
    var = np.maximum(moments[1] / count - mean ** 2, 1e-8) + NOISE_STD ** 2
    return mean.astype(np.float32), var.astype(np.float32)


def fit_student(args, mu_t, var_t, grid):
    smod = mx.mod.Module(student_symbol(args.hidden),
                         data_names=("data",), label_names=("mu_t", "var_t"))
    it = mx.io.NDArrayIter({"data": grid[:, None]},
                           {"mu_t": mu_t, "var_t": var_t},
                           batch_size=args.batch, shuffle=True)
    smod.fit(it, num_epoch=args.student_epochs, optimizer="adam",
             optimizer_params={"learning_rate": 3e-3},
             initializer=mx.init.Xavier(),
             eval_metric=mx.metric.Torch())
    smod_p = mx.mod.Module(student_symbol(args.hidden),
                           data_names=("data",),
                           label_names=("mu_t", "var_t"))
    smod_p.bind(data_shapes=[("data", (len(grid), 1))],
                label_shapes=[("mu_t", (len(grid),)),
                              ("var_t", (len(grid),))],
                for_training=False, shared_module=smod)
    smod_p.forward(mx.io.DataBatch(
        [mx.nd.array(grid[:, None])],
        [mx.nd.array(mu_t), mx.nd.array(var_t)]), is_train=False)
    outs = smod_p.get_outputs()
    mu_s = outs[1].asnumpy().ravel()
    var_s = np.exp(outs[2].asnumpy().ravel())
    return mu_s, var_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--n", type=int, default=160)
    ap.add_argument("--sgld-lr", type=float, default=4e-6)
    ap.add_argument("--burn-in", type=int, default=600)
    ap.add_argument("--thin", type=int, default=10)
    ap.add_argument("--samples", type=int, default=150)
    ap.add_argument("--student-epochs", type=int, default=300)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    logging.getLogger().setLevel(logging.WARNING)  # quiet the fit loop
    rs = np.random.RandomState(0)
    np.random.seed(0)
    mx.random.seed(0)

    x, y = make_data(rs, args.n)
    # grid spans BEYOND the data: the out-of-distribution region is
    # where the posterior must show its uncertainty
    grid = np.linspace(-1.5, 1.5, 121).astype(np.float32)

    mu_t, var_t = fit_teacher_sgld(args, x, y, grid)
    inside = np.abs(grid) <= 0.75
    outside = np.abs(grid) >= 1.25
    rmse_in = float(np.sqrt(np.mean(
        (mu_t[inside] - true_fn(grid[inside])) ** 2)))
    std_in = float(np.sqrt(var_t[inside]).mean())
    std_out = float(np.sqrt(var_t[outside]).mean())
    print(f"teacher: rmse(in)={rmse_in:.3f} "
          f"std(in)={std_in:.3f} std(out)={std_out:.3f} "
          f"ratio={std_out / std_in:.2f}")
    assert rmse_in < 0.25, rmse_in
    assert std_out > 1.5 * std_in, (std_in, std_out)

    mu_s, var_s = fit_student(args, mu_t, var_t, grid)
    s_rmse = float(np.sqrt(np.mean((mu_s[inside] - mu_t[inside]) ** 2)))
    s_std_in = float(np.sqrt(var_s[inside]).mean())
    s_std_out = float(np.sqrt(var_s[outside]).mean())
    print(f"student: rmse-vs-teacher(in)={s_rmse:.3f} "
          f"std(in)={s_std_in:.3f} std(out)={s_std_out:.3f} "
          f"ratio={s_std_out / s_std_in:.2f}")
    assert s_rmse < 0.25, s_rmse
    assert s_std_out > 1.3 * s_std_in, (s_std_in, s_std_out)
    print("BDK OK: posterior distilled, uncertainty preserved")


if __name__ == "__main__":
    main()
