#!/usr/bin/env python
"""Neural style transfer (parity: example/neural-style/nstyle.py +
model_vgg19.py): optimize the INPUT image against a fixed VGG-19 conv
trunk — Gram-matrix style losses on relu1_1/2_1/3_1/4_1, content loss
on relu4_2, total-variation regularization, Adam on the image with a
factor lr schedule, and early stop on relative image change
(nstyle.py's stop_eps).

TPU-first notes: the whole objective INCLUDING the TV term is one
compiled loss graph (the reference computes the TV gradient with a
separate hand-rolled depthwise conv kernel each step); the image update
runs through the framework's Adam.  Without a downloaded checkpoint the
trunk uses Xavier random weights — random VGG features carry enough
loss geometry for the demo to converge and assert; pass --params with a
VGG-19 .params file (save_checkpoint format, e.g. imported from a
reference checkpoint via mxnet_tpu.interop) for the real thing, and
--content-image/--style-image for real photos (PIL).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

STYLE_LAYERS = ("relu1_1", "relu2_1", "relu3_1", "relu4_1")
CONTENT_LAYER = "relu4_2"
MEAN = np.array([123.68, 116.779, 103.939], np.float32)  # RGB, vgg convention


def vgg19_features():
    """VGG-19 conv trunk up to relu4_2 with the reference's layer names
    (model_vgg19.py); avg pooling, as the style-transfer recipe uses."""
    cfg = [(1, 2, 64), (2, 2, 128), (3, 4, 256), (4, 4, 512)]
    data = sym.Variable("data")
    taps = {}
    body = data
    for stage, num, filters in cfg:
        for i in range(num):
            body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                                   num_filter=filters,
                                   name=f"conv{stage}_{i + 1}")
            body = sym.Activation(body, act_type="relu",
                                  name=f"relu{stage}_{i + 1}")
            taps[f"relu{stage}_{i + 1}"] = body
            if stage == 4 and i + 1 == 2:
                style = [taps[n] for n in STYLE_LAYERS]
                return style, taps[CONTENT_LAYER]
        body = sym.Pooling(body, pool_type="avg", kernel=(2, 2),
                           stride=(2, 2), name=f"pool{stage}")
    raise AssertionError("unreachable")


def make_loss(style_feats, content_feat, style_weight, content_weight,
              tv_weight):
    """One graph: weighted Gram style + content + TV, grads w.r.t. data."""
    losses = []
    for i, f in enumerate(style_feats):
        flat = sym.Reshape(f, shape=(0, 0, -1))             # (1, C, HW)
        gram = sym.batch_dot(flat, flat, transpose_b=True)  # (1, C, C)
        target = sym.Variable(f"sgram{i}")
        losses.append((style_weight / len(style_feats))
                      * sym.mean(sym.square(gram - target)))
    target_c = sym.Variable("content")
    losses.append(content_weight * sym.mean(sym.square(content_feat
                                                       - target_c)))
    img = sym.Variable("data")
    dx = sym.slice_axis(img, axis=3, begin=1, end=None) \
        - sym.slice_axis(img, axis=3, begin=0, end=-1)
    dy = sym.slice_axis(img, axis=2, begin=1, end=None) \
        - sym.slice_axis(img, axis=2, begin=0, end=-1)
    losses.append(tv_weight * (sym.mean(sym.square(dx))
                               + sym.mean(sym.square(dy))))
    total = losses[0]
    for term in losses[1:]:
        total = total + term
    return sym.MakeLoss(total, name="style_loss")


def load_image(path, size):
    from PIL import Image

    img = Image.open(path).convert("RGB").resize((size, size), Image.LANCZOS)
    arr = np.asarray(img, np.float32)  # (H, W, 3) RGB 0..255
    return (arr - MEAN).transpose(2, 0, 1)[None]


def save_image(path, arr):
    out = np.clip(arr[0].transpose(1, 2, 0) + MEAN, 0, 255).astype(np.uint8)
    try:
        from PIL import Image

        Image.fromarray(out).save(path)
    except ImportError:
        np.save(path + ".npy", out)
        path = path + ".npy"
    print(f"saved {path}")


def synth_images(rs, size):
    """Checkerboard content / wave-texture style, vgg-normalized range."""
    yy, xx = np.mgrid[0:size, 0:size]
    content = (80.0 * ((xx + yy) % 16 < 8) - 40.0
               + rs.randn(3, size, size) * 5.0)[None].astype(np.float32)
    style = (60.0 * np.sin(xx / 3.0) + rs.randn(3, size, size)
             * 5.0)[None].astype(np.float32)
    return content, style


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--content-image")
    ap.add_argument("--style-image")
    ap.add_argument("--params", help="VGG-19 .params file (converted)")
    ap.add_argument("--output", default="/tmp/nstyle_out.png")
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--style-weight", type=float, default=1.0)
    ap.add_argument("--content-weight", type=float, default=10.0)
    ap.add_argument("--tv-weight", type=float, default=1e-4)
    ap.add_argument("--stop-eps", type=float, default=0.004,
                    help="stop when relative image change falls below this")
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    ctx = mx.context.default_accelerator_context()

    if bool(args.content_image) != bool(args.style_image):
        ap.error("--content-image and --style-image must be given together")
    if args.content_image:
        content_img = load_image(args.content_image, args.size)
        style_img = load_image(args.style_image, args.size)
    else:
        content_img, style_img = synth_images(rs, args.size)

    style_feats, content_feat = vgg19_features()
    extractor = sym.Group(list(style_feats) + [content_feat])
    fe = extractor.simple_bind(ctx=ctx, grad_req="null",
                               data=content_img.shape)
    if args.params:
        loaded = mx.nd.load(args.params)
        arg_params = {k.split(":", 1)[1]: v for k, v in loaded.items()
                      if k.startswith("arg:")}
        missing = [n for n in fe.arg_dict
                   if n != "data" and n not in arg_params]
        if missing:
            # a wrong-format file would otherwise leave zero weights and
            # still "converge" on the TV term alone
            raise SystemExit(f"--params covers no value for {missing[:5]} "
                             "(expected save_checkpoint-style arg: keys)")
        fe.copy_params_from(arg_params, {}, allow_extra_params=True)
        weights = {k: v.asnumpy() for k, v in fe.arg_dict.items()
                   if k != "data"}
    else:
        init = mx.init.Xavier()
        weights = {}
        for name, arr in fe.arg_dict.items():
            if name != "data":
                init(name, arr)
                weights[name] = arr.asnumpy()

    def extract(img):
        fe.forward(is_train=False, data=img)
        outs = [o.asnumpy() for o in fe.outputs]
        grams = []
        for f in outs[:-1]:
            flat = f.reshape(f.shape[0], f.shape[1], -1)
            grams.append(np.matmul(flat, flat.transpose(0, 2, 1)))
        return grams, outs[-1]

    style_grams, _ = extract(style_img)
    _, content_tgt = extract(content_img)

    loss = make_loss(style_feats, content_feat, args.style_weight,
                     args.content_weight, args.tv_weight)
    shapes = {"data": content_img.shape, "content": content_tgt.shape}
    for i, g in enumerate(style_grams):
        shapes[f"sgram{i}"] = g.shape
    ex = loss.simple_bind(ctx=ctx, grad_req={"data": "write"}, **shapes)
    for name, w in weights.items():
        ex.arg_dict[name][:] = w
    for i, g in enumerate(style_grams):
        ex.arg_dict[f"sgram{i}"][:] = g
    ex.arg_dict["content"][:] = content_tgt

    img = mx.nd.array(content_img.copy())
    opt = mx.optimizer.create(
        "adam", learning_rate=args.lr,
        lr_scheduler=mx.lr_scheduler.FactorScheduler(step=40, factor=0.75))
    state = opt.create_state(0, img)

    first = last = None
    for step in range(args.steps):
        old = img.asnumpy()
        ex.arg_dict["data"][:] = img
        ex.forward(is_train=True)
        ex.backward()
        opt.update(0, img, ex.grad_dict["data"], state)
        new = img.asnumpy()
        last = float(ex.outputs[0].asnumpy())
        if step == 0:
            first = last
        eps = np.linalg.norm(new - old) / (np.linalg.norm(new) + 1e-12)
        if step % 20 == 0:
            print(f"step {step}: loss {last:.4f} rel-change {eps:.5f}")
        if eps < args.stop_eps:
            print(f"converged at step {step} (eps {eps:.5f})")
            break

    save_image(args.output, img.asnumpy())
    print(f"first {first:.4f} last {last:.4f}")
    assert last < first
    print("STYLE OK")


if __name__ == "__main__":
    main()
