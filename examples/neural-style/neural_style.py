#!/usr/bin/env python
"""Neural style transfer (parity: example/neural-style/).

The reference optimizes the INPUT image against a fixed conv net:
content loss on deep features, style loss on Gram matrices of shallower
features, gradients taken w.r.t. the image (inputs_need_grad / arg grad
on 'data').  Same structure here with a small random-weight encoder
(random conv features famously suffice for the loss geometry) and
synthetic content/style images, so the demo is self-contained.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

IM = 48


def encoder():
    data = sym.Variable("data")
    feats = []
    net = data
    for i, nf in enumerate((8, 16, 32)):
        net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=nf,
                              name=f"conv{i}")
        net = sym.Activation(net, act_type="relu")
        feats.append(net)
        if i < 2:
            net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                              pool_type="avg")
    return feats  # two style layers + one content layer


def style_content_loss(feats, style_grams, content_feat):
    losses = []
    for i, f in enumerate(feats[:2]):
        flat = sym.Reshape(f, shape=(0, 0, -1))           # (N, C, HW)
        gram = sym.batch_dot(flat, flat, transpose_b=True)  # (N, C, C)
        target = sym.Variable(f"gram{i}")
        losses.append(sym.mean(sym.square(gram - target)))
    target_c = sym.Variable("content")
    losses.append(0.1 * sym.mean(sym.square(feats[2] - target_c)))
    total = losses[0] + losses[1] + losses[2]
    return sym.MakeLoss(total, name="style_loss")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    rs = np.random.RandomState(0)

    ctx = mx.context.default_accelerator_context()
    feats = encoder()
    loss = style_content_loss(feats, None, None)

    # feature extraction pass: bind the bare encoder to compute targets
    grp = sym.Group(feats)
    fe = grp.simple_bind(ctx=ctx, grad_req="null", data=(1, 3, IM, IM))
    init = mx.init.Xavier()
    weights = {}
    for name, arr in fe.arg_dict.items():
        if name != "data":
            init(name, arr)
            weights[name] = arr.asnumpy()

    yy, xx = np.mgrid[0:IM, 0:IM]
    content_img = np.clip(
        0.3 + 0.7 * ((xx + yy) % 16 < 8)[None, None].astype(np.float32)
        + rs.rand(1, 3, IM, IM).astype(np.float32) * 0.1, 0, 1)
    style_img = np.clip(
        0.5 + 0.5 * np.sin(xx / 3.0)[None, None].astype(np.float32)
        + rs.rand(1, 3, IM, IM).astype(np.float32) * 0.1, 0, 1)

    def grams_and_content(img):
        fe.forward(is_train=False, data=img)
        outs = [o.asnumpy() for o in fe.outputs]
        grams = []
        for f in outs[:2]:
            flat = f.reshape(f.shape[0], f.shape[1], -1)
            grams.append(np.matmul(flat, flat.transpose(0, 2, 1)))
        return grams, outs[2]

    style_grams, _ = grams_and_content(style_img)
    _, content_feat = grams_and_content(content_img)

    ex = loss.simple_bind(ctx=ctx, grad_req={"data": "write"},
                          data=(1, 3, IM, IM), gram0=style_grams[0].shape,
                          gram1=style_grams[1].shape,
                          content=content_feat.shape)
    for name, w in weights.items():
        ex.arg_dict[name][:] = w
    ex.arg_dict["gram0"][:] = style_grams[0]
    ex.arg_dict["gram1"][:] = style_grams[1]
    ex.arg_dict["content"][:] = content_feat
    img = content_img.copy()  # optimize starting from the content image

    first = last = None
    for step in range(args.steps):
        ex.arg_dict["data"][:] = img
        ex.forward(is_train=True)
        ex.backward()
        g = ex.grad_dict["data"].asnumpy()
        img = np.clip(img - args.lr * g / (np.abs(g).mean() + 1e-8) * 0.01,
                      0, 1)
        val = float(ex.outputs[0].asnumpy())
        if step == 0:
            first = val
        last = val
        if step % 20 == 0:
            print(f"step {step}: loss {val:.5f}")
    print(f"first {first:.5f} last {last:.5f}")
    assert last < first
    print("STYLE OK")


if __name__ == "__main__":
    main()
