#!/usr/bin/env python
"""One-forward stylization with the trained generator (parity:
example/neural-style/end_to_end/boost_inference.py): load the
checkpoint train_end_to_end.py saved and push a held-out content image
through it — no per-image optimization.

Usage: python stylize.py [--image photo.jpg] [--output out.png]
"""
import argparse
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, "..", "..", ".."))
sys.path.insert(0, os.path.join(HERE, ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

from neural_style import load_image, save_image  # noqa: E402
from train_end_to_end import synth_content_batch  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--prefix", default="/tmp/fast_style/gen")
    ap.add_argument("--epoch", type=int, default=120)
    ap.add_argument("--image")
    ap.add_argument("--size", type=int, default=48)
    ap.add_argument("--output", default="/tmp/fast_style/out.png")
    args = ap.parse_args()
    if args.size % 4:
        ap.error(f"--size must be a multiple of 4 (generator has two "
                 f"stride-2 down/upsamples); got {args.size}")

    if args.image:
        img = load_image(args.image, args.size)
    else:
        img = synth_content_batch(np.random.RandomState(99), 1, args.size)

    from mxnet_tpu.predict import Predictor

    symbol, arg_params, aux_params = mx.model.load_checkpoint(
        args.prefix, args.epoch)
    p = Predictor(symbol=symbol, arg_params=arg_params,
                  aux_params=aux_params,
                  input_shapes={"data": img.shape},
                  dev_type=mx.context.default_accelerator_context())
    p.forward(data=img)
    out = p.get_output(0)
    assert out.shape == img.shape
    assert float(np.abs(out - img).mean()) > 1.0  # it did SOMETHING
    save_image(args.output, out)
    print("STYLIZE OK")


if __name__ == "__main__":
    main()
