"""Feed-forward style-transfer generator (parity:
example/neural-style/end_to_end/{basic,gen_v3,gen_v4}.py — the
reference's trained generators that replace per-image optimization
with one forward pass).

Architecture (the Johnson-et-al shape the reference's gen_v4
approximates): reflection-ish padded conv stem, two stride-2
downsamples, residual blocks, two deconv upsamples, tanh output scaled
to the vgg-normalized range.  InstanceNorm throughout — the
style-transfer-critical normalization (batch stats bleed styles across
images).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

from mxnet_tpu import sym  # noqa: E402


def _conv_in_relu(x, num_filter, kernel, stride, name):
    pad = (kernel // 2, kernel // 2)
    x = sym.Convolution(x, kernel=(kernel, kernel), stride=(stride, stride),
                        pad=pad, num_filter=num_filter, name=f"{name}_conv")
    x = sym.InstanceNorm(x, name=f"{name}_in")
    return sym.Activation(x, act_type="relu", name=f"{name}_relu")


def _res_block(x, num_filter, name):
    h = _conv_in_relu(x, num_filter, 3, 1, f"{name}_a")
    h = sym.Convolution(h, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        num_filter=num_filter, name=f"{name}_b_conv")
    h = sym.InstanceNorm(h, name=f"{name}_b_in")
    return x + h


def _deconv_in_relu(x, num_filter, name):
    x = sym.Deconvolution(x, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
                          num_filter=num_filter, name=f"{name}_deconv")
    x = sym.InstanceNorm(x, name=f"{name}_in")
    return sym.Activation(x, act_type="relu", name=f"{name}_relu")


def generator(prefix="g", base=16, n_res=3, out_scale=150.0):
    """data (N,3,H,W) -> stylized (N,3,H,W), vgg-normalized range."""
    data = sym.Variable("data")
    x = _conv_in_relu(data, base, 9, 1, f"{prefix}0")
    x = _conv_in_relu(x, base * 2, 3, 2, f"{prefix}1")
    x = _conv_in_relu(x, base * 4, 3, 2, f"{prefix}2")
    for i in range(n_res):
        x = _res_block(x, base * 4, f"{prefix}res{i}")
    x = _deconv_in_relu(x, base * 2, f"{prefix}3")
    x = _deconv_in_relu(x, base, f"{prefix}4")
    x = sym.Convolution(x, kernel=(9, 9), stride=(1, 1), pad=(4, 4),
                        num_filter=3, name=f"{prefix}out_conv")
    return out_scale * sym.Activation(x, act_type="tanh",
                                      name=f"{prefix}out_tanh")
