#!/usr/bin/env python
"""Train a feed-forward style generator against a fixed perceptual loss
(parity: example/neural-style/end_to_end/train.py — the reference
chains a generator executor into the VGG descriptor executor and routes
the style/content gradients back through the generator; same two-
executor manual grad routing here).

  content batch -> generator -> stylized image
                                  |  (grad w.r.t. data flows back)
                stylized image -> VGG loss graph (style grams fixed from
                                  ONE style image; content target = the
                                  input batch's own VGG features)

After training, stylize.py runs the saved generator on held-out images
in one forward.  Synthetic content/style images keep it standalone;
point --params at converted VGG weights and feed real images for the
real recipe.
"""
import argparse
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, "..", "..", ".."))
sys.path.insert(0, os.path.join(HERE, ".."))
sys.path.insert(0, HERE)

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

from gen_model import generator  # noqa: E402
from neural_style import (MEAN, make_loss, synth_images,  # noqa: E402
                          vgg19_features)


def synth_content_batch(rs, n, size):
    """Random checkerboards/stripes with varying phase and scale."""
    out = np.zeros((n, 3, size, size), np.float32)
    yy, xx = np.mgrid[0:size, 0:size]
    for i in range(n):
        kind = rs.randint(3)
        period = int(rs.randint(8, 24))
        phase = int(rs.randint(period))
        if kind == 0:
            base = 80.0 * (((xx + yy + phase) % period) < period // 2) - 40.0
        elif kind == 1:
            base = 80.0 * (((xx + phase) % period) < period // 2) - 40.0
        else:
            base = 60.0 * np.sin((yy + phase) / (period / 6.0))
        out[i] = base + rs.randn(3, size, size) * 5.0
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=48)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--iters", type=int, default=120)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--style-weight", type=float, default=1.0)
    ap.add_argument("--content-weight", type=float, default=4.0)
    ap.add_argument("--tv-weight", type=float, default=1e-4)
    ap.add_argument("--prefix", default="/tmp/fast_style/gen")
    args = ap.parse_args()
    if args.size % 4:
        ap.error(f"--size must be a multiple of 4 (two stride-2 "
                 f"down/upsamples); got {args.size}")
    rs = np.random.RandomState(0)
    ctx = mx.context.default_accelerator_context()
    shape = (args.batch, 3, args.size, args.size)

    # ---- fixed descriptor: VGG feature extractor + perceptual loss ----
    style_feats, content_feat = vgg19_features()
    fe = sym.Group(list(style_feats) + [content_feat]).simple_bind(
        ctx=ctx, grad_req="null", data=shape)
    init = mx.init.Xavier()
    vgg_weights = {}
    for name, arr in fe.arg_dict.items():
        if name != "data":
            init(name, arr)
            vgg_weights[name] = arr.asnumpy()

    def extract(imgs):
        fe.forward(is_train=False, data=imgs)
        outs = [o.asnumpy() for o in fe.outputs]
        grams = []
        for f in outs[:-1]:
            flat = f.reshape(f.shape[0], f.shape[1], -1)
            grams.append(np.matmul(flat, flat.transpose(0, 2, 1))
                         .mean(axis=0, keepdims=True))
        return grams, outs[-1]

    _, style_img = synth_images(rs, args.size)
    style_grams, _ = extract(np.repeat(style_img, args.batch, axis=0))

    loss = make_loss(style_feats, content_feat, args.style_weight,
                     args.content_weight, args.tv_weight)
    lshapes = {"data": shape,
               "content": (args.batch,) + fe.outputs[-1].shape[1:]}
    # style targets are the ONE style image's grams repeated per sample
    for i, g in enumerate(style_grams):
        lshapes[f"sgram{i}"] = (args.batch,) + g.shape[1:]
    dex = loss.simple_bind(ctx=ctx, grad_req={"data": "write"}, **lshapes)
    for name, w in vgg_weights.items():
        dex.arg_dict[name][:] = w
    for i, g in enumerate(style_grams):
        dex.arg_dict[f"sgram{i}"][:] = np.repeat(g, args.batch, axis=0)

    # ---- trainable generator module: the RAW symbol, so backward()
    # takes the descriptor's dLoss/dImage as its head gradient (MakeLoss
    # would override it with ones — the dcgan example's routing) ----
    gen = generator()
    gmod = mx.mod.Module(gen, context=ctx,
                         data_names=("data",), label_names=())
    gmod.bind(data_shapes=[("data", shape)], label_shapes=None,
              for_training=True)
    gmod.init_params(mx.init.Xavier())
    gmod.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr})

    first = last = None
    for it in range(args.iters):
        batch = synth_content_batch(rs, args.batch, args.size)
        _, content_tgt = extract(batch)
        dex.arg_dict["content"][:] = content_tgt

        gmod.forward(mx.io.DataBatch(data=[mx.nd.array(batch)], label=None),
                     is_train=True)
        stylized = gmod.get_outputs()[0]

        dex.arg_dict["data"][:] = stylized
        dex.forward(is_train=True)
        dex.backward()
        grad = dex.grad_dict["data"]

        gmod.backward(out_grads=[grad])
        gmod.update()

        last = float(dex.outputs[0].asnumpy())
        if it == 0:
            first = last
        if it % 20 == 0:
            print(f"iter {it}: perceptual loss {last:.1f}")

    os.makedirs(os.path.dirname(args.prefix), exist_ok=True)
    arg_params, aux_params = gmod.get_params()
    mx.model.save_checkpoint(args.prefix, args.iters, sym.Group([gen]),
                             arg_params, aux_params)
    print(f"first {first:.1f} last {last:.1f}")
    assert last < first * 0.7, (first, last)
    print("E2E TRAIN OK")


if __name__ == "__main__":
    main()
