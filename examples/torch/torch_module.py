#!/usr/bin/env python
"""Use PyTorch layers inside an mxnet_tpu graph (parity: example/torch/
torch_module.py — which embedded Lua-torch nn modules).

``TorchModule`` runs the torch layer on the host behind the compiled XLA
graph (pure_callback + custom VJP via torch autograd); its parameters
are ordinary graph inputs, trained by the framework optimizer.  Here a
torch ``Linear`` replaces the hidden layer of an MLP and trains to the
same accuracy as the native version."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402
import torch  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.plugins import torch_plugin as tp  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description="torch layer inside mxnet_tpu")
    ap.add_argument("--batch-size", type=int, default=50)
    ap.add_argument("--num-epochs", type=int, default=3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rs = np.random.RandomState(0)
    x = rs.uniform(0, 1, (2000, 32)).astype(np.float32)
    w = rs.normal(size=(32, 5)).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.float32)
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)

    hidden = torch.nn.Linear(32, 64)
    mid = tp.register_module(hidden)

    data = mx.sym.Variable("data")
    # torch params are plain graph inputs; their shapes come from the
    # torch layer, so declare them for shape inference
    tw = mx.sym.Variable("torch_weight", shape=(64, 32))
    tb = mx.sym.Variable("torch_bias", shape=(64,))
    net = mx.sym.TorchModule(data, tw, tb, module_id=mid, name="torch_fc")
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc_out", num_hidden=5)
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net)
    mod.fit(train,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    metric = mx.metric.Accuracy()
    mod.score(train, metric)
    logging.info("torch-hybrid MLP: train %s", metric.get())


if __name__ == "__main__":
    main()
