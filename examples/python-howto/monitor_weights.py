#!/usr/bin/env python
"""Watch per-tensor statistics during training with mx.mon.Monitor
(parity: example/python-howto/monitor_weights.py).

The monitor taps every op output (and optionally weights) matching a
regex each `interval` batches — the observability hook for diagnosing
exploding/vanishing activations.  On TPU the taps are compiled once and
fetched only on monitored steps (executor.py _run_monitor)."""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def norm_stat(d):
    return mx.nd.norm(d) / np.sqrt(d.size)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(0)
    x = rs.uniform(0, 1, (1000, 64)).astype(np.float32)
    y = rs.randint(0, 10, 1000).astype(np.float32)
    train = mx.io.NDArrayIter(x, y, 50, shuffle=True)

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.Variable("data"),
                                      name="fc1", num_hidden=32),
                name="relu1", act_type="relu"),
            name="fc2", num_hidden=10),
        name="softmax")

    mon = mx.mon.Monitor(10, stat_func=norm_stat,
                         pattern=".*weight|.*output", sort=True)
    mod = mx.mod.Module(net)
    mod.fit(train, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            monitor=mon,
            batch_end_callback=mx.callback.Speedometer(50, 10))
