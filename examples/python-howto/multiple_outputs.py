#!/usr/bin/env python
"""Expose intermediate tensors as extra outputs (parity:
example/python-howto/multiple_outputs.py).

Two mechanisms:
1. ``mx.sym.Group([a, b])`` — bind a graph with several heads.
2. ``net.get_internals()`` — list every internal output of an existing
   symbol and re-bind a subgraph ending at any of them (the feature-
   extraction idiom used by fine-tune.py and neural-style)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

if __name__ == "__main__":
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=10)
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")

    # 1. group two heads into one executor
    group = mx.sym.Group([out, fc1])
    print("group outputs:", group.list_outputs())
    ex = group.simple_bind(ctx=mx.cpu(), data=(4, 16))
    ex.arg_dict["data"][:] = np.random.uniform(size=(4, 16))
    ex.forward(is_train=False)
    print("softmax:", ex.outputs[0].shape, " fc1:", ex.outputs[1].shape)

    # 2. carve a feature subgraph out of a finished network
    internals = out.get_internals()
    print("internals:", internals.list_outputs()[:8], "...")
    feat = internals["relu1_output"]
    fex = feat.simple_bind(ctx=mx.cpu(), data=(4, 16))
    fex.arg_dict["data"][:] = np.random.uniform(size=(4, 16))
    fex.forward(is_train=False)
    print("relu1 features:", fex.outputs[0].shape)
