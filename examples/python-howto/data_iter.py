#!/usr/bin/env python
"""How to write a custom DataIter (parity: example/python-howto/
data_iter.py).

A DataIter yields DataBatch objects and advertises its shapes through
``provide_data`` / ``provide_label`` so ``Module.bind`` can allocate
executors before the first batch arrives."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


class SimpleIter(mx.io.DataIter):
    """Generates batches from a user-supplied callable."""

    def __init__(self, data_shapes, label_shapes, data_gen, label_gen,
                 num_batches=10):
        super().__init__()
        self._provide_data = [mx.io.DataDesc(n, s) for n, s in data_shapes]
        self._provide_label = [mx.io.DataDesc(n, s) for n, s in label_shapes]
        self.num_batches = num_batches
        self.data_gen = data_gen
        self.label_gen = label_gen
        self.cur_batch = 0

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        self.cur_batch = 0

    def next(self):
        if self.cur_batch >= self.num_batches:
            raise StopIteration
        self.cur_batch += 1
        data = [mx.nd.array(self.data_gen(d.shape))
                for d in self._provide_data]
        label = [mx.nd.array(self.label_gen(d.shape))
                 for d in self._provide_label]
        return mx.io.DataBatch(data, label,
                               pad=0, index=None,
                               provide_data=self._provide_data,
                               provide_label=self._provide_label)


if __name__ == "__main__":
    n, batch = 32, 16
    rs = np.random.RandomState(0)
    it = SimpleIter([("data", (batch, n))], [("softmax_label", (batch,))],
                    lambda shape: rs.uniform(size=shape),
                    lambda shape: rs.randint(0, 4, shape), num_batches=20)

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4),
        name="softmax")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    print("custom iterator drove fit() for 2 epochs")
