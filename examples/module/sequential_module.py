#!/usr/bin/env python
"""SequentialModule: chain independently-defined Modules into one
trainable pipeline (parity: example/module/sequential_module.py).

The first sub-module consumes the data; each later one consumes the
previous outputs; only the last gets labels.  ``take_labels`` routes the
loss, and intermediate modules receive gradients through
``inputs_need_grad`` chaining — the same plumbing a GAN or a frozen-trunk
fine-tune uses manually."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description="SequentialModule demo")
    ap.add_argument("--batch-size", type=int, default=50)
    ap.add_argument("--num-epochs", type=int, default=2)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rs = np.random.RandomState(0)
    x = rs.uniform(0, 1, (2000, 32)).astype(np.float32)
    w = rs.normal(size=(32, 5)).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.float32)
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)

    # trunk module: features only, no loss
    data = mx.sym.Variable("data")
    trunk = mx.sym.Activation(
        mx.sym.FullyConnected(data, name="fc1", num_hidden=64),
        name="relu1", act_type="relu")
    m1 = mx.mod.Module(trunk, label_names=[])

    # head module: consumes trunk output, owns the loss
    feat = mx.sym.Variable("fc1_output")
    head = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(feat, name="fc2", num_hidden=5),
        name="softmax")
    m2 = mx.mod.Module(head, data_names=["fc1_output"])

    seq = mx.mod.SequentialModule()
    seq.add(m1).add(m2, take_labels=True, auto_wiring=True)

    seq.fit(train,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    metric = mx.metric.Accuracy()
    seq.score(train, metric)
    logging.info("sequential module: train %s", metric.get())


if __name__ == "__main__":
    main()
