#!/usr/bin/env python
"""Three levels of the Module API on an MNIST-shaped MLP (parity:
example/module/mnist_mlp.py).

Level 1 — ``mod.fit(...)``: the high-level estimator loop.
Level 2 — the intermediate API the fit loop is made of:
``bind / init_params / init_optimizer / forward / backward / update``,
which is what you drop down to for custom training schemes (GANs,
RL, gradient surgery).
Level 3 — checkpointing: ``save_checkpoint`` / ``Module.load`` with
optimizer state, resuming mid-training.

Runs on synthetic data so it works out of the box on one chip (or CPU
with ``MXTPU_PLATFORM=cpu``)."""
import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def mlp_symbol(num_classes=10):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=64)
    net = mx.sym.Activation(net, name="relu2", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc3", num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(net, name="softmax")


_PROJ = np.random.RandomState(42).normal(size=(784, 10)).astype(np.float32)


def synthetic_mnist(num, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.uniform(0, 1, (num, 784)).astype(np.float32)
    y = (x @ _PROJ).argmax(axis=1).astype(np.float32)
    return x, y


def main():
    ap = argparse.ArgumentParser(description="Module API walkthrough")
    ap.add_argument("--batch-size", type=int, default=100)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    x, y = synthetic_mnist(5000)
    vx, vy = synthetic_mnist(1000, seed=1)
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(vx, vy, args.batch_size)

    # ---- level 1: fit ---------------------------------------------------
    mod = mx.mod.Module(mlp_symbol())
    mod.fit(train, eval_data=val,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    acc = mx.metric.Accuracy()
    mod.score(val, acc)
    logging.info("fit(): validation %s", acc.get())

    # ---- level 2: the loop fit() is made of -----------------------------
    train.reset()
    mod2 = mx.mod.Module(mlp_symbol())
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod2.init_params(initializer=mx.init.Xavier())
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": args.lr})
    metric = mx.metric.Accuracy()
    for epoch in range(args.num_epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod2.forward(batch, is_train=True)
            mod2.update_metric(metric, batch.label)
            mod2.backward()
            mod2.update()
        logging.info("manual loop epoch %d: train %s", epoch, metric.get())

    # ---- level 3: checkpoint / resume -----------------------------------
    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "mnist_mlp")
        mod2.save_checkpoint(prefix, args.num_epochs,
                             save_optimizer_states=True)
        resumed = mx.mod.Module.load(prefix, args.num_epochs,
                                     load_optimizer_states=True)
        resumed.bind(data_shapes=train.provide_data,
                     label_shapes=train.provide_label)
        resumed.init_optimizer(optimizer="sgd",
                               optimizer_params={"learning_rate": args.lr})
        train.reset()
        for batch in train:
            resumed.forward(batch, is_train=True)
            resumed.backward()
            resumed.update()
        acc = mx.metric.Accuracy()
        resumed.score(val, acc)
        logging.info("resumed from checkpoint: validation %s", acc.get())


if __name__ == "__main__":
    main()
