#!/usr/bin/env python
"""Softmax through the legacy NumpyOp protocol (parity:
example/numpy-ops/numpy_softmax.py — the reference's older
forward(in_data, out_data) API, pre-CustomOp; mxnet_tpu keeps the shim
so old user operators run unchanged on the CustomOp machinery).

Trains the same toy classifier as custom_softmax.py, through the other
frontend, and asserts it learns.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402


class NumpySoftmax(mx.operator.NumpyOp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape]

    def forward(self, in_data, out_data):
        x = in_data[0]
        y = out_data[0]
        y[:] = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)

    def backward(self, out_grad, in_data, out_data, in_grad):
        l = in_data[1].astype(int)
        y = out_data[0]
        dx = in_grad[0]
        dx[:] = y
        dx[np.arange(l.shape[0]), l] -= 1.0


def main():
    (X, Y), _ = get_synthetic_mnist(512, 8)
    mysoftmax = NumpySoftmax()
    data = sym.Variable("data")
    fc = sym.FullyConnected(sym.Flatten(data), num_hidden=10, name="fc")
    label = sym.Variable("softmax_label")
    net = mysoftmax(fc, label, name="softmax")
    mod = mx.mod.Module(net, label_names=["softmax_label"],
                        context=mx.context.default_accelerator_context())
    it = mx.io.NDArrayIter(X, Y, 64, shuffle=True)
    mod.fit(it, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier(), eval_metric="acc")
    acc = dict(mod.score(it, mx.metric.create("acc")))["accuracy"]
    print(f"train acc {acc:.3f}")
    assert acc > 0.7, acc
    print("NUMPYOP OK")


if __name__ == "__main__":
    main()
