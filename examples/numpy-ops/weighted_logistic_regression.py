#!/usr/bin/env python
"""Class-weighted logistic loss as a CustomOp (parity:
example/numpy-ops/weighted_logistic_regression.py — the reference
scales positive/negative gradients differently, the standard trick for
imbalanced binary data, and checks the op against the built-in
LogisticRegressionOutput).

Same contract: forward is a plain sigmoid (identical to the built-in);
backward applies the class weights.  Asserts (a) forward parity with
LogisticRegressionOutput, (b) the weighted gradient matches the closed
form, (c) with weights 1/1 the gradient reduces to the unweighted one.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402


class WeightedLogisticRegression(mx.operator.CustomOp):
    def __init__(self, pos_grad_scale, neg_grad_scale):
        self.pos = float(pos_grad_scale)
        self.neg = float(neg_grad_scale)

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0],
                    mx.nd.array(1.0 / (1.0 + np.exp(-x))))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        p = out_data[0].asnumpy()
        label = in_data[1].asnumpy()
        grad = ((p - 1) * label * self.pos
                + p * (1 - label) * self.neg) / p.shape[1]
        self.assign(in_grad[0], req[0], mx.nd.array(grad))


@mx.operator.register("weighted_logistic_regression")
class WeightedLogisticRegressionProp(mx.operator.CustomOpProp):
    def __init__(self, pos_grad_scale, neg_grad_scale):
        self.pos = pos_grad_scale
        self.neg = neg_grad_scale
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], in_shape[0]], [in_shape[0]]

    def create_operator(self, ctx, shapes, dtypes):
        return WeightedLogisticRegression(self.pos, self.neg)


def grads_for(pos, neg, x, labels):
    m2, n = x.shape  # noqa: F841
    data = sym.Variable("data")
    label = sym.Variable("wlr_label")
    wlr = sym.Custom(data, label, pos_grad_scale=pos, neg_grad_scale=neg,
                     name="wlr", op_type="weighted_logistic_regression")
    exe = wlr.simple_bind(mx.context.default_accelerator_context(),
                          data=(m2, n), wlr_label=(m2, n))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["wlr_label"][:] = labels
    exe.forward(is_train=True)
    out = exe.outputs[0].asnumpy()
    exe.backward()
    return out, exe.grad_dict["data"].asnumpy()


def main():
    m, n = 2, 5
    rs = np.random.RandomState(0)
    x = rs.randn(2 * m, n).astype(np.float32)
    labels = np.vstack([np.ones([m, n]), np.zeros([m, n])]).astype(np.float32)

    out_w, grad_w = grads_for(1.0, 0.1, x, labels)

    # (a) forward parity with the built-in LogisticRegressionOutput
    data = sym.Variable("data")
    lr = sym.LogisticRegressionOutput(data, name="lr")
    exe = lr.simple_bind(mx.context.default_accelerator_context(),
                         data=(2 * m, n))
    exe.arg_dict["data"][:] = x
    exe.arg_dict["lr_label"][:] = labels
    ref = exe.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out_w, ref, rtol=1e-5, atol=1e-6)

    # (b) closed-form weighted gradient
    p = 1.0 / (1.0 + np.exp(-x))
    expect = ((p - 1) * labels * 1.0 + p * (1 - labels) * 0.1) / n
    np.testing.assert_allclose(grad_w, expect, rtol=1e-5, atol=1e-6)

    # (c) weights 1/1 == the unweighted gradient
    _, grad_u = grads_for(1.0, 1.0, x, labels)
    np.testing.assert_allclose(grad_u, (p - labels) / n, rtol=1e-5,
                               atol=1e-6)
    print("positive-class grads scaled 10x over negative:",
          float(np.abs(grad_w[:m]).mean() / np.abs(grad_w[m:]).mean()))
    print("WLR OK")


if __name__ == "__main__":
    main()
