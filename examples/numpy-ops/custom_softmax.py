#!/usr/bin/env python
"""User-defined operator in Python (parity: example/numpy-ops/
custom_softmax.py): a CustomOp softmax with numpy forward/backward,
registered and used inside a symbolic network.

On TPU the custom op runs through the host-callback bridge — the
symbolic graph stays compiled, with an escape hatch for the op body
(mxnet_tpu/ops/custom.py)."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(int)
        y = out_data[0].asnumpy().copy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y))


@mx.operator.register("softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    net = sym.FullyConnected(sym.Flatten(data), name="fc1", num_hidden=128)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=10)
    net = sym.Custom(net, label, name="softmax", op_type="softmax")

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(2048, 256)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch_size)
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    logging.info("val acc: %.3f", mod.score(val, "acc")[0][1])
