#!/usr/bin/env python
"""CNN text classification (parity: example/cnn_text_classification/).

Kim-2014 architecture as in the reference's text_cnn.py: embedding ->
parallel conv branches with filter widths 3/4/5 over the token axis ->
max-over-time pooling -> concat -> dropout -> FC -> softmax.  Synthetic
sentiment task: sentences containing "positive" token clusters vs
"negative" ones.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

VOCAB, SEQ, EMBED = 120, 24, 16


def build(batch):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                          name="embed")
    # (N, 1, SEQ, EMBED) image-like layout, as the reference reshapes
    x = sym.Reshape(embed, shape=(batch, 1, SEQ, EMBED))
    pooled = []
    for width in (3, 4, 5):
        c = sym.Convolution(x, kernel=(width, EMBED), num_filter=8,
                            name=f"conv{width}")
        c = sym.Activation(c, act_type="relu")
        p = sym.Pooling(c, kernel=(SEQ - width + 1, 1), pool_type="max",
                        name=f"pool{width}")
        pooled.append(sym.Flatten(p))
    h = sym.Concat(*pooled, dim=1)
    h = sym.Dropout(h, p=0.3)
    fc = sym.FullyConnected(h, num_hidden=2, name="fc")
    return sym.SoftmaxOutput(fc, label, name="softmax")


def synth(rs, n):
    x = rs.randint(20, VOCAB, (n, SEQ)).astype(np.float32)
    y = rs.randint(0, 2, n).astype(np.float32)
    for i in range(n):
        # sentiment tokens: ids 1-9 positive, 10-18 negative
        toks = rs.randint(1, 10, 4) if y[i] > 0 else rs.randint(10, 19, 4)
        pos = rs.choice(SEQ, 4, replace=False)
        x[i, pos] = toks
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    xtr, ytr = synth(rs, 512)
    xte, yte = synth(rs, 128)

    mod = mx.mod.Module(build(args.batch),
                        context=mx.context.default_accelerator_context())
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch)
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": 2e-3},
            initializer=mx.init.Xavier(),
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(args.batch, 8))
    score = mod.score(val, mx.metric.create("acc"))
    acc = dict(score)["accuracy"]
    print(f"val acc {acc:.3f}")
    assert acc > 0.8, acc
    print("TRAIN OK")


if __name__ == "__main__":
    main()
