#!/usr/bin/env python
"""CNN text classification (parity: example/cnn_text_classification/
text_cnn.py, Kim 2014).

Architecture as in the reference: embedding -> parallel conv branches
with filter widths 3/4/5 over the token axis -> max-over-time pooling
-> concat -> dropout -> FC -> softmax.  The data path is the full
data_helpers pipeline (clean raw text, build vocab, pad+index) over a
synthetic review corpus; training keeps the best-dev checkpoint and the
final score runs through a RELOADED module, proving the save/load round
trip the reference's deployment path relies on.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

import data_helpers  # noqa: E402

SEQ, EMBED = 24, 16


def build(batch, vocab_size):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, input_dim=vocab_size, output_dim=EMBED,
                          name="embed")
    # (N, 1, SEQ, EMBED) image-like layout, as the reference reshapes
    x = sym.Reshape(embed, shape=(batch, 1, SEQ, EMBED))
    pooled = []
    for width in (3, 4, 5):
        c = sym.Convolution(x, kernel=(width, EMBED), num_filter=8,
                            name=f"conv{width}")
        c = sym.Activation(c, act_type="relu")
        p = sym.Pooling(c, kernel=(SEQ - width + 1, 1), pool_type="max",
                        name=f"pool{width}")
        pooled.append(sym.Flatten(p))
    h = sym.Concat(*pooled, dim=1)
    h = sym.Dropout(h, p=0.3)
    fc = sym.FullyConnected(h, num_hidden=2, name="fc")
    return sym.SoftmaxOutput(fc, label, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--prefix", type=str, default="/tmp/text_cnn")
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    mx.random.seed(0)

    # raw text -> cleaned/indexed/padded arrays through data_helpers
    pairs = data_helpers.synthetic_reviews(768, rs)
    x, y, vocab = data_helpers.load_corpus(pairs, SEQ)
    n_dev = 128
    xtr, ytr = x[:-n_dev], y[:-n_dev]
    xde, yde = x[-n_dev:], y[-n_dev:]
    print(f"vocab {len(vocab)} train {len(xtr)} dev {len(xde)}")

    net = build(args.batch, len(vocab))
    ctx = mx.context.default_accelerator_context()
    mod = mx.mod.Module(net, context=ctx)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch,
                              shuffle=True)
    dev = mx.io.NDArrayIter(xde, yde, batch_size=args.batch)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 2e-3})

    best = (-1.0, -1)  # (dev acc, epoch) — keep the best checkpoint
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        dev.reset()
        dev_acc = dict(mod.score(dev, mx.metric.create("acc")))["accuracy"]
        print(f"epoch {epoch}: train acc {metric.get()[1]:.3f} "
              f"dev acc {dev_acc:.3f}")
        if dev_acc > best[0]:
            best = (dev_acc, epoch)
            mod.save_checkpoint(args.prefix, epoch)

    # deployment path: reload the BEST checkpoint into a fresh module
    loaded = mx.mod.Module.load(args.prefix, best[1], context=ctx)
    loaded.bind(data_shapes=dev.provide_data,
                label_shapes=dev.provide_label, for_training=False)
    dev.reset()
    acc = dict(loaded.score(dev, mx.metric.create("acc")))["accuracy"]
    print(f"reloaded best (epoch {best[1]}) dev acc {acc:.3f}")
    assert abs(acc - best[0]) < 1e-6, (acc, best)
    assert acc > 0.85, acc
    print("TRAIN OK")


if __name__ == "__main__":
    main()
