"""Text-data plumbing for the CNN classifier (parity:
example/cnn_text_classification/data_helpers.py — the reference's
loader cleans raw sentences, builds a vocabulary, pads to a fixed
length, and yields shuffled (x, y) arrays; same pipeline here over any
iterable of (text, label) pairs, with a synthetic sentiment corpus
generator standing in for the MR dataset this image cannot download).
"""
import re

import numpy as np

PAD, UNK = "<pad>", "<unk>"


def clean_str(s):
    """Reference-style token normalization (punctuation split,
    lowercase)."""
    s = re.sub(r"[^A-Za-z0-9(),!?'`]", " ", s)
    for p in ("'s", "'ve", "n't", "'re", "'d", "'ll"):
        s = s.replace(p, " " + p)
    s = re.sub(r"([(),!?])", r" \1 ", s)
    s = re.sub(r"\s{2,}", " ", s)
    return s.strip().lower()


def build_vocab(sentences, max_vocab=None):
    """token -> id, with <pad>=0 and <unk>=1, most-frequent-first."""
    from collections import Counter

    counts = Counter(tok for s in sentences for tok in s.split())
    items = counts.most_common(None if max_vocab is None
                               else max_vocab - 2)
    vocab = {PAD: 0, UNK: 1}
    for tok, _ in items:
        vocab[tok] = len(vocab)
    return vocab


def pad_and_index(sentences, vocab, seq_len):
    """(N, seq_len) int array: tokens -> ids, truncated/right-padded."""
    out = np.zeros((len(sentences), seq_len), np.float32)
    unk = vocab[UNK]
    for i, s in enumerate(sentences):
        for j, tok in enumerate(s.split()[:seq_len]):
            out[i, j] = vocab.get(tok, unk)
    return out


def load_corpus(pairs, seq_len, max_vocab=None, seed=0):
    """(texts, labels) -> shuffled (x (N,seq_len), y (N,), vocab)."""
    texts = [clean_str(t) for t, _ in pairs]
    y = np.asarray([l for _, l in pairs], np.float32)
    vocab = build_vocab(texts, max_vocab)
    x = pad_and_index(texts, vocab, seq_len)
    rs = np.random.RandomState(seed)
    idx = rs.permutation(len(x))
    return x[idx], y[idx], vocab


# --------------------------------------------------------------------------
# Synthetic sentiment corpus (the MR dataset needs a download this image
# cannot make; the generator produces raw TEXT so the whole pipeline
# above still runs for real)
# --------------------------------------------------------------------------
_POS = ("great wonderful moving superb delightful brilliant touching "
        "charming").split()
_NEG = ("dull tedious lifeless boring clumsy shallow bland stale").split()
_FILL = ("the a this that film movie plot actor scene story it was is "
         "with and of really quite very").split()


def synthetic_reviews(n, rs=None):
    """n raw (sentence, label) pairs with injected sentiment words."""
    rs = rs or np.random.RandomState(0)
    pairs = []
    for _ in range(n):
        y = int(rs.randint(0, 2))
        words = list(rs.choice(_FILL, rs.randint(8, 16)))
        bank = _POS if y else _NEG
        for w in rs.choice(bank, 3):
            words.insert(int(rs.randint(0, len(words) + 1)), w)
        pairs.append((" ".join(words) + ("!" if y else "."), y))
    return pairs
