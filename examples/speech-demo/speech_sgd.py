"""speechSGD: momentum SGD whose lr scheduler also schedules MOMENTUM
(parity: example/speech-demo/speechSGD.py — acoustic-model recipes ramp
momentum up after the first epochs while lr decays on held-out
improvement; the scheduler returns (lr, momentum) pairs).

Registered with the framework's optimizer registry, so
``optimizer="speechsgd"`` works anywhere an optimizer name does
(Module.fit, FusedTrainer, kvstore set_optimizer).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from mxnet_tpu import ndarray as nd  # noqa: E402
from mxnet_tpu import optimizer as opt  # noqa: E402


class EpochScheduler:
    """(lr, momentum) schedule: momentum 0 for ``ramp`` updates, then the
    configured value; lr halves every ``half_life`` updates (a stand-in
    for the reference recipes' held-out-driven halving)."""

    def __init__(self, momentum=0.9, ramp=100, half_life=0):
        self.base_lr = 0.01  # overwritten by Optimizer.__init__
        self.momentum = momentum
        self.ramp = ramp
        self.half_life = half_life

    def __call__(self, num_update):
        lr = self.base_lr
        if self.half_life:
            lr *= 0.5 ** (num_update // self.half_life)
        mom = 0.0 if num_update < self.ramp else self.momentum
        return lr, mom


@opt.register
class SpeechSGD(opt.Optimizer):
    """SGD+momentum where ``lr_scheduler(num_update) -> (lr, momentum)``.

    Without a scheduler it degrades to plain momentum SGD, so it can be
    parity-tested against the stock "sgd" optimizer.
    """

    def __init__(self, momentum=0.0, **kwargs):
        # the base class calls the scheduler expecting a scalar in its
        # repr paths; it only ever invokes it inside _get_lr, which we
        # override, so the tuple protocol stays contained here
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def _get_lr_mom(self, index):
        if self.lr_scheduler is not None:
            lr, mom = self.lr_scheduler(self.num_update)
        else:
            lr, mom = self.lr, self.momentum
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr, mom

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, mom = self._get_lr_mom(index)
        wd = self._get_wd(index)
        new_w, new_mom = nd.sgd_mom_update(
            weight, grad, state, momentum=mom, lr=lr, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or 0.0)
        weight._set(new_w._read())
        state._set(new_mom._read())
