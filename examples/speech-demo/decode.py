#!/usr/bin/env python
"""Posterior decoding: run the trained acoustic model over a feature scp
and write frame log-likelihoods as a Kaldi TEXT archive that an external
decoder (kaldi latgen-faster-mapped) consumes (parity:
example/speech-demo/decode_mxnet.py + decode_mxnet.sh).

Acoustic-model scaling follows the standard hybrid recipe: output =
log p(state|x) - log p(state) (posteriors divided by the label priors
computed from the training alignments).

Usage (after train_lstm_proj.py):
  python decode.py                          # decodes the dev set
  python decode.py --scp F --ali A --out O  # any feature scp
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

from config_util import parse_args  # noqa: E402
from io_util import (add_deltas, apply_cmvn, load_cmvn,  # noqa: E402
                     read_scp_matrices, read_text_ark, write_text_ark)

HERE = os.path.dirname(os.path.abspath(__file__))


def compute_priors(ali_ark, num_states):
    """State priors from training alignments (decode_mxnet.sh feeds
    kaldi's class counts; here they come from the same alignment ark)."""
    counts = np.zeros(num_states)
    for _, a in read_text_ark(ali_ark):
        idx, c = np.unique(a[:, 0].astype(np.int64), return_counts=True)
        counts[idx] += c
    return counts / counts.sum()


def main():
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--scp")
    ap.add_argument("--out")
    ap.add_argument("--ali")
    cli, rest = ap.parse_known_args()
    sys.argv = [sys.argv[0]] + rest
    cfg = parse_args(os.path.join(HERE, "default.cfg"))

    work = cfg.get("data", "workdir")
    scp = cli.scp or os.path.join(work, "dev.scp")
    ali = cli.ali or os.path.join(work, "train_ali.ark")
    out = cli.out or os.path.join(work, "dev_loglikes.ark")
    prefix = cfg.get("train", "checkpoint_prefix")
    epoch = cfg.getint("train", "num_epochs")
    num_states = cfg.getint("data", "num_states")

    stats = load_cmvn(os.path.join(work, "cmvn.npy"))
    log_priors = np.log(compute_priors(ali, num_states) + 1e-10)

    symbol, arg_params, aux_params = mx.model.load_checkpoint(prefix, epoch)
    from mxnet_tpu.predict import Predictor

    # load everything, pad to ONE static length (a single compile —
    # padding frames are sliced off the output)
    deltas = cfg.getint("arch", "add_deltas")
    entries = []
    for utt, raw in read_scp_matrices(scp):
        feats = apply_cmvn(raw, stats)
        if deltas:
            feats = add_deltas(feats)
        entries.append((utt, feats))
    max_t = max(len(f) for _, f in entries)
    dim = entries[0][1].shape[1]
    shapes = {"data": (1, max_t, dim)}
    # initial LSTMP states are inputs of the saved graph; bind batch-1
    # zeros (they are never fed per utterance)
    for i in range(cfg.getint("arch", "num_layers")):
        shapes[f"l{i}_begin_state_0"] = (1, cfg.getint("arch", "num_proj"))
        shapes[f"l{i}_begin_state_1"] = (1, cfg.getint("arch", "num_hidden"))
    # the train symbol's label head stays in the graph; bind a zero
    # label (softmax ignores it at inference)
    shapes["softmax_label"] = (1, max_t)
    p = Predictor(
        symbol=symbol, arg_params=arg_params, aux_params=aux_params,
        input_shapes=shapes,
        dev_type=mx.context.default_accelerator_context())
    loglikes = {}
    for utt, feats in entries:
        t = len(feats)
        buf = np.zeros((1, max_t, dim), np.float32)
        buf[0, :t] = feats
        p.forward(data=buf)
        post = p.get_output(0).reshape(max_t, num_states)[:t]
        loglikes[utt] = np.log(post + 1e-10) - log_priors

    write_text_ark(out, loglikes)
    print(f"wrote {len(loglikes)} utterances to {out}")

    # sanity: frame accuracy of argmax loglikes vs alignments when the
    # scp's alignment ark exists (dev set in the synthetic corpus)
    dev_ali = os.path.join(work, "dev_ali.ark")
    if os.path.exists(dev_ali) and scp.endswith("dev.scp"):
        refs = {u: a[:, 0] for u, a in read_text_ark(dev_ali)}
        correct = total = 0
        for utt, ll in loglikes.items():
            hyp = ll.argmax(axis=1)
            correct += int((hyp == refs[utt].astype(np.int64)).sum())
            total += len(hyp)
        acc = correct / total
        print(f"frame accuracy from decoded loglikes: {acc:.3f}")
        assert acc > cfg.getfloat("train", "min_frame_acc"), acc
        print("DECODE OK")


if __name__ == "__main__":
    main()
