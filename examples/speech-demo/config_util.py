"""Config handling (parity: example/speech-demo/config_util.py — the
reference drives training from .cfg files with CLI overrides)."""
import argparse
import configparser
import os


def parse_args(default_cfg):
    """--configfile picks the .cfg; any remaining --section_key=value
    overrides that entry (the reference's override convention)."""
    ap = argparse.ArgumentParser(
        description="config-driven speech training",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    ap.add_argument("--configfile", default=default_cfg)
    args, overrides = ap.parse_known_args()
    cfg = configparser.ConfigParser()
    if not os.path.exists(args.configfile):
        raise FileNotFoundError(args.configfile)
    cfg.read(args.configfile)
    for ov in overrides:
        if not ov.startswith("--") or "=" not in ov:
            raise ValueError(f"override must look like --section_key=value: {ov}")
        key, value = ov[2:].split("=", 1)
        section, opt = key.split("_", 1)
        if not cfg.has_section(section):
            raise ValueError(f"unknown config section {section!r} in {ov}")
        cfg.set(section, opt, value)
    return cfg
