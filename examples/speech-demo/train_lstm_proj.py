#!/usr/bin/env python
"""Acoustic-model training: stacked LSTMP over fbank-like features with
frame-level senone targets (parity: example/speech-demo/train_lstm_proj.py,
the reference's Kaldi-fed recipe).

The full system path runs end to end with no Kaldi install:
  1. a synthetic formant corpus is written as REAL Kaldi binary archives
     (ark + scp + alignment text ark) under [data] workdir,
  2. CMVN stats are computed from the scp (make_stats.py's function),
  3. features get deltas appended and are normalized,
  4. whole utterances are bucketed by length into padded batches
     (UtteranceIter) and trained through BucketingModule with the
     framework's LSTMPCell stack,
  5. frame accuracy on held-out utterances is asserted, a checkpoint is
     saved for decode.py.
Point [data] train_scp at Kaldi-prepared archives to train on real data.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

import speech_sgd  # noqa: E402,F401 — registers the optimizer
from config_util import parse_args  # noqa: E402
from io_util import (UtteranceIter, add_deltas, apply_cmvn,  # noqa: E402
                     compute_cmvn_stats_scp, read_scp_matrices,
                     read_text_ark, save_cmvn, write_ark, write_text_ark)
from speech_sgd import EpochScheduler  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))


def synth_corpus(rs, n, feat_dim, num_states, min_len=30, max_len=90):
    """Formant-like synthetic corpus: each phone-state occupies a band of
    filterbank channels for a 3-8 frame run (same task shape as senone
    classification over fbank)."""
    band = feat_dim // num_states
    utts, aligns = {}, {}
    for i in range(n):
        t_total = int(rs.randint(min_len, max_len + 1))
        x = (rs.randn(t_total, feat_dim) * 0.3).astype(np.float32)
        y = np.zeros((t_total,), np.int32)
        t = 0
        while t < t_total:
            c = int(rs.randint(num_states))
            run = min(int(rs.randint(3, 9)), t_total - t)
            x[t:t + run, c * band:(c + 1) * band] += 1.2
            y[t:t + run] = c
            t += run
        utt = f"utt{i:05d}"
        utts[utt] = x
        aligns[utt] = y
    return utts, aligns


def stage_corpus(cfg):
    """Write the synthetic corpus as real Kaldi containers (or reuse an
    already-staged directory)."""
    d = cfg.get("data", "workdir")
    os.makedirs(d, exist_ok=True)
    paths = {k: os.path.join(d, k) for k in
             ("train.ark", "train.scp", "train_ali.ark",
              "dev.ark", "dev.scp", "dev_ali.ark")}
    if not all(os.path.exists(p) for p in paths.values()):
        rs = np.random.RandomState(0)
        fd = cfg.getint("data", "feat_dim")
        ns = cfg.getint("data", "num_states")
        tr, tr_ali = synth_corpus(rs, cfg.getint("data", "num_train_utts"),
                                  fd, ns)
        dv, dv_ali = synth_corpus(rs, cfg.getint("data", "num_dev_utts"),
                                  fd, ns)
        write_ark(paths["train.ark"], tr, paths["train.scp"])
        write_ark(paths["dev.ark"], dv, paths["dev.scp"])
        write_text_ark(paths["train_ali.ark"],
                       {u: a[:, None].astype(np.float32)
                        for u, a in tr_ali.items()})
        write_text_ark(paths["dev_ali.ark"],
                       {u: a[:, None].astype(np.float32)
                        for u, a in dv_ali.items()})
    return paths


def load_set(scp, ali_ark, stats, deltas):
    ali = {u: a[:, 0] for u, a in read_text_ark(ali_ark)}
    utts, labels = [], []
    for utt, raw in read_scp_matrices(scp):
        feats = apply_cmvn(raw, stats)
        if deltas:
            feats = add_deltas(feats)
        utts.append((utt, feats))
        labels.append(ali[utt])
    return utts, labels


def build_sym_gen(cfg, feat_dim, batch_size):
    nh = cfg.getint("arch", "num_hidden")
    npj = cfg.getint("arch", "num_proj")
    nl = cfg.getint("arch", "num_layers")
    ns = cfg.getint("data", "num_states")

    init_states = []
    for i in range(nl):
        init_states += [(f"l{i}_begin_state_0", (batch_size, npj)),
                        (f"l{i}_begin_state_1", (batch_size, nh))]

    def sym_gen(seq_len):
        stack = mx.rnn.SequentialRNNCell()
        for i in range(nl):
            stack.add(mx.rnn.LSTMPCell(nh, npj, prefix=f"l{i}_"))
        data = sym.Variable("data")  # (N, T, D)
        outputs, _ = stack.unroll(seq_len, inputs=data, layout="NTC",
                                  merge_outputs=True)
        pred = sym.Reshape(outputs, shape=(-1, npj))
        pred = sym.FullyConnected(pred, num_hidden=ns, name="fc")
        label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
        net = sym.SoftmaxOutput(pred, label, ignore_label=-1,
                                use_ignore=True, normalization="valid",
                                name="softmax")
        data_names = ("data",) + tuple(n for n, _ in init_states)
        return net, data_names, ("softmax_label",)

    return sym_gen, init_states


def main():
    cfg = parse_args(os.path.join(HERE, "default.cfg"))
    paths = stage_corpus(cfg)

    stats = compute_cmvn_stats_scp(paths["train.scp"])
    save_cmvn(os.path.join(cfg.get("data", "workdir"), "cmvn.npy"), stats)
    deltas = cfg.getint("arch", "add_deltas")
    train_utts, train_labels = load_set(
        paths["train.scp"], paths["train_ali.ark"], stats, deltas)
    dev_utts, dev_labels = load_set(
        paths["dev.scp"], paths["dev_ali.ark"], stats, deltas)
    feat_dim = train_utts[0][1].shape[1]
    batch = cfg.getint("train", "batch_size")

    sym_gen, init_states = build_sym_gen(cfg, feat_dim, batch)
    buckets = [40, 60, 90]
    train = UtteranceIter(train_utts, train_labels, batch, buckets=buckets,
                          init_states=init_states)
    dev = UtteranceIter(dev_utts, dev_labels, batch, buckets=buckets,
                        init_states=init_states, shuffle=False)

    sched = EpochScheduler(momentum=cfg.getfloat("train", "momentum"),
                           ramp=cfg.getint("train", "momentum_ramp"))
    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=train.default_bucket_key,
        context=mx.context.default_accelerator_context())
    mod.fit(train, eval_data=dev,
            num_epoch=cfg.getint("train", "num_epochs"),
            optimizer=cfg.get("train", "optimizer"),
            optimizer_params={
                "learning_rate": cfg.getfloat("train", "learning_rate"),
                "lr_scheduler": sched},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Accuracy(ignore_label=-1),
            batch_end_callback=mx.callback.Speedometer(batch, 20))

    acc = dict(mod.score(dev, mx.metric.Accuracy(ignore_label=-1)))["accuracy"]
    print(f"dev frame accuracy {acc:.3f}")

    prefix = cfg.get("train", "checkpoint_prefix")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    # save the largest-bucket symbol + shared params for decode.py
    arg_params, aux_params = mod.get_params()
    net, _, _ = sym_gen(train.default_bucket_key)
    mx.model.save_checkpoint(prefix, cfg.getint("train", "num_epochs"),
                             net, arg_params, aux_params)
    floor = cfg.getfloat("train", "min_frame_acc")
    assert acc > floor, (acc, floor)
    print("TRAIN OK")


if __name__ == "__main__":
    main()
