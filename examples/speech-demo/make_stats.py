#!/usr/bin/env python
"""Compute CMVN statistics from a feature scp (parity:
example/speech-demo/make_stats.py — the reference computes feature
stats before training; stats use the Kaldi (2, D+1) layout).

Usage: python make_stats.py --scp /path/feats.scp --out /path/cmvn.npy
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from io_util import compute_cmvn_stats_scp, save_cmvn  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scp", required=True)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()
    stats = compute_cmvn_stats_scp(args.scp)
    save_cmvn(args.out, stats)
    count = stats[0, -1]
    print(f"accumulated {int(count)} frames, dim {stats.shape[1] - 1}")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
