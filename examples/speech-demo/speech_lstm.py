#!/usr/bin/env python
"""Speech acoustic model demo (parity: example/speech-demo/): frame-level
senone classification with a (bi)LSTM over filterbank features — the
reference's Kaldi-fed train_lstm.py, on synthetic formant-like data so it
runs standalone.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

FEATS, SEQ, HIDDEN, STATES = 24, 20, 64, 6


def build(batch):
    data = sym.Variable("data")            # (N, SEQ, FEATS)
    label = sym.Variable("softmax_label")  # (N, SEQ)
    x = sym.transpose(data, axes=(1, 0, 2))
    rnn = sym.RNN(x, state_size=HIDDEN, num_layers=2, mode="lstm",
                  name="lstm")             # (SEQ, N, H)
    h = sym.Reshape(rnn, shape=(-1, HIDDEN))
    fc = sym.FullyConnected(h, num_hidden=STATES, name="fc")
    fc = sym.Reshape(fc, shape=(SEQ, batch, STATES))
    fc = sym.transpose(fc, axes=(1, 2, 0))  # (N, STATES, SEQ)
    return sym.SoftmaxOutput(fc, label, multi_output=True,
                             normalization="valid", name="softmax")


def synth(rs, n):
    """Each frame's class = which formant band carries energy; classes
    persist for runs of 3-6 frames like phone states."""
    x = rs.randn(n, SEQ, FEATS).astype(np.float32) * 0.3
    y = np.zeros((n, SEQ), np.float32)
    for i in range(n):
        t = 0
        while t < SEQ:
            c = rs.randint(STATES)
            run = min(int(rs.randint(3, 7)), SEQ - t)
            band = slice(c * 4, c * 4 + 4)
            x[i, t:t + run, band] += 1.2
            y[i, t:t + run] = c
            t += run
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=5)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    xtr, ytr = synth(rs, 768)
    xte, yte = synth(rs, 192)

    mod = mx.mod.Module(build(args.batch),
                        context=mx.context.default_accelerator_context())
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch)
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": 3e-3},
            initializer=mx.init.Xavier(), eval_metric="acc")
    acc = dict(mod.score(val, mx.metric.create("acc")))["accuracy"]
    print(f"frame accuracy {acc:.3f}")
    assert acc > 0.85, acc
    print("TRAIN OK")


if __name__ == "__main__":
    main()
