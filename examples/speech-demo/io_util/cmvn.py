"""Cepstral mean/variance normalization (parity: the reference's
make_stats.py computes feature statistics before training; Kaldi's
compute-cmvn-stats layout is used so stats interoperate).

Stats matrix layout (Kaldi convention): shape (2, D+1) —
  row 0 = [sum_1..sum_D, frame_count]
  row 1 = [sumsq_1..sumsq_D, 0]
"""
import numpy as np

from .kaldi import read_scp_matrices


def compute_cmvn_stats(utts):
    """Accumulate global stats over {utt: (T, D)} or an iterable of
    (utt, feats)."""
    items = utts.items() if hasattr(utts, "items") else utts
    stats = None
    for _, feats in items:
        feats = np.asarray(feats, dtype=np.float64)
        if stats is None:
            stats = np.zeros((2, feats.shape[1] + 1))
        stats[0, :-1] += feats.sum(axis=0)
        stats[0, -1] += feats.shape[0]
        stats[1, :-1] += np.square(feats).sum(axis=0)
    if stats is None:
        raise ValueError("no utterances")
    return stats


def compute_cmvn_stats_scp(scp_path):
    """Accumulate stats straight from an scp index (streamed, one open
    handle per ark)."""
    return compute_cmvn_stats(read_scp_matrices(scp_path))


def apply_cmvn(feats, stats, var_norm=True, floor=1e-8):
    """Normalize (T, D) features to zero mean (and unit variance)."""
    count = stats[0, -1]
    mean = stats[0, :-1] / count
    out = np.asarray(feats, dtype=np.float32) - mean.astype(np.float32)
    if var_norm:
        var = np.maximum(stats[1, :-1] / count - np.square(mean), floor)
        out /= np.sqrt(var).astype(np.float32)
    return out


def save_cmvn(path, stats):
    np.save(path, stats)


def load_cmvn(path):
    return np.load(path)
