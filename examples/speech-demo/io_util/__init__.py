"""Speech feature IO (parity: example/speech-demo/io_util.py + io_func/):
readers/writers for the two standard acoustic-feature containers (HTK
feature files, Kaldi ark/scp), CMVN statistics, delta/splice transforms,
and the utterance iterator that feeds BucketingModule.

Everything is implemented from the public format specifications (HTKBook
§5.10; Kaldi I/O docs) in numpy — no Kaldi/HTK installation needed.
"""
from .htk import read_htk, write_htk, PARM_FBANK, PARM_MFCC, PARM_USER
from .kaldi import (read_ark, read_ark_entry, write_ark, read_scp,
                    read_scp_matrices, write_text_ark, read_text_ark)
from .cmvn import (compute_cmvn_stats, compute_cmvn_stats_scp, apply_cmvn,
                   save_cmvn, load_cmvn)
from .feats import add_deltas, splice_frames, UtteranceIter
