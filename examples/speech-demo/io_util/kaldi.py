"""Kaldi ark/scp float-matrix reader/writer (public format, Kaldi I/O
docs "The Table concept" / kaldi-matrix binary layout).

Binary archive entry:   <utt_id> <space> \\0B FM <i4:rows> <i4:cols> data
  - "\\0B" is the binary-mode marker, "FM " the float-matrix token,
  - each dimension is written as \\x04 (byte count) + int32 LE,
  - data is row-major float32 LE.
Text archive entry:     <utt_id>  [\\n  v v v\\n  v v v ]\\n
Script file (scp) line: <utt_id> <path>:<byte offset of \\0B>

Parity: the reference speech demo trains from Kaldi archives via its
io_func/ readers; these functions produce/consume the same containers so
the demo interoperates with Kaldi-prepared data while running without
Kaldi itself.
"""
import struct

import numpy as np


def write_ark(ark_path, utts, scp_path=None):
    """Write {utt_id: (T, D) array} to a binary ark; optionally also an
    scp index.  Returns {utt_id: offset}."""
    offsets = {}
    with open(ark_path, "wb") as f:
        for utt, feats in utts.items():
            feats = np.asarray(feats, dtype=np.float32)
            if feats.ndim != 2:
                raise ValueError(f"{utt}: expected (T, D), got {feats.shape}")
            f.write(utt.encode() + b" ")
            offsets[utt] = f.tell()
            f.write(b"\0BFM ")
            f.write(b"\x04" + struct.pack("<i", feats.shape[0]))
            f.write(b"\x04" + struct.pack("<i", feats.shape[1]))
            f.write(feats.astype("<f4").tobytes())
    if scp_path:
        with open(scp_path, "w") as f:
            for utt, off in offsets.items():
                f.write(f"{utt} {ark_path}:{off}\n")
    return offsets


def _read_entry_at(f):
    """Read one binary matrix at the current position (after the id)."""
    marker = f.read(2)
    if marker != b"\0B":
        raise ValueError(f"bad binary marker {marker!r}")
    token = f.read(3)
    if token != b"FM ":
        raise ValueError(f"unsupported kaldi type token {token!r}")
    sizes = []
    for _ in range(2):
        nb = f.read(1)
        if nb != b"\x04":
            raise ValueError("bad dimension byte-count")
        sizes.append(struct.unpack("<i", f.read(4))[0])
    rows, cols = sizes
    data = np.frombuffer(f.read(rows * cols * 4), dtype="<f4")
    if data.size != rows * cols:
        raise ValueError("truncated matrix data")
    return data.reshape(rows, cols).astype(np.float32)


def read_ark(ark_path):
    """Stream a binary ark -> yields (utt_id, feats)."""
    with open(ark_path, "rb") as f:
        while True:
            utt = bytearray()
            while True:
                c = f.read(1)
                if not c:
                    return
                if c == b" ":
                    break
                utt += c
            yield utt.decode(), _read_entry_at(f)


def read_ark_entry(ark_path, offset):
    """Random access via an scp offset."""
    with open(ark_path, "rb") as f:
        f.seek(offset)
        return _read_entry_at(f)


def read_scp_matrices(scp_path):
    """Yield (utt_id, feats) for every scp entry in order, keeping one
    open handle per distinct ark (a real corpus has thousands of
    utterances per archive — one open/seek cycle per utterance is O(N)
    syscall churn read_ark_entry callers should avoid)."""
    handles = {}
    try:
        for utt, path, off in read_scp(scp_path):
            f = handles.get(path)
            if f is None:
                f = handles[path] = open(path, "rb")
            f.seek(off)
            yield utt, _read_entry_at(f)
    finally:
        for f in handles.values():
            f.close()


def read_scp(scp_path):
    """Read an scp file -> list of (utt_id, ark_path, offset)."""
    entries = []
    with open(scp_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            utt, loc = line.split(None, 1)
            path, off = loc.rsplit(":", 1)
            entries.append((utt, path, int(off)))
    return entries


def write_text_ark(path, utts):
    """Write {utt_id: (T, D)} as a Kaldi text archive (the format
    `copy-feats ark:- ark,t:-` emits; also what the decode step writes
    so Kaldi's latgen reads our posteriors)."""
    with open(path, "w") as f:
        for utt, feats in utts.items():
            feats = np.asarray(feats, dtype=np.float32)
            if len(feats) == 0:
                f.write(f"{utt}  [ ]\n")
                continue
            f.write(f"{utt}  [\n")
            for i, row in enumerate(feats):
                end = " ]" if i == len(feats) - 1 else ""
                f.write("  " + " ".join(f"{v:.7g}" for v in row) + end + "\n")


def read_text_ark(path):
    """Read a Kaldi text archive -> yields (utt_id, feats)."""
    with open(path) as f:
        utt, rows = None, []
        for line in f:
            line = line.strip()
            if utt is None:
                if not line:
                    continue
                utt, bracket = line.split(None, 1)
                bracket = bracket.strip()
                if bracket == "[ ]":  # empty matrix, kaldi inline form
                    yield utt, np.zeros((0, 0), dtype=np.float32)
                    utt = None
                    continue
                if bracket != "[":
                    raise ValueError(f"{utt}: expected '[', got {bracket!r}")
                rows = []
            else:
                done = line.endswith("]")
                line = line[:-1].strip() if done else line
                if line:
                    rows.append([float(v) for v in line.split()])
                if done:
                    yield utt, np.asarray(rows, dtype=np.float32)
                    utt, rows = None, []
        if utt is not None:
            raise ValueError(f"{utt}: unterminated matrix")
