"""Feature transforms + the utterance iterator feeding BucketingModule.

Parity: the reference's io_util.py wraps Kaldi/TNet readers into
TruncatedSentenceIter/SimpleIter with frame labels from alignment files;
here UtteranceIter buckets whole utterances by length (the TPU-friendly
choice: a handful of padded static shapes, loss-masked padding, one
compile per bucket — docs/how_to/bucketing.md) instead of the
reference's fixed-length truncated-BPTT chopping.
"""
import bisect

import numpy as np

from mxnet_tpu import ndarray as nd
from mxnet_tpu.io import DataBatch, DataDesc, DataIter


def add_deltas(feats, order=2, window=2):
    """Append delta (and delta-delta...) features via the standard
    regression formula over +/-window frames (HTKBook eq. 5.16)."""
    feats = np.asarray(feats, dtype=np.float32)
    blocks = [feats]
    denom = 2.0 * sum(n * n for n in range(1, window + 1))
    cur = feats
    for _ in range(order):
        padded = np.pad(cur, ((window, window), (0, 0)), mode="edge")
        delta = np.zeros_like(cur)
        for n in range(1, window + 1):
            delta += n * (padded[window + n:len(padded) - window + n]
                          - padded[window - n:len(padded) - window - n])
        cur = (delta / denom).astype(np.float32)
        blocks.append(cur)
    return np.concatenate(blocks, axis=1)


def splice_frames(feats, left=5, right=5):
    """Stack a context window around every frame (edge-padded) — the
    standard DNN acoustic-model input transform."""
    feats = np.asarray(feats, dtype=np.float32)
    padded = np.pad(feats, ((left, right), (0, 0)), mode="edge")
    t = len(feats)
    return np.concatenate(
        [padded[k:k + t] for k in range(left + right + 1)], axis=1)


class UtteranceIter(DataIter):
    """Bucket whole utterances by length into padded (N, T, D) batches
    with frame labels (N, T); padding frames carry ``ignore_label`` so
    the masked softmax drops them from loss and gradient."""

    def __init__(self, utts, labels, batch_size, buckets=None,
                 ignore_label=-1, data_name="data",
                 label_name="softmax_label", init_states=None,
                 shuffle=True):
        super().__init__()
        lengths = [len(f) for _, f in utts]
        if not buckets:
            buckets = sorted(set(
                int(np.ceil(l / 10.0) * 10) for l in lengths))
        self.buckets = sorted(buckets)
        dim = utts[0][1].shape[1]
        self.data = [[] for _ in self.buckets]
        self.label = [[] for _ in self.buckets]
        ndiscard = 0
        for (utt, feats), lab in zip(utts, labels):
            if len(feats) != len(lab):
                raise ValueError(f"{utt}: {len(feats)} frames vs "
                                 f"{len(lab)} labels")
            i = bisect.bisect_left(self.buckets, len(feats))
            if i == len(self.buckets):
                ndiscard += 1
                continue
            t = self.buckets[i]
            fbuf = np.zeros((t, dim), np.float32)
            fbuf[:len(feats)] = feats
            lbuf = np.full((t,), ignore_label, np.float32)
            lbuf[:len(lab)] = lab
            self.data[i].append(fbuf)
            self.label[i].append(lbuf)
        if ndiscard:
            print(f"UtteranceIter: discarded {ndiscard} utterances longer "
                  f"than the largest bucket ({self.buckets[-1]})")
        self.data = [np.asarray(b) for b in self.data]
        self.label = [np.asarray(b) for b in self.label]
        self.batch_size = batch_size
        self.ignore_label = ignore_label
        self.data_name = data_name
        self.label_name = label_name
        self.shuffle = shuffle
        self.default_bucket_key = max(self.buckets)
        self.init_states = list(init_states or [])
        self._init_arrays = [nd.array(np.zeros(s, np.float32))
                             for _, s in self.init_states]
        self.provide_data = [DataDesc(
            data_name, (batch_size, self.default_bucket_key, dim))] + \
            [DataDesc(n, s) for n, s in self.init_states]
        self.provide_label = [DataDesc(
            label_name, (batch_size, self.default_bucket_key))]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend(
                (i, j) for j in range(0, len(buck) - batch_size + 1,
                                      batch_size))
        self.reset()

    def reset(self):
        self.curr_idx = 0
        if self.shuffle:
            np.random.shuffle(self.idx)
            for i in range(len(self.data)):
                perm = np.random.permutation(len(self.data[i]))
                self.data[i] = self.data[i][perm]
                self.label[i] = self.label[i][perm]

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.data[i][j:j + self.batch_size]
        label = self.label[i][j:j + self.batch_size]
        return DataBatch(
            [nd.array(data)] + self._init_arrays, [nd.array(label)], pad=0,
            bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape)] +
                         [DataDesc(n, s) for n, s in self.init_states],
            provide_label=[DataDesc(self.label_name, label.shape)])
