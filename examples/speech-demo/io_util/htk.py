"""HTK feature-file reader/writer (public format: HTKBook §5.10.1).

Layout: a 12-byte big-endian header — nSamples (int32), sampPeriod
(int32, 100ns units), sampSize (int16, bytes per frame), parmKind
(int16) — followed by nSamples frames of big-endian float32.

Parity: the reference's io_func/feat_readers read HTK/TNet feature files
for its Kaldi-fed speech demo; this module provides the same container
so features produced by HTK tooling load directly.
"""
import struct

import numpy as np

# base parameter kinds (HTKBook table 5.2)
PARM_WAVEFORM = 0
PARM_LPC = 1
PARM_MFCC = 6
PARM_FBANK = 7
PARM_MELSPEC = 8
PARM_USER = 9
PARM_PLP = 11

# qualifier bits
Q_E = 0o100      # log energy appended
Q_D = 0o400      # delta coefficients appended
Q_A = 0o1000     # acceleration coefficients appended
Q_Z = 0o4000     # zero-mean normalized


def write_htk(path, feats, samp_period=100000, parm_kind=PARM_USER):
    """Write a (T, D) float array as an HTK feature file.

    samp_period is in 100ns units (100000 = the standard 10ms shift).
    """
    feats = np.asarray(feats, dtype=np.float32)
    if feats.ndim != 2:
        raise ValueError(f"expected (T, D) features, got {feats.shape}")
    n, dim = feats.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">iihh", n, samp_period, dim * 4, parm_kind))
        f.write(feats.astype(">f4").tobytes())


def read_htk(path):
    """Read an HTK feature file -> (feats (T, D) float32, samp_period,
    parm_kind)."""
    with open(path, "rb") as f:
        header = f.read(12)
        if len(header) != 12:
            raise ValueError(f"{path}: truncated HTK header")
        n, samp_period, samp_size, parm_kind = struct.unpack(">iihh", header)
        if samp_size <= 0 or samp_size % 4:
            raise ValueError(
                f"{path}: sampSize {samp_size} is not float32 frames "
                "(compressed (_C) files are not supported)")
        dim = samp_size // 4
        data = np.frombuffer(f.read(n * samp_size), dtype=">f4")
    if data.size != n * dim:
        raise ValueError(f"{path}: expected {n}x{dim} floats, got {data.size}")
    return data.reshape(n, dim).astype(np.float32), samp_period, parm_kind
