#!/usr/bin/env python
"""LSTM + CTC OCR (parity: example/warpctc/lstm_ocr.py — the
reference trains an LSTM over captcha image columns with warp-ctc and
reports sequence accuracy from the greedy CTC decode; same system here
on synthetic seven-segment captchas, so it runs with no font/captcha
dependency).

Variable-length digit strings (3-5 digits) render at jittered positions
and widths; labels are 0-padded (the warp-ctc blank/padding
convention); the alignment-free CTC loss (WarpCTC,
a built-in op here — lax.scan alpha recursion, no plugin) learns the
column<->digit correspondence itself.  After training, the checkpoint
feeds ocr_predict.py (the predictor path).

Run:  MXTPU_PLATFORM=cpu python lstm_ocr.py --assert-acc 0.8
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

H, W = 16, 48          # image size (rows, columns=timesteps)
MAX_DIGITS = 5
BLANK = 0              # class 0 = CTC blank; digits are 1..10

# seven-segment truth table: (top, top-l, top-r, mid, bot-l, bot-r, bot)
SEGMENTS = {
    0: (1, 1, 1, 0, 1, 1, 1), 1: (0, 0, 1, 0, 0, 1, 0),
    2: (1, 0, 1, 1, 1, 0, 1), 3: (1, 0, 1, 1, 0, 1, 1),
    4: (0, 1, 1, 1, 0, 1, 0), 5: (1, 1, 0, 1, 0, 1, 1),
    6: (1, 1, 0, 1, 1, 1, 1), 7: (1, 0, 1, 0, 0, 1, 0),
    8: (1, 1, 1, 1, 1, 1, 1), 9: (1, 1, 1, 1, 0, 1, 1),
}


def draw_digit(digit, height, width):
    """Render one seven-segment digit into a (height, width) patch."""
    img = np.zeros((height, width), np.float32)
    t, tl, tr, m, bl, br, b = SEGMENTS[digit]
    mid = height // 2
    if t:
        img[0:2, 1:width - 1] = 1
    if m:
        img[mid - 1:mid + 1, 1:width - 1] = 1
    if b:
        img[height - 2:height, 1:width - 1] = 1
    if tl:
        img[0:mid, 0:2] = 1
    if tr:
        img[0:mid, width - 2:width] = 1
    if bl:
        img[mid:height, 0:2] = 1
    if br:
        img[mid:height, width - 2:width] = 1
    return img


def gen_captcha(rs):
    """-> (image (H, W), label (MAX_DIGITS,) 0-padded, digits list).
    The label lists exactly the digits that fit on the canvas."""
    n = int(rs.randint(3, MAX_DIGITS + 1))
    want = [int(rs.randint(0, 10)) for _ in range(n)]
    img = np.zeros((H, W), np.float32)
    x = int(rs.randint(0, 4))
    drawn = []
    for d in want:
        w = int(rs.randint(6, 9))
        if x + w > W:
            break
        y0 = int(rs.randint(0, 3))
        img[y0:y0 + 12, x:x + w] = np.maximum(
            img[y0:y0 + 12, x:x + w], draw_digit(d, 12, w))
        drawn.append(d)
        x += w + int(rs.randint(1, 4))
    img = np.clip(img + rs.normal(0, 0.08, img.shape), 0, 1)
    # warp-ctc label convention: 0 is blank AND padding; digits -> 1..10
    label = np.zeros((MAX_DIGITS,), np.float32)
    for i, d in enumerate(drawn):
        label[i] = d + 1
    return img.astype(np.float32), label, drawn


def ctc_greedy_decode(path, blank=BLANK):
    """Collapse repeats then drop blanks (best-path decoding)."""
    out, prev = [], None
    for p in path:
        if p != prev and p != blank:
            out.append(int(p))
        prev = p
    return out


def build_net(batch, num_hidden, num_classes, for_training=True):
    data = sym.Variable("data")                    # (N, H, W)
    cols = sym.transpose(data, axes=(0, 2, 1))     # (N, T=W, H)
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(num_hidden, prefix="l0_"))
    outputs, _ = stack.unroll(W, inputs=cols, layout="NTC",
                              merge_outputs=True)  # (N, T, Hdn)
    feat = sym.Reshape(outputs, shape=(-1, num_hidden))
    fc = sym.FullyConnected(feat, num_hidden=num_classes, name="pred_fc")
    pred = sym.Reshape(fc, shape=(-1, W, num_classes))
    pred = sym.transpose(pred, axes=(1, 0, 2))     # (T, N, C)
    if not for_training:
        return sym.SoftmaxActivation(sym.Reshape(pred, shape=(-1, num_classes)),
                                     name="probs")
    label = sym.Variable("label")                  # (N, MAX_DIGITS)
    return sym.WarpCTC(pred, label, label_length=MAX_DIGITS,
                       input_length=W, name="ctc")


def seq_accuracy(probs_TNC, labels, blank=BLANK):
    """probs (T, N, C) -> greedy decode vs 0-padded labels."""
    paths = probs_TNC.argmax(axis=2).T             # (N, T)
    correct = 0
    for row, lab in zip(paths, labels):
        truth = [int(v) for v in lab if v > 0]     # 0 = blank/padding
        if ctc_greedy_decode(row, blank) == truth:
            correct += 1
    return correct / len(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--num-hidden", type=int, default=48)
    ap.add_argument("--lr", type=float, default=4e-3)
    ap.add_argument("--log-interval", type=int, default=50)
    ap.add_argument("--save-prefix", default="/tmp/ocr/model")
    ap.add_argument("--assert-acc", type=float, default=None)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(0)
    np.random.seed(0)
    num_classes = 11  # blank + 10 digits
    b = args.batch_size

    net = build_net(b, args.num_hidden, num_classes)
    state_shapes = {"l0_begin_state_0": (b, args.num_hidden),
                    "l0_begin_state_1": (b, args.num_hidden)}
    ex = net.simple_bind(ctx=None, data=(b, H, W),
                         label=(b, MAX_DIGITS), **state_shapes)
    init = mx.init.Xavier()
    params = {}
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "label") and "state" not in name:
            init(name, arr)
            params[name] = arr
    opt = mx.optimizer.create("adam", learning_rate=args.lr)
    updater = mx.optimizer.get_updater(opt)

    def batch_of(n):
        imgs, labels = [], []
        for _ in range(n):
            img, lab, _ = gen_captcha(rs)
            imgs.append(img)
            labels.append(lab)
        return np.stack(imgs), np.stack(labels)

    for step in range(args.steps):
        imgs, labels = batch_of(b)
        ex.arg_dict["data"][:] = imgs
        ex.arg_dict["label"][:] = labels
        ex.forward(is_train=True)
        ex.backward()
        for i, (name, arr) in enumerate(sorted(params.items())):
            updater(i, ex.grad_dict[name], arr)
        if step % args.log_interval == 0 or step == args.steps - 1:
            out = ex.outputs[0].asnumpy()          # (T, N, C) softmaxed
            acc = seq_accuracy(out, labels)
            logging.info("step %d  train seq-acc %.3f", step, acc)

    # held-out evaluation
    imgs, labels = batch_of(b)
    ex.arg_dict["data"][:] = imgs
    ex.arg_dict["label"][:] = labels
    ex.forward(is_train=False)
    acc = seq_accuracy(ex.outputs[0].asnumpy(), labels)
    print(f"held-out sequence accuracy: {acc:.3f}")

    os.makedirs(os.path.dirname(args.save_prefix), exist_ok=True)
    deploy = build_net(b, args.num_hidden, num_classes, for_training=False)
    mx.model.save_checkpoint(
        args.save_prefix, 1, deploy,
        {k: v for k, v in params.items()}, {})
    print(f"saved {args.save_prefix}-0001.params (ocr_predict.py loads it)")
    if args.assert_acc is not None:
        assert acc >= args.assert_acc, (acc, args.assert_acc)
        print("OCR OK")


if __name__ == "__main__":
    main()
