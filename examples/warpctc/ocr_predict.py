#!/usr/bin/env python
"""OCR inference through the predictor (parity:
example/warpctc/ocr_predict.py — the reference loads the trained OCR
checkpoint with its predict API and best-path-decodes the CTC output;
same flow here through mxnet_tpu.predict, the exact path the C ABI and
bindings serve).

Run after lstm_ocr.py:  MXTPU_PLATFORM=cpu python ocr_predict.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.predict import Predictor  # noqa: E402

from lstm_ocr import (H, W, ctc_greedy_decode, gen_captcha,  # noqa: E402
                      seq_accuracy)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--prefix", default="/tmp/ocr/model")
    ap.add_argument("--epoch", type=int, default=1)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=48)
    ap.add_argument("--assert-acc", type=float, default=0.8)
    args = ap.parse_args()
    b = args.batch

    symbol, arg_params, aux_params = mx.model.load_checkpoint(
        args.prefix, args.epoch)
    p = Predictor(
        symbol=symbol, arg_params=arg_params, aux_params=aux_params,
        input_shapes={"data": (b, H, W),
                      "l0_begin_state_0": (b, args.num_hidden),
                      "l0_begin_state_1": (b, args.num_hidden)},
        dev_type=mx.context.default_accelerator_context())

    rs = np.random.RandomState(123)  # unseen captchas
    imgs, labels = [], []
    for _ in range(b):
        img, lab, _ = gen_captcha(rs)
        imgs.append(img)
        labels.append(lab)
    p.forward(data=np.stack(imgs))
    probs = p.get_output(0).reshape(W, b, -1)
    acc = seq_accuracy(probs, np.stack(labels))

    hyp = ctc_greedy_decode(probs.argmax(axis=2).T[0])
    truth = [int(v) for v in labels[0] if v > 0]
    print(f"sample: decoded {[d - 1 for d in hyp]} "
          f"truth {[d - 1 for d in truth]}")
    print(f"predictor sequence accuracy: {acc:.3f}")
    assert acc >= args.assert_acc, (acc, args.assert_acc)
    print("PREDICT OK")


if __name__ == "__main__":
    main()
