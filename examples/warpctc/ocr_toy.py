#!/usr/bin/env python
"""CTC OCR toy (parity: example/warpctc/ — digit-sequence images trained
with CTC loss; the reference needs the warpctc plugin, here WarpCTC is a
built-in op backed by a lax.scan alpha recursion)."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402


def gen_sample(rs, seq_len, num_digit, num_classes):
    """Image = seq of digit 'glyph' columns; label = the digit ids + pad."""
    glyphs = gen_sample.glyphs
    cols = rs.randint(1, num_classes, num_digit)
    img = np.concatenate([glyphs[c] for c in cols], axis=1)
    img = img + rs.normal(0, 0.1, img.shape)
    label = np.full((num_digit,), -1.0, np.float32)
    label[: len(cols)] = cols
    return img.astype(np.float32), label


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-steps", type=int, default=30)
    ap.add_argument("--seq-len", type=int, default=4)
    ap.add_argument("--num-classes", type=int, default=11,
                    help="10 digits + blank(0)")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rs = np.random.RandomState(0)
    gen_sample.glyphs = rs.uniform(0, 1, (args.num_classes, 8, 6))

    T = args.seq_len * 6  # input time steps = image columns
    data = sym.Variable("data")          # (N, 8, T)
    label = sym.Variable("label")        # (N, seq_len)
    net = sym.transpose(data, axes=(2, 0, 1))   # (T, N, 8)
    net = sym.Reshape(net, shape=(-1, 8))
    net = sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=args.num_classes, name="fc2")
    net = sym.Reshape(net, shape=(T, -1, args.num_classes))
    net = sym.WarpCTC(net, label, label_length=args.seq_len,
                      input_length=T, name="ctc")

    ex = net.simple_bind(ctx=None, data=(args.batch_size, 8, T),
                         label=(args.batch_size, args.seq_len))
    init = mx.init.Xavier()
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "label"):
            init(name, arr)

    opt = mx.optimizer.create("adam", learning_rate=0.01)
    updater = mx.optimizer.get_updater(opt)
    for step in range(args.num_steps):
        imgs, labels = zip(*[gen_sample(rs, args.seq_len, args.seq_len,
                                        args.num_classes)
                             for _ in range(args.batch_size)])
        ex.arg_dict["data"][:] = np.stack(imgs)
        ex.arg_dict["label"][:] = np.stack(labels)
        ex.forward(is_train=True)
        ex.backward()
        for i, name in enumerate(ex.symbol.list_arguments()):
            if name in ("data", "label"):
                continue
            updater(i, ex.grad_dict[name], ex.arg_dict[name])
        if step % 10 == 0:
            out = ex.outputs[0].asnumpy()  # (T, N, C) post-softmax
            pred = out.argmax(axis=2).T    # greedy decode
            logging.info("step %d  sample pred path %s", step, pred[0][:12])
    logging.info("done")
