#!/usr/bin/env python
"""Stochastic-depth sanity chain (parity:
example/stochastic-depth/sd_mnist.py — the reference composes a conv
stem, one StochasticDepthModule residual block, and a softmax tail
inside SequentialModule and trains a couple of epochs as a check on the
module plumbing).

Same chain here on the synthetic digit corpus: stem Module -> two
StochasticDepthModule blocks (death rates 0.2/0.4) -> softmax tail with
take_labels.  Asserts (a) the gate statistics actually fire (both open
and closed batches observed), and (b) the chain trains to a val
accuracy far above chance.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

import sd_module  # noqa: E402

NF = 16


def stem_symbol():
    data = sym.Variable("data")
    h = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=NF,
                        name="stem_conv")
    return sym.Activation(h, act_type="relu")


def block_symbol(name):
    """Residual compute branch: conv-bn-relu-conv, shape-preserving."""
    data = sym.Variable("data")
    h = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=NF,
                        name=f"{name}_conv1")
    h = sym.BatchNorm(h, fix_gamma=False, name=f"{name}_bn")
    h = sym.Activation(h, act_type="relu")
    return sym.Convolution(h, kernel=(3, 3), pad=(1, 1), num_filter=NF,
                           name=f"{name}_conv2")


def tail_symbol():
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    h = sym.Pooling(data, global_pool=True, pool_type="avg", kernel=(1, 1))
    fc = sym.FullyConnected(sym.Flatten(h), num_hidden=4, name="fc")
    return sym.SoftmaxOutput(fc, label, name="softmax")


def synth(rs, n):
    x = rs.rand(n, 3, 8, 8).astype(np.float32) * 0.3
    y = rs.randint(0, 4, n).astype(np.float32)
    for i in range(n):
        q = int(y[i])
        x[i, q % 3, (q // 2) * 4:(q // 2) * 4 + 4,
          (q % 2) * 4:(q % 2) * 4 + 4] += 0.7
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    ctx = mx.context.default_accelerator_context()

    blocks = [
        sd_module.StochasticDepthModule(block_symbol("block0"),
                                        context=ctx, death_rate=0.2, seed=1),
        sd_module.StochasticDepthModule(block_symbol("block1"),
                                        context=ctx, death_rate=0.4, seed=2),
    ]
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(stem_symbol(), label_names=[], context=ctx))
    for b in blocks:
        seq.add(b)
    seq.add(mx.mod.Module(tail_symbol(), context=ctx), take_labels=True)

    rs = np.random.RandomState(0)
    xtr, ytr = synth(rs, 1024)
    xte, yte = synth(rs, 256)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch)

    seq.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": 2e-3},
            initializer=mx.init.Xavier(), eval_metric="acc")

    # the gates must have actually fired both ways during training
    for b in blocks:
        print(f"gate open/closed: {b.open_count}/{b.closed_count}")
        assert b.open_count > 0 and b.closed_count > 0, (
            b.open_count, b.closed_count)
    acc = dict(seq.score(val, mx.metric.create("acc")))["accuracy"]
    print(f"val acc {acc:.3f}")
    assert acc > 0.9, acc
    print("SD OK")


if __name__ == "__main__":
    main()
