"""Module-level stochastic depth (parity:
example/stochastic-depth/sd_module.py — the reference implements the
Huang et al. 2016 layer-drop as a BaseModule subclass: a residual block
whose COMPUTE branch is a wrapped Module, gated per batch by a
Bernoulli draw, with the skip branch carrying the identity; at
inference the compute branch is scaled by its survival probability).

Composable inside SequentialModule exactly like the reference's: the
wrapper forwards/backwards through the inner Module only when the gate
is open, passes input gradients through the identity either way, and
exposes the data/output plumbing SequentialModule wires on.

sd_resnet.py in this directory is the TPU-native alternative (the gate
as a Dropout inside ONE fused graph — no per-block Module dispatch);
this file exists to prove the module-composition surface the reference
example is about.
"""
import logging

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.module.base_module import BaseModule


class StochasticDepthModule(BaseModule):
    """Identity-skip residual wrapper: out = x + gate * compute(x).

    The compute symbol must map its input ('data') to an output of the
    SAME shape (identity skip only, like the reference's default
    symbol_skip=None path).
    """

    def __init__(self, symbol_compute, data_names=("data",),
                 logger=logging, context=None, death_rate=0.0, seed=None):
        super().__init__(logger=logger)
        self._symbol = symbol_compute
        self._module = mx.mod.Module(
            symbol_compute, data_names=data_names, label_names=[],
            logger=logger,
            context=context or mx.context.default_accelerator_context())
        self._open_rate = 1.0 - float(death_rate)
        self._gate_open = True
        self._rs = np.random.RandomState(seed)
        self.open_count = 0
        self.closed_count = 0
        self._outputs = None
        self._input_grads = None

    # ---- plumbing SequentialModule wires on -------------------------
    @property
    def data_names(self):
        return self._module.data_names

    @property
    def output_names(self):
        return self._module.output_names

    @property
    def data_shapes(self):
        return self._module.data_shapes

    @property
    def label_shapes(self):
        return None

    @property
    def output_shapes(self):
        return self._module.output_shapes

    def get_params(self):
        return self._module.get_params()

    def init_params(self, *args, **kwargs):
        self._module.init_params(*args, **kwargs)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        # the identity branch always needs the input grad path when
        # training, and the inner module's input grads are ADDED to it
        self._module.bind(data_shapes, None, for_training=for_training,
                          inputs_need_grad=True,
                          force_rebind=force_rebind, grad_req=grad_req)
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def init_optimizer(self, **kwargs):
        self._module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    # ---- the stochastic part ----------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        x = data_batch.data
        if is_train:
            self._gate_open = float(self._rs.rand()) < self._open_rate
            self.open_count += self._gate_open
            self.closed_count += not self._gate_open
            if self._gate_open:
                self._module.forward(data_batch, is_train=True)
                comp = self._module.get_outputs()
                self._outputs = [a + b for a, b in zip(x, comp)]
            else:
                self._outputs = list(x)
        else:
            # expectation at inference: x + p_survive * compute(x)
            self._module.forward(data_batch, is_train=False)
            comp = self._module.get_outputs()
            self._outputs = [a + self._open_rate * b
                             for a, b in zip(x, comp)]

    def backward(self, out_grads=None):
        self._input_grads = list(out_grads)
        if self._gate_open:
            self._module.backward(out_grads=out_grads)
            comp = self._module.get_input_grads()
            self._input_grads = [a + b
                                 for a, b in zip(self._input_grads, comp)]

    def update(self):
        if self._gate_open:
            self._module.update()

    def update_metric(self, eval_metric, labels):
        pass  # interior block: no labels

    def get_outputs(self, merge_multi_context=True):
        return self._outputs

    def get_input_grads(self, merge_multi_context=True):
        return self._input_grads
