#!/usr/bin/env python
"""Stochastic depth (parity: example/stochastic-depth/): residual blocks
are randomly DROPPED during training (the whole residual branch gated by
a Bernoulli survival draw, scaled by survival probability at test time —
Huang et al. 2016).  The reference implements the gate with a custom op;
here the gate rides the Dropout primitive: dropping a (N,1,1,1) mask of
ones gates the entire branch per sample and bakes in the 1/p rescale.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402


def res_block(net, nf, death_rate, name):
    branch = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=nf,
                             name=f"{name}_conv1")
    branch = sym.BatchNorm(branch, fix_gamma=False, name=f"{name}_bn1")
    branch = sym.Activation(branch, act_type="relu")
    branch = sym.Convolution(branch, kernel=(3, 3), pad=(1, 1), num_filter=nf,
                             name=f"{name}_conv2")
    if death_rate > 0:
        # Bernoulli(1-death_rate) gate on the whole branch, per sample:
        # Dropout of a ones-tensor broadcast over the branch.  Dropout's
        # train-time 1/keep rescale realizes E[gate]=1, and at inference
        # Dropout is identity — the survival-prob scaling of the paper.
        gate = sym.Dropout(sym.sum(sym.slice_axis(branch, axis=1, begin=0,
                                                  end=1) * 0.0,
                                   axis=(1, 2, 3), keepdims=True) + 1.0,
                           p=death_rate, name=f"{name}_gate")
        branch = sym.broadcast_mul(branch, gate)
    return net + branch


def build(num_blocks=4, death_rate=0.3):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    net = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                          name="conv0")
    net = sym.Activation(net, act_type="relu")
    for i in range(num_blocks):
        # linearly increasing death rate over depth, as in the paper
        rate = death_rate * (i + 1) / num_blocks
        net = res_block(net, 16, rate, f"block{i}")
    net = sym.Pooling(net, kernel=(8, 8), pool_type="avg", name="gap")
    fc = sym.FullyConnected(sym.Flatten(net), num_hidden=4, name="fc")
    return sym.SoftmaxOutput(fc, label, name="softmax")


def synth(rs, n):
    x = rs.rand(n, 3, 8, 8).astype(np.float32) * 0.3
    y = rs.randint(0, 4, n).astype(np.float32)
    for i in range(n):
        q = int(y[i])
        x[i, q % 3, (q // 2) * 4:(q // 2) * 4 + 4, (q % 2) * 4:(q % 2) * 4 + 4] += 0.7
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    xtr, ytr = synth(rs, 1024)
    xte, yte = synth(rs, 256)

    mod = mx.mod.Module(build(), context=mx.context.default_accelerator_context())
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch)
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": 2e-3},
            initializer=mx.init.Xavier(), eval_metric="acc")
    acc = dict(mod.score(val, mx.metric.create("acc")))["accuracy"]
    print(f"val acc {acc:.3f}")
    assert acc > 0.9, acc
    print("TRAIN OK")


if __name__ == "__main__":
    main()
