"""Sort-task data plumbing (parity: example/bi-lstm-sort/sort_io.py —
the reference builds a vocabulary over number tokens and an iterator
yielding (sequence, sorted-sequence) batches for per-position softmax).

Same contract: integer token ids 1..VOCAB-1 (0 reserved for padding,
as in the reference's vocab), labels are the same tokens sorted, and a
DataIter subclass feeds Module.fit.  encode/decode map printable number
strings to ids for the inference demo.
"""
import numpy as np

import mxnet_tpu as mx

VOCAB, SEQ = 30, 5


def make_data(rs, n, seq=SEQ):
    x = rs.randint(1, VOCAB, (n, seq)).astype(np.float32)
    y = np.sort(x, axis=1)
    return x, y


def encode(numbers, seq=SEQ):
    """List of ints (1..VOCAB-1) -> (1, seq) float array."""
    assert len(numbers) == seq and all(1 <= v < VOCAB for v in numbers)
    return np.asarray(numbers, np.float32).reshape(1, seq)


def decode(ids):
    return [int(v) for v in np.asarray(ids).ravel()]


class SortIter(mx.io.DataIter):
    """Fixed-corpus iterator: the CORPUS is deterministic given the
    seed; batch order comes from NDArrayIter's shuffle, which draws the
    global numpy RNG (seed np.random for a fully deterministic run, as
    lstm_sort.py does).  One fixed-length bucket keeps the toy graph
    static where the reference shuffles buckets."""

    def __init__(self, num, batch_size, seed=0, seq=SEQ):
        super().__init__()
        self.batch_size = batch_size
        self.seq = seq
        x, y = make_data(np.random.RandomState(seed), num, seq)
        self._inner = mx.io.NDArrayIter(x, y, batch_size=batch_size,
                                        shuffle=True)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()
