#!/usr/bin/env python
"""Sort-inference demo (parity: example/bi-lstm-sort/infer_sort.py —
the reference reads five numbers, runs the trained bi-LSTM, prints them
sorted).

Loads the checkpoint lstm_sort.py saved (trains one quickly if absent),
sorts sample sequences at batch 1, and asserts most come out exactly
sorted.
"""
import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import rnn_model  # noqa: E402
import sort_io  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--impl", choices=("cells", "fused"), default="fused")
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--work", default="/tmp/bilstm_sort")
    ap.add_argument("--trials", type=int, default=32)
    args = ap.parse_args()
    prefix = os.path.join(args.work, f"sort-{args.impl}")
    # epoch-specific params file, not just the symbol: a stale run with
    # different --epochs must retrain, not crash in load_checkpoint
    if not os.path.exists("%s-%04d.params" % (prefix, args.epochs)):
        subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "lstm_sort.py"),
             "--impl", args.impl, "--work", args.work,
             "--epochs", str(args.epochs)], check=True)
    model = rnn_model.BiLSTMSortModel(prefix, args.epochs, args.impl)
    rs = np.random.RandomState(3)
    good = 0
    for i in range(args.trials):
        seq = [int(v) for v in rs.randint(1, sort_io.VOCAB, sort_io.SEQ)]
        pred = sort_io.decode(model.sort(sort_io.encode(seq)))
        ok = pred == sorted(seq)
        good += ok
        if i < 3:
            print(f"{seq} -> {pred}{'' if ok else '  (expected %s)' % sorted(seq)}")
    rate = good / args.trials
    print(f"exact sorts: {good}/{args.trials}")
    assert rate >= 0.3, rate
    print("INFER OK")


if __name__ == "__main__":
    main()
