#!/usr/bin/env python
"""Bi-LSTM sort training driver (parity:
example/bi-lstm-sort/lstm_sort.py — the reference trains the
bidirectional stack with per-position softmax and Perplexity metric).

Trains either symbol builder (--impl cells|fused, lstm.py), reports
per-position accuracy AND whole-sequence exact-sort rate, and saves a
Module checkpoint infer_sort.py loads.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

import lstm  # noqa: E402
import sort_io  # noqa: E402


def exact_sort_rate(mod, it):
    """Fraction of sequences whose WHOLE output is the correct sort."""
    it.reset()
    good = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(1)   # (N, seq)
        truth = batch.label[0].asnumpy()
        good += int((pred == truth).all(axis=1).sum())
        total += pred.shape[0]
    return good / total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--impl", choices=("cells", "fused"), default="fused")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--work", default="/tmp/bilstm_sort")
    ap.add_argument("--min-exact", type=float, default=0.3)
    # chance exact-sort rate is (1/VOCAB)^SEQ ~= 4e-8, so 0.3 is
    # already an unambiguous "it sorts" signal at toy budget
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    os.makedirs(args.work, exist_ok=True)
    # seed EVERYTHING: Xavier init and NDArrayIter's shuffle draw from
    # the global numpy RNG, and the quality gates below sit close
    # enough to typical results that an unseeded run would flake CI
    np.random.seed(42)
    mx.random.seed(42)

    net = lstm.build(args.impl, args.batch)
    train = sort_io.SortIter(2048, args.batch, seed=0)
    val = sort_io.SortIter(256, args.batch, seed=1)
    # the fused RNN's begin states are symbol arguments; pin them to
    # zero and freeze them (mx.init.Mixed + fixed_param_names) so train
    # and inference agree on "sequences start from a zero state"
    state_names = [n for n in net.list_arguments() if "state" in n]
    mod = mx.mod.Module(net, fixed_param_names=state_names,
                        context=mx.context.default_accelerator_context())
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Mixed([".*state.*", ".*"],
                                      [mx.init.Zero(), mx.init.Xavier()]),
            eval_metric=mx.metric.Perplexity(ignore_label=None, axis=1))
    acc = dict(mod.score(val, mx.metric.create("acc")))["accuracy"]
    exact = exact_sort_rate(mod, val)
    print(f"impl={args.impl} per-position acc {acc:.3f} "
          f"exact-sort rate {exact:.3f}")
    # assert BEFORE saving: a failed run must not leave a checkpoint
    # that infer_sort.py would trust on its next invocation
    assert acc > 0.8, acc
    assert exact >= args.min_exact, exact
    prefix = os.path.join(args.work, f"sort-{args.impl}")
    arg_p, aux_p = mod.get_params()
    mx.model.save_checkpoint(prefix, args.epochs, net, arg_p, aux_p)
    print("SORT OK")


if __name__ == "__main__":
    main()
