"""Bi-LSTM sort symbols (parity: example/bi-lstm-sort/lstm.py — the
reference hand-builds the forward and backward LSTM stacks step by step
and concatenates per-position states).

Two equivalent builders here, both returning the same multi_output
softmax head:

- ``build_cells``: the reference's shape, expressed through the cell
  API — explicit ``mx.rnn.LSTMCell`` pair under a ``BidirectionalCell``
  unroll (each timestep is its own symbol node, like the reference's
  per-step ``lstm()`` calls).
- ``build_fused``: the TPU-native fast path — one ``sym.RNN`` op whose
  whole bidirectional scan compiles as a single fused XLA loop.

lstm_sort.py trains either and infer_sort.py loads either; agreement
between the two is asserted by the per-position accuracy floors.
"""
import mxnet_tpu as mx
from mxnet_tpu import sym

from sort_io import SEQ, VOCAB

EMBED, HIDDEN = 16, 64


def _head(h2, batch, seq):
    """(N*seq, 2H) feature rows -> per-position VOCAB softmax."""
    fc = sym.FullyConnected(h2, num_hidden=VOCAB, name="fc")
    fc = sym.Reshape(fc, shape=(batch, seq, VOCAB))
    fc = sym.transpose(fc, axes=(0, 2, 1))          # (N, VOCAB, seq)
    label = sym.Variable("softmax_label")
    return sym.SoftmaxOutput(fc, label, multi_output=True,
                             normalization="valid", name="softmax")


def build_cells(batch, seq=SEQ):
    data = sym.Variable("data")
    embed = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                          name="embed")             # (N, seq, EMBED)
    bi = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(HIDDEN, prefix="l_"),
        mx.rnn.LSTMCell(HIDDEN, prefix="r_"))
    # constant zero initial states (sym.zeros) instead of begin_state
    # Variables: no extra bind inputs, so Module sees only data/label
    zeros = [sym.zeros(shape=(batch, HIDDEN)) for _ in range(4)]
    outputs, _ = bi.unroll(seq, inputs=embed, begin_state=zeros,
                           layout="NTC")
    steps = [sym.expand_dims(o, axis=1) for o in outputs]
    h = sym.Concat(*steps, dim=1)                   # (N, seq, 2H)
    h2 = sym.Reshape(h, shape=(-1, 2 * HIDDEN))
    return _head(h2, batch, seq)


def build_fused(batch, seq=SEQ):
    data = sym.Variable("data")
    embed = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                          name="embed")
    x = sym.transpose(embed, axes=(1, 0, 2))        # (seq, N, EMBED)
    rnn = sym.RNN(x, state_size=HIDDEN, num_layers=1, mode="lstm",
                  bidirectional=True, name="bilstm")  # (seq, N, 2H)
    h = sym.transpose(rnn, axes=(1, 0, 2))
    h2 = sym.Reshape(h, shape=(-1, 2 * HIDDEN))
    return _head(h2, batch, seq)


def build(impl, batch, seq=SEQ):
    if impl == "cells":
        return build_cells(batch, seq)
    if impl == "fused":
        return build_fused(batch, seq)
    raise ValueError(f"impl must be cells|fused, got {impl!r}")
