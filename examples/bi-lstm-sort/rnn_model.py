"""Inference wrapper (parity: example/bi-lstm-sort/rnn_model.py — the
reference's BiLSTMInferenceModel binds the trained symbol at batch 1
and exposes a forward() that returns per-position probabilities)."""
import numpy as np

import mxnet_tpu as mx

from sort_io import SEQ


class BiLSTMSortModel:
    def __init__(self, prefix, epoch, impl="fused", seq=SEQ, ctx=None):
        # like the reference's BiLSTMInferenceModel, REBUILD the symbol
        # at batch 1 and load only the params — the training symbol has
        # the train batch baked into its head reshape
        import lstm

        _, arg, aux = mx.model.load_checkpoint(prefix, epoch)
        net = lstm.build(impl, 1, seq)
        self._mod = mx.mod.Module(
            net, context=ctx or mx.context.default_accelerator_context())
        self._mod.bind(data_shapes=[("data", (1, seq))],
                       label_shapes=[("softmax_label", (1, seq))],
                       for_training=False)
        # the fused RNN's begin-state args were saved at TRAIN batch
        # shape ((dirs, 64, H)); inference starts from zero states at
        # batch 1, so drop them and let Zero() init fill the slots
        expected = dict(zip(net.list_arguments(), net.infer_shape(
            data=(1, seq), softmax_label=(1, seq))[0]))
        arg = {k: v for k, v in arg.items()
               if "state" not in k or v.shape == tuple(expected[k])}
        self._mod.init_params(mx.init.Zero())
        self._mod.set_params(arg, aux, allow_missing=True)
        self._seq = seq

    def sort(self, x):
        """(1, seq) token ids -> (seq,) predicted sorted ids."""
        batch = mx.io.DataBatch(
            [mx.nd.array(x)],
            [mx.nd.array(np.zeros((1, self._seq), np.float32))])
        self._mod.forward(batch, is_train=False)
        probs = self._mod.get_outputs()[0].asnumpy()  # (1, VOCAB, seq)
        return probs[0].argmax(0)
