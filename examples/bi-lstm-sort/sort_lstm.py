#!/usr/bin/env python
"""bi-lstm-sort (parity: example/bi-lstm-sort/): learn to sort short
sequences of symbols with a bidirectional LSTM — input a sequence of
token ids, output the same tokens in sorted order, trained per-position.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

VOCAB, SEQ, HIDDEN, EMBED = 30, 5, 64, 16


def build(batch):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                          name="embed")             # (N, SEQ, EMBED)
    x = sym.transpose(embed, axes=(1, 0, 2))        # (SEQ, N, EMBED)
    rnn = sym.RNN(x, state_size=HIDDEN, num_layers=1, mode="lstm",
                  bidirectional=True, name="bilstm")  # (SEQ, N, 2H)
    h = sym.transpose(rnn, axes=(1, 0, 2))          # (N, SEQ, 2H)
    h = sym.Reshape(h, shape=(-1, 2 * HIDDEN))
    fc = sym.FullyConnected(h, num_hidden=VOCAB, name="fc")  # (N*SEQ, VOCAB)
    fc = sym.Reshape(fc, shape=(batch, SEQ, VOCAB))
    fc = sym.transpose(fc, axes=(0, 2, 1))          # (N, VOCAB, SEQ)
    return sym.SoftmaxOutput(fc, label, multi_output=True,
                             normalization="valid", name="softmax")


def synth(rs, n):
    x = rs.randint(1, VOCAB, (n, SEQ)).astype(np.float32)
    y = np.sort(x, axis=1)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=8)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    xtr, ytr = synth(rs, 2048)
    xte, yte = synth(rs, 256)

    mod = mx.mod.Module(build(args.batch),
                        context=mx.context.default_accelerator_context())
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch)
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier(),
            eval_metric="acc")
    acc = dict(mod.score(val, mx.metric.create("acc")))["accuracy"]
    print(f"per-position sort accuracy {acc:.3f}")
    assert acc > 0.7, acc
    print("TRAIN OK")


if __name__ == "__main__":
    main()
