#!/usr/bin/env python
"""Multi-process data-parallel training (parity: tests/nightly/
dist_lenet.py — the reference's canonical dist_sync workload).

Run with the launcher:

    python tools/launch.py -n 2 -s 1 --launcher local \
        python examples/distributed/dist_lenet.py --kv-store dist_sync

Each worker trains on its shard (part_index=rank / num_parts=size, the
same sharding contract as dmlc::InputSplit); gradients aggregate on the
parameter server.  On a TPU pod, drop the servers and use
kvstore=device: the aggregation rides ICI collectives inside the step."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--kv-store", default="dist_sync")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    kv = mx.kv.create(args.kv_store)
    (xtr, ytr), (xte, yte) = get_synthetic_mnist(4096, 512)
    # shard the data by worker rank (parity: InputSplit part_index)
    shard = slice(kv.rank, len(xtr), kv.num_workers)
    train = mx.io.NDArrayIter(xtr[shard], ytr[shard],
                              batch_size=args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch_size)

    net = models.get_symbol("lenet", num_classes=10, image_shape=(1, 28, 28))
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs, kvstore=kv,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    acc = mod.score(val, "acc")[0][1]
    logging.info("worker %d/%d final val acc %.3f", kv.rank,
                 kv.num_workers, acc)
    if acc < 0.8:
        raise SystemExit(f"accuracy gate failed: {acc}")
