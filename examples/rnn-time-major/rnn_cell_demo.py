#!/usr/bin/env python
"""Time-major RNN unrolling (parity: example/rnn-time-major/
rnn_cell_demo.py).

The reference keeps sequences time-major (T, N, C) so each unrolled step
slices a contiguous (N, C) block — on GPU that saves a transpose per
step.  The same layout choice exists here through ``unroll(layout=...)``;
on TPU the fused `scan` path of FusedRNNCell consumes time-major
directly.  This demo trains the same model both ways and checks they
agree."""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def build(seq_len, vocab, num_embed, num_hidden, num_classes, layout,
          batch_size):
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                             name="embed")
    if layout == "TNC":
        # batch-major input -> time-major for the unroll
        embed = mx.sym.SwapAxis(embed, dim1=0, dim2=1)
    cell = mx.rnn.LSTMCell(num_hidden, prefix="lstm_")
    # zero init states with declared shapes so bind-time inference closes
    begin = [mx.sym.Variable(f"init_{t}", shape=(batch_size, num_hidden),
                             init=mx.init.Zero(), lr_mult=0.0)
             for t in ("h", "c")]
    outputs, _ = cell.unroll(seq_len, inputs=embed, begin_state=begin,
                             layout=layout, merge_outputs=False)
    last = outputs[-1]
    fc = mx.sym.FullyConnected(last, num_hidden=num_classes, name="out_fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def main():
    ap = argparse.ArgumentParser(description="time-major RNN demo")
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-hidden", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=2)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    vocab, num_classes = 100, 5
    rs = np.random.RandomState(0)
    seqs = rs.randint(0, vocab, (4000, args.seq_len)).astype(np.float32)
    labels = (seqs.sum(axis=1) % num_classes).astype(np.float32)

    results = {}
    for layout in ("NTC", "TNC"):
        it = mx.io.NDArrayIter(seqs, labels, args.batch_size, shuffle=True)
        net = build(args.seq_len, vocab, 32, args.num_hidden, num_classes,
                    layout, args.batch_size)
        mod = mx.mod.Module(net)
        tic = time.time()
        mod.fit(it, optimizer="adam",
                optimizer_params={"learning_rate": 0.005},
                initializer=mx.init.Xavier(),
                num_epoch=args.num_epochs)
        metric = mx.metric.Accuracy()
        it.reset()
        mod.score(it, metric)
        results[layout] = (metric.get()[1], time.time() - tic)
        logging.info("%s: acc %.3f, %.1fs", layout, *results[layout])

    a, b = results["NTC"][0], results["TNC"][0]
    print(f"NTC acc={a:.3f}  TNC acc={b:.3f} (layouts agree on the task)")


if __name__ == "__main__":
    main()
