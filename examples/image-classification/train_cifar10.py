#!/usr/bin/env python
"""Train a ResNet on CIFAR-10 (parity: example/image-classification/
train_cifar10.py)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from common import data, fit  # noqa: E402
from mxnet_tpu import models  # noqa: E402

if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train CIFAR-10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    parser.set_defaults(network="resnet-20", num_epochs=10, batch_size=128,
                        lr=0.05, lr_step_epochs="60,80", num_classes=10,
                        num_examples=4096)
    args = parser.parse_args()

    net = models.get_symbol(args.network, num_classes=args.num_classes,
                            image_shape=(3, 32, 32))
    fit.fit(args, net, data.get_cifar10_iter)
