#!/usr/bin/env python
"""Inference throughput across the symbol zoo (parity:
example/image-classification/benchmark_score.py — the script behind the
reference's perf.md tables, docs/how_to/perf.md:30-100)."""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def score(network, batch_size, num_batches=10, image_shape=(3, 224, 224),
          num_classes=1000, dev=None):
    sym = models.get_symbol(network, num_classes=num_classes)
    data_shape = (batch_size,) + image_shape
    ex = sym.simple_bind(ctx=dev, grad_req="null", data=data_shape)
    init = mx.init.Xavier(magnitude=2.0)
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(name, arr)
    data = mx.nd.array(np.random.uniform(size=data_shape).astype(np.float32))

    # warmup (compile) then timed steps
    ex.arg_dict["data"][:] = data.asnumpy()
    for _ in range(2):
        ex.forward(is_train=False)
        ex.outputs[0].wait_to_read()
    tic = time.time()
    for _ in range(num_batches):
        ex.forward(is_train=False)
    ex.outputs[0].wait_to_read()
    return num_batches * batch_size / (time.time() - tic)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--networks", type=str,
                    default="alexnet,vgg-16,inception-bn,inception-v3,"
                            "resnet-50,resnet-152")
    ap.add_argument("--batch-sizes", type=str, default="1,32")
    ap.add_argument("--num-classes", type=int, default=1000)
    args = ap.parse_args()
    for net in args.networks.split(","):
        for b in (int(x) for x in args.batch_sizes.split(",")):
            try:
                ips = score(net, b)
                print(f"network: {net:20s} batch: {b:3d}  {ips:9.1f} img/s",
                      flush=True)
            except Exception as e:
                print(f"network: {net:20s} batch: {b:3d}  FAILED {e}",
                      flush=True)
