"""Dataset iterators for the image-classification examples.

Parity: example/image-classification/common/data.py (reference) — which
downloads MNIST/CIFAR RecordIO.  This environment has no network egress,
so each loader prefers on-disk data (``data/`` next to the scripts, same
filenames as the reference) and otherwise synthesizes a deterministic
learnable dataset of the same shape, keeping every example runnable.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

import mxnet_tpu as mx

DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "data")


def _synthetic_images(num, shape, num_classes, seed):
    """Class-dependent blob patterns + noise: learnable by small convnets
    but not trivially linearly separable."""
    rs = np.random.RandomState(seed)
    c, h, w = shape
    proto = rs.uniform(0, 1, (num_classes, c, h, w)).astype(np.float32)
    y = rs.randint(0, num_classes, num).astype(np.float32)
    x = proto[y.astype(int)] + rs.normal(0, 0.3, (num, c, h, w)).astype(np.float32)
    return x.astype(np.float32), y


def get_mnist_iter(args):
    """MNIST (real idx files if present, else synthetic 1x28x28)."""
    batch = args.batch_size
    names = ["train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
             "t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"]
    paths = [os.path.join(DATA_DIR, n) for n in names]
    if all(os.path.exists(p) for p in paths):
        def read(images, labels):
            with gzip.open(labels) as f:
                struct.unpack(">II", f.read(8))
                lab = np.frombuffer(f.read(), dtype=np.uint8).astype(np.float32)
            with gzip.open(images) as f:
                _, num, rows, cols = struct.unpack(">IIII", f.read(16))
                img = np.frombuffer(f.read(), dtype=np.uint8)
                img = img.reshape(num, 1, rows, cols).astype(np.float32) / 255
            return img, lab

        xtr, ytr = read(paths[0], paths[1])
        xte, yte = read(paths[2], paths[3])
    else:
        xtr, ytr = _synthetic_images(4096, (1, 28, 28), 10, seed=7)
        xte, yte = _synthetic_images(1024, (1, 28, 28), 10, seed=8)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=batch, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=batch)
    return train, val


def get_cifar10_iter(args):
    """CIFAR-10 (RecordIO shards if present, else synthetic 3x32x32)."""
    batch = args.batch_size
    rec = os.path.join(DATA_DIR, "cifar10_train.rec")
    if os.path.exists(rec):
        train = mx.image.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 32, 32), batch_size=batch,
            rand_crop=True, rand_mirror=True)
        val = mx.image.ImageRecordIter(
            path_imgrec=os.path.join(DATA_DIR, "cifar10_val.rec"),
            data_shape=(3, 32, 32), batch_size=batch)
        return train, val
    xtr, ytr = _synthetic_images(4096, (3, 32, 32), 10, seed=11)
    xte, yte = _synthetic_images(1024, (3, 32, 32), 10, seed=12)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=batch, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=batch)
    return train, val


def get_imagenet_iter(args):
    """ImageNet RecordIO pipeline (parity: train_imagenet.py data args);
    synthetic 3x224x224 when no --data-train rec is given."""
    batch = args.batch_size
    shape = tuple(int(x) for x in args.image_shape.split(","))
    if getattr(args, "data_train", None) and os.path.exists(args.data_train):
        workers = int(getattr(args, "data_nprocs", 0) or 0)
        if workers > 0:
            # sharded-host pipeline: N decode processes over a
            # shared-memory ring (mp_io.py) — the scale-out path when
            # one process's threads can't feed the chip.  Host sharding
            # (part_index/num_parts) composes with the worker fan-out;
            # --data-nthreads is split across the workers; the device
            # copy overlaps via DevicePrefetchIter.
            train = mx.io.DevicePrefetchIter(
                mx.image.MultiProcessImageRecordIter(
                    path_imgrec=args.data_train, data_shape=shape,
                    batch_size=batch, num_workers=workers,
                    part_index=getattr(args, "part_index", 0),
                    num_parts=getattr(args, "num_parts", 1),
                    preprocess_threads=max(1,
                                           args.data_nthreads // workers),
                    rand_crop=True, rand_mirror=True))
        else:
            train = mx.image.ImageRecordIter(
                path_imgrec=args.data_train, data_shape=shape,
                batch_size=batch, rand_crop=True, rand_mirror=True,
                part_index=getattr(args, "part_index", 0),
                num_parts=getattr(args, "num_parts", 1),
                preprocess_threads=args.data_nthreads)
        val = None
        if getattr(args, "data_val", None) and os.path.exists(args.data_val):
            val = mx.image.ImageRecordIter(
                path_imgrec=args.data_val, data_shape=shape, batch_size=batch,
                preprocess_threads=args.data_nthreads)
        return train, val
    xtr, ytr = _synthetic_images(args.num_examples, shape,
                                 args.num_classes, seed=21)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=batch, shuffle=True)
    return train, None
