"""Shared training harness for the image-classification examples.

Parity: example/image-classification/common/fit.py (reference): one
argparse surface (network, devices, batch, lr schedule, kvstore,
checkpointing, resume) + one ``fit()`` that wires iterators, Module,
Speedometer and checkpoint callbacks.  Device flags are TPU-flavored:
``--devices 0,1,..`` builds the data-parallel mesh (the reference's
``--gpus``).
"""
from __future__ import annotations

import argparse
import logging
import os

import mxnet_tpu as mx


def add_fit_args(parser: argparse.ArgumentParser):
    train = parser.add_argument_group("Training")
    train.add_argument("--network", type=str, default="resnet-18")
    train.add_argument("--devices", type=str, default="",
                       help="comma list of device ids for data parallelism"
                            " (empty = default device)")
    train.add_argument("--kv-store", type=str, default="device")
    train.add_argument("--num-epochs", type=int, default=10)
    train.add_argument("--lr", type=float, default=0.1)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default="",
                       help="e.g. 30,60 — epochs to decay lr at")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--batch-size", type=int, default=128)
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str, default=None)
    train.add_argument("--load-epoch", type=int, default=None)
    train.add_argument("--monitor", type=int, default=0,
                       help="log weight/grad stats every N batches")
    train.add_argument("--num-examples", type=int, default=4096)
    train.add_argument("--num-classes", type=int, default=10)
    train.add_argument("--data-nthreads", type=int, default=4)
    train.add_argument("--data-nprocs", type=int, default=0,
                       help="decode worker PROCESSES (shared-memory ring"
                            " pipeline, mp_io.py); 0 = threaded iterator")
    return parser


def _devices(args):
    if not args.devices:
        return None
    ids = [int(x) for x in args.devices.split(",") if x != ""]
    dev = mx.context.default_accelerator_context().device_type
    return [mx.Context(dev, i) for i in ids]


def _lr_scheduler(args, steps_per_epoch):
    if not args.lr_step_epochs:
        return None
    epochs = [int(e) for e in args.lr_step_epochs.split(",")]
    begin = args.load_epoch or 0
    steps = [(e - begin) * steps_per_epoch for e in epochs if e > begin]
    if not steps:
        return None
    return mx.lr_scheduler.MultiFactorScheduler(step=steps,
                                                factor=args.lr_factor)


def fit(args, network, data_loader, **kwargs):
    """Parity: common/fit.py fit() — train `network` with `data_loader`
    (a fn(args) -> (train_iter, val_iter))."""
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    logging.info("start with arguments %s", args)
    train, val = data_loader(args)

    devs = _devices(args)
    mod = mx.mod.Module(symbol=network, context=devs)

    arg_params, aux_params = None, None
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        logging.info("resumed from %s-%04d.params",
                     args.model_prefix, args.load_epoch)

    steps_per_epoch = max(args.num_examples // args.batch_size, 1)
    optimizer_params = {
        "learning_rate": args.lr,
        "wd": args.wd,
        "lr_scheduler": _lr_scheduler(args, steps_per_epoch),
    }
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom

    checkpoint = (mx.callback.do_checkpoint(args.model_prefix)
                  if args.model_prefix else None)
    monitor = (mx.Monitor(args.monitor, pattern=".*") if args.monitor > 0
               else None)

    mod.fit(train,
            begin_epoch=args.load_epoch or 0,
            num_epoch=args.num_epochs,
            eval_data=val,
            eval_metric=kwargs.get("eval_metric", "acc"),
            kvstore=args.kv_store,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            arg_params=arg_params,
            aux_params=aux_params,
            allow_missing=True,
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       args.disp_batches),
            epoch_end_callback=checkpoint,
            monitor=monitor)
    return mod
