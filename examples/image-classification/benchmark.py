#!/usr/bin/env python
"""Training-throughput sweep (parity:
example/image-classification/benchmark.py — the reference sweeps
network × batch-size × #GPUs on dummy data and logs img/s; here the
device axis is a dp mesh over however many devices the backend exposes,
the TPU-native equivalent of its multi-GPU KVStore sweep).

  python benchmark.py --networks resnet-50 inception-v3 \
                      --batch-sizes 16 32 --dp 1 2 4

On a CPU box set XLA_FLAGS=--xla_force_host_platform_device_count=8
MXTPU_PLATFORM=cpu to sweep virtual devices.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402


def bench_one(network, batch, dp, iters, dtype):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.trainer import FusedTrainer

    if dp > len(jax.devices()):
        return None
    mesh = create_mesh((dp,), axes=("data",),
                       devices=jax.devices()[:dp]) if dp > 1 else None
    if network == "mlp":
        net, shape = models.get_symbol("mlp"), (784,)
    else:
        net, shape = models.get_symbol(network, num_classes=1000), \
            (3, 224, 224)
    tr = FusedTrainer(
        net, optimizer="sgd",
        optimizer_params={"lr": 0.05, "momentum": 0.9,
                          "rescale_grad": 1.0 / batch},
        dtype=jnp.bfloat16 if dtype == "bf16" else jnp.float32,
        mesh=mesh)
    tr.init(data=(batch,) + shape)
    rs = np.random.RandomState(0)
    feed = {"data": jax.device_put(
        rs.uniform(0, 1, (batch,) + shape).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 1000, batch).astype(np.float32))}

    def barrier():
        name = sorted(tr.params)[0]
        return float(np.asarray(tr.params[name]).ravel()[0])

    for _ in range(4):
        tr.step(**feed)
    barrier()
    tic = time.perf_counter()
    for _ in range(iters):
        tr.step(**feed)
    barrier()
    return batch * iters / (time.perf_counter() - tic)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--networks", nargs="+",
                    default=["resnet-50", "inception-v3"])
    ap.add_argument("--batch-sizes", type=int, nargs="+", default=[32])
    ap.add_argument("--dp", type=int, nargs="+", default=[1],
                    help="data-parallel device counts to sweep")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "fp32"])
    args = ap.parse_args()

    print(f"{'network':16s} {'batch':>5s} {'dp':>3s} {'img/s':>9s}")
    for net in args.networks:
        for batch in args.batch_sizes:
            for dp in args.dp:
                if batch % dp:
                    print(f"{net:16s} {batch:5d} {dp:3d}   (batch not "
                          f"divisible by dp)")
                    continue
                rate = bench_one(net, batch, dp, args.iters, args.dtype)
                if rate is None:
                    print(f"{net:16s} {batch:5d} {dp:3d}   (needs {dp} "
                          "devices)")
                    continue
                print(f"{net:16s} {batch:5d} {dp:3d} {rate:9.1f}")


if __name__ == "__main__":
    main()
