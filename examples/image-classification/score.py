#!/usr/bin/env python
"""Score a saved checkpoint on a validation set (parity:
example/image-classification/score.py — load with mx.model, bind
forward-only, run acc/top-5 over a rec file).

With --data-val absent, runs the self-contained path: trains a small
model for one epoch on synthetic data, saves it, scores it back, and
asserts the scored accuracy matches Module.score.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def score(model_prefix, epoch, data_iter, metrics, ctx, max_num_examples=None):
    """The reference's score(): checkpoint -> forward-only module ->
    metric sweep; returns (metric results, images/sec)."""
    symbol, arg_params, aux_params = mx.model.load_checkpoint(
        model_prefix, epoch)
    mod = mx.mod.Module(symbol, context=ctx, label_names=["softmax_label"])
    mod.bind(for_training=False, data_shapes=data_iter.provide_data,
             label_shapes=data_iter.provide_label)
    mod.set_params(arg_params, aux_params)
    if not isinstance(metrics, list):
        metrics = [metrics]
    num = 0
    tic = time.time()
    for batch in data_iter:
        mod.forward(batch, is_train=False)
        for m in metrics:
            mod.update_metric(m, batch.label)
        num += batch.data[0].shape[0]
        if max_num_examples and num >= max_num_examples:
            break
    return [m.get() for m in metrics], num / (time.time() - tic)


def self_test(ctx):
    np.random.seed(0)  # initializers draw from numpy's global RNG
    rs = np.random.RandomState(0)
    x = rs.uniform(size=(512, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.float32)
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)
    val = mx.io.NDArrayIter(x[:128], y[:128], batch_size=32)

    from mxnet_tpu import sym

    net = sym.SoftmaxOutput(sym.FullyConnected(sym.Activation(
        sym.FullyConnected(sym.Variable("data"), num_hidden=32, name="fc1"),
        act_type="relu"), num_hidden=2, name="fc2"), name="softmax")
    mod = mx.mod.Module(net, context=ctx)
    mod.fit(train, num_epoch=25, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Xavier())
    prefix = "/tmp/score_selftest"
    mod.save_checkpoint(prefix, 25)

    val.reset()
    oracle = dict(mod.score(val, mx.metric.Accuracy()))["accuracy"]
    val.reset()
    (results,), speed = score(prefix, 25, val, mx.metric.Accuracy(), ctx)
    name, acc = results
    print(f"scored {name}={acc:.4f} at {speed:.0f} img/s "
          f"(module oracle {oracle:.4f})")
    assert abs(acc - oracle) < 1e-6
    assert acc > 0.9, acc
    print("SCORE OK")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-prefix")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--data-val", help="validation .rec file")
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--max-num-examples", type=int)
    args = ap.parse_args()
    ctx = mx.context.default_accelerator_context()

    if not args.data_val:
        self_test(ctx)
        return
    shape = tuple(int(v) for v in args.image_shape.split(","))
    val = mx.io.ImageRecordIter(
        path_imgrec=args.data_val, data_shape=shape,
        batch_size=args.batch_size, rand_crop=False, rand_mirror=False)
    metrics = [mx.metric.Accuracy(), mx.metric.TopKAccuracy(top_k=5)]
    results, speed = score(args.model_prefix, args.epoch, val, metrics, ctx,
                           args.max_num_examples)
    print(f"{speed:.1f} img/s")
    for name, value in results:
        print(f"{name}: {value:.5f}")


if __name__ == "__main__":
    main()
