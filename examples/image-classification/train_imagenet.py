#!/usr/bin/env python
"""Train ImageNet-scale networks (parity: example/image-classification/
train_imagenet.py — the reference's north-star benchmark config,
kvstore=device ⇒ ICI all-reduce on TPU).

With --fused 1 the whole train step (fwd+bwd+optimizer) compiles to one
donated XLA computation with bf16 compute — the TPU-native fast path
bench.py measures."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from common import data, fit  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def train_fused(args, net):
    import logging
    import time

    import numpy as np

    from mxnet_tpu.trainer import FusedTrainer

    logging.basicConfig(level=logging.INFO)
    shape = tuple(int(x) for x in args.image_shape.split(","))
    train, _ = data.get_imagenet_iter(args)
    tr = FusedTrainer(net, optimizer=args.optimizer,
                      optimizer_params={"lr": args.lr, "momentum": args.mom,
                                        "wd": args.wd,
                                        "rescale_grad": 1.0 / args.batch_size})
    tr.init(data=(args.batch_size,) + shape)
    for epoch in range(args.num_epochs):
        train.reset()
        tic, n = time.time(), 0
        for batch in train:
            tr.step(data=batch.data[0].asnumpy(),
                    softmax_label=batch.label[0].asnumpy())
            n += args.batch_size
            if n % (args.disp_batches * args.batch_size) == 0:
                logging.info("Epoch[%d] %.1f img/s", epoch,
                             n / (time.time() - tic))
        logging.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train ImageNet",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--data-train", type=str, default=None,
                        help="RecordIO file (synthetic data if absent)")
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--fused", type=int, default=1,
                        help="1: FusedTrainer one-XLA-computation step")
    parser.set_defaults(network="resnet-50", num_epochs=1, batch_size=32,
                        lr=0.1, num_classes=1000, num_examples=1024)
    args = parser.parse_args()

    net = models.get_symbol(args.network, num_classes=args.num_classes)
    if args.fused:
        train_fused(args, net)
    else:
        fit.fit(args, net, data.get_imagenet_iter)
