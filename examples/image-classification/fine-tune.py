#!/usr/bin/env python
"""Fine-tune a pretrained checkpoint on a new dataset (parity:
example/image-classification/fine-tune.py): load ``--pretrained-model``,
chop the head at the last feature layer, attach a fresh FC+Softmax for
``--num-classes``, and train with the backbone params loaded."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from common import data, fit  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def get_fine_tune_model(symbol, arg_params, num_classes,
                        layer_name="flatten"):
    """Parity: fine-tune.py get_fine_tune_model — new head on an internal
    feature layer; backbone weights reused, head initialized fresh."""
    internals = symbol.get_internals()
    outputs = internals.list_outputs()
    candidates = [n for n in outputs if layer_name in n]
    if not candidates:
        raise ValueError(f"no internal output matching {layer_name!r}")
    net = internals[outputs.index(candidates[-1])]
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc_new")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    new_args = {k: v for k, v in arg_params.items()
                if k in net.list_arguments()}
    return net, new_args


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="fine-tune a pretrained model",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    parser.add_argument("--pretrained-model", type=str, required=True,
                        help="checkpoint prefix to start from")
    parser.add_argument("--pretrained-epoch", type=int, default=0)
    parser.add_argument("--layer-before-fullc", type=str, default="flatten")
    parser.set_defaults(network="resnet-18", num_epochs=2, batch_size=64,
                        lr=0.01, num_classes=10)
    args = parser.parse_args()

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.pretrained_model, args.pretrained_epoch)
    net, new_args = get_fine_tune_model(sym, arg_params, args.num_classes,
                                        args.layer_before_fullc)

    logging.basicConfig(level=logging.INFO)
    train, val = data.get_cifar10_iter(args)
    mod = mx.mod.Module(net, context=None)
    mod.fit(train, eval_data=val,
            num_epoch=args.num_epochs,
            arg_params=new_args, aux_params=aux_params, allow_missing=True,
            kvstore=args.kv_store, optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr, "momentum": args.mom,
                              "wd": args.wd},
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       args.disp_batches))
