#!/usr/bin/env python
"""Train MLP/LeNet on MNIST (parity: example/image-classification/
train_mnist.py — the reference's minimum end-to-end slice and the first
milestone of SURVEY.md §7's build order)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from common import data, fit  # noqa: E402
from mxnet_tpu import models  # noqa: E402

if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train MNIST",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    parser.set_defaults(network="mlp", num_epochs=5, batch_size=64, lr=0.05,
                        num_classes=10, num_examples=4096, kv_store="local")
    args = parser.parse_args()

    if args.network == "mlp":
        net = models.mlp.get_symbol(num_classes=args.num_classes)
    else:
        net = models.get_symbol(args.network, num_classes=args.num_classes,
                                image_shape=(1, 28, 28))
    fit.fit(args, net, data.get_mnist_iter)
