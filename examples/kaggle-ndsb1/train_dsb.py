#!/usr/bin/env python
"""National Data Science Bowl (plankton) pipeline (parity:
example/kaggle-ndsb1/ — gen_img_list + train_dsb + predict_dsb).

End-to-end competition workflow on one script: build a RecordIO dataset
from an image folder tree (class = subdirectory), train a small conv
net with the ImageRecordIter augmentation pipeline, then write a
probability-matrix submission CSV.  With no dataset present it
fabricates a tiny synthetic image tree first, so the whole flow runs
out of the box."""
import argparse
import csv
import glob
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def synth_dataset(root, num_classes=6, per_class=40, size=48):
    from PIL import Image

    rs = np.random.RandomState(0)
    for c in range(num_classes):
        d = os.path.join(root, f"class_{c:02d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            # each class = blob at a class-specific location + noise
            img = rs.randint(0, 60, (size, size), dtype=np.uint8)
            cx, cy = 8 + 5 * (c % 3), 8 + 10 * (c // 3)
            img[cy:cy + 12, cx:cx + 12] += 150
            Image.fromarray(img).convert("L").save(
                os.path.join(d, f"{i:03d}.png"))


def gen_img_list(root):
    """Parity: gen_img_list.py — (index, label, relpath) triples."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    items = []
    for label, cls in enumerate(classes):
        for path in sorted(glob.glob(os.path.join(root, cls, "*"))):
            items.append((len(items), float(label),
                          os.path.relpath(path, root)))
    return items, classes


def net_symbol(num_classes):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, name="conv1", kernel=(3, 3),
                             num_filter=32, pad=(1, 1))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Convolution(net, name="conv2", kernel=(3, 3),
                             num_filter=64, pad=(1, 1))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=128)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Dropout(net, p=0.25)
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    ap = argparse.ArgumentParser(description="NDSB plankton workflow")
    ap.add_argument("--data-root", type=str, default=None,
                    help="image folder tree (class per subdir); synthetic "
                         "data is generated when omitted")
    ap.add_argument("--work-dir", type=str, default="/tmp/ndsb_demo")
    ap.add_argument("--size", type=int, default=48)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    os.makedirs(args.work_dir, exist_ok=True)

    root = args.data_root
    if root is None:
        root = os.path.join(args.work_dir, "images")
        if not os.path.isdir(root):
            synth_dataset(root, size=args.size)

    # 1. gen_img_list + im2rec: folder tree -> .lst -> RecordIO shard
    items, classes = gen_img_list(root)
    lst = os.path.join(args.work_dir, "train.lst")
    with open(lst, "w") as f:
        for idx, label, rel in items:
            f.write(f"{idx}\t{label}\t{rel}\n")
    rec = os.path.join(args.work_dir, "train.rec")
    sys.argv = ["im2rec", lst.replace(".lst", ""), root + "/",
                "--resize", str(args.size), "--quality", "95"]
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "..", "..", "tools"))
    import im2rec  # noqa: E402

    im2rec.main()
    logging.info("packed %d images of %d classes into %s",
                 len(items), len(classes), rec)

    # 2. train with the augmenting RecordIO pipeline
    train = mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, args.size, args.size),
        batch_size=args.batch_size, shuffle=True, rand_mirror=True,
        label_name="softmax_label")
    mod = mx.mod.Module(net_symbol(len(classes)))
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.002},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    # 3. submission: probability matrix over the "test" set
    train.reset()
    sub = os.path.join(args.work_dir, "submission.csv")
    with open(sub, "w", newline="") as f:
        wr = csv.writer(f)
        wr.writerow(["image"] + classes)
        i = 0
        for batch in train:
            mod.forward(batch, is_train=False)
            probs = mod.get_outputs()[0].asnumpy()
            for row in probs[:batch.data[0].shape[0] - batch.pad]:
                wr.writerow([f"img_{i:05d}.png"] +
                            [f"{p:.5f}" for p in row])
                i += 1
    logging.info("wrote %s (%d rows)", sub, i)


if __name__ == "__main__":
    main()
