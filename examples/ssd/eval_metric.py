"""Detection mAP metrics (parity surface: example/ssd/evaluate/
eval_metric.py — MApMetric + VOC07MApMetric).

Original implementation of the standard VOC protocol: per-class
ranked-detection matching against ground truth at an IoU threshold,
precision/recall curve, AP by continuous integration (MApMetric) or the
VOC-2007 11-point interpolation (VOC07MApMetric).

update(labels, preds):
- preds:  [batch, num_det, 6] rows (cls_id, score, x1, y1, x2, y2);
  cls_id < 0 marks padding (MultiBoxDetection output layout).
- labels: [batch, num_gt, 5] rows (cls_id, x1, y1, x2, y2); cls_id < 0
  marks padding.
"""
from __future__ import annotations

import numpy as np

from mxnet_tpu.metric import EvalMetric


def _iou(box, boxes):
    x1 = np.maximum(box[0], boxes[:, 0])
    y1 = np.maximum(box[1], boxes[:, 1])
    x2 = np.minimum(box[2], boxes[:, 2])
    y2 = np.minimum(box[3], boxes[:, 3])
    inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    area = (box[2] - box[0]) * (box[3] - box[1])
    areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    union = area + areas - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


class MApMetric(EvalMetric):
    """Mean average precision over detection outputs."""

    def __init__(self, iou_thresh=0.5, class_names=None, name="mAP"):
        self.iou_thresh = iou_thresh
        self.class_names = class_names
        super().__init__(name)

    def reset(self):
        # (class, score, matched) per detection + gt counts per class;
        # epoch-wide accumulators plus a current-window copy so the
        # reset_local() protocol works: Speedometer(auto_reset=True)
        # reads per-interval mAP from get(), epoch mAP from get_global()
        self._records = []
        self._gt_counts = {}
        self._win_records = []
        self._win_gt_counts = {}
        super().reset()

    def reset_local(self):
        self._win_records = []
        self._win_gt_counts = {}
        # base accumulators stay untouched (zero) — mAP is computed from
        # ranked records, not from sum_metric/num_inst

    def update(self, labels, preds):
        for lab, pred in zip(labels, preds):
            lab = np.asarray(lab.asnumpy() if hasattr(lab, "asnumpy")
                             else lab)
            pred = np.asarray(pred.asnumpy() if hasattr(pred, "asnumpy")
                              else pred)
            for b in range(lab.shape[0]):
                self._update_one(lab[b], pred[b])

    def _update_one(self, gts, dets):
        gts = gts[gts[:, 0] >= 0]
        dets = dets[dets[:, 0] >= 0]
        for c in np.unique(gts[:, 0]).astype(int):
            n = int((gts[:, 0] == c).sum())
            self._gt_counts[c] = self._gt_counts.get(c, 0) + n
            self._win_gt_counts[c] = self._win_gt_counts.get(c, 0) + n
        order = np.argsort(-dets[:, 1]) if len(dets) else []
        taken = np.zeros(len(gts), bool)
        for di in order:
            d = dets[di]
            c = int(d[0])
            cand = np.where(gts[:, 0] == c)[0]
            matched = False
            if len(cand):
                ious = _iou(d[2:6], gts[cand, 1:5])
                best = int(np.argmax(ious))
                # VOC protocol: match against the overall-best gt; if that
                # gt is already claimed by a higher-scored detection, this
                # one is a false positive (no re-matching to runner-ups)
                if (ious[best] >= self.iou_thresh
                        and not taken[cand[best]]):
                    taken[cand[best]] = True
                    matched = True
            self._records.append((c, float(d[1]), matched))
            self._win_records.append((c, float(d[1]), matched))

    def _average_precision(self, rec, prec):
        # continuous AP: integrate the precision envelope
        mrec = np.concatenate([[0.0], rec, [1.0]])
        mpre = np.concatenate([[0.0], prec, [0.0]])
        for i in range(len(mpre) - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = np.where(mrec[1:] != mrec[:-1])[0]
        return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))

    def _map_over(self, records, gt_counts):
        aps = []
        for c, n_gt in sorted(gt_counts.items()):
            recs = sorted((r for r in records if r[0] == c),
                          key=lambda r: -r[1])
            if n_gt == 0:
                continue
            tp = np.cumsum([1.0 if m else 0.0 for _, _, m in recs])
            fp = np.cumsum([0.0 if m else 1.0 for _, _, m in recs])
            rec = tp / n_gt if len(recs) else np.array([0.0])
            prec = (tp / np.maximum(tp + fp, 1e-12)
                    if len(recs) else np.array([0.0]))
            aps.append(self._average_precision(rec, prec))
        return float(np.mean(aps)) if aps else float("nan")

    def get(self):  # current window (since the last reset_local)
        return (self.name, self._map_over(self._win_records,
                                          self._win_gt_counts))

    def get_global(self):  # full epoch
        return (self.name, self._map_over(self._records, self._gt_counts))


class VOC07MApMetric(MApMetric):
    """mAP with the VOC-2007 11-point interpolation."""

    def __init__(self, iou_thresh=0.5, class_names=None, name="VOC07_mAP"):
        super().__init__(iou_thresh, class_names, name)

    def _average_precision(self, rec, prec):
        ap = 0.0
        for t in np.linspace(0, 1, 11):
            mask = rec >= t
            ap += (float(np.max(prec[mask])) if mask.any() else 0.0) / 11.0
        return ap
