#!/usr/bin/env python
"""SSD-VGG16 detection training (parity: example/ssd/train.py with the
custom multibox ops from example/ssd/operator/ — here MultiBoxPrior/
Target/Detection are built-in ops, SURVEY.md Appendix A custom tail).

Synthetic detection data: images with colored rectangles, boxes as
(cls, x1, y1, x2, y2) normalized, -1 padded."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.models import ssd  # noqa: E402


def synthetic_detections(num, size, max_boxes, num_classes, seed=5):
    rs = np.random.RandomState(seed)
    imgs = np.zeros((num, 3, size, size), np.float32)
    labels = np.full((num, max_boxes, 5), -1.0, np.float32)
    for i in range(num):
        nbox = rs.randint(1, max_boxes + 1)
        for j in range(nbox):
            cls = rs.randint(0, num_classes)
            w, h = rs.uniform(0.2, 0.5, 2)
            x1, y1 = rs.uniform(0, 1 - w), rs.uniform(0, 1 - h)
            x2, y2 = x1 + w, y1 + h
            px = (np.array([x1, y1, x2, y2]) * size).astype(int)
            imgs[i, cls % 3, px[1]:px[3], px[0]:px[2]] = 1.0
            labels[i, j] = [cls, x1, y1, x2, y2]
    return imgs, labels


def evaluate(arg_dict, args, imgs, labels):
    """VOC mAP over the deploy graph (parity: example/ssd/evaluate/) —
    MultiBoxDetection decodes + NMSes, the metric ranks detections."""
    from eval_metric import MApMetric, VOC07MApMetric

    from mxnet_tpu import nd

    deploy = ssd.get_symbol(num_classes=args.num_classes,
                            backbone=args.backbone)
    b = args.batch_size
    dex = deploy.simple_bind(ctx=None,
                             data=(b, 3, args.data_size, args.data_size))
    for name, arr in arg_dict.items():
        if name in dex.arg_dict and name != "data":
            dex.arg_dict[name][:] = arr.asnumpy()
    m, m07 = MApMetric(), VOC07MApMetric()
    for i in range(0, len(imgs) - b + 1, b):
        dex.arg_dict["data"][:] = imgs[i:i + b]
        det = dex.forward(is_train=False)[0]
        lab = nd.array(labels[i:i + b])
        m.update([lab], [det])
        m07.update([lab], [det])
    return m.get()[1], m07.get()[1]


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-classes", type=int, default=3)
    ap.add_argument("--data-size", type=int, default=300)
    ap.add_argument("--num-steps", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.001)
    ap.add_argument("--backbone", default="vgg16",
                    choices=["vgg16", "tiny"],
                    help="tiny: small from-scratch-trainable trunk "
                         "(VGG16 needs pretrained weights to learn "
                         "in a short run, as in the reference)")
    ap.add_argument("--eval", action="store_true",
                    help="compute VOC mAP with the deploy graph after "
                         "training")
    ap.add_argument("--assert-map", type=float, default=None,
                    help="fail unless VOC07 mAP exceeds this floor "
                         "(implies --eval)")
    args = ap.parse_args()
    if args.assert_map is not None:
        args.eval = True
    logging.basicConfig(level=logging.INFO)

    net = ssd.get_symbol_train(num_classes=args.num_classes,
                               backbone=args.backbone)
    b = args.batch_size
    ex = net.simple_bind(ctx=None, data=(b, 3, args.data_size, args.data_size),
                         label=(b, 8, 5))
    init = mx.init.Xavier()
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "label"):
            init(name, arr)

    imgs, labels = synthetic_detections(64, args.data_size, 8,
                                        args.num_classes)
    opt = mx.optimizer.create("sgd", learning_rate=args.lr, momentum=0.9,
                              wd=5e-4)
    updater = mx.optimizer.get_updater(opt)
    import time

    tic = None
    for step in range(args.num_steps):
        if step == 1:
            ex.outputs[0].asnumpy()  # sync step 0 before timing starts
            tic = time.perf_counter()  # discard the compile step
        sel = slice((step * b) % 64, (step * b) % 64 + b)
        ex.arg_dict["data"][:] = imgs[sel]
        ex.arg_dict["label"][:] = labels[sel]
        ex.forward(is_train=True)
        ex.backward()
        for i, name in enumerate(ex.symbol.list_arguments()):
            if name in ("data", "label") or ex.grad_dict.get(name) is None:
                continue
            updater(i, ex.grad_dict[name], ex.arg_dict[name])
        if step % 10 == 0:
            cls_prob = ex.outputs[0].asnumpy()  # (N, C+1, A) softmax
            logging.info("step %d  mean max cls prob %.3f", step,
                         float(cls_prob.max(axis=1).mean()))
    ex.outputs[0].asnumpy()  # barrier before the perf line
    if tic is not None and args.num_steps > 1:
        rate = b * (args.num_steps - 1) / (time.perf_counter() - tic)
        print("train_perf: %.1f img/s" % rate)
    if args.eval:
        mAP, mAP07 = evaluate(ex.arg_dict, args, imgs, labels)
        logging.info("eval: mAP=%.4f  VOC07_mAP=%.4f", mAP, mAP07)
        print("mAP: %.4f" % mAP)
        print("VOC07_mAP: %.4f" % mAP07)
        if args.assert_map is not None:
            assert mAP07 > args.assert_map, \
                f"VOC07 mAP {mAP07:.4f} below floor {args.assert_map}"
            print("MAP_FLOOR_OK")
    logging.info("done — deploy graph: models.ssd.get_symbol() adds "
                 "softmax + NMS MultiBoxDetection")
