#!/usr/bin/env python
"""SSD inference demo (parity: example/ssd/demo.py): deploy graph with
softmax + MultiBoxDetection NMS, prints detections [cls, score, box]."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.models import ssd  # noqa: E402

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-classes", type=int, default=3)
    ap.add_argument("--data-size", type=int, default=300)
    ap.add_argument("--nms-thresh", type=float, default=0.45)
    ap.add_argument("--thresh", type=float, default=0.2)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = ssd.get_symbol(num_classes=args.num_classes,
                         nms_thresh=args.nms_thresh)
    ex = net.simple_bind(ctx=None, grad_req="null",
                         data=(1, 3, args.data_size, args.data_size))
    init = mx.init.Xavier()
    for name, arr in ex.arg_dict.items():
        if name != "data":
            init(name, arr)
    img = np.random.uniform(0, 1,
                            (1, 3, args.data_size, args.data_size))
    ex.arg_dict["data"][:] = img.astype(np.float32)
    ex.forward(is_train=False)
    dets = ex.outputs[0].asnumpy()[0]
    keep = dets[dets[:, 1] > args.thresh]
    logging.info("detections above %.2f: %d (of %d anchors)",
                 args.thresh, len(keep), dets.shape[0])
    for d in keep[:10]:
        logging.info("cls=%d score=%.2f box=(%.2f,%.2f,%.2f,%.2f)",
                     int(d[0]), d[1], *d[2:6])
