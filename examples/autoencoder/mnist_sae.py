#!/usr/bin/env python
"""Stacked autoencoder with layerwise pretraining (parity:
example/autoencoder/): each layer pretrained as a shallow
encoder/decoder with LinearRegressionOutput, then the full stack
finetuned end-to-end."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402


def ae_symbol(dims, out_name="decoded"):
    """Encoder dims[0]->dims[-1] then mirrored decoder, MSE loss against
    the input itself."""
    data = sym.Variable("data")
    target = sym.Variable("target_label")
    net = data
    for i, d in enumerate(dims[1:]):
        net = sym.FullyConnected(net, num_hidden=d, name=f"enc{i}")
        net = sym.Activation(net, act_type="relu")
    for i, d in enumerate(reversed(dims[:-1])):
        net = sym.FullyConnected(net, num_hidden=d, name=f"dec{i}")
        if i < len(dims) - 2:
            net = sym.Activation(net, act_type="relu")
    return sym.LinearRegressionOutput(net, target, name=out_name)


def train_ae(x, dims, num_epochs, batch_size, lr, arg_params=None):
    net = ae_symbol(dims)
    it = mx.io.NDArrayIter({"data": x}, {"target_label": x},
                           batch_size=batch_size, shuffle=True)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("target_label",))
    mod.fit(it, num_epoch=num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": lr},
            arg_params=arg_params, allow_missing=True,
            eval_metric="mse")
    args_out, _ = mod.get_params()
    score = mod.score(it, "mse")[0][1]
    return args_out, score


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--pretrain-epochs", type=int, default=2)
    ap.add_argument("--finetune-epochs", type=int, default=3)
    ap.add_argument("--dims", type=str, default="784,128,32")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    dims = [int(d) for d in args.dims.split(",")]
    (xtr, _), _ = get_synthetic_mnist(2048, 16)
    x = xtr.reshape(len(xtr), -1).astype(np.float32)

    # layerwise pretraining: train each (d_i -> d_{i+1}) pair alone
    pretrained = {}
    h = x
    for i in range(len(dims) - 1):
        pair_args, mse = train_ae(h, [dims[i], dims[i + 1]],
                                  args.pretrain_epochs, args.batch_size,
                                  1e-3)
        logging.info("layer %d pretrain mse %.4f", i, mse)
        pretrained[f"enc{i}_weight"] = pair_args["enc0_weight"]
        pretrained[f"enc{i}_bias"] = pair_args["enc0_bias"]
        pretrained[f"dec{len(dims) - 2 - i}_weight"] = pair_args["dec0_weight"]
        pretrained[f"dec{len(dims) - 2 - i}_bias"] = pair_args["dec0_bias"]
        # encode h for the next layer with the trained encoder
        w = pair_args["enc0_weight"].asnumpy()
        bset = pair_args["enc0_bias"].asnumpy()
        h = np.maximum(h @ w.T + bset, 0.0)

    _, final_mse = train_ae(x, dims, args.finetune_epochs, args.batch_size,
                            1e-4, arg_params=pretrained)
    logging.info("finetuned stack mse %.4f", final_mse)
