#!/usr/bin/env python
"""Stacked denoising autoencoder on (synthetic) MNIST (parity:
example/autoencoder/mnist_sae.py — greedy layerwise pretraining, then
end-to-end finetuning, driven through the Solver/MXModel system).

Self-asserting A/B: finetuning must improve reconstruction over the
purely-layerwise stack, the final MSE must beat a fixed floor, and the
denoising corruption must not destroy either property.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402

from autoencoder import AutoEncoderModel  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--pretrain-epochs", type=int, default=3)
    ap.add_argument("--finetune-epochs", type=int, default=5)
    ap.add_argument("--dims", type=str, default="784,128,32")
    ap.add_argument("--corruption", type=float, default=0.3)
    ap.add_argument("--max-mse", type=float, default=0.025)
    ap.add_argument("--monitor", action="store_true",
                    help="print per-batch stat taps via mx.mon.Monitor")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)

    dims = [int(d) for d in args.dims.split(",")]
    (xtr, _), (xte, _) = get_synthetic_mnist(2048, 256)
    x = xtr.reshape(len(xtr), -1).astype(np.float32)
    xt = xte.reshape(len(xte), -1).astype(np.float32)

    monitor = (mx.mon.Monitor(50, pattern=".*weight") if args.monitor
               else None)
    model = AutoEncoderModel(dims, corruption=args.corruption)

    model.layerwise_pretrain(x, args.batch_size, args.pretrain_epochs,
                             1e-3, monitor=monitor)
    pre_mse = model.reconstruct_mse(xt)
    logging.info("pretrain-only test mse %.5f", pre_mse)

    model.finetune(x, args.batch_size, args.finetune_epochs, 1e-3,
                   monitor=monitor)
    fin_mse = model.reconstruct_mse(xt)
    logging.info("finetuned   test mse %.5f", fin_mse)

    ckpt = "/tmp/mnist_sae_params.nd"
    model.save(ckpt)
    reloaded = AutoEncoderModel(dims, corruption=0.0)
    reloaded.load(ckpt)
    assert abs(reloaded.reconstruct_mse(xt) - fin_mse) < 1e-6

    z = model.encode(xt)
    assert z.shape == (len(xt), dims[-1])
    assert fin_mse <= pre_mse + 1e-6, (pre_mse, fin_mse)
    assert fin_mse <= args.max_mse, fin_mse
    print("SAE OK pre %.5f -> fine %.5f" % (pre_mse, fin_mse))


if __name__ == "__main__":
    main()
