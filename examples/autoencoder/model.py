"""Minimal model container for the unsupervised examples (parity:
example/autoencoder/model.py — the reference's MXModel holds a symbol,
its arg/aux arrays and a simple save/load; solvers operate on it).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


class MXModel(object):
    """A symbol plus its materialized parameters.

    Subclasses implement setup(*args) to build self.loss (a training
    symbol) and may add more symbols sharing the same parameter names;
    all parameters live in self.args / self.auxs as NDArrays keyed by
    name, so any number of executors can be bound against them.
    """

    def __init__(self, *args, **kwargs):
        self.loss = None
        self.args = {}
        self.auxs = {}
        self.ctx = kwargs.pop("ctx", None) or mx.context.cpu()
        self.setup(*args, **kwargs)

    def setup(self, *args, **kwargs):
        raise NotImplementedError("subclass builds symbols + params here")

    def init_params(self, initializer=None, data_shapes=None):
        """Materialize every argument of self.loss except data/labels."""
        initializer = initializer or mx.init.Xavier()
        arg_shapes, _, aux_shapes = self.loss.infer_shape(**data_shapes)
        arg_names = self.loss.list_arguments()
        aux_names = self.loss.list_auxiliary_states()
        for name, shape in zip(arg_names, arg_shapes):
            if name in data_shapes:
                continue
            arr = mx.nd.empty(shape, ctx=self.ctx)
            initializer(name, arr)
            self.args[name] = arr
        for name, shape in zip(aux_names, aux_shapes):
            self.auxs[name] = mx.nd.zeros(shape, ctx=self.ctx)

    def save(self, fname):
        mx.nd.save(fname, {("arg:%s" % k): v for k, v in self.args.items()}
                   | {("aux:%s" % k): v for k, v in self.auxs.items()})

    def load(self, fname):
        for k, v in mx.nd.load(fname).items():
            tag, name = k.split(":", 1)
            (self.args if tag == "arg" else self.auxs)[name] = v

    def predict_feature(self, symbol, x, batch_size=256):
        """Run `symbol` (sharing this model's param names) over x.

        Executors are cached per (symbol, input shape) — callers like
        DEC's refinement loop predict through the same symbol dozens of
        times, and only the param VALUES change between calls."""
        cache = self.__dict__.setdefault("_exec_cache", {})
        outs = []
        n = x.shape[0]
        for i in range(0, n, batch_size):
            xb = x[i:i + batch_size]
            key = (id(symbol), xb.shape)
            hit = cache.get(key)
            # the cached entry keeps a reference to its symbol: that both
            # pins the id (no reuse after gc) and lets identity be checked
            ex = hit[1] if hit is not None and hit[0] is symbol else None
            if ex is None:
                ex = symbol.simple_bind(ctx=self.ctx, grad_req="null",
                                        data=xb.shape)
                cache[key] = (symbol, ex)
            for name, arr in self.args.items():
                if name in ex.arg_dict:
                    ex.arg_dict[name][:] = arr
            ex.forward(is_train=False, data=xb)
            outs.append(ex.outputs[0].asnumpy())
        return np.concatenate(outs, axis=0)
