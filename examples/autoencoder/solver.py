"""Generic low-level training loop (parity: example/autoencoder/
solver.py — the reference's Solver binds an executor over an MXModel's
arrays, drives forward/backward with an updater, and reports through a
metric + optional Monitor).

Deliberately NOT Module.fit: the examples use this to exercise the
executor / optimizer.get_updater / Monitor surfaces directly.
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


class Solver(object):
    def __init__(self, optimizer, **opt_params):
        self.optimizer = mx.optimizer.create(optimizer, **opt_params)
        self.metric = None
        self.monitor = None
        self.iter_end_callback = None

    def set_metric(self, metric):
        self.metric = metric

    def set_monitor(self, monitor):
        self.monitor = monitor

    def set_iter_end_callback(self, cb):
        self.iter_end_callback = cb

    def solve(self, model, train_x, train_y, batch_size, num_epochs,
              data_name="data", label_name="target_label",
              trainable=None, transform=None):
        """SGD over (train_x, train_y) against model.loss.

        trainable: optional name filter — only these args get grads and
        updates (the stacked AE freezes earlier layers this way).
        transform: optional fn applied to each INPUT batch right before
        forward (labels untouched) — the denoising AE draws a fresh
        corruption mask per batch here.
        """
        b = batch_size
        shapes = {data_name: (b,) + train_x.shape[1:],
                  label_name: (b,) + train_y.shape[1:]}
        grad_req = {}
        for name in model.loss.list_arguments():
            if name in shapes:
                grad_req[name] = "null"
            elif trainable is not None and name not in trainable:
                grad_req[name] = "null"
            else:
                grad_req[name] = "write"
        ex = model.loss.simple_bind(ctx=model.ctx, grad_req=grad_req,
                                    **shapes)
        for name, arr in model.args.items():
            if name in ex.arg_dict:
                ex.arg_dict[name][:] = arr
        for name, arr in model.auxs.items():
            if name in ex.aux_dict:
                ex.aux_dict[name][:] = arr
        if self.monitor is not None:
            self.monitor.install(ex)

        updater = mx.optimizer.get_updater(self.optimizer)
        updated = [n for n in sorted(ex.arg_dict)
                   if grad_req.get(n) == "write"]
        rng = np.random.RandomState(0)
        idx = np.arange(train_x.shape[0])
        last = None
        for epoch in range(num_epochs):
            rng.shuffle(idx)
            if self.metric is not None:
                self.metric.reset()
            for i in range(0, len(idx) - b + 1, b):
                xb = train_x[idx[i:i + b]]
                yb = train_y[idx[i:i + b]]
                if transform is not None:
                    xb = transform(xb)
                if self.monitor is not None:
                    self.monitor.tic()
                ex.forward(is_train=True, **{data_name: xb, label_name: yb})
                ex.backward()
                for j, name in enumerate(updated):
                    updater(j, ex.grad_dict[name], ex.arg_dict[name])
                if self.monitor is not None:
                    self.monitor.toc_print()
                if self.metric is not None:
                    self.metric.update([mx.nd.array(yb)],
                                       [ex.outputs[0]])
            if self.metric is not None:
                name, last = self.metric.get()
                logging.info("epoch %d %s %.5f", epoch, name, last)
            if self.iter_end_callback is not None:
                self.iter_end_callback(epoch)
        # fold the trained values back into the model's arrays
        for name in ex.arg_dict:
            if name in model.args:
                model.args[name][:] = ex.arg_dict[name]
        for name in ex.aux_dict:
            model.auxs[name][:] = ex.aux_dict[name]
        return last
