"""Stacked (denoising) autoencoder as a reusable model class (parity:
example/autoencoder/autoencoder.py — the reference's AutoEncoderModel
builds per-layer encode/decode symbols from an `internals` walk, trains
layers greedily with masking-noise corruption, then finetunes the whole
stack; example/dec/dec.py imports it for its pretraining stage).
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

from model import MXModel  # noqa: E402
from solver import Solver  # noqa: E402


class AutoEncoderModel(MXModel):
    """dims = [input, h1, ..., bottleneck]; relu between layers.

    Symbols built once and shared by parameter NAME:
      self.loss     — full stack, MSE against target_label
      self.encoder  — data -> bottleneck
      layer pairs   — shallow (d_i -> d_{i+1} -> d_i) AEs for greedy
                      pretraining, reusing the stack's own param names
                      so their training writes the stack directly.
    """

    def setup(self, dims, corruption=0.0):
        self.dims = list(dims)
        self.corruption = float(corruption)
        self.loss = self._stack_sym()
        self.encoder = self._encoder_sym(len(dims) - 1)
        self.init_params(data_shapes={"data": (1, dims[0]),
                                      "target_label": (1, dims[0])})

    # ---- symbols ----------------------------------------------------
    def _encoder_sym(self, depth):
        net = sym.Variable("data")
        for i in range(depth):
            net = sym.FullyConnected(net, num_hidden=self.dims[i + 1],
                                     name="enc%d" % i)
            if i < depth - 1:
                net = sym.Activation(net, act_type="relu")
        return net

    def _stack_sym(self):
        net = self._encoder_sym(len(self.dims) - 1)
        net = sym.Activation(net, act_type="relu")
        for j, d in enumerate(reversed(self.dims[:-1])):
            net = sym.FullyConnected(net, num_hidden=d, name="dec%d" % j)
            if j < len(self.dims) - 2:
                net = sym.Activation(net, act_type="relu")
        return sym.LinearRegressionOutput(net, sym.Variable("target_label"),
                                          name="rec")

    def _pair_sym(self, i):
        """Shallow AE for layer i, named so its params ARE the stack's."""
        net = sym.Variable("data")
        net = sym.FullyConnected(net, num_hidden=self.dims[i + 1],
                                 name="enc%d" % i)
        net = sym.Activation(net, act_type="relu")
        net = sym.FullyConnected(net, num_hidden=self.dims[i],
                                 name="dec%d" % (len(self.dims) - 2 - i))
        return sym.LinearRegressionOutput(net, sym.Variable("target_label"),
                                          name="rec")

    # ---- data plumbing ----------------------------------------------
    def _corrupt(self, x, rng):
        """Masking noise: zero a random fraction of inputs (the
        denoising-AE corruption; reconstruction target stays clean)."""
        if self.corruption <= 0:
            return x
        mask = rng.uniform(size=x.shape) >= self.corruption
        return (x * mask).astype(x.dtype)

    def encode(self, x, depth=None):
        """Bottleneck features (or the first `depth` layers' output)."""
        symb = (self.encoder if depth is None
                else self._encoder_sym(depth))
        return self.predict_feature(symb, x)

    def reconstruct_mse(self, x, batch_size=256):
        rec = self.predict_feature(self.loss, x, batch_size)
        return float(np.mean((rec - x) ** 2))

    # ---- training ---------------------------------------------------
    def layerwise_pretrain(self, x, batch_size, epochs, lr,
                           monitor=None):
        """Greedy per-layer training (reference: AutoEncoderModel's
        l-wise stage): layer i trains on the (clean) encoding of the
        layers below it, with corruption applied to its own input."""
        rng = np.random.RandomState(1)
        for i in range(len(self.dims) - 1):
            # post-ReLU features: that is what layer i consumes in the
            # full stack (_encoder_sym applies relu between layers)
            h = np.maximum(self.encode(x, depth=i), 0.0) if i else x
            pair = AutoEncoderModel.__new__(AutoEncoderModel)
            pair.ctx = self.ctx
            pair.loss = self._pair_sym(i)
            pair.args = {k: v for k, v in self.args.items()
                         if k in pair.loss.list_arguments()}
            pair.auxs = {}
            solver = Solver("adam", learning_rate=lr)
            solver.set_metric(mx.metric.MSE())
            if monitor is not None:
                solver.set_monitor(monitor)
            mse = solver.solve(pair, h, h, batch_size, epochs,
                               transform=lambda xb: self._corrupt(xb, rng))
            logging.info("pretrain layer %d mse %.5f", i, mse)

    def finetune(self, x, batch_size, epochs, lr, monitor=None):
        """End-to-end reconstruction training of the whole stack; a
        fresh corruption mask is drawn for every batch (the denoising
        property needs the mask to vary, not a fixed corrupted copy)."""
        rng = np.random.RandomState(2)
        solver = Solver("adam", learning_rate=lr)
        solver.set_metric(mx.metric.MSE())
        if monitor is not None:
            solver.set_monitor(monitor)
        return solver.solve(self, x, x, batch_size, epochs,
                            transform=lambda xb: self._corrupt(xb, rng))
