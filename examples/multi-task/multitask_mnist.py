#!/usr/bin/env python
"""Multi-task training: one trunk, two softmax heads (parity:
example/multi-task/example_multi_task.py — digit class + parity bit),
with a Group'd symbol and a custom composite metric."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402


class MultiTaskIter(mx.io.DataIter):
    """Wraps an iter to emit two labels (digit, parity)."""

    def __init__(self, base):
        super().__init__()
        self._base = base
        self.batch_size = base.batch_size

    @property
    def provide_data(self):
        return self._base.provide_data

    @property
    def provide_label(self):
        (name, shape) = self._base.provide_label[0][:2]
        return [("softmax1_label", shape), ("softmax2_label", shape)]

    def reset(self):
        self._base.reset()

    def next(self):
        batch = self._base.next()
        digit = batch.label[0]
        parity = mx.nd.array(digit.asnumpy() % 2)
        return mx.io.DataBatch(batch.data, [digit, parity], pad=batch.pad)


class MultiAccuracy(mx.metric.EvalMetric):
    """Parity: example_multi_task.py Multi_Accuracy — per-output acc
    (EvalMetric's ``num`` gives per-output sum/inst lists)."""

    def __init__(self, num=2):
        super().__init__("multi-accuracy", num=num)

    def update(self, labels, preds):
        for i, (label, pred) in enumerate(zip(labels, preds)):
            y = label.asnumpy().astype(int)
            p = pred.asnumpy().argmax(axis=1)
            self.sum_metric[i] += float((y == p).sum())
            self.num_inst[i] += y.shape[0]


def build_net():
    data = sym.Variable("data")
    net = sym.FullyConnected(sym.Flatten(data), num_hidden=128, name="fc1")
    net = sym.Activation(net, act_type="relu")
    digit = sym.FullyConnected(net, num_hidden=10, name="fcd")
    digit = sym.SoftmaxOutput(digit, name="softmax1")
    parity = sym.FullyConnected(net, num_hidden=2, name="fcp")
    parity = sym.SoftmaxOutput(parity, name="softmax2")
    return sym.Group([digit, parity])


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=4)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(4096, 512)
    train = MultiTaskIter(mx.io.NDArrayIter(xtr, ytr,
                                            batch_size=args.batch_size,
                                            shuffle=True))
    val = MultiTaskIter(mx.io.NDArrayIter(xte, yte,
                                          batch_size=args.batch_size))
    mod = mx.mod.Module(build_net(),
                        label_names=("softmax1_label", "softmax2_label"))
    mod.fit(train, eval_data=val, eval_metric=MultiAccuracy(),
            num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    logging.info("scores: %s", mod.score(val, MultiAccuracy()))
