#!/usr/bin/env python
"""Large-margin (SVM) output head (parity: example/svm_mnist/
svm_mnist.py): same MLP trunk, SVMOutput loss instead of softmax."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--use-l2", type=int, default=1,
                    help="1: squared hinge (L2-SVM), 0: hinge")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    data = sym.Variable("data")
    net = sym.FullyConnected(sym.Flatten(data), name="fc1", num_hidden=256)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=10)
    net = sym.SVMOutput(net, name="svm", use_linear=not args.use_l2)

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(4096, 512)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                              shuffle=True, label_name="svm_label")
    val = mx.io.NDArrayIter(xte, yte, batch_size=args.batch_size,
                            label_name="svm_label")
    mod = mx.mod.Module(net, label_names=("svm_label",))
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 1e-4},
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    logging.info("val acc: %.3f", mod.score(val, "acc")[0][1])
