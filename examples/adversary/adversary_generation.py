#!/usr/bin/env python
"""FGSM adversarial examples (parity: example/adversary/): train a small
net, then bind with inputs_need_grad=True and perturb inputs along
sign(dLoss/dx) to flip predictions."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402


def build_net():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(5, 5), num_filter=8, name="c1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.FullyConnected(sym.Flatten(net), num_hidden=10, name="fc")
    return sym.SoftmaxOutput(net, name="softmax")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epsilon", type=float, default=0.15)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(2048, 256)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                              shuffle=True)
    net = build_net()
    mod = mx.mod.Module(net)
    mod.fit(train, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    arg_params, aux_params = mod.get_params()

    # rebind with input grads enabled
    b = args.batch_size
    atk = mx.mod.Module(net)
    atk.bind(data_shapes=[("data", (b,) + xte.shape[1:])],
             label_shapes=[("softmax_label", (b,))],
             for_training=True, inputs_need_grad=True)
    atk.set_params(arg_params, aux_params)

    x, y = xte[:b], yte[:b]
    atk.forward(mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(y)]),
                is_train=True)
    clean_pred = atk.get_outputs()[0].asnumpy().argmax(axis=1)
    atk.backward()
    grad = atk.get_input_grads()[0].asnumpy()

    x_adv = np.clip(x + args.epsilon * np.sign(grad), 0, 1)
    atk.forward(mx.io.DataBatch([mx.nd.array(x_adv)], [mx.nd.array(y)]),
                is_train=False)
    adv_pred = atk.get_outputs()[0].asnumpy().argmax(axis=1)

    clean_acc = float((clean_pred == y).mean())
    adv_acc = float((adv_pred == y).mean())
    logging.info("clean acc %.3f -> adversarial acc %.3f (eps=%.2f)",
                 clean_acc, adv_acc, args.epsilon)
