#!/usr/bin/env python
"""Adversarial example generation (parity: example/adversary/
adversary_generation.ipynb): train a small convnet, then craft FGSM,
targeted-FGSM and PGD perturbations through a second Module bound with
inputs_need_grad=True that SHARES the trained module's parameter
storage (shared_module), so no weight copying is ever needed.

Self-asserting: the untargeted attacks must collapse accuracy well
below clean accuracy, PGD at least as hard as FGSM, and the targeted
attack must steer a majority of examples to the chosen class.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402

import attacks  # noqa: E402


def build_net():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(5, 5), num_filter=8, name="c1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.FullyConnected(sym.Flatten(net), num_hidden=10, name="fc")
    return sym.SoftmaxOutput(net, name="softmax")


def bind_attacker(net, train_mod, batch_size, shape):
    """A Module sharing train_mod's live parameter storage, with input
    gradients enabled — updates to the donor are visible here without
    any set_params round trip."""
    atk = mx.mod.Module(net)
    atk.bind(data_shapes=[("data", (batch_size,) + shape)],
             label_shapes=[("softmax_label", (batch_size,))],
             for_training=True, inputs_need_grad=True,
             shared_module=train_mod)
    return atk


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epsilon", type=float, default=0.5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--pgd-steps", type=int, default=8)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(42)  # param init draws from the global RNG

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(2048, 256)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                              shuffle=True)
    net = build_net()
    mod = mx.mod.Module(net)
    mod.fit(train, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})

    b = args.batch_size
    atk = bind_attacker(net, mod, b, xte.shape[1:])
    x, y = xte[:b], yte[:b]
    rng = np.random.RandomState(7)
    # adversarial images stay inside the data's own valid range
    clip = (float(xtr.min()), float(xtr.max()))

    clean_acc = attacks.accuracy(atk, x, y)
    x_fgsm = attacks.fgsm(atk, x, y, args.epsilon, clip=clip)
    fgsm_acc = attacks.accuracy(atk, x_fgsm, y)
    x_pgd = attacks.pgd(atk, x, y, args.epsilon, steps=args.pgd_steps,
                        rng=rng, clip=clip)
    pgd_acc = attacks.accuracy(atk, x_pgd, y)

    target = np.full_like(y, 3)
    x_tgt = attacks.targeted_fgsm(atk, x, target, args.epsilon, clip=clip)
    atk.forward(mx.io.DataBatch([mx.nd.array(x_tgt)], [mx.nd.array(y)]),
                is_train=False)
    tgt_pred = atk.get_outputs()[0].asnumpy().argmax(axis=1)
    hit = float((tgt_pred == 3).mean())

    logging.info("clean %.3f | fgsm %.3f | pgd %.3f | targeted->3 %.3f",
                 clean_acc, fgsm_acc, pgd_acc, hit)
    # perturbations stay inside the eps-ball by construction
    assert np.abs(x_fgsm - x).max() <= args.epsilon + 1e-6
    assert np.abs(x_pgd - x).max() <= args.epsilon + 1e-6
    # the attacks must actually work
    assert clean_acc >= 0.85, clean_acc
    assert fgsm_acc <= clean_acc - 0.3, (clean_acc, fgsm_acc)
    assert pgd_acc <= fgsm_acc + 0.05, (fgsm_acc, pgd_acc)
    assert hit >= 0.5, hit
    print("ADVERSARY OK")


if __name__ == "__main__":
    main()
