#!/usr/bin/env python
"""Adversarial training (PGD/Madry-style) on top of the attack library.

Trains two models on synthetic MNIST:
  1. an undefended baseline (clean batches only);
  2. a defended model trained Goodfellow-style — after one clean
     warmup epoch, every batch is half clean / half PGD examples
     crafted AGAINST ITS OWN CURRENT WEIGHTS.  The attacker Module is
     bound with shared_module=trainer, so each optimizer step is
     instantly reflected in the attack gradients with no param copying.

Self-asserting: the defended model must be dramatically more robust
under the same PGD attack, while keeping reasonable clean accuracy.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.test_utils import get_synthetic_mnist  # noqa: E402

import attacks  # noqa: E402
from adversary_generation import bind_attacker, build_net  # noqa: E402


def fit_model(xtr, ytr, batch_size, epochs, eps=None, pgd_steps=4,
              seed=11, clip=None):
    """Train a model; with eps set, each batch is adversarial."""
    b = batch_size
    net = build_net()
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (b,) + xtr.shape[1:])],
             label_shapes=[("softmax_label", (b,))])
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2,
                                   factor_type="in"))
    # adam: the adversarial half-batches put training on a knife's edge
    # under plain SGD (occasional full collapse); adaptive steps keep the
    # defended run stable across seeds and XLA:CPU thread nondeterminism
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 2e-3})
    # the attacker only ever crafts the adversarial HALF of each batch,
    # so bind it at b//2 — PGD's fwd+bwd loop dominates defended
    # training and crafting then discarding a full batch doubles it
    atk = (bind_attacker(net, mod, b // 2, xtr.shape[1:])
           if eps else None)
    rng = np.random.RandomState(seed)
    idx = np.arange(xtr.shape[0])
    metric = mx.metric.Accuracy()
    for epoch in range(epochs):
        rng.shuffle(idx)
        metric.reset()
        for i in range(0, len(idx) - b + 1, b):
            x = xtr[idx[i:i + b]]
            y = ytr[idx[i:i + b]]
            if eps and epoch > 0:
                # curriculum: ramp the attack radius up over the epochs
                # (training at full eps from the start is a knife's edge
                # — runs collapse or never gain robustness); half the
                # batch becomes adversarial, and the attacker sees the
                # trainer's CURRENT weights through the shared parameter
                # storage
                eps_e = eps * min(1.0, epoch / max(epochs - 3, 1))
                x = x.copy()
                h = b // 2
                x[:h] = attacks.pgd(atk, x[:h], y[:h], eps_e,
                                    steps=pgd_steps, rng=rng, clip=clip)
            batch = mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(y)])
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        logging.info("epoch %d %s acc %.3f", epoch,
                     "adv" if eps else "clean", metric.get()[1])
    return net, mod


def evaluate(net, mod, xte, yte, eps, pgd_steps, batch_size, clip=None):
    atk = bind_attacker(net, mod, batch_size, xte.shape[1:])
    x, y = xte[:batch_size], yte[:batch_size]
    clean = attacks.accuracy(atk, x, y)
    x_adv = attacks.pgd(atk, x, y, eps, steps=pgd_steps,
                        rng=np.random.RandomState(3), clip=clip)
    robust = attacks.accuracy(atk, x_adv, y)
    return clean, robust


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epsilon", type=float, default=0.4)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=7)
    ap.add_argument("--pgd-steps", type=int, default=4)
    ap.add_argument("--min-robust-gain", type=float, default=0.25)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(42)  # param init draws from the global RNG

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(2048, 256)
    b = args.batch_size
    # adversarial images stay inside the data's own valid range
    clip = (float(xtr.min()), float(xtr.max()))

    base_net, base = fit_model(xtr, ytr, b, args.epochs)
    base_clean, base_robust = evaluate(base_net, base, xte, yte,
                                       args.epsilon, args.pgd_steps, b,
                                       clip=clip)
    logging.info("undefended: clean %.3f robust %.3f",
                 base_clean, base_robust)

    def_net, defended = fit_model(xtr, ytr, b, args.epochs,
                                  eps=args.epsilon,
                                  pgd_steps=args.pgd_steps, clip=clip)
    def_clean, def_robust = evaluate(def_net, defended, xte, yte,
                                     args.epsilon, args.pgd_steps, b,
                                     clip=clip)
    logging.info("defended:   clean %.3f robust %.3f", def_clean,
                 def_robust)

    assert base_clean >= 0.85, base_clean
    assert def_clean >= 0.70, def_clean
    gain = def_robust - base_robust
    assert gain >= args.min_robust_gain, (base_robust, def_robust)
    print("ADVTRAIN OK robust %.3f -> %.3f" % (base_robust, def_robust))


if __name__ == "__main__":
    main()
