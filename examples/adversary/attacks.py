"""Gradient-based adversarial attacks over the Module input-grad path.

Parity: example/adversary/adversary_generation.ipynb — the reference
crafts FGSM perturbations from an executor bound with input gradients
enabled; this library generalizes that to the standard attack family
(FGSM, targeted FGSM, PGD) against any bound Module.

`clip` bounds the valid data range (e.g. (0, 1) for unit images);
None (default) skips range clipping, keeping perturbations exactly in
the eps-ball whatever the input scaling.

Every attack drives the same framework surface:
    mod.bind(..., for_training=True, inputs_need_grad=True)
    mod.forward(batch, is_train=True); mod.backward()
    g = mod.get_input_grads()[0]
so the attacks double as a workout for input-gradient plumbing through
the fused forward+backward executor.
"""
import numpy as np

import mxnet_tpu as mx


def input_grad(mod, x, y):
    """dLoss/dx for a batch, via one fused forward+backward."""
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(y)]),
                is_train=True)
    mod.backward()
    return mod.get_input_grads()[0].asnumpy()


def _range_clip(x_adv, clip):
    if clip is None:
        return x_adv
    return np.clip(x_adv, clip[0], clip[1])


def fgsm(mod, x, y, eps, clip=None):
    """Fast gradient sign: one step of size eps up the loss surface."""
    g = input_grad(mod, x, y)
    return _range_clip(x + eps * np.sign(g), clip).astype(x.dtype)


def targeted_fgsm(mod, x, target, eps, clip=None):
    """Step DOWN the loss toward a chosen target class: the perturbation
    pushes predictions to `target` rather than merely off the truth."""
    g = input_grad(mod, x, target)
    return _range_clip(x - eps * np.sign(g), clip).astype(x.dtype)


def pgd(mod, x, y, eps, alpha=None, steps=8, random_start=True,
        clip=None, rng=None):
    """Projected gradient descent inside the L-inf eps-ball around x.

    The strongest first-order attack (Madry et al.): `steps` FGSM steps
    of size alpha, each followed by projection back into the ball."""
    if alpha is None:
        alpha = 2.5 * eps / steps
    rng = rng or np.random
    if random_start:
        x_adv = x + rng.uniform(-eps, eps, size=x.shape).astype(x.dtype)
        x_adv = _range_clip(x_adv, clip)
    else:
        x_adv = x.copy()
    for _ in range(steps):
        g = input_grad(mod, x_adv, y)
        x_adv = x_adv + alpha * np.sign(g)
        x_adv = np.clip(x_adv, x - eps, x + eps)  # project into the ball
        x_adv = _range_clip(x_adv, clip)
    return x_adv.astype(x.dtype)


def accuracy(mod, x, y, batch_size=None):
    """Clean-forward accuracy of a bound module on ALL of (x, y); a
    trailing partial batch is padded to the bound batch size and only
    its valid rows counted."""
    b = batch_size or x.shape[0]
    correct = 0
    for i in range(0, x.shape[0], b):
        xb, yb = x[i:i + b], y[i:i + b]
        valid = len(xb)
        if valid < b:
            pad = b - valid
            xb = np.concatenate([xb, np.repeat(xb[:1], pad, axis=0)])
            yb = np.concatenate([yb, np.repeat(yb[:1], pad, axis=0)])
        mod.forward(mx.io.DataBatch([mx.nd.array(xb)], [mx.nd.array(yb)]),
                    is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        correct += int((pred[:valid] == y[i:i + b]).sum())
    return correct / float(x.shape[0])
