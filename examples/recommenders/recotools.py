"""Shared recommender pieces (parity: example/recommenders/recotools.py +
crossentropy.py's role): synthetic implicit-feedback data and the ranking
metrics the workloads assert on, built as mx.metric.EvalMetric
subclasses so they plug into Module.score/fit like any built-in."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def synth_implicit(rs, users, items, rank, interactions_per_user):
    """Low-rank preference matrix -> each user 'consumes' their top-k
    items (plus noise).  Returns (positives[user, item], heldout[user ->
    one positive item held out of training])."""
    gu = rs.randn(users, rank).astype(np.float32)
    gi = rs.randn(items, rank).astype(np.float32)
    scores = gu @ gi.T + rs.randn(users, items).astype(np.float32) * 0.3
    pos, heldout = [], {}
    k = interactions_per_user
    for u in range(users):
        top = np.argpartition(-scores[u], k + 1)[: k + 1]
        top = top[np.argsort(-scores[u][top])]
        heldout[u] = int(top[0])        # best item: held out for eval
        for i in top[1:]:
            pos.append((u, int(i)))
    return np.asarray(pos, np.int64), heldout


class AUCMetric(mx.metric.EvalMetric):
    """Pairwise AUC over a binary-labelled batch: P(score_pos >
    score_neg) estimated from all pos/neg pairs in the batch (the metric
    implicit-feedback recommenders report; label 1 = observed pair)."""

    def __init__(self):
        super().__init__("auc")

    def update(self, labels, preds):
        lab = labels[0].asnumpy().ravel()
        p = preds[0].asnumpy().ravel()
        pos, neg = p[lab > 0.5], p[lab <= 0.5]
        if len(pos) == 0 or len(neg) == 0:
            return
        # exact pairwise count via rank-sum (O(n log n))
        allp = np.concatenate([pos, neg])
        ranks = allp.argsort().argsort().astype(np.float64) + 1
        auc = (ranks[: len(pos)].sum() - len(pos) * (len(pos) + 1) / 2) \
            / (len(pos) * len(neg))
        self.sum_metric += float(auc)
        self.num_inst += 1


class HitRateAtK:
    """HitRate@K over held-out positives: score EVERY item for a user,
    hit if the held-out item ranks in the top K.  Not an EvalMetric
    (needs full score vectors, not batch preds) — the workloads call
    ``update(rank)`` directly."""

    def __init__(self, k):
        self.k = k
        self.hits = 0
        self.total = 0

    def update(self, rank):
        self.hits += int(rank < self.k)
        self.total += 1

    def get(self):
        return ("hitrate@%d" % self.k,
                self.hits / max(self.total, 1))
