"""Negative-sampling data iterator (parity:
example/recommenders/negativesample.py — there a DataIter wrapper that
emits each positive (user, item) pair followed by k corrupted pairs with
label 0; same contract here)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

from mxnet_tpu import io as mio  # noqa: E402
from mxnet_tpu import ndarray as nd  # noqa: E402


class NegativeSamplingIter(mio.DataIter):
    """Wraps positive (user, item) pairs; each epoch re-draws ``k``
    random negative items per positive (label 0) and shuffles.  Negatives
    are corrupted on the ITEM side, the standard implicit-feedback
    recipe; known positives are NOT excluded (with sparse data the
    collision rate is negligible, and the reference sampler accepts the
    same bias)."""

    def __init__(self, positives, num_items, batch_size, k=4, seed=0):
        super().__init__()
        self.positives = np.asarray(positives, np.int64)
        self.num_items = int(num_items)
        self.batch_size = int(batch_size)
        self.k = int(k)
        self._rs = np.random.RandomState(seed)
        self._build_epoch()

    @property
    def provide_data(self):
        return [mio.DataDesc("user", (self.batch_size,)),
                mio.DataDesc("item", (self.batch_size,))]

    @property
    def provide_label(self):
        return [mio.DataDesc("label", (self.batch_size,))]

    def _build_epoch(self):
        n = len(self.positives)
        users = np.repeat(self.positives[:, 0], 1 + self.k)
        items = np.empty(n * (1 + self.k), np.int64)
        labels = np.zeros(n * (1 + self.k), np.float32)
        items[:: 1 + self.k] = self.positives[:, 1]
        labels[:: 1 + self.k] = 1.0
        for j in range(self.k):
            items[j + 1:: 1 + self.k] = self._rs.randint(
                0, self.num_items, n)
        order = self._rs.permutation(len(users))
        self._users = users[order].astype(np.float32)
        self._items = items[order].astype(np.float32)
        self._labels = labels[order]
        self.cur = 0

    def reset(self):
        self._build_epoch()  # fresh negatives every epoch

    def next(self):
        lo = self.cur
        if lo + self.batch_size > len(self._users):
            raise StopIteration
        hi = lo + self.batch_size
        self.cur = hi
        return mio.DataBatch(
            [nd.array(self._users[lo:hi]), nd.array(self._items[lo:hi])],
            [nd.array(self._labels[lo:hi])], pad=0)
