#!/usr/bin/env python
"""Row-sparse matrix-factorization recommender (ISSUE-9 end-to-end
example; parity: example/recommenders/ + the sparse embedding workload
the source paper's KVStore was built for).

Same model as matrix_fact.py — user/item embeddings dotted into a
rating prediction — but at ranking-workload scale: the embedding
tables are orders of magnitude larger than one batch's lookups, and
both are annotated ``grad_stype="row_sparse"`` so each training step
updates ONLY the rows the batch touched (executor row-sparse backward
-> KVStore sparse buckets; docs/sparse.md).  The dense path would
scatter into (and run the optimizer over) every row of both tables
every step.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

USERS, ITEMS, RANK = 20000, 8000, 8


def build():
    user = sym.Variable("user")
    item = sym.Variable("item")
    score = sym.Variable("score_label")
    uw = sym.Variable("user_embed_weight", grad_stype="row_sparse")
    iw = sym.Variable("item_embed_weight", grad_stype="row_sparse")
    u = sym.Embedding(user, weight=uw, input_dim=USERS, output_dim=RANK,
                      name="user_embed")
    v = sym.Embedding(item, weight=iw, input_dim=ITEMS, output_dim=RANK,
                      name="item_embed")
    pred = sym.sum(u * v, axis=1)
    return sym.LinearRegressionOutput(pred, score, name="score")


def synth(rs, n):
    """Synthetic low-rank ratings over a popularity-skewed catalog —
    a batch touches a tiny, non-uniform slice of each table, like real
    ranking traffic."""
    gu = rs.randn(USERS, RANK).astype(np.float32) * 0.7
    gi = rs.randn(ITEMS, RANK).astype(np.float32) * 0.7
    users = rs.randint(0, USERS, n)
    # zipf-ish item popularity, clipped into the catalog
    items = np.minimum((rs.pareto(1.2, n) * ITEMS / 60).astype(np.int64),
                       ITEMS - 1)
    ratings = (gu[users] * gi[items]).sum(1) \
        + rs.randn(n).astype(np.float32) * 0.1
    return (users.astype(np.float32), items.astype(np.float32),
            ratings.astype(np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--samples", type=int, default=40000)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    users, items, ratings = synth(rs, args.samples)

    mod = mx.mod.Module(build(), data_names=("user", "item"),
                        label_names=("score_label",),
                        context=mx.context.default_accelerator_context())
    it = mx.io.NDArrayIter({"user": users, "item": items},
                           {"score_label": ratings},
                           batch_size=args.batch, shuffle=True)
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.05},
            initializer=mx.init.Normal(0.1),
            eval_metric="rmse",
            batch_end_callback=mx.callback.Speedometer(args.batch, 50))
    # the gradients really were row-sparse end to end
    ex = mod._exec_group.execs[0]
    for w in ("user_embed_weight", "item_embed_weight"):
        g = ex.grad_dict[w]
        assert getattr(g, "stype", "default") == "row_sparse", (w, type(g))
    rmse = dict(mod.score(it, mx.metric.create("rmse")))["rmse"]
    print(f"train rmse {rmse:.3f}")
    assert rmse < 0.9, rmse
    print("SPARSE OK")


if __name__ == "__main__":
    main()
