#!/usr/bin/env python
"""Matrix-factorization recommender (parity: example/recommenders/):
user/item embeddings dotted into a rating prediction, LinearRegression
loss — the reference's demo1-MF notebook as a script, on a synthetic
low-rank rating matrix.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

USERS, ITEMS, RANK = 200, 150, 6


def build():
    user = sym.Variable("user")
    item = sym.Variable("item")
    score = sym.Variable("score_label")
    u = sym.Embedding(user, input_dim=USERS, output_dim=RANK, name="user_embed")
    v = sym.Embedding(item, input_dim=ITEMS, output_dim=RANK, name="item_embed")
    pred = sym.sum(u * v, axis=1)
    return sym.LinearRegressionOutput(pred, score, name="score")


def synth(rs, n):
    gu = rs.randn(USERS, RANK).astype(np.float32) * 0.7
    gi = rs.randn(ITEMS, RANK).astype(np.float32) * 0.7
    users = rs.randint(0, USERS, n)
    items = rs.randint(0, ITEMS, n)
    ratings = (gu[users] * gi[items]).sum(1) + rs.randn(n).astype(np.float32) * 0.1
    return (users.astype(np.float32), items.astype(np.float32),
            ratings.astype(np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=12)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    users, items, ratings = synth(rs, 20000)

    mod = mx.mod.Module(build(), data_names=("user", "item"),
                        label_names=("score_label",),
                        context=mx.context.default_accelerator_context())
    it = mx.io.NDArrayIter({"user": users, "item": items},
                           {"score_label": ratings},
                           batch_size=args.batch, shuffle=True)
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.02},
            initializer=mx.init.Normal(0.1),
            eval_metric="rmse")
    rmse = dict(mod.score(it, mx.metric.create("rmse")))["rmse"]
    print(f"train rmse {rmse:.3f}")
    assert rmse < 0.8, rmse
    print("TRAIN OK")


if __name__ == "__main__":
    main()
