#!/usr/bin/env python
"""Implicit-feedback recommender with negative sampling (parity:
example/recommenders/ demo2-binary + negativesample.py as one runnable
workload).

Observed (user, item) interactions only — no ratings.  Training pairs
each positive with k random item corruptions (NegativeSamplingIter),
the model scores pairs with dotted user/item embeddings + biases through
a logistic head, and evaluation is RANKING quality, asserted above
floor:
  - pairwise AUC on a held-back batch mix (custom EvalMetric),
  - HitRate@10: the held-out item of each user must crack the top-10 of
    ALL items far more often than the random floor.

Run:  MXTPU_PLATFORM=cpu python implicit.py
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

from negativesample import NegativeSamplingIter  # noqa: E402
from recotools import AUCMetric, HitRateAtK, synth_implicit  # noqa: E402

USERS, ITEMS, RANK = 160, 120, 8


def build(dim):
    user = sym.Variable("user")
    item = sym.Variable("item")
    label = sym.Variable("label")
    u = sym.Embedding(user, input_dim=USERS, output_dim=dim,
                      name="user_embed")
    v = sym.Embedding(item, input_dim=ITEMS, output_dim=dim,
                      name="item_embed")
    ub = sym.Flatten(sym.Embedding(user, input_dim=USERS, output_dim=1,
                                   name="user_bias"))
    vb = sym.Flatten(sym.Embedding(item, input_dim=ITEMS, output_dim=1,
                                   name="item_bias"))
    score = sym.sum(u * v, axis=1) + sym.Reshape(ub + vb, shape=(-1,))
    return sym.LogisticRegressionOutput(score, label, name="out")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--negatives", type=int, default=4)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(0)
    mx.random.seed(0)
    np.random.seed(0)

    positives, heldout = synth_implicit(rs, USERS, ITEMS, RANK,
                                        interactions_per_user=12)
    it = NegativeSamplingIter(positives, ITEMS, args.batch,
                              k=args.negatives, seed=1)
    mod = mx.mod.Module(build(args.dim), data_names=("user", "item"),
                        label_names=("label",))
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.01, "wd": 1e-5},
            initializer=mx.init.Normal(0.05), eval_metric=AUCMetric())

    # --- ranking eval: AUC on a fresh sampled mix
    auc_metric = AUCMetric()
    it.reset()
    auc = dict(mod.score(it, auc_metric))["auc"]

    # --- HitRate@10 on held-out items: score ALL items per user
    hr = HitRateAtK(10)
    eval_users = sorted(heldout)[:80]
    score_mod = mx.mod.Module(mod.symbol, data_names=("user", "item"),
                              label_names=("label",))
    score_mod.bind(data_shapes=[("user", (ITEMS,)), ("item", (ITEMS,))],
                   label_shapes=[("label", (ITEMS,))], for_training=False,
                   shared_module=mod)
    all_items = np.arange(ITEMS, dtype=np.float32)
    for u in eval_users:
        batch = mx.io.DataBatch(
            [mx.nd.array(np.full(ITEMS, u, np.float32)),
             mx.nd.array(all_items)],
            [mx.nd.zeros((ITEMS,))])
        score_mod.forward(batch, is_train=False)
        scores = score_mod.get_outputs()[0].asnumpy().ravel()
        rank = int((scores > scores[heldout[u]]).sum())
        hr.update(rank)
    name, rate = hr.get()
    floor = 10.0 / ITEMS  # random ranking
    logging.info("auc %.3f  %s %.3f (random floor %.3f)",
                 auc, name, rate, floor)
    assert auc > 0.80, auc
    assert rate > 4 * floor, (rate, floor)
    print(f"IMPLICIT OK: auc {auc:.3f} {name} {rate:.3f}")


if __name__ == "__main__":
    main()
