#!/usr/bin/env python
"""Model-parallel LSTM: layers placed on different devices via ctx_group.

Parity: example/model-parallel-lstm/lstm_ptb.py (reference): each LSTM
layer is annotated with ``AttrScope(ctx_group=...)`` and ``bind(
group2ctx={group: device})`` places it; the engine overlaps the stages.

TPU-native meaning (SURVEY.md §7 PlaceDevice row): the executor cuts the
graph into per-device segments, compiles each as its own XLA program, and
jax.device_put between segments is the explicit transfer point — the
_CrossDeviceCopy parity (executor.py placement_plan/_build_placed_fn).
XLA's async dispatch overlaps the stages the way the reference's engine
did.  Run with MXTPU_PLATFORM=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=2 to see two-device
placement without hardware."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.models.lstm import LSTMParam, LSTMState, lstm  # noqa: E402


def model_parallel_lstm(num_layers, seq_len, vocab_size, num_hidden,
                        num_embed, group_per_layer):
    """Parity: model-parallel-lstm/lstm.py lstm_unroll with per-layer
    ctx_group annotations (reference lstm.py:48-99)."""
    with mx.AttrScope(ctx_group="embed"):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        embed_weight = sym.Variable("embed_weight")
        embed = sym.Embedding(data, weight=embed_weight,
                              input_dim=vocab_size, output_dim=num_embed,
                              name="embed")
        slices = sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                                  squeeze_axis=True)

    params, states = [], []
    for layer in range(num_layers):
        with mx.AttrScope(ctx_group=group_per_layer[layer]):
            params.append(LSTMParam(
                i2h_weight=sym.Variable(f"l{layer}_i2h_weight"),
                i2h_bias=sym.Variable(f"l{layer}_i2h_bias"),
                h2h_weight=sym.Variable(f"l{layer}_h2h_weight"),
                h2h_bias=sym.Variable(f"l{layer}_h2h_bias")))
            states.append(LSTMState(c=sym.Variable(f"l{layer}_init_c"),
                                    h=sym.Variable(f"l{layer}_init_h")))

    outputs = []
    for t in range(seq_len):
        x = slices[t]
        for layer in range(num_layers):
            with mx.AttrScope(ctx_group=group_per_layer[layer]):
                states[layer] = lstm(num_hidden, x, states[layer],
                                     params[layer], t, layer)
                x = states[layer].h
        outputs.append(x)

    with mx.AttrScope(ctx_group="out"):
        concat = sym.Concat(*outputs, dim=0)
        pred = sym.FullyConnected(concat, num_hidden=vocab_size, name="pred")
        label_t = sym.transpose(label)
        label_flat = sym.Reshape(label_t, shape=(-1,))
        return sym.SoftmaxOutput(pred, label_flat, name="softmax")


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description="model-parallel LSTM")
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--vocab-size", type=int, default=1000)
    ap.add_argument("--num-hidden", type=int, default=128)
    ap.add_argument("--num-embed", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-batches", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    ndev = mx.num_devices(mx.context.default_accelerator_context().device_type)
    groups = [f"layer{i}" for i in range(args.num_layers)]
    net = model_parallel_lstm(args.num_layers, args.seq_len, args.vocab_size,
                              args.num_hidden, args.num_embed, groups)

    # each layer group on its own device (wraps when layers > devices)
    dev_t = mx.context.default_accelerator_context().device_type
    group2ctx = {"embed": mx.Context(dev_t, 0), "out": mx.Context(dev_t, 0)}
    for i, g in enumerate(groups):
        group2ctx[g] = mx.Context(dev_t, i % max(ndev, 1))
    logging.info("placement: %s", {k: str(v) for k, v in group2ctx.items()})

    shapes = {"data": (args.batch_size, args.seq_len),
              "softmax_label": (args.batch_size, args.seq_len)}
    for layer in range(args.num_layers):
        shapes[f"l{layer}_init_c"] = (args.batch_size, args.num_hidden)
        shapes[f"l{layer}_init_h"] = (args.batch_size, args.num_hidden)
    ex = net.simple_bind(ctx=mx.Context(dev_t, 0), group2ctx=group2ctx,
                         **shapes)

    init = mx.init.Xavier(magnitude=2.34)
    rs = np.random.RandomState(0)
    for name, arr in ex.arg_dict.items():
        if name not in shapes:
            init(name, arr)

    for step in range(args.num_batches):
        data = rs.randint(0, args.vocab_size,
                          (args.batch_size, args.seq_len)).astype(np.float32)
        label = np.roll(data, -1, axis=1)
        ex.arg_dict["data"][:] = data
        ex.arg_dict["softmax_label"][:] = label
        ex.forward(is_train=True)
        ex.backward()
        for name, grad in ex.grad_dict.items():
            if grad is not None and name not in shapes:
                ex.arg_dict[name][:] = (ex.arg_dict[name] - args.lr * grad).asnumpy()
        loss = -np.log(np.maximum(
            ex.outputs[0].asnumpy()[np.arange(args.batch_size * args.seq_len),
                                    label.T.reshape(-1).astype(int)], 1e-9)).mean()
        logging.info("step %d loss %.3f", step, loss)
