#!/usr/bin/env python
"""LSTM-PTB through the scheduled-microbatch pipeline (parallel/pipeline.py).

The reference's model-parallel LSTM places each layer on a device and
relies on the engine's opportunistic overlap
(/root/reference/example/model-parallel-lstm/lstm.py:48-99,
docs/how_to/model_parallel_lstm.md).  The TPU-native upgrade is a real
GPipe schedule: one LSTM *layer per pipeline stage*, each stage scanning
its layer over the full sequence for one microbatch per tick, with
activations ([mb, T, H] hidden sequences) rotating over the 'pipe' mesh
axis — fill/steady/drain is one XLA program and backward is its exact
transpose.

Equal-width trunk: embedding width == hidden width (the classic PTB
config), embedding + softmax head run OUTSIDE the pipelined region.

Run (no hardware needed — virtual CPU mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python lstm_pipeline.py [--self-test]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mxnet_tpu.parallel import pipeline as pp  # noqa: E402
from mxnet_tpu.parallel.mesh import create_mesh  # noqa: E402


def lstm_layer(params, xs):
    """One LSTM layer over a hidden-state sequence: [mb, T, H] -> [mb, T, H].

    Same cell math as models/lstm.py (i2h + h2h -> i/f/o/c gates), written
    functionally so a pipeline stage can scan it over time.
    """
    mb, T, H = xs.shape
    c0 = jnp.zeros((mb, H), xs.dtype)
    h0 = jnp.zeros((mb, H), xs.dtype)

    def step(carry, x_t):
        c, h = carry
        gates = x_t @ params["i2h_w"].T + params["i2h_b"] \
            + h @ params["h2h_w"].T + params["h2h_b"]
        i, f, o, g = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (c, h), h

    _, hs = jax.lax.scan(step, (c0, h0), xs.transpose(1, 0, 2))
    return hs.transpose(1, 0, 2)


def layer_params(rs, H):
    g = lambda *s: jnp.asarray(rs.normal(0, 0.1, s).astype(np.float32))
    return {"i2h_w": g(4 * H, H), "i2h_b": jnp.zeros(4 * H),
            "h2h_w": g(4 * H, H), "h2h_b": jnp.zeros(4 * H)}


def build(n_layers, H, vocab, mesh):
    rs = np.random.RandomState(0)
    trunk = pp.shard_stacked(
        mesh, pp.stack_stage_params([layer_params(rs, H)
                                     for _ in range(n_layers)]))
    return {
        "embed": jnp.asarray(rs.normal(0, 0.1, (vocab, H)).astype(np.float32)),
        "head_w": jnp.asarray(rs.normal(0, 0.1, (H, vocab)).astype(np.float32)),
        "head_b": jnp.zeros(vocab),
        "trunk": trunk,
    }


def make_losses(mesh, n_micro, X, Y, vocab):
    stage_fn = lambda p, x, stage: lstm_layer(p, x)

    def nll(logits, Y):
        lp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(lp, Y[..., None], axis=-1).mean()

    def pipe_loss(params):
        h = params["embed"][X]
        out = pp.pipeline_apply(stage_fn, params["trunk"],
                                pp.microbatch(h, n_micro), mesh, "pipe")
        logits = out.reshape(X.shape + (-1,)) @ params["head_w"] + params["head_b"]
        return nll(logits, Y)

    def seq_loss(params):
        h = params["embed"][X]
        n_layers = next(iter(params["trunk"].values())).shape[0]
        for i in range(n_layers):
            h = lstm_layer({k: v[i] for k, v in params["trunk"].items()}, h)
        return nll(h @ params["head_w"] + params["head_b"], Y)

    return pipe_loss, seq_loss


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--n-micro", type=int, default=4)
    ap.add_argument("--micro-batch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--self-test", action="store_true",
                    help="assert pipeline grads == sequential, then train")
    args = ap.parse_args(argv)

    S = args.num_layers
    mesh = create_mesh((S,), ("pipe",), devices=jax.devices("cpu")[:S])
    rs = np.random.RandomState(42)
    batch = args.n_micro * args.micro_batch
    # synthetic PTB stand-in: learnable bigram-ish stream
    X_np = rs.randint(0, args.vocab, (batch, args.seq_len))
    Y_np = (X_np * 3 + 1) % args.vocab  # deterministic next-token rule
    X, Y = jnp.asarray(X_np), jnp.asarray(Y_np)

    params = build(S, args.hidden, args.vocab, mesh)
    pipe_loss, seq_loss = make_losses(mesh, args.n_micro, X, Y, args.vocab)

    if args.self_test:
        lp, gp = jax.jit(jax.value_and_grad(pipe_loss))(params)
        ls, gs = jax.jit(jax.value_and_grad(seq_loss))(params)
        np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
        pf = jax.tree_util.tree_leaves_with_path(gp)
        sf = dict(jax.tree_util.tree_leaves_with_path(gs))
        for path, leaf in pf:
            np.testing.assert_allclose(np.asarray(leaf), np.asarray(sf[path]),
                                       rtol=2e-4, atol=1e-5, err_msg=str(path))
        print("self-test: pipeline == sequential (loss %.4f)" % float(lp))

    step = jax.jit(lambda p: (pipe_loss(p), jax.grad(pipe_loss)(p)))
    first = None
    for i in range(args.steps):
        loss, grads = step(params)
        params = jax.tree_util.tree_map(lambda w, d: w - args.lr * d,
                                        params, grads)
        if first is None:
            first = float(loss)
        if i % 5 == 0 or i == args.steps - 1:
            print("step %3d  ppl %8.2f  (bubble %.0f%%)"
                  % (i, float(jnp.exp(loss)),
                     100 * pp.bubble_fraction(S, args.n_micro)))
    final = float(loss)
    assert final < first, (first, final)
    print("converged: loss %.3f -> %.3f over %d steps" %
          (first, final, args.steps))


if __name__ == "__main__":
    main()
