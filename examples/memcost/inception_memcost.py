#!/usr/bin/env python
"""Gradient-mirroring memory/speed trade (parity: example/memcost/):
the reference's MXNET_BACKWARD_DO_MIRROR recomputes cheap activations in
backward; on TPU the same trade is jax.checkpoint (rematerialization)
applied to the fused train step.  This script times both settings."""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402
from mxnet_tpu.trainer import FusedTrainer  # noqa: E402


def run(remat, args):
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1" if remat else "0"
    net = models.get_symbol(args.network, num_classes=10,
                            image_shape=(3, 32, 32))
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.05},
                      remat=remat)
    tr.init(data=(args.batch_size, 3, 32, 32))
    rs = np.random.RandomState(0)
    x = rs.uniform(size=(args.batch_size, 3, 32, 32)).astype(np.float32)
    y = rs.randint(0, 10, args.batch_size).astype(np.float32)
    tr.step(data=x, softmax_label=y)  # compile
    tic = time.time()
    for _ in range(args.iterations):
        out = tr.step(data=x, softmax_label=y)
    import jax

    jax.block_until_ready(out)
    return (time.time() - tic) / args.iterations


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="resnet-20")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--iterations", type=int, default=10)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    base = run(False, args)
    remat = run(True, args)
    logging.info("no-mirror %.1f ms/step, mirror(remat) %.1f ms/step "
                 "(%.0f%% slower, activations not stored)",
                 base * 1e3, remat * 1e3, (remat / base - 1) * 100)
