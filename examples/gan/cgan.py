#!/usr/bin/env python
"""Conditional GAN (parity family: example/gan/dcgan.py, extended the
way the original cGAN paper conditions both nets on the class label).

Beyond dcgan.py, this exercises:
  - class conditioning through Embedding + Concat in BOTH modules,
  - mx.mon.Monitor installed on the discriminator (fixed-point
    monitoring: per-tensor RMS of weights/activations every N steps —
    the classic way to see a GAN collapse before the loss shows it),
  - a custom EvalMetric (discriminator balance: |acc_real - 0.5| +
    |acc_fake - 0.5|, small when G and D are in equilibrium),
  - the manual two-module update loop with inputs_need_grad.

The synthetic task is class-conditional by construction: class c images
are gaussian blobs with mean intensity MEANS[c].  After training, the
generator must reproduce that ordering from the label alone — asserted,
not eyeballed.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

N_CLASSES = 3
MEANS = np.array([-0.6, 0.0, 0.6], np.float32)  # tanh-space class means
IMG_DIM = 64  # flattened 8x8


def make_generator(code_dim, hidden):
    rand = sym.Variable("rand")
    cls = sym.Variable("cls")
    emb = sym.Flatten(sym.Embedding(cls, input_dim=N_CLASSES,
                                    output_dim=code_dim, name="g_cls_embed"))
    h = sym.Concat(rand, emb, dim=1)
    h = sym.Activation(sym.FullyConnected(h, num_hidden=hidden, name="g_fc1"),
                       act_type="relu")
    h = sym.Activation(sym.FullyConnected(h, num_hidden=hidden, name="g_fc2"),
                       act_type="relu")
    out = sym.FullyConnected(h, num_hidden=IMG_DIM, name="g_out")
    return sym.Activation(out, act_type="tanh")


def make_discriminator(hidden):
    data = sym.Variable("data")
    cls = sym.Variable("cls")
    label = sym.Variable("label")
    emb = sym.Flatten(sym.Embedding(cls, input_dim=N_CLASSES,
                                    output_dim=16, name="d_cls_embed"))
    h = sym.Concat(data, emb, dim=1)
    h = sym.LeakyReLU(sym.FullyConnected(h, num_hidden=hidden, name="d_fc1"),
                      act_type="leaky", slope=0.2)
    h = sym.LeakyReLU(sym.FullyConnected(h, num_hidden=hidden, name="d_fc2"),
                      act_type="leaky", slope=0.2)
    out = sym.FullyConnected(h, num_hidden=1, name="d_out")
    return sym.LogisticRegressionOutput(sym.Flatten(out), label, name="dloss")


class DiscriminatorBalance(mx.metric.EvalMetric):
    """|acc_real - 0.5| + |acc_fake - 0.5| — near 0 at the GAN
    equilibrium (D can't tell), near 1 when one side has collapsed.
    Shows the custom-metric API the reference documents
    (python/mxnet/metric.py CustomMetric)."""

    def __init__(self):
        super().__init__("d_balance")

    def update(self, labels, preds):
        lab = labels[0].asnumpy().ravel()
        p = preds[0].asnumpy().ravel()
        real, fake = lab > 0.5, lab <= 0.5
        acc_r = float(((p > 0.5) == (lab > 0.5))[real].mean()) if real.any() else 0.5
        acc_f = float(((p > 0.5) == (lab > 0.5))[fake].mean()) if fake.any() else 0.5
        self.sum_metric += abs(acc_r - 0.5) + abs(acc_f - 0.5)
        self.num_inst += 1


def real_batch(rs, b):
    cls = rs.randint(0, N_CLASSES, b)
    imgs = rs.normal(MEANS[cls][:, None], 0.15, (b, IMG_DIM))
    return (np.clip(imgs, -1, 1).astype(np.float32),
            cls.astype(np.float32))


def main():
    ap = argparse.ArgumentParser(description="conditional GAN")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--code-dim", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--num-batches", type=int, default=400)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--monitor-every", type=int, default=50)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(0)
    mx.random.seed(0)
    np.random.seed(0)
    b, z = args.batch_size, args.code_dim

    gen = mx.mod.Module(make_generator(z, args.hidden),
                        data_names=("rand", "cls"), label_names=[])
    gen.bind(data_shapes=[("rand", (b, z)), ("cls", (b,))],
             for_training=True, inputs_need_grad=False)
    gen.init_params(mx.init.Normal(0.05))
    gen.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr,
                                         "beta1": 0.5})

    disc = mx.mod.Module(make_discriminator(args.hidden),
                         data_names=("data", "cls"), label_names=("label",))
    disc.bind(data_shapes=[("data", (b, IMG_DIM)), ("cls", (b,))],
              label_shapes=[("label", (b,))], for_training=True,
              inputs_need_grad=True)
    disc.init_params(mx.init.Normal(0.05))
    disc.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    # fixed-point monitoring: RMS of every d_* weight + activation, every
    # --monitor-every batches (mx.mon.Monitor over the D executor)
    mon = mx.mon.Monitor(args.monitor_every, pattern=".*d_(fc1|out).*")
    disc.install_monitor(mon)

    balance = DiscriminatorBalance()
    for step in range(args.num_batches):
        noise = rs.normal(0, 1, (b, z)).astype(np.float32)
        g_cls = rs.randint(0, N_CLASSES, b).astype(np.float32)
        gen.forward(mx.io.DataBatch([mx.nd.array(noise),
                                     mx.nd.array(g_cls)], None),
                    is_train=True)
        fake = gen.get_outputs()[0]

        mon.tic()
        # --- D on fake (0) then real (1); accumulate grads manually
        disc.forward(mx.io.DataBatch([fake, mx.nd.array(g_cls)],
                                     [mx.nd.zeros((b,))]), is_train=True)
        disc.backward()
        balance.update([mx.nd.zeros((b,))],
                       [disc.get_outputs()[0].reshape((b,))])
        grads_fake = [[g.copy() for g in gl] for gl in
                      disc._exec_group.grad_arrays]
        r_img, r_cls = real_batch(rs, b)
        disc.forward(mx.io.DataBatch([mx.nd.array(r_img),
                                      mx.nd.array(r_cls)],
                                     [mx.nd.ones((b,))]), is_train=True)
        disc.backward()
        balance.update([mx.nd.ones((b,))],
                       [disc.get_outputs()[0].reshape((b,))])
        for gl, gf in zip(disc._exec_group.grad_arrays, grads_fake):
            for gi, gfi in zip(gl, gf):
                gi += gfi
        disc.update()
        mon.toc_print()

        # --- G: D(fake | cls) should read "real"
        disc.forward(mx.io.DataBatch([fake, mx.nd.array(g_cls)],
                                     [mx.nd.ones((b,))]), is_train=True)
        disc.backward()
        gen.backward([disc.get_input_grads()[0]])
        gen.update()

        if step % 25 == 0:
            logging.info("step %d  d_balance %.3f", step, balance.get()[1])
            balance.reset()

    # the assertion: conditioning works — per-class generated mean
    # intensity must reproduce the data's class ordering and be close to
    # the class means
    per_class = []
    for c in range(N_CLASSES):
        noise = rs.normal(0, 1, (b, z)).astype(np.float32)
        cls = np.full((b,), c, np.float32)
        gen.forward(mx.io.DataBatch([mx.nd.array(noise),
                                     mx.nd.array(cls)], None),
                    is_train=False)
        per_class.append(float(gen.get_outputs()[0].asnumpy().mean()))
    logging.info("class means generated=%s target=%s",
                 np.round(per_class, 2), MEANS)
    assert per_class[0] < per_class[1] < per_class[2], per_class
    assert all(abs(g - t) < 0.35 for g, t in zip(per_class, MEANS)), \
        (per_class, MEANS)
    print("CGAN OK: conditional means", np.round(per_class, 3))


if __name__ == "__main__":
    main()
