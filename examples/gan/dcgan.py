#!/usr/bin/env python
"""DCGAN on (synthetic) MNIST (parity: example/gan/dcgan.py).

Exercises the framework pieces the fit() loop hides: two Modules bound
for_training with inputs_need_grad on the discriminator, manual
forward/backward chaining (G's update uses dD/dx back-propagated into
G's output), and per-module optimizers."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402


def make_generator(ngf, nc, code_dim):
    rand = sym.Variable("rand")
    g = sym.Deconvolution(rand, name="g1", kernel=(4, 4), num_filter=ngf * 4,
                          no_bias=True)
    g = sym.BatchNorm(g, name="gbn1", fix_gamma=True)
    g = sym.Activation(g, name="gact1", act_type="relu")
    g = sym.Deconvolution(g, name="g2", kernel=(4, 4), stride=(2, 2),
                          pad=(1, 1), num_filter=ngf * 2, no_bias=True)
    g = sym.BatchNorm(g, name="gbn2", fix_gamma=True)
    g = sym.Activation(g, name="gact2", act_type="relu")
    g = sym.Deconvolution(g, name="g3", kernel=(4, 4), stride=(2, 2),
                          pad=(1, 1), num_filter=ngf, no_bias=True)
    g = sym.BatchNorm(g, name="gbn3", fix_gamma=True)
    g = sym.Activation(g, name="gact3", act_type="relu")
    g = sym.Deconvolution(g, name="g4", kernel=(4, 4), stride=(2, 2),
                          pad=(1, 1), num_filter=nc, no_bias=True)
    return sym.Activation(g, name="gact4", act_type="tanh")


def make_discriminator(ndf):
    data = sym.Variable("data")
    label = sym.Variable("label")
    d = sym.Convolution(data, name="d1", kernel=(4, 4), stride=(2, 2),
                        pad=(1, 1), num_filter=ndf, no_bias=True)
    d = sym.LeakyReLU(d, name="dact1", act_type="leaky", slope=0.2)
    d = sym.Convolution(d, name="d2", kernel=(4, 4), stride=(2, 2),
                        pad=(1, 1), num_filter=ndf * 2, no_bias=True)
    d = sym.BatchNorm(d, name="dbn2", fix_gamma=True)
    d = sym.LeakyReLU(d, name="dact2", act_type="leaky", slope=0.2)
    d = sym.Convolution(d, name="d3", kernel=(4, 4), stride=(2, 2),
                        pad=(1, 1), num_filter=ndf * 4, no_bias=True)
    d = sym.BatchNorm(d, name="dbn3", fix_gamma=True)
    d = sym.LeakyReLU(d, name="dact3", act_type="leaky", slope=0.2)
    d = sym.Convolution(d, name="d4", kernel=(4, 4), num_filter=1,
                        no_bias=True)
    d = sym.Flatten(d)
    return sym.LogisticRegressionOutput(d, label, name="dloss")


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description="DCGAN")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--code-dim", type=int, default=100)
    ap.add_argument("--ngf", type=int, default=32)
    ap.add_argument("--ndf", type=int, default=32)
    ap.add_argument("--num-batches", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.0002)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    b, z = args.batch_size, args.code_dim
    gen = mx.mod.Module(make_generator(args.ngf, 1, z),
                        data_names=("rand",), label_names=[])
    gen.bind(data_shapes=[("rand", (b, z, 1, 1))], for_training=True,
             inputs_need_grad=False)
    gen.init_params(mx.init.Normal(0.02))
    gen.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr,
                                         "beta1": 0.5})

    disc = mx.mod.Module(make_discriminator(args.ndf),
                         data_names=("data",), label_names=("label",))
    disc.bind(data_shapes=[("data", (b, 1, 32, 32))],
              label_shapes=[("label", (b,))], for_training=True,
              inputs_need_grad=True)
    disc.init_params(mx.init.Normal(0.02))
    disc.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    rs = np.random.RandomState(0)
    real = rs.uniform(-1, 1, (1024, 1, 32, 32)).astype(np.float32)
    metric = mx.metric.create("acc")
    for step in range(args.num_batches):
        noise = rs.normal(0, 1, (b, z, 1, 1)).astype(np.float32)
        gen.forward(mx.io.DataBatch([mx.nd.array(noise)], None),
                    is_train=True)
        fake = gen.get_outputs()[0]

        # --- train D on fake (label 0) then real (label 1)
        disc.forward(mx.io.DataBatch([fake], [mx.nd.zeros((b,))]),
                     is_train=True)
        disc.backward()
        grads_fake = [[g.copy() for g in gl] for gl in
                      disc._exec_group.grad_arrays]
        batch_real = real[(step * b) % 1024:(step * b) % 1024 + b]
        disc.forward(mx.io.DataBatch([mx.nd.array(batch_real)],
                                     [mx.nd.ones((b,))]), is_train=True)
        disc.backward()
        # accumulate fake+real grads manually (parity: dcgan.py gmod trick)
        for gl, gf in zip(disc._exec_group.grad_arrays, grads_fake):
            for gi, gfi in zip(gl, gf):
                gi += gfi
        disc.update()

        # --- train G: D(fake) should be "real"; push dD/dx through G
        disc.forward(mx.io.DataBatch([fake], [mx.nd.ones((b,))]),
                     is_train=True)
        disc.backward()
        gen.backward([disc.get_input_grads()[0]])
        gen.update()

        metric.reset()
        metric.update([mx.nd.ones((b,))],
                      [disc.get_outputs()[0].reshape((b,))])
        if step % 5 == 0:
            logging.info("step %d  D(fake-as-real) acc %.2f", step,
                         metric.get()[1])
    logging.info("done")
