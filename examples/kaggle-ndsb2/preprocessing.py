#!/usr/bin/env python
"""Data staging for the second National Data Science Bowl (cardiac MRI
volume estimation).  Parity: example/kaggle-ndsb2/Preprocessing.py —
the reference crops/rescales each study's 30-frame short-axis cine
into 64x64 frames and writes one CSV row per study
(train-64x64-data.csv) plus a label CSV (id, systole, diastole).

Real DICOM decoding needs pydicom (absent from this image), so this
script synthesizes the same artifact: a pulsating-disc "heart" whose
min/max area over the cycle IS the systole/diastole label — the CSV
formats match the reference exactly, so a real preprocessed dataset
drops straight into train.py.
"""
import argparse
import os

import numpy as np

FRAMES, SIZE = 30, 64


def synth_study(rs):
    """A 30-frame cine: a disc whose radius pulses over the cycle, plus
    chest-like background structure and noise."""
    diastole_r = rs.uniform(8, 22)                  # max radius
    systole_r = diastole_r * rs.uniform(0.45, 0.8)  # min radius
    cx, cy = rs.uniform(24, 40, 2)
    phase = rs.uniform(0, 2 * np.pi)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    bg = rs.uniform(0, 60) + 20 * np.sin(xx / rs.uniform(6, 14))
    video = np.zeros((FRAMES, SIZE, SIZE), np.float32)
    for t in range(FRAMES):
        # radius swings diastole -> systole -> diastole over the cycle
        c = 0.5 * (1 + np.cos(2 * np.pi * t / FRAMES + phase))
        r = systole_r + (diastole_r - systole_r) * c
        disc = ((xx - cx) ** 2 + (yy - cy) ** 2) < r ** 2
        video[t] = np.clip(bg + 200 * disc + rs.randn(SIZE, SIZE) * 8,
                           0, 255)
    # labels: ventricle "volume" in the competition's mL-like range
    systole = np.pi * systole_r ** 2 * 0.3
    diastole = np.pi * diastole_r ** 2 * 0.3
    return video, systole, diastole


def write_split(path_prefix, n, rs, with_labels=True):
    data_rows, labels = [], []
    for i in range(n):
        video, sys_v, dia_v = synth_study(rs)
        data_rows.append(video.reshape(-1))
        labels.append((i + 1, sys_v, dia_v))
    np.savetxt(path_prefix + "-64x64-data.csv",
               np.asarray(data_rows, np.float32), delimiter=",", fmt="%g")
    if with_labels:
        np.savetxt(path_prefix + "-label.csv", np.asarray(labels),
                   delimiter=",", fmt="%g")
    return labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/ndsb2")
    ap.add_argument("--train", type=int, default=500)
    ap.add_argument("--validate", type=int, default=100)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)
    rs = np.random.RandomState(0)
    write_split(os.path.join(args.out, "train"), args.train, rs)
    write_split(os.path.join(args.out, "validate"), args.validate, rs)
    print(f"staged {args.train}+{args.validate} studies under {args.out}")


if __name__ == "__main__":
    main()
