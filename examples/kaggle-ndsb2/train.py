#!/usr/bin/env python
"""Second Data Science Bowl: predict cardiac systole/diastole volume
CDFs from 30-frame MRI cine (parity: example/kaggle-ndsb2/Train.py).

The reference's recipe, reproduced end to end:
  - frame-DIFFERENCE input: SliceChannel into 30 frames, 29 adjacent
    diffs concatenated (motion is the signal, anatomy is nuisance),
  - LeNet-style conv net ending in 600 sigmoid outputs
    (LogisticRegressionOutput) that regress the volume's cumulative
    distribution P(V < v) for v = 0..599 mL,
  - labels encoded as step CDFs (encode_label), trained with the CSV
    pack written by preprocessing.py through CSVIter + FeedForward,
  - CRPS (the competition metric) as an mx.metric-wrapped numpy
    function, with the monotonicity repair before scoring,
  - separate systole and diastole models, one submission CSV row per
    study ("Id_Systole", then 600 cumulative probabilities).
"""
import argparse
import csv
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

from preprocessing import FRAMES, SIZE, write_split  # noqa: E402


def get_lenet():
    """Frame-difference LeNet head -> 600-way CDF regression."""
    source = sym.Variable("data")
    source = (source - 128) * (1.0 / 128)
    frames = sym.SliceChannel(source, num_outputs=FRAMES)
    diffs = [frames[i + 1] - frames[i] for i in range(FRAMES - 1)]
    net = sym.Concat(*diffs)
    net = sym.Convolution(net, kernel=(5, 5), num_filter=40)
    net = sym.BatchNorm(net, fix_gamma=True)
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = sym.Convolution(net, kernel=(3, 3), num_filter=40)
    net = sym.BatchNorm(net, fix_gamma=True)
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    flatten = sym.Flatten(net)
    flatten = sym.Dropout(flatten)
    fc1 = sym.FullyConnected(flatten, num_hidden=600)
    return sym.LogisticRegressionOutput(fc1, name="softmax")


def crps(label, pred):
    """Continuous Ranked Probability Score with the competition's
    monotonicity repair (a CDF must be non-decreasing)."""
    pred = pred.copy()
    np.maximum.accumulate(pred, axis=1, out=pred)
    return np.sum(np.square(label - pred)) / label.size


def encode_label(volumes):
    """volume v -> step CDF over thresholds 0..599 (the reference's
    (x < arange(600)) encoding)."""
    return np.array([(x < np.arange(600)) for x in volumes],
                    dtype=np.float32)


def train_one(target, work, batch, epochs, lr, ctx):
    labels = np.loadtxt(os.path.join(work, "train-label.csv"), delimiter=",")
    col = 1 if target == "systole" else 2
    enc = encode_label(labels[:, col])
    enc_csv = os.path.join(work, f"train-{target}.csv")
    np.savetxt(enc_csv, enc, delimiter=",", fmt="%g")

    data_train = mx.io.CSVIter(
        data_csv=os.path.join(work, "train-64x64-data.csv"),
        data_shape=(FRAMES, SIZE, SIZE),
        label_csv=enc_csv, label_shape=(600,), batch_size=batch)
    model = mx.model.FeedForward(
        ctx=ctx, symbol=get_lenet(), num_epoch=epochs,
        learning_rate=lr, wd=1e-5, momentum=0.9,
        initializer=mx.init.Xavier())
    model.fit(X=data_train, eval_metric=mx.metric.CustomMetric(crps, "crps"))
    return model


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--work", default="/tmp/ndsb2")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=25)
    ap.add_argument("--lr", type=float, default=0.003)
    ap.add_argument("--submission",
                    help="output CSV (default: <work>/submission.csv)")
    ap.add_argument("--max-crps", type=float, default=0.10)
    args = ap.parse_args()
    if args.submission is None:
        args.submission = os.path.join(args.work, "submission.csv")
    ctx = mx.context.default_accelerator_context()

    if not os.path.exists(os.path.join(args.work, "train-64x64-data.csv")):
        os.makedirs(args.work, exist_ok=True)
        rs = np.random.RandomState(0)
        write_split(os.path.join(args.work, "train"), 500, rs)
        write_split(os.path.join(args.work, "validate"), 100, rs)

    models = {t: train_one(t, args.work, args.batch, args.epochs, args.lr,
                           ctx) for t in ("systole", "diastole")}

    # held-out CRPS + submission (reference: accumulate_result + the
    # submission loop at Train.py's tail).  The validate pack is loaded
    # whole and padded to a batch multiple so the LAST PARTIAL BATCH is
    # kept — CSVIter's discard mode would silently drop studies from the
    # submission, which Kaggle rejects.
    val_data = np.loadtxt(
        os.path.join(args.work, "validate-64x64-data.csv"),
        delimiter=",").astype(np.float32).reshape(-1, FRAMES, SIZE, SIZE)
    val_labels = np.loadtxt(os.path.join(args.work, "validate-label.csv"),
                            delimiter=",")
    n = len(val_data)
    pad = (-n) % args.batch
    if pad:
        val_data = np.concatenate(
            [val_data, np.zeros((pad,) + val_data.shape[1:], np.float32)])
    val = mx.io.NDArrayIter(val_data, batch_size=args.batch)
    scores = {}
    with open(args.submission, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["Id"] + [f"P{i}" for i in range(600)])
        for tname, col in (("Systole", 1), ("Diastole", 2)):
            model = models[tname.lower()]
            val.reset()
            prob = model.predict(val)[:n]
            prob = np.maximum.accumulate(prob, axis=1)
            enc = encode_label(val_labels[:, col])
            scores[tname] = crps(enc, prob)
            for i, row in enumerate(prob):
                w.writerow([f"{int(val_labels[i, 0])}_{tname}"]
                           + [f"{p:.5f}" for p in row])
    print(f"validation CRPS: systole {scores['Systole']:.4f} "
          f"diastole {scores['Diastole']:.4f}")
    print(f"wrote {args.submission}")
    total = (scores["Systole"] + scores["Diastole"]) / 2
    assert total < args.max_crps, (total, args.max_crps)
    print("NDSB2 OK")


if __name__ == "__main__":
    main()
