#!/usr/bin/env python
"""Advantage actor-critic (parity: example/reinforcement-learning/a3c/ —
the synchronous variant of the same estimator; the reference's a3c.py
runs parallel workers feeding one set of weights, here K parallel
environments step in lockstep).  Shared trunk with policy + value heads:
the policy trains on advantage-weighted log-likelihood plus an entropy
bonus, the value head on n-step bootstrapped returns — all expressed
symbolically through MakeLoss, no custom ops.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

from dqn_gridworld import GRID, ACTIONS, GridWorld  # noqa: E402


def ac_net(batch):
    data = sym.Variable("data")
    act = sym.Variable("action")        # (N,) taken actions
    adv = sym.Variable("advantage")     # (N,) advantages
    ret = sym.Variable("return_label")  # (N,) bootstrapped returns
    mask = sym.Variable("mask")         # (N,) 1 for real samples
    trunk = sym.FullyConnected(sym.Flatten(data), num_hidden=64, name="fc1")
    trunk = sym.Activation(trunk, act_type="relu")
    logits = sym.FullyConnected(trunk, num_hidden=ACTIONS, name="policy")
    value = sym.FullyConnected(trunk, num_hidden=1, name="value")

    logp = sym.log_softmax(logits)
    onehot = sym.one_hot(act, depth=ACTIONS)
    denom = sym.sum(mask) + 1e-8
    pg_loss = -sym.sum(sym.sum(logp * onehot, axis=1) * adv * mask) / denom
    entropy = -sym.sum(sym.broadcast_mul(sym.exp(logp) * logp,
                                         sym.Reshape(mask, shape=(batch, 1)))) / denom
    v_err = sym.Reshape(value, shape=(batch,)) - ret
    v_loss = sym.sum(sym.square(v_err) * mask) / denom
    total = pg_loss + 0.5 * v_loss - 0.05 * entropy
    return sym.Group([sym.MakeLoss(total, name="loss"),
                      sym.BlockGrad(sym.softmax(logits), name="pi"),
                      sym.BlockGrad(value, name="v")])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=80)
    ap.add_argument("--envs", type=int, default=16)
    ap.add_argument("--horizon", type=int, default=30)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    envs = [GridWorld(np.random.RandomState(100 + i))
            for i in range(args.envs)]
    gamma = 0.95
    n_total = args.envs * args.horizon

    ctx = mx.context.default_accelerator_context()
    # two executors over shared weights: an acting one (batch = n_envs)
    # and a training one (batch = envs*horizon) — the reference a3c
    # similarly separates acting nets from the training update
    zeros_small = {"action": np.zeros(args.envs, np.float32),
                   "advantage": np.zeros(args.envs, np.float32),
                   "return_label": np.zeros(args.envs, np.float32),
                   "mask": np.zeros(args.envs, np.float32)}
    act_ex = ac_net(args.envs).simple_bind(
        ctx=ctx, grad_req="null", data=(args.envs, 2, GRID, GRID),
        action=(args.envs,), advantage=(args.envs,),
        return_label=(args.envs,), mask=(args.envs,))
    train_ex = ac_net(n_total).simple_bind(
        ctx=ctx, grad_req="write", data=(n_total, 2, GRID, GRID),
        action=(n_total,), advantage=(n_total,), return_label=(n_total,),
        mask=(n_total,))
    init = mx.init.Xavier()
    params = {n: a for n, a in train_ex.arg_dict.items()
              if n.endswith(("weight", "bias"))}
    for n, a in params.items():
        init(n, a)
    opt = mx.optimizer.create("adam", learning_rate=3e-3)
    upd = mx.optimizer.get_updater(opt)

    finish_hist = []
    for it in range(args.iters):
        for n, a in params.items():
            act_ex.arg_dict[n][:] = a.asnumpy()
        states = np.stack([e.reset() for e in envs])
        obs = np.zeros((args.horizon, args.envs, 2, GRID, GRID), np.float32)
        acts = np.zeros((args.horizon, args.envs), np.float32)
        rews = np.zeros((args.horizon, args.envs), np.float32)
        alive = np.ones((args.horizon, args.envs), np.float32)
        done = np.zeros(args.envs, bool)
        steps_used = np.full(args.envs, args.horizon, np.float32)
        for t in range(args.horizon):
            act_ex.forward(is_train=False, data=states, **zeros_small)
            pi = act_ex.outputs[1].asnumpy()
            obs[t] = states
            alive[t] = ~done
            for i, env in enumerate(envs):
                if done[i]:
                    continue
                p = pi[i] / pi[i].sum()
                a = int(rs.choice(ACTIONS, p=p))
                s2, r, d = env.step(a)
                acts[t, i] = a
                rews[t, i] = r
                states[i] = s2
                if d:
                    done[i] = True
                    steps_used[i] = t + 1
        finish_hist.append(steps_used.mean())

        # bootstrapped returns per env (value of the final state if alive)
        act_ex.forward(is_train=False, data=states, **zeros_small)
        v_last = act_ex.outputs[2].asnumpy().reshape(-1)
        returns = np.zeros_like(rews)
        acc = np.where(done, 0.0, v_last)
        for t in reversed(range(args.horizon)):
            acc = rews[t] + gamma * acc * alive[t]
            returns[t] = acc

        flat = lambda a: a.reshape(n_total, *a.shape[2:])  # noqa: E731
        data = flat(obs)
        mask = flat(alive)
        train_ex.forward(is_train=False, data=data,
                         action=flat(acts), advantage=np.zeros(n_total, np.float32),
                         return_label=flat(returns), mask=mask)
        values = train_ex.outputs[2].asnumpy().reshape(-1)
        adv = (flat(returns) - values) * mask
        # normalize advantages over real samples (standard A2C stabilizer)
        m = mask > 0
        if m.any():
            adv[m] = (adv[m] - adv[m].mean()) / (adv[m].std() + 1e-6)
        train_ex.forward(is_train=True, data=data, action=flat(acts),
                         advantage=adv, return_label=flat(returns),
                         mask=mask)
        train_ex.backward()
        for i, (nname, arr) in enumerate(sorted(params.items())):
            upd(i, train_ex.grad_dict[nname], arr)
        if it % 20 == 19:
            print(f"iter {it}: mean steps-to-goal {np.mean(finish_hist[-10:]):.1f}")

    early = np.mean(finish_hist[:10])
    late = np.mean(finish_hist[-10:])
    print(f"mean steps: first10 {early:.1f} last10 {late:.1f}")
    assert late < early * 0.75, (early, late)
    print("TRAIN OK")


if __name__ == "__main__":
    main()
