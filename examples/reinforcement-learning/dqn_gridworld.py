#!/usr/bin/env python
"""DQN (parity: example/reinforcement-learning/dqn/): Q-learning with an
experience-replay buffer and a frozen target network, the reference's
Atari recipe scaled to a self-contained grid world (agent walks a 5x5
grid to the goal; reward 1 at goal, -0.02 per step).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

GRID, ACTIONS = 5, 4  # up/down/left/right


class GridWorld:
    def __init__(self, rs):
        self.rs = rs
        self.goal = (GRID - 1, GRID - 1)
        self.reset()

    def reset(self):
        # random start (not the goal): denser reward signal early on
        while True:
            self.pos = (int(self.rs.randint(GRID)), int(self.rs.randint(GRID)))
            if self.pos != self.goal:
                break
        return self.obs()

    def obs(self):
        o = np.zeros((2, GRID, GRID), np.float32)
        o[0][self.pos] = 1.0
        o[1][self.goal] = 1.0
        return o

    def step(self, a):
        dr = [(-1, 0), (1, 0), (0, -1), (0, 1)][a]
        r, c = self.pos
        self.pos = (min(max(r + dr[0], 0), GRID - 1),
                    min(max(c + dr[1], 0), GRID - 1))
        done = self.pos == self.goal
        return self.obs(), (1.0 if done else -0.02), done


def q_net():
    data = sym.Variable("data")
    target = sym.Variable("target")     # (N, ACTIONS) regression target
    net = sym.FullyConnected(sym.Flatten(data), num_hidden=64, name="fc1")
    net = sym.Activation(net, act_type="relu")
    q = sym.FullyConnected(net, num_hidden=ACTIONS, name="qvals")
    return sym.LinearRegressionOutput(q, target, name="q")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=200)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    env = GridWorld(rs)
    gamma, eps = 0.95, 1.0

    ctx = mx.context.default_accelerator_context()
    net = q_net()
    ex = net.simple_bind(ctx=ctx, grad_req="write",
                         data=(args.batch, 2, GRID, GRID),
                         target=(args.batch, ACTIONS))
    one = net.simple_bind(ctx=ctx, grad_req="null",
                          data=(1, 2, GRID, GRID), target=(1, ACTIONS))
    init = mx.init.Xavier()
    # master (online) weights live OUTSIDE the executor: the executor's
    # arg arrays get reloaded with target-net weights during Q(s')
    # evaluation, so aliasing them as the online copy would wipe training
    params = {}
    for n, a in ex.arg_dict.items():
        if n.endswith(("weight", "bias")):
            init(n, a)
            params[n] = mx.nd.array(a.asnumpy())
    target_params = {n: a.asnumpy() for n, a in params.items()}
    opt = mx.optimizer.create("adam", learning_rate=1e-3)
    updater = mx.optimizer.get_updater(opt)

    replay = []
    steps_hist = []
    zeros1 = np.zeros((1, ACTIONS), np.float32)
    for ep in range(args.episodes):
        s = env.reset()
        total_steps = 0
        # online weights change once per episode (after the updates below)
        for n, arr in params.items():
            one.arg_dict[n][:] = arr.asnumpy()
        for _ in range(40):
            if rs.rand() < eps:
                a = rs.randint(ACTIONS)
            else:
                one.forward(is_train=False, data=s[None], target=zeros1)
                a = int(one.outputs[0].asnumpy()[0].argmax())
            s2, r, done = env.step(a)
            replay.append((s, a, r, s2, done))
            if len(replay) > 2000:
                replay.pop(0)
            s = s2
            total_steps += 1
            if done:
                break
        steps_hist.append(total_steps)
        eps = max(0.05, eps * 0.985)

        # several training batches per episode from replay
        for _upd in range(4 if len(replay) >= args.batch else 0):
            idx = rs.choice(len(replay), args.batch, replace=False)
            bs = np.stack([replay[i][0] for i in idx])
            bs2 = np.stack([replay[i][3] for i in idx])
            # target net Q(s')
            for n, arr in params.items():
                ex.arg_dict[n][:] = target_params[n]
            ex.forward(is_train=False, data=bs2,
                       target=np.zeros((args.batch, ACTIONS), np.float32))
            qn = ex.outputs[0].asnumpy()
            # current Q(s) for target construction (online weights)
            for n, arr in params.items():
                ex.arg_dict[n][:] = arr.asnumpy()
            ex.forward(is_train=False, data=bs,
                       target=np.zeros((args.batch, ACTIONS), np.float32))
            tgt = np.array(ex.outputs[0].asnumpy())
            for j, i in enumerate(idx):
                _, a, r, _, done = replay[i]
                tgt[j, a] = r if done else r + gamma * qn[j].max()
            ex.forward(is_train=True, data=bs, target=tgt)
            ex.backward()
            for i, (n, arr) in enumerate(sorted(params.items())):
                updater(i, ex.grad_dict[n], arr)
        if ep % 10 == 9:
            target_params = {n: a.asnumpy() for n, a in params.items()}
        if ep % 50 == 49:
            print(f"ep {ep}: steps-to-goal (last 20 avg) "
                  f"{np.mean(steps_hist[-20:]):.1f} eps {eps:.2f}")

    early = np.mean(steps_hist[:20])
    late = np.mean(steps_hist[-20:])
    print(f"avg steps: first20 {early:.1f} last20 {late:.1f}")
    assert late < early * 0.6, (early, late)
    print("TRAIN OK")


if __name__ == "__main__":
    main()
