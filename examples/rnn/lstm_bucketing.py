#!/usr/bin/env python
"""LSTM language model with bucketing (parity: example/rnn/
lstm_bucketing.py — PTB next-word prediction).

Variable-length sentences are binned into buckets; BucketingModule keeps
one executor per bucket sharing parameters.  On TPU each bucket is one
jit cache entry (SURVEY.md §5.7: the reference's shared memory pool
becomes the compile cache), so the bucket list should stay short.

Uses the PTB text at ``data/ptb.train.txt`` when present; otherwise a
synthetic corpus with Zipf-distributed tokens and sentence lengths."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.models.lstm import lstm_unroll  # noqa: E402


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    """Parity: lstm_bucketing.py tokenize_text."""
    with open(fname) as f:
        lines = f.read().splitlines()
    sentences = [line.split() for line in lines if line.strip()]
    if vocab is None:
        vocab = {}
    out = []
    for words in sentences:
        ids = []
        for w in words:
            if w not in vocab:
                vocab[w] = len(vocab) + start_label
            ids.append(vocab[w])
        out.append(ids)
    return out, vocab


def synthetic_corpus(num_sentences, vocab_size, seed=3):
    rs = np.random.RandomState(seed)
    ranks = np.arange(1, vocab_size + 1)
    probs = (1.0 / ranks) / (1.0 / ranks).sum()  # Zipf
    sentences = []
    for _ in range(num_sentences):
        length = int(rs.randint(5, 33))
        # ids offset +1: 0 is the padding/ignore label (like the PTB
        # path's start_label=1)
        ids = rs.choice(vocab_size, size=length, p=probs) + 1
        sentences.append(ids.tolist())
    return sentences


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description="LSTM bucketing LM")
    ap.add_argument("--num-hidden", type=int, default=200)
    ap.add_argument("--num-embed", type=int, default=200)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--buckets", type=str, default="8,16,24,32")
    ap.add_argument("--vocab-size", type=int, default=2000)
    ap.add_argument("--num-sentences", type=int, default=2000)
    ap.add_argument("--no-compile-sharing", action="store_true",
                    help="bind one XLA executable per bucket (the naive "
                         "path) instead of padding to the largest bucket")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    ptb = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "ptb.train.txt")
    if os.path.exists(ptb):
        sentences, vocab = tokenize_text(ptb, start_label=1)
        vocab_size = len(vocab) + 1
    else:
        sentences = synthetic_corpus(args.num_sentences, args.vocab_size - 2)
        vocab_size = args.vocab_size

    buckets = [int(b) for b in args.buckets.split(",")]
    # init LSTM states are fed through the iterator as zero arrays (the
    # v0.9 bucketing pattern); BucketSentenceIter produces next-token
    # labels (shift-by-one) itself
    init_states = []
    for layer in range(args.num_layers):
        init_states += [(f"l{layer}_init_c", (args.batch_size, args.num_hidden)),
                        (f"l{layer}_init_h", (args.batch_size, args.num_hidden))]
    train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                      buckets=buckets, invalid_label=0,
                                      init_states=init_states)

    def sym_gen(seq_len):
        # ignore_label=0 masks padding out of loss AND gradient — this is
        # what makes compile-bucket padding exact, and also fixes the
        # within-bucket padding the reference example silently trains on
        symbol = lstm_unroll(args.num_layers, seq_len, vocab_size,
                             args.num_hidden, args.num_embed, vocab_size,
                             dropout=0.2, ignore_label=0)
        data_names = ("data",) + tuple(n for n, _ in init_states)
        return symbol, data_names, ("softmax_label",)

    # compile sharing: all buckets pad to the default (largest) bucket and
    # run through ONE compiled fwd+bwd — seconds of XLA compile per bucket
    # collapse to a single compile (docs/how_to/bucketing.md)
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=train.default_bucket_key,
                                 compile_buckets=not args.no_compile_sharing)
    mod.fit(train,
            eval_metric=mx.metric.Perplexity(ignore_label=0),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-5},
            initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
