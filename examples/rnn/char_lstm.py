#!/usr/bin/env python
"""Character-level LSTM language model + sampling (parity:
example/rnn/old/char-rnn.ipynb / lstm.py — the classic char-rnn).

Trains a stacked-LSTM next-character model on a text file (or a built-in
synthetic grammar when no file is given), then samples new text one
character at a time with a single-step executor — demonstrating train
graph / step graph weight sharing.

Usage::

    python char_lstm.py --text /path/to/corpus.txt --num-epochs 5
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def build_cell(num_layers, num_hidden, dropout):
    stack = mx.rnn.SequentialRNNCell()
    for i in range(num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden, prefix=f"lstm_l{i}_"))
        if dropout > 0 and i < num_layers - 1:
            stack.add(mx.rnn.DropoutCell(dropout, prefix=f"drop_l{i}_"))
    return stack


def state_vars(num_layers):
    """Init-state symbols fed through the data iterator (the v0.9 idiom);
    LSTMCell state order is [h, c]."""
    syms, names = [], []
    for i in range(num_layers):
        for tag in ("h", "c"):
            name = f"l{i}_init_{tag}"
            syms.append(mx.sym.Variable(name))
            names.append(name)
    return syms, names


def train_symbol(cell, begin_state, seq_len, vocab_size, num_embed,
                 num_hidden):
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data, input_dim=vocab_size,
                             output_dim=num_embed, name="embed")
    outputs, _ = cell.unroll(seq_len, inputs=embed, begin_state=begin_state,
                             merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))  # (N*T, H)
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
    label = mx.sym.Reshape(mx.sym.Variable("softmax_label"), shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label, name="softmax")


def step_symbol(cell, begin_state, vocab_size, num_embed):
    """One-character step graph sharing weights with the train graph."""
    data = mx.sym.Variable("data")  # (1, 1)
    embed = mx.sym.Embedding(data, input_dim=vocab_size,
                             output_dim=num_embed, name="embed")
    outputs, states = cell.unroll(1, inputs=embed, begin_state=begin_state,
                                  merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(0, -1))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
    return mx.sym.SoftmaxActivation(pred, name="prob"), states


def synthetic_text(n=20000, seed=0):
    """ab-alternating grammar with spaces — enough structure to learn."""
    rs = np.random.RandomState(seed)
    words, out = ["aba", "abba", "baab", "bab"], []
    while sum(len(w) + 1 for w in out) < n:
        out.append(words[rs.randint(len(words))])
    return " ".join(out)


def main():
    ap = argparse.ArgumentParser(description="char-rnn")
    ap.add_argument("--text", type=str, default=None)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=128)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--dropout", type=float, default=0.0)
    ap.add_argument("--sample-len", type=int, default=120)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    text = (open(args.text).read() if args.text else synthetic_text())
    chars = sorted(set(text))
    vocab = {c: i for i, c in enumerate(chars)}
    inv_vocab = {i: c for c, i in vocab.items()}
    ids = np.array([vocab[c] for c in text], dtype=np.float32)
    logging.info("corpus: %d chars, vocab %d", len(ids), len(vocab))

    # slice the stream into (batch, seq_len) windows; labels are shift-by-1
    n_win = (len(ids) - 1) // args.seq_len
    data = ids[:n_win * args.seq_len].reshape(n_win, args.seq_len)
    label = ids[1:n_win * args.seq_len + 1].reshape(n_win, args.seq_len)
    state_arrays = {
        f"l{i}_init_{tag}": np.zeros((n_win, args.num_hidden), np.float32)
        for i in range(args.num_layers) for tag in ("h", "c")}
    train = mx.io.NDArrayIter({"data": data, **state_arrays}, label,
                              args.batch_size, shuffle=True,
                              label_name="softmax_label")

    cell = build_cell(args.num_layers, args.num_hidden, args.dropout)
    states, state_names = state_vars(args.num_layers)
    net = train_symbol(cell, states, args.seq_len, len(vocab),
                       args.num_embed, args.num_hidden)
    mod = mx.mod.Module(net, data_names=["data"] + state_names)
    mod.fit(train,
            eval_metric=mx.metric.Perplexity(ignore_label=None),
            optimizer="adam",
            optimizer_params={"learning_rate": 0.003},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))

    # ---- sampling: 1-step executor fed by its own output ----------------
    step_cell = build_cell(args.num_layers, args.num_hidden, 0.0)
    step_states, state_names = state_vars(args.num_layers)
    prob_sym, state_syms = step_symbol(step_cell, step_states, len(vocab),
                                       args.num_embed)
    group = mx.sym.Group([prob_sym] + list(state_syms))
    arg_params, _ = mod.get_params()
    shapes = {"data": (1, 1)}
    for name in state_names:
        shapes[name] = (1, args.num_hidden)
    sampler = group.simple_bind(ctx=mx.current_context(), **shapes)
    for name, arr in arg_params.items():
        if name in sampler.arg_dict:
            sampler.arg_dict[name][:] = arr

    rs = np.random.RandomState(7)
    cur = rs.randint(len(vocab))
    out_chars = [inv_vocab[cur]]
    for _ in range(args.sample_len):
        sampler.arg_dict["data"][:] = np.array([[cur]], dtype=np.float32)
        sampler.forward(is_train=False)
        p = sampler.outputs[0].asnumpy().ravel()
        cur = int(rs.choice(len(vocab), p=p / p.sum()))
        out_chars.append(inv_vocab[cur])
        for name, out in zip(state_names, sampler.outputs[1:]):
            sampler.arg_dict[name][:] = out.asnumpy()
    print("sample:", "".join(out_chars))


if __name__ == "__main__":
    main()
