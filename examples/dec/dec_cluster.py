#!/usr/bin/env python
"""Deep Embedded Clustering (parity: example/dec/dec.py, Xie et al. 2016).

Stage 1: pretrain an autoencoder on the data.  Stage 2: k-means in the
embedding initializes cluster centroids; then the encoder is refined by
matching the soft assignment q (Student-t kernel to centroids) to the
sharpened target p = q^2 / freq, with KL(p||q) gradients flowing into
both encoder and centroids.  The reference hand-codes dL/dz; here the
loss is expressed symbolically and autodiff does the rest.  Synthetic
Gaussian blobs stand in for MNIST; clustering accuracy must improve over
the k-means initialization.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

DIM, EMBED, K = 20, 2, 3


def encoder_sym():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="enc1")
    net = sym.Activation(net, act_type="relu")
    return sym.FullyConnected(net, num_hidden=EMBED, name="enc2")


def autoencoder_sym():
    z = encoder_sym()
    net = sym.FullyConnected(z, num_hidden=32, name="dec1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=DIM, name="dec2")
    return sym.LinearRegressionOutput(net, sym.Variable("rec_label"),
                                      name="rec")


def dec_sym(batch):
    """KL(p||q) with q = Student-t soft assignment to centroid variables."""
    z = encoder_sym()                            # (N, EMBED)
    mu = sym.Variable("centroids")               # (K, EMBED)
    p = sym.Variable("p_target")                 # (N, K)
    zz = sym.Reshape(z, shape=(batch, 1, EMBED))
    diff = sym.broadcast_sub(zz, sym.Reshape(mu, shape=(1, K, EMBED)))
    dist2 = sym.sum(diff * diff, axis=2)         # (N, K)
    qu = 1.0 / (1.0 + dist2)
    q = sym.broadcast_div(qu, sym.sum(qu, axis=1, keepdims=True))
    kl = sym.sum(p * (sym.log(p + 1e-8) - sym.log(q + 1e-8))) / batch
    return sym.MakeLoss(kl, name="kl"), q


def kmeans(z, k, rs, iters=20):
    mu = z[rs.choice(len(z), k, replace=False)]
    for _ in range(iters):
        d = ((z[:, None] - mu[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for j in range(k):
            if (a == j).any():
                mu[j] = z[a == j].mean(0)
    return mu, a


def cluster_acc(assign, y, k):
    # best-match accuracy over label permutations (hungarian-lite: greedy)
    acc = 0
    for j in range(k):
        if (assign == j).any():
            acc += np.bincount(y[assign == j].astype(int),
                               minlength=k).max()
    return acc / len(y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=600)
    args = ap.parse_args()
    rs = np.random.RandomState(0)

    # blobs in DIM-d space
    centers = rs.randn(K, DIM) * 2.0
    y = rs.randint(0, K, args.n)
    x = (centers[y] + rs.randn(args.n, DIM) * 0.9).astype(np.float32)

    ctx = mx.context.default_accelerator_context()
    # ---- stage 1: autoencoder pretrain
    mod = mx.mod.Module(autoencoder_sym(), data_names=("data",),
                        label_names=("rec_label",), context=ctx)
    it = mx.io.NDArrayIter({"data": x}, {"rec_label": x}, batch_size=60,
                           shuffle=True)
    mod.fit(it, num_epoch=30, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3},
            initializer=mx.init.Xavier(), eval_metric="rmse")

    # ---- embed + k-means init
    args_p, _ = mod.get_params()
    feat = mx.mod.Module(sym.Group([encoder_sym()]), data_names=("data",),
                         label_names=(), context=ctx)
    feat.bind([("data", (args.n, DIM))], None, for_training=False)
    feat.set_params({k_: v for k_, v in args_p.items() if "enc" in k_}, {})
    feat.forward(mx.io.DataBatch([mx.nd.array(x)], None), is_train=False)
    z0 = feat.get_outputs()[0].asnumpy()
    mu, assign0 = kmeans(z0.copy(), K, rs)
    acc0 = cluster_acc(assign0, y, K)
    print(f"k-means init acc {acc0:.3f}")

    # ---- stage 2: DEC refinement
    loss, _ = dec_sym(args.n)
    ex = loss.simple_bind(ctx=ctx, grad_req="write", data=(args.n, DIM),
                          centroids=(K, EMBED), p_target=(args.n, K))
    for k_, v in args_p.items():
        if "enc" in k_:
            ex.arg_dict[k_][:] = v.asnumpy()
    ex.arg_dict["centroids"][:] = mu
    trainable = {k_: ex.arg_dict[k_] for k_ in ex.arg_dict
                 if "enc" in k_ or k_ == "centroids"}
    opt = mx.optimizer.create("adam", learning_rate=2e-3)
    upd = mx.optimizer.get_updater(opt)

    for it_ in range(40):
        # soft assignment q from the current encoder/centroids (host side)
        feat.set_params({k_: mx.nd.array(ex.arg_dict[k_].asnumpy())
                         for k_ in ex.arg_dict if "enc" in k_}, {},
                        allow_missing=True)
        feat.forward(mx.io.DataBatch([mx.nd.array(x)], None), is_train=False)
        z = feat.get_outputs()[0].asnumpy()
        d2 = ((z[:, None] - ex.arg_dict["centroids"].asnumpy()[None]) ** 2).sum(-1)
        qu = 1.0 / (1.0 + d2)
        q = qu / qu.sum(1, keepdims=True)
        f = q.sum(0)
        p = (q ** 2 / f) / (q ** 2 / f).sum(1, keepdims=True)
        ex.forward(is_train=True, data=x, p_target=p)
        ex.backward()
        for i, (k_, arr) in enumerate(sorted(trainable.items())):
            upd(i, ex.grad_dict[k_], arr)

    d2 = ((z[:, None] - ex.arg_dict["centroids"].asnumpy()[None]) ** 2).sum(-1)
    acc1 = cluster_acc(d2.argmin(1), y, K)
    print(f"DEC refined acc {acc1:.3f}")
    assert acc1 >= acc0 - 0.02, (acc0, acc1)
    print("DEC OK")


if __name__ == "__main__":
    main()
