#!/usr/bin/env python
"""Deep Embedded Clustering (parity: example/dec/dec.py, Xie et al. 2016
— the reference's dec.py imports example/autoencoder/ for its
pretraining stage; this file does the same against our
examples/autoencoder system).

Stage 1: pretrain a stacked autoencoder (AutoEncoderModel: greedy
layerwise + finetune through the Solver).  Stage 2: k-means in the
bottleneck embedding initializes cluster centroids; then the encoder is
refined by matching the soft assignment q (Student-t kernel to
centroids) to the sharpened target p = q^2 / freq, with KL(p||q)
gradients flowing into both encoder and centroids.  The reference
hand-codes dL/dz; here the loss is expressed symbolically and autodiff
does the rest.  Synthetic Gaussian blobs stand in for MNIST; clustering
accuracy must improve over the k-means initialization.
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))
sys.path.insert(0, os.path.join(_HERE, "..", "autoencoder"))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

from autoencoder import AutoEncoderModel  # noqa: E402

DIM, EMBED, K = 20, 2, 3
DIMS = [DIM, 32, EMBED]


def encoder_sym():
    """Same topology/param names as AutoEncoderModel(DIMS)._encoder_sym:
    enc0 -> relu -> enc1 (bottleneck, linear)."""
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=DIMS[1], name="enc0")
    net = sym.Activation(net, act_type="relu")
    return sym.FullyConnected(net, num_hidden=EMBED, name="enc1")


def dec_sym(batch):
    """KL(p||q) with q = Student-t soft assignment to centroid variables."""
    z = encoder_sym()                            # (N, EMBED)
    mu = sym.Variable("centroids")               # (K, EMBED)
    p = sym.Variable("p_target")                 # (N, K)
    zz = sym.Reshape(z, shape=(batch, 1, EMBED))
    diff = sym.broadcast_sub(zz, sym.Reshape(mu, shape=(1, K, EMBED)))
    dist2 = sym.sum(diff * diff, axis=2)         # (N, K)
    qu = 1.0 / (1.0 + dist2)
    q = sym.broadcast_div(qu, sym.sum(qu, axis=1, keepdims=True))
    kl = sym.sum(p * (sym.log(p + 1e-8) - sym.log(q + 1e-8))) / batch
    return sym.MakeLoss(kl, name="kl"), q


def kmeans(z, k, rs, iters=20):
    mu = z[rs.choice(len(z), k, replace=False)]
    for _ in range(iters):
        d = ((z[:, None] - mu[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for j in range(k):
            if (a == j).any():
                mu[j] = z[a == j].mean(0)
    return mu, a


def cluster_acc(assign, y, k):
    # best-match accuracy over label permutations (hungarian-lite: greedy)
    acc = 0
    for j in range(k):
        if (assign == j).any():
            acc += np.bincount(y[assign == j].astype(int),
                               minlength=k).max()
    return acc / len(y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=600)
    ap.add_argument("--pretrain-epochs", type=int, default=8)
    ap.add_argument("--finetune-epochs", type=int, default=22)
    args = ap.parse_args()
    rs = np.random.RandomState(0)
    mx.random.seed(0)

    # blobs in DIM-d space
    centers = rs.randn(K, DIM) * 2.0
    y = rs.randint(0, K, args.n)
    x = (centers[y] + rs.randn(args.n, DIM) * 1.5).astype(np.float32)

    # ---- stage 1: stacked-AE pretraining through the shared system
    model = AutoEncoderModel(DIMS, corruption=0.0)
    model.layerwise_pretrain(x, batch_size=60,
                             epochs=args.pretrain_epochs, lr=2e-3)
    model.finetune(x, batch_size=60, epochs=args.finetune_epochs, lr=2e-3)

    # ---- embed + k-means init
    z0 = model.encode(x)
    mu, assign0 = kmeans(z0.copy(), K, rs)
    acc0 = cluster_acc(assign0, y, K)
    print(f"k-means init acc {acc0:.3f}")

    # ---- stage 2: DEC refinement
    ctx = mx.context.default_accelerator_context()
    loss, _ = dec_sym(args.n)
    ex = loss.simple_bind(ctx=ctx, grad_req="write", data=(args.n, DIM),
                          centroids=(K, EMBED), p_target=(args.n, K))
    for k_, arr in model.args.items():
        if k_ in ex.arg_dict:
            ex.arg_dict[k_][:] = arr
    ex.arg_dict["centroids"][:] = mu
    trainable = {k_: ex.arg_dict[k_] for k_ in ex.arg_dict
                 if "enc" in k_ or k_ == "centroids"}
    opt = mx.optimizer.create("adam", learning_rate=2e-3)
    upd = mx.optimizer.get_updater(opt)

    z = z0
    for it_ in range(40):
        # soft assignment q from the current encoder/centroids (host side)
        for k_ in model.args:
            if k_ in ex.arg_dict and "enc" in k_:
                model.args[k_][:] = ex.arg_dict[k_]
        z = model.encode(x)
        d2 = ((z[:, None] - ex.arg_dict["centroids"].asnumpy()[None]) ** 2).sum(-1)
        qu = 1.0 / (1.0 + d2)
        q = qu / qu.sum(1, keepdims=True)
        f = q.sum(0)
        p = (q ** 2 / f) / (q ** 2 / f).sum(1, keepdims=True)
        ex.forward(is_train=True, data=x, p_target=p)
        ex.backward()
        for i, (k_, arr) in enumerate(sorted(trainable.items())):
            upd(i, ex.grad_dict[k_], arr)

    d2 = ((z[:, None] - ex.arg_dict["centroids"].asnumpy()[None]) ** 2).sum(-1)
    acc1 = cluster_acc(d2.argmin(1), y, K)
    print(f"DEC refined acc {acc1:.3f}")
    assert acc1 >= acc0 - 0.02, (acc0, acc1)
    print("DEC OK")


if __name__ == "__main__":
    main()
