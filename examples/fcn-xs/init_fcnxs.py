"""Stage-wise FCN initialization (parity: example/fcn-xs/init_fcnxs.py
— the reference carries every weight of the coarser stage forward,
zero-fills the NEW score heads (background dominates, so zero output is
the right prior), and fills every NEW Deconvolution with a frozen-shape
bilinear interpolation kernel).
"""
import numpy as np

import mxnet_tpu as mx


def upsample_filt(size):
    """Bilinear interpolation kernel of side `size` (init_fcnxs.py:11-19
    — the standard tent filter every FCN implementation shares)."""
    factor = (size + 1) // 2
    center = factor - 1.0 if size % 2 == 1 else factor - 0.5
    og = np.ogrid[:size, :size]
    return ((1 - abs(og[0] - center) / factor)
            * (1 - abs(og[1] - center) / factor))


def _bilinear_weight(shape):
    """(C, C, k, k) deconv weight applying per-channel bilinear
    upsampling: diagonal channels get the tent filter."""
    w = np.zeros(shape, np.float32)
    filt = upsample_filt(shape[3])
    for c in range(min(shape[0], shape[1])):
        w[c, c] = filt
    return w


def init_from_fcnxs(symbol, args_from, auxs_from, data_shape):
    """Build the finer stage's (args, auxs) from the coarser stage's:
    shared names carry over, new `score_pool*` heads start at zero, new
    deconv weights start bilinear (init_fcnxs.py:47-89's
    rest_params/deconv_params split, driven by name here instead of a
    per-stage hardcoded list)."""
    arg_names = symbol.list_arguments()
    arg_shapes, _, aux_shapes = symbol.infer_shape(data=data_shape)
    shapes = dict(zip(arg_names, arg_shapes))
    args = {}
    for name in arg_names:
        if name in ("data", "softmax_label"):
            continue
        if name in args_from and tuple(args_from[name].shape) == tuple(
                shapes[name]):
            args[name] = args_from[name].copy()
        elif name.endswith("_weight") and (
                name.startswith("bigscore") or name.startswith("score2")
                or name.startswith("score4")):
            args[name] = mx.nd.array(_bilinear_weight(shapes[name]))
        else:  # new score head (score_poolN_*): zero prior
            args[name] = mx.nd.zeros(shapes[name])
    auxs = {k: v.copy() for k, v in auxs_from.items()}
    for name, shape in zip(symbol.list_auxiliary_states(), aux_shapes):
        if name not in auxs:
            auxs[name] = mx.nd.zeros(shape)
    return args, auxs


def init_fcn32s(symbol, data_shape, seed=0):
    """From-scratch fcn32s init: Xavier trunk, zero score, bilinear
    deconv (the reference's init_from_vgg16 with the trunk replaced by
    fresh Xavier, since there is no downloaded VGG here)."""
    arg_names = symbol.list_arguments()
    arg_shapes, _, aux_shapes = symbol.infer_shape(data=data_shape)
    init = mx.init.Xavier(magnitude=2.0)
    mx.random.seed(seed)
    args = {}
    for name, shape in zip(arg_names, arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shape)
        if name.startswith("bigscore") and name.endswith("_weight"):
            arr = mx.nd.array(_bilinear_weight(shape))
        else:
            # the reference zero-inits score heads because its trunk is
            # PRETRAINED VGG (zero logits on good features escape the
            # background optimum fast); from a random trunk that sits at
            # the all-background floor, so the from-scratch stage gets
            # Xavier score heads — zero-init stays the rule for the
            # stage-wise transfers (init_from_fcnxs), matching the
            # reference where it matters
            init(name, arr)
        args[name] = arr
    auxs = {name: mx.nd.zeros(shape) for name, shape in
            zip(symbol.list_auxiliary_states(), aux_shapes)}
    return args, auxs
