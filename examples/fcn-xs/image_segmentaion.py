#!/usr/bin/env python
"""Segmentation inference demo (parity:
example/fcn-xs/image_segmentaion.py — the reference loads the trained
FCN checkpoint, forwards one image, argmaxes the score map into a
palette PNG).

Loads the fcn8s checkpoint fcn_xs.py saved (trains a quick one if
absent), forwards a fresh batch, reports per-class IoU, and writes the
predicted masks as .npy (no image codecs needed).
"""
import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

import data  # noqa: E402


def iou(pred, truth, cls):
    p, t = pred == cls, truth == cls
    inter, union = (p & t).sum(), (p | t).sum()
    return inter / union if union else float("nan")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--work", default="/tmp/fcnxs")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--min-mean-iou", type=float, default=0.45)
    args = ap.parse_args()
    prefix = os.path.join(args.work, "fcn8s")
    if not os.path.exists(prefix + "-symbol.json"):
        subprocess.run([sys.executable,
                        os.path.join(os.path.dirname(
                            os.path.abspath(__file__)), "fcn_xs.py"),
                        "--work", args.work], check=True)
    net, arg, aux = mx.model.load_checkpoint(prefix, 1)
    mod = mx.mod.Module(net, context=mx.context.default_accelerator_context())
    mod.bind(data_shapes=[("data", (args.batch, 3, data.IM, data.IM))],
             label_shapes=[("softmax_label",
                            (args.batch, data.IM * data.IM))],
             for_training=False)
    mod.set_params(arg, aux)
    rs = np.random.RandomState(7)
    x, y = data.render(rs, args.batch)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)],
                                [mx.nd.array(np.zeros_like(y))]),
                is_train=False)
    scores = mod.get_outputs()[0].asnumpy()           # (N, C, H*W)
    pred = scores.argmax(1).reshape(args.batch, data.IM, data.IM)
    truth = y.reshape(args.batch, data.IM, data.IM)
    ious = [iou(pred, truth, c) for c in range(data.NCLS)]
    mean_iou = float(np.nanmean(ious))
    print("per-class IoU:", [round(v, 3) for v in ious],
          "mean:", round(mean_iou, 3))
    np.save(os.path.join(args.work, "masks.npy"), pred)
    assert mean_iou >= args.min_mean_iou, ious
    print("SEG OK")


if __name__ == "__main__":
    main()
