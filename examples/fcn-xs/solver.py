"""Training solver (parity: example/fcn-xs/solver.py — the reference
wraps the Module-style loop in a Solver class holding symbol + initial
params, with SGD, an epoch callback, and a custom eval metric).

Adds the piece the reference solver leaves implicit: a per-pixel
accuracy EvalMetric (multi_output softmax emits (N, C, H*W)).
"""
import logging

import numpy as np

import mxnet_tpu as mx


class PixelAccuracy(mx.metric.EvalMetric):
    """Fraction of pixels whose argmax class matches the label."""

    def __init__(self):
        super().__init__("pixel-acc")

    def update(self, labels, preds):
        y = labels[0].asnumpy()            # (N, H*W)
        p = preds[0].asnumpy().argmax(1)   # (N, H*W)
        self.sum_metric += float((p == y).mean()) * y.shape[0]
        self.num_inst += y.shape[0]


class Solver:
    def __init__(self, symbol, args, auxs, ctx=None, lr=0.5, momentum=0.9):
        self.symbol = symbol
        self.args = args
        self.auxs = auxs
        self.ctx = ctx or mx.context.default_accelerator_context()
        self.lr = lr
        self.momentum = momentum

    def fit(self, train_iter, epochs=2, log=None):
        log = log or logging.getLogger("fcn-xs")
        batch = train_iter.provide_data[0][1][0]
        mod = mx.mod.Module(self.symbol, context=self.ctx)
        mod.bind(data_shapes=train_iter.provide_data,
                 label_shapes=train_iter.provide_label)
        # no init_params first: set_params on the freshly-bound module
        # keeps allow_missing=False meaningful (a name init_fcnxs missed
        # must fail loudly, not fall back to leftover random values)
        mod.set_params(self.args, self.auxs, allow_missing=False)
        mod.init_optimizer(optimizer="sgd", optimizer_params={
            "learning_rate": self.lr, "momentum": self.momentum,
            "rescale_grad": 1.0 / batch})
        metric = PixelAccuracy()
        acc = None
        for epoch in range(epochs):
            train_iter.reset()
            metric.reset()
            for b in train_iter:
                mod.forward(b, is_train=True)
                mod.update_metric(metric, b.label)
                mod.backward()
                mod.update()
            acc = metric.get()[1]
            log.info("epoch %d: pixel-acc %.3f", epoch, acc)
        self.args, self.auxs = mod.get_params()
        return acc
