"""Segmentation data iterator (parity: example/fcn-xs/data.py — the
reference's FileIter subclasses mx.io.DataIter to stream (image, pixel
label) pairs with provide_data/provide_label shapes).

Same DataIter contract here over a synthetic shape corpus (this image
cannot download PASCAL VOC): each sample composites a square (class 1)
and a disk (class 2) onto noise, the label is the per-pixel class map
flattened to (H*W,) for multi_output SoftmaxOutput.
"""
import numpy as np

import mxnet_tpu as mx

IM = 32
NCLS = 3


def render(rs, n, im=IM):
    x = rs.rand(n, 3, im, im).astype(np.float32) * 0.2
    y = np.zeros((n, im, im), np.float32)
    yy, xx = np.mgrid[0:im, 0:im]
    for i in range(n):
        s = rs.randint(6, 12)
        x0, y0 = rs.randint(0, im - s, 2)
        x[i, 0, y0:y0 + s, x0:x0 + s] += 0.8
        y[i, y0:y0 + s, x0:x0 + s] = 1
        r = rs.randint(4, 7)
        cx, cy = rs.randint(r, im - r, 2)
        mask = (xx - cx) ** 2 + (yy - cy) ** 2 <= r * r
        x[i, 1][mask] += 0.8
        y[i][mask] = 2
    return np.clip(x, 0, 1), y.reshape(n, -1)


class ShapeSegIter(mx.io.DataIter):
    """FileIter-shaped iterator: fixed epoch of `num_batches` batches,
    reset() re-seeds to the epoch start so every epoch sees the same
    corpus (deterministic convergence assertions)."""

    def __init__(self, batch_size=8, num_batches=24, seed=0, im=IM):
        super().__init__()
        self.batch_size = batch_size
        self.num_batches = num_batches
        self.seed = seed
        self.im = im
        self._cursor = 0
        self._rs = np.random.RandomState(seed)

    @property
    def provide_data(self):
        return [("data", (self.batch_size, 3, self.im, self.im))]

    @property
    def provide_label(self):
        return [("softmax_label", (self.batch_size, self.im * self.im))]

    def reset(self):
        self._cursor = 0
        self._rs = np.random.RandomState(self.seed)

    def next(self):
        if self._cursor >= self.num_batches:
            raise StopIteration
        self._cursor += 1
        x, y = render(self._rs, self.batch_size, self.im)
        return mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(y)],
                               pad=0, index=None,
                               provide_data=self.provide_data,
                               provide_label=self.provide_label)
