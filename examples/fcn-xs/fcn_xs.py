#!/usr/bin/env python
"""FCN-xs semantic segmentation (parity: example/fcn-xs/).

The reference fine-tunes VGG into FCN-32s/16s/8s: 1x1 "score" convs on
intermediate feature maps, Deconvolution (bilinear-initialized) upsampling,
Crop to input size, and skip fusion (fcn_xs.py + symbol_fcnxs.py).  This
runs the same FCN-8s-shaped topology at toy scale on synthetic shape
masks, trained with per-pixel multi_output SoftmaxOutput.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402

IM, NCLS = 32, 3  # background, square, disk


def build():
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")  # (N, H*W)
    c1 = sym.Activation(sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                                        num_filter=16, name="conv1"),
                        act_type="relu")
    p1 = sym.Pooling(c1, kernel=(2, 2), stride=(2, 2), pool_type="max")  # /2
    c2 = sym.Activation(sym.Convolution(p1, kernel=(3, 3), pad=(1, 1),
                                        num_filter=32, name="conv2"),
                        act_type="relu")
    p2 = sym.Pooling(c2, kernel=(2, 2), stride=(2, 2), pool_type="max")  # /4
    c3 = sym.Activation(sym.Convolution(p2, kernel=(3, 3), pad=(1, 1),
                                        num_filter=64, name="conv3"),
                        act_type="relu")
    p3 = sym.Pooling(c3, kernel=(2, 2), stride=(2, 2), pool_type="max")  # /8

    # score heads (1x1 convs) at /8 and /4, like score_fr + score_pool4
    score8 = sym.Convolution(p3, kernel=(1, 1), num_filter=NCLS,
                             name="score8")
    up4 = sym.Deconvolution(score8, kernel=(2, 2), stride=(2, 2),
                            num_filter=NCLS, no_bias=True, name="up4")  # /4
    score4 = sym.Convolution(p2, kernel=(1, 1), num_filter=NCLS,
                             name="score4")
    fuse = up4 + score4
    up1 = sym.Deconvolution(fuse, kernel=(4, 4), stride=(4, 4),
                            num_filter=NCLS, no_bias=True, name="up1")  # /1
    flat = sym.Reshape(up1, shape=(0, NCLS, -1), name="score_flat")
    return sym.SoftmaxOutput(flat, label, multi_output=True,
                             normalization="valid", name="softmax")


def synth(rs, n):
    x = rs.rand(n, 3, IM, IM).astype(np.float32) * 0.2
    y = np.zeros((n, IM, IM), np.float32)
    yy, xx = np.mgrid[0:IM, 0:IM]
    for i in range(n):
        # a square of class 1
        s = rs.randint(6, 12)
        x0, y0 = rs.randint(0, IM - s, 2)
        x[i, 0, y0:y0 + s, x0:x0 + s] += 0.8
        y[i, y0:y0 + s, x0:x0 + s] = 1
        # a disk of class 2
        r = rs.randint(4, 7)
        cx, cy = rs.randint(r, IM - r, 2)
        mask = (xx - cx) ** 2 + (yy - cy) ** 2 <= r * r
        x[i, 1][mask] += 0.8
        y[i][mask] = 2
    return np.clip(x, 0, 1), y.reshape(n, -1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()
    rs = np.random.RandomState(0)

    mod = mx.mod.Module(build(), context=mx.context.default_accelerator_context())
    mod.bind([("data", (args.batch, 3, IM, IM))],
             [("softmax_label", (args.batch, IM * IM))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5, "momentum": 0.9,
                                         "rescale_grad": 1.0 / args.batch})
    first = last = None
    for step in range(args.steps):
        x, y = synth(rs, args.batch)
        batch = mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(y)])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        p = mod.get_outputs()[0].asnumpy()  # (N, NCLS, H*W)
        picked = np.take_along_axis(p, y[:, None, :].astype(int), 1)[:, 0]
        loss = -np.log(np.maximum(picked, 1e-8)).mean()
        if step == 0:
            first = loss
        last = loss
        if step % 10 == 0:
            acc = (p.argmax(1) == y).mean()
            print(f"step {step}: pixel loss {loss:.4f} acc {acc:.3f}")
    print(f"first {first:.4f} last {last:.4f}")
    assert last < first * 0.9
    print("TRAIN OK")


if __name__ == "__main__":
    main()
