#!/usr/bin/env python
"""FCN-xs stage-wise training driver (parity: example/fcn-xs/fcn_xs.py
+ run_fcnxs.sh — the reference trains fcn32s from VGG, then fcn16s from
the fcn32s checkpoint, then fcn8s from fcn16s, each stage initialized
by init_fcnxs.py and solved by solver.py).

Same three-stage ladder at toy scale on the synthetic shape corpus:
every stage must not regress the previous stage's pixel accuracy, and
the final fcn8s must clear an absolute floor.  Saves a Module-format
checkpoint per stage (image_segmentaion.py loads the last one).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

import data  # noqa: E402
import init_fcnxs  # noqa: E402
import solver  # noqa: E402
import symbol_fcnxs  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--batches-per-epoch", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3,
                    help="epochs per TRANSFER stage (16s/8s start trained)")
    ap.add_argument("--epochs32", type=int, default=8,
                    help="epochs for the from-scratch fcn32s stage (it "
                         "spends ~4 epochs escaping the all-background "
                         "optimum before segmenting)")
    ap.add_argument("--work", default="/tmp/fcnxs")
    ap.add_argument("--min-final-acc", type=float, default=0.85)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger("fcn-xs")
    os.makedirs(args.work, exist_ok=True)
    shape = (args.batch, 3, data.IM, data.IM)

    accs = {}
    prev_args = prev_auxs = None
    for stage in ("fcn32s", "fcn16s", "fcn8s"):
        net = symbol_fcnxs.get_symbol(stage)
        if prev_args is None:
            st_args, st_auxs = init_fcnxs.init_fcn32s(net, shape)
        else:
            st_args, st_auxs = init_fcnxs.init_from_fcnxs(
                net, prev_args, prev_auxs, shape)
            # the mechanism under test: every shared name must carry the
            # previous stage's trained values forward bit-exactly
            carried = [k for k in st_args if k in prev_args
                       and st_args[k].shape == prev_args[k].shape]
            assert len(carried) >= 8, carried
            for k in carried:
                np.testing.assert_array_equal(
                    st_args[k].asnumpy(), prev_args[k].asnumpy(),
                    err_msg=f"stage init dropped {k}")
        sv = solver.Solver(net, st_args, st_auxs)
        it = data.ShapeSegIter(batch_size=args.batch,
                               num_batches=args.batches_per_epoch)
        epochs = args.epochs32 if stage == "fcn32s" else args.epochs
        accs[stage] = sv.fit(it, epochs=epochs, log=log)
        prev_args, prev_auxs = sv.args, sv.auxs
        mx.model.save_checkpoint(os.path.join(args.work, stage), 1,
                                 net, prev_args, prev_auxs)
        log.info("%s done: pixel-acc %.3f", stage, accs[stage])

    log.info("stage ladder: %s", {k: round(v, 3) for k, v in accs.items()})
    # each stage must beat the trivial all-background predictor (the
    # corpus is ~0.85 background, so 0.846 == predicting nothing), the
    # ladder must not regress, and the finest stage must clear the floor
    assert accs["fcn32s"] > 0.87, accs
    assert accs["fcn16s"] >= accs["fcn32s"] - 0.02, accs
    assert accs["fcn8s"] >= accs["fcn16s"] - 0.02, accs
    assert accs["fcn8s"] >= args.min_final_acc, accs
    print("FCNXS OK")


if __name__ == "__main__":
    main()
