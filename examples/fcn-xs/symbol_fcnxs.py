"""FCN-32s/16s/8s symbols (parity: example/fcn-xs/symbol_fcnxs.py —
the reference builds three segmentation heads over one VGG trunk:
1x1 "score" convs, stride-f Deconvolution upsampling with kernel 2f,
Crop back to the input geometry, and elementwise skip fusion).

Toy-scale trunk here (three conv/pool stages instead of VGG16), same
head topology and the same stage-naming contract init_fcnxs.py keys on:
each finer stage ADDS `score_poolN` + one deconv, so stage-wise
initialization can carry every coarser weight forward.
"""
import sys

from mxnet_tpu import sym

NCLS = 3  # background, square, disk (data.py)


def _trunk(data):
    """Shared feature trunk: /2, /4, /8 pyramid (stands in for VGG16's
    pool3/pool4/pool5 in symbol_fcnxs.py:14-96)."""
    h = data
    pools = {}
    for i, nf in ((1, 16), (2, 32), (3, 64)):
        h = sym.Activation(sym.Convolution(h, kernel=(3, 3), pad=(1, 1),
                                           num_filter=nf, name=f"conv{i}"),
                           act_type="relu")
        h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max",
                        name=f"pool{i}")
        pools[i] = h
    return pools


def _upscore(score, factor, name):
    """Stride-f bilinear-shaped upsampling head: Deconvolution with
    kernel 2f (the shape upsample_filt() fills), followed by Crop to the
    reference geometry (symbol_fcnxs.py:150-160 bigscore + crop)."""
    return sym.Deconvolution(score, kernel=(2 * factor, 2 * factor),
                             stride=(factor, factor),
                             pad=(factor // 2, factor // 2),
                             num_filter=NCLS, no_bias=True, name=name)


def _head(up, data, label):
    crop = sym.Crop(up, data, num_args=2, name="crop_final")
    flat = sym.Reshape(crop, shape=(0, NCLS, -1), name="score_flat")
    return sym.SoftmaxOutput(flat, label, multi_output=True,
                             normalization="valid", name="softmax")


def get_fcn32s():
    """Coarsest head: score at /8, one x8 upsample (fcn32s in
    symbol_fcnxs.py:99-117)."""
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    pools = _trunk(data)
    score = sym.Convolution(pools[3], kernel=(1, 1), num_filter=NCLS,
                            name="score")
    up = _upscore(score, 8, "bigscore")
    return _head(up, data, label)


def get_fcn16s():
    """Adds score_pool2 (/4) skip: score x2 up, fuse, x4 up
    (fcn16s in symbol_fcnxs.py:119-143)."""
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    pools = _trunk(data)
    score = sym.Convolution(pools[3], kernel=(1, 1), num_filter=NCLS,
                            name="score")
    score2 = _upscore(score, 2, "score2")          # /8 -> /4
    skip4 = sym.Convolution(pools[2], kernel=(1, 1), num_filter=NCLS,
                            name="score_pool4")
    fuse = sym.Crop(score2, skip4, num_args=2, name="crop_pool4") + skip4
    up = _upscore(fuse, 4, "bigscore")
    return _head(up, data, label)


def get_fcn8s():
    """Adds score_pool3 (/2) skip on top of fcn16s: one more x2 stage
    (fcn8s in symbol_fcnxs.py:145-189)."""
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    pools = _trunk(data)
    score = sym.Convolution(pools[3], kernel=(1, 1), num_filter=NCLS,
                            name="score")
    score2 = _upscore(score, 2, "score2")          # /8 -> /4
    skip4 = sym.Convolution(pools[2], kernel=(1, 1), num_filter=NCLS,
                            name="score_pool4")
    fuse4 = sym.Crop(score2, skip4, num_args=2, name="crop_pool4") + skip4
    score4 = _upscore(fuse4, 2, "score4")          # /4 -> /2
    skip3 = sym.Convolution(pools[1], kernel=(1, 1), num_filter=NCLS,
                            name="score_pool3")
    fuse3 = sym.Crop(score4, skip3, num_args=2, name="crop_pool3") + skip3
    up = _upscore(fuse3, 2, "bigscore")
    return _head(up, data, label)


def get_symbol(stage):
    try:
        return {"fcn32s": get_fcn32s, "fcn16s": get_fcn16s,
                "fcn8s": get_fcn8s}[stage]()
    except KeyError:
        sys.exit(f"unknown stage {stage!r} (fcn32s|fcn16s|fcn8s)")
