"""Module/training tests (parity model: tests/python/unittest/test_module.py
+ tests/python/train/test_mlp.py — the end-to-end convergence gate)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import get_synthetic_mnist


def _mlp_sym(num_hidden=32, num_classes=10):
    data = sym.Variable("data")
    net = sym.Flatten(data)
    net = sym.FullyConnected(net, name="fc1", num_hidden=num_hidden)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=num_classes)
    return sym.SoftmaxOutput(net, name="softmax")


def _make_iters(batch_size=64):
    (xtr, ytr), (xte, yte) = get_synthetic_mnist(512, 128)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=batch_size, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=batch_size)
    return train, val


def test_module_train_mlp_converges():
    # parity: tests/python/train/test_mlp.py accuracy gate
    train, val = _make_iters()
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.5),), num_epoch=4)
    assert mod.score(val, "acc")[0][1] > 0.9


def test_module_predict_and_outputs():
    train, val = _make_iters()
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.5),), num_epoch=2)
    preds = mod.predict(val)
    assert preds.shape == (128, 10)
    np.testing.assert_allclose(preds.asnumpy().sum(axis=1), np.ones(128), rtol=1e-4)


def test_module_checkpoint_roundtrip(tmp_path):
    train, val = _make_iters()
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.5),), num_epoch=2)
    acc_before = mod.score(val, "acc")[0][1]
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 2)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0002.params")

    mod2 = mx.mod.Module.load(prefix, 2, context=mx.cpu())
    mod2.bind(data_shapes=val.provide_data, label_shapes=val.provide_label,
              for_training=False)
    mod2.set_params(*mod2._arg_params and (mod2._arg_params, mod2._aux_params))
    acc_after = mod2.score(val, "acc")[0][1]
    assert abs(acc_before - acc_after) < 1e-6


def test_module_multi_device_data_parallel():
    # parity: multi-device training on cpu contexts
    train, val = _make_iters(batch_size=64)
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(i) for i in range(4)])
    mod.fit(train, optimizer="sgd", kvstore="device",
            optimizer_params=(("learning_rate", 0.5),), num_epoch=3)
    assert mod.score(val, "acc")[0][1] > 0.9


def test_module_optimizers_run():
    for optname in ["sgd", "adam", "rmsprop", "adagrad", "adadelta", "nag"]:
        train, _ = _make_iters()
        mod = mx.mod.Module(_mlp_sym(16), context=mx.cpu())
        mod.fit(train, optimizer=optname,
                optimizer_params=(("learning_rate", 0.05),), num_epoch=1)


def test_feedforward_api():
    (xtr, ytr), (xte, yte) = get_synthetic_mnist(512, 64)
    model = mx.model.FeedForward(_mlp_sym(), ctx=mx.cpu(), num_epoch=5,
                                 learning_rate=0.5, numpy_batch_size=64)
    model.fit(xtr, ytr)
    acc = model.score(xte, yte)
    assert acc > 0.9
    preds = model.predict(xte)
    assert preds.shape == (64, 10)


def test_optimizer_updates_match_reference_math():
    # SGD: w -= lr*(rescale*grad + wd*w)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, wd=0.01, rescale_grad=1.0)
    w = nd.array([1.0, 2.0])
    g = nd.array([0.5, 0.5])
    state = opt.create_state(0, w)
    opt.update(0, w, g, state)
    expect = np.array([1.0, 2.0]) - 0.1 * (np.array([0.5, 0.5]) + 0.01 * np.array([1.0, 2.0]))
    np.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-6)

    # momentum
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    w = nd.array([1.0])
    state = opt.create_state(0, w)
    opt.update(0, w, nd.array([1.0]), state)
    np.testing.assert_allclose(w.asnumpy(), [0.9], rtol=1e-6)
    opt.update(0, w, nd.array([1.0]), state)
    # mom = 0.9*(-0.1) - 0.1 = -0.19; w = 0.9 - 0.19 = 0.71
    np.testing.assert_allclose(w.asnumpy(), [0.71], rtol=1e-5)


def test_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    assert sched(5) == 1.0
    assert sched(11) == 0.5
    msched = mx.lr_scheduler.MultiFactorScheduler(step=[5, 10], factor=0.1)
    msched.base_lr = 1.0
    assert msched(2) == 1.0
    assert abs(msched(7) - 0.1) < 1e-9
    assert abs(msched(12) - 0.01) < 1e-9


def test_metrics():
    pred = nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = nd.array([1, 0, 0])
    m = mx.metric.create("acc")
    m.update([label], [pred])
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6
    m2 = mx.metric.create("mse")
    m2.update([nd.array([[1.0], [2.0]])], [nd.array([[1.5], [2.0]])])
    assert abs(m2.get()[1] - 0.125) < 1e-6
    m3 = mx.metric.CompositeEvalMetric(metrics=[mx.metric.Accuracy(), mx.metric.CrossEntropy()])
    m3.update([label], [pred])
    names, vals = m3.get()


def test_initializers():
    for init in [mx.init.Uniform(0.1), mx.init.Normal(0.1),
                 mx.init.Xavier(), mx.init.Orthogonal(), mx.init.MSRAPrelu()]:
        arr = nd.zeros((8, 8))
        init("test_weight", arr)
        assert np.abs(arr.asnumpy()).sum() > 0
    arr = nd.zeros((4,))
    mx.init.Uniform()("test_bias", arr)
    assert (arr.asnumpy() == 0).all()
    arr = nd.zeros((4,))
    mx.init.Uniform()("bn_gamma", arr)
    assert (arr.asnumpy() == 1).all()


def test_mixed_initializer():
    init = mx.init.Mixed([".*bias", ".*"], [mx.init.Zero(), mx.init.One()])
    w = nd.zeros((3,))
    init("fc_weight", w)
    assert (w.asnumpy() == 1).all()
    b = nd.ones((3,))
    init("fc_bias", b)
    assert (b.asnumpy() == 0).all()


def test_ndarray_iter():
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it.reset()
    batches2 = list(it)
    assert len(batches2) == 3
    it2 = mx.io.NDArrayIter(x, y, batch_size=4, last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_resize_iter():
    x = np.zeros((8, 2), dtype=np.float32)
    it = mx.io.ResizeIter(mx.io.NDArrayIter(x, batch_size=4), size=5)
    assert len(list(it)) == 5


def test_prefetching_iter():
    x = np.random.rand(16, 3).astype(np.float32)
    y = np.arange(16, dtype=np.float32)
    base = mx.io.NDArrayIter(x, y, batch_size=4)
    pre = mx.io.PrefetchingIter(base)
    n = 0
    for batch in pre:
        assert batch.data[0].shape == (4, 3)
        n += 1
    assert n == 4


def test_kvstore_local_math():
    # parity: tests/python/unittest/test_kvstore.py
    kv = mx.kv.create("local")
    shape = (4, 4)
    kv.init(3, nd.ones(shape))
    out = nd.zeros(shape)
    kv.push(3, [nd.ones(shape)] * 4)
    kv.pull(3, out=out)
    # aggregation-only: store now holds sum of pushes
    np.testing.assert_allclose(out.asnumpy(), 4 * np.ones(shape))


def test_kvstore_device_merge_balanced_and_device_side():
    """'device' kvstore parity with CommDevice (src/kvstore/comm.h:200-360):
    per-key merge buffers are load-balanced across the pushed copies'
    devices, the reduction and in-store value live on that device, and
    every push/pull is async dispatch (no global barrier — each key's
    reduction overlaps the caller's remaining work by construction)."""
    kv = mx.kv.create("device")
    shape = (8, 8)
    devices = [mx.cpu(i) for i in range(4)]
    for k in range(8):
        kv.init(k, nd.zeros(shape))
    for k in range(8):
        kv.push(k, [nd.ones(shape, ctx=c) for c in devices], priority=-k)
    # keys spread across all four devices (InitMergeBuffer parity)
    assert len({repr(c) for c in kv._merge_ctx.values()}) == 4
    for k in range(8):
        out = nd.zeros(shape)
        kv.pull(k, out=out)
        np.testing.assert_allclose(out.asnumpy(), 4 * np.ones(shape))
        # the stored value is resident on the key's merge device
        assert kv._store[k].context == kv._merge_ctx[k]


def test_module_device_kvstore_matches_single_device():
    """update_on_kvstore via kv('device') on 4 devices reproduces
    single-device training numerically (VERDICT r1 item 3)."""
    def run(ctx, kvstore):
        mx.random.seed(0)
        np.random.seed(0)
        train, _ = _make_iters(batch_size=64)
        mod = mx.mod.Module(_mlp_sym(8), context=ctx)
        mod.fit(train, optimizer="sgd", kvstore=kvstore,
                optimizer_params=(("learning_rate", 0.1),), num_epoch=1)
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    single = run(mx.cpu(0), None)
    multi = run([mx.cpu(i) for i in range(4)], "device")
    for k in single:
        np.testing.assert_allclose(single[k], multi[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_kvstore_with_updater():
    kv = mx.kv.create("device")
    kv.set_optimizer(mx.optimizer.create("test"))
    shape = (2, 2)
    kv.init(0, nd.zeros(shape))
    for _ in range(3):
        kv.push(0, [nd.ones(shape), nd.ones(shape)])
    out = nd.zeros(shape)
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), 6 * np.ones(shape))


def test_monitor():
    train, _ = _make_iters()
    mod = mx.mod.Module(_mlp_sym(8), context=mx.cpu())
    mod.bind(data_shapes=train.provide_data, label_shapes=train.provide_label)
    mon = mx.Monitor(1, pattern=".*fc1.*")
    mod.install_monitor(mon)
    mod.init_params()
    batch = next(train)
    mon.tic()
    mod.forward(batch, is_train=False)
    res = mon.toc()
    assert any("fc1" in r[1] for r in res)


def test_uneven_batch_warns_and_uses_divisor_devices(caplog):
    """batch % n_devices != 0 must not silently drop to one device: the
    group uses the largest dividing count and warns (VERDICT weak #7;
    reference parity: _split_input_slice handled uneven workloads)."""
    import logging

    from mxnet_tpu.module.executor_group import DataParallelExecutorGroup

    ctxs = [mx.context.cpu(i) for i in range(4)]  # batch 6 % 4 != 0
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(sym.FullyConnected(data, num_hidden=4, name="fc"),
                            sym.Variable("softmax_label"), name="softmax")
    with caplog.at_level(logging.WARNING):
        grp = DataParallelExecutorGroup(
            net, ctxs, None, [("data", (6, 8))], [("softmax_label", (6,))],
            param_names=["fc_weight", "fc_bias"], for_training=True,
            inputs_need_grad=False)
    assert "not divisible" in caplog.text
    assert len(grp.mesh.devices.ravel()) == 3  # largest divisor of 6 <= 4


def test_shared_module_params_track_donor_updates():
    """Reference parity (module.py:346-349 + the shared memory pool):
    a module bound with shared_module SHARES parameter storage — donor
    updates are visible through the sharee WITHOUT any re-sync call
    (bucketing and train-then-serve sharing rely on this)."""
    net = sym.LinearRegressionOutput(
        sym.Flatten(sym.FullyConnected(sym.Variable("data"), num_hidden=1,
                                       name="f")),
        sym.Variable("y_label"), name="reg")
    mod = mx.mod.Module(net, data_names=("data",), label_names=("y_label",))
    mod.bind(data_shapes=[("data", (4, 1))],
             label_shapes=[("y_label", (4,))])
    mod.init_params(mx.init.Uniform(0.5))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    pred = mx.mod.Module(net, data_names=("data",),
                         label_names=("y_label",))
    pred.bind(data_shapes=[("data", (2, 1))],
              label_shapes=[("y_label", (2,))],
              for_training=False, shared_module=mod)

    x = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
    y = 2 * x[:, 0]
    outs = []
    for _ in range(3):
        mod.forward_backward(mx.io.DataBatch([nd.array(x)], [nd.array(y)]))
        mod.update()
        pred.forward(mx.io.DataBatch([nd.array(x[:2])], [nd.zeros(2)]),
                     is_train=False)
        w = mod.get_params()[0]["f_weight"].asnumpy().item()
        b = mod.get_params()[0]["f_bias"].asnumpy().item()
        got = pred.get_outputs()[0].asnumpy().ravel()
        np.testing.assert_allclose(got, w * x[:2, 0] + b, rtol=1e-5,
                                   atol=1e-5)
        outs.append(got.copy())
    assert not np.allclose(outs[0], outs[-1])  # it really moved


def test_module_load_then_bind_restores_params():
    """Module.load -> bind -> score must run with the CHECKPOINT's
    parameters: bind() on a params_initialized module pushes the held
    params into the fresh executors (parity: the reference's bind,
    module.py:276 — this exact flow is every deployment script's
    first three lines)."""
    import tempfile

    (xtr, ytr), _ = get_synthetic_mnist(256, 64)
    net = _mlp_sym()
    mod = mx.mod.Module(net)
    it = mx.io.NDArrayIter(xtr, ytr, batch_size=32, shuffle=True)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier())
    it.reset()
    ref = mod.score(it, "acc")[0][1]

    with tempfile.TemporaryDirectory() as d:
        prefix = d + "/m"
        mod.save_checkpoint(prefix, 0)
        loaded = mx.mod.Module.load(prefix, 0)
        loaded.bind(data_shapes=it.provide_data,
                    label_shapes=it.provide_label, for_training=False)
        it.reset()
        got = loaded.score(it, "acc")[0][1]
    assert abs(got - ref) < 1e-6, (got, ref)


def test_module_force_rebind_keeps_trained_params():
    """force_rebind after training must carry the TRAINED parameters
    into the fresh executors (bind syncs from devices before discarding
    them), e.g. re-binding to a new batch size for deployment."""
    (xtr, ytr), _ = get_synthetic_mnist(256, 64)
    net = _mlp_sym()
    mod = mx.mod.Module(net)
    it = mx.io.NDArrayIter(xtr, ytr, batch_size=32, shuffle=True)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier())
    it.reset()
    ref = mod.score(it, "acc")[0][1]
    assert ref > 0.8, ref

    big = mx.io.NDArrayIter(xtr, ytr, batch_size=64)
    mod.bind(data_shapes=big.provide_data,
             label_shapes=big.provide_label, for_training=False,
             force_rebind=True)
    got = mod.score(big, "acc")[0][1]
    assert abs(got - ref) < 0.02, (got, ref)
