"""End-to-end convergence gates (parity: tests/python/train/ —
test_mlp.py / test_conv.py / test_dtype.py train small nets and assert
accuracy thresholds)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.test_utils import get_synthetic_mnist


def _conv_sym(num_classes=10):
    data = sym.Variable("data")
    net = sym.Convolution(data, name="conv1", kernel=(3, 3), num_filter=8)
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, name="conv2", kernel=(3, 3), num_filter=16)
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.FullyConnected(sym.Flatten(net), name="fc", num_hidden=num_classes)
    return sym.SoftmaxOutput(net, name="softmax")


def test_conv_converges():
    (xtr, ytr), (xte, yte) = get_synthetic_mnist(2048, 512)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=64)
    mod = mx.mod.Module(_conv_sym())
    mod.fit(train, eval_data=val, num_epoch=2,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    assert mod.score(val, "acc")[0][1] > 0.9


def test_fused_trainer_bf16_converges():
    """Parity: test_dtype.py — training in reduced precision (bf16
    compute, fp32 master weights) must still hit the accuracy gate."""
    import jax.numpy as jnp

    from mxnet_tpu.trainer import FusedTrainer

    (xtr, ytr), (xte, yte) = get_synthetic_mnist(2048, 512)
    tr = FusedTrainer(_conv_sym(), optimizer="sgd",
                      optimizer_params={"lr": 0.1, "momentum": 0.9,
                                        "rescale_grad": 1.0 / 64},
                      initializer=mx.init.Xavier(),
                      dtype=jnp.bfloat16)
    tr.init(data=(64, 1, 28, 28))
    for epoch in range(2):
        for i in range(0, len(xtr), 64):
            tr.step(data=xtr[i:i + 64], softmax_label=ytr[i:i + 64])
    preds = []
    for i in range(0, len(xte), 64):
        outs = tr.eval(data=xte[i:i + 64])
        preds.append(np.asarray(outs[0]).argmax(axis=1))
    acc = float((np.concatenate(preds) == yte).mean())
    assert acc > 0.9, acc


def test_adam_and_schedulers_converge():
    (xtr, ytr), (xte, yte) = get_synthetic_mnist(1024, 256)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=64)
    data = sym.Variable("data")
    net = sym.FullyConnected(sym.Flatten(data), name="fc1", num_hidden=64)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=10)
    net = sym.SoftmaxOutput(net, name="softmax")
    sched = mx.lr_scheduler.FactorScheduler(step=20, factor=0.9)
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, num_epoch=3,
            initializer=mx.init.Xavier(),
            optimizer="adam",
            optimizer_params={"learning_rate": 1e-3, "lr_scheduler": sched})
    assert mod.score(val, "acc")[0][1] > 0.9


def test_fused_trainer_fixed_param_names():
    """Fixed params: unchanged by steps, no optimizer state, and the
    trainable subset still learns (Module fixed_param_names parity on the
    fused path)."""
    import jax.numpy as jnp

    from mxnet_tpu.trainer import FusedTrainer

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc1",
                                      num_hidden=16),
                act_type="relu"),
            name="fc2", num_hidden=4),
        name="softmax")
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.5},
                      fixed_param_names=["fc1_weight", "fc1_bias"])
    tr.init(data=(8, 10))
    frozen_w = np.asarray(tr.params["fc1_weight"]).copy()
    live_w = np.asarray(tr.params["fc2_weight"]).copy()
    assert "fc1_weight" not in tr.opt_state
    rs = np.random.RandomState(0)
    for _ in range(3):
        tr.step(data=rs.uniform(size=(8, 10)).astype(np.float32),
                softmax_label=rs.randint(0, 4, 8).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(tr.params["fc1_weight"]),
                                  frozen_w)
    assert not np.allclose(np.asarray(tr.params["fc2_weight"]), live_w)


def test_fused_trainer_bf16_cache_tracks_masters():
    """Mixed precision carries a DONATED bf16 compute copy updated
    inside the optimizer step; it must equal the f32 masters' bf16 cast
    after every step, and eval consumes it (same outputs as a fresh
    trainer loaded from the same masters)."""
    import jax.numpy as jnp

    from mxnet_tpu import sym
    from mxnet_tpu.trainer import FusedTrainer

    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=4, name="fc"), name="softmax")
    tr = FusedTrainer(net, optimizer="adam", optimizer_params={"lr": 0.05},
                      dtype=jnp.bfloat16)
    tr.init(data=(8, 6))
    rs = np.random.RandomState(3)
    for i in range(5):
        tr.step(data=rs.rand(8, 6).astype(np.float32),
                softmax_label=rs.randint(0, 4, 8).astype(np.float32))
    for k, master in tr.params.items():
        assert master.dtype == jnp.float32
        cached = tr._cparams[k]
        assert cached.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(cached, np.float32),
            np.asarray(master.astype(jnp.bfloat16), np.float32),
            err_msg=k)
    # eval reads the carried cache: its outputs must match a fresh
    # trainer whose cache was rebuilt from these same masters
    x = rs.rand(8, 6).astype(np.float32)
    out_live = np.asarray(tr.eval(data=x)[0])
    tr2 = FusedTrainer(net, optimizer="adam", optimizer_params={"lr": 0.05},
                       dtype=jnp.bfloat16)
    tr2.init(data=(8, 6))
    tr2.params = dict(tr.params)
    tr2.aux = dict(tr.aux)
    tr2._refresh_compute_cache()
    np.testing.assert_array_equal(out_live, np.asarray(tr2.eval(data=x)[0]))


def test_fused_trainer_rmsprop_matches_module():
    """FusedTrainer's rmsprop rule == the Module/optimizer path after
    identical steps (the same oracle discipline the sgd/adam rules
    carry)."""
    from mxnet_tpu import nd, sym
    from mxnet_tpu.trainer import FusedTrainer

    rs = np.random.RandomState(5)
    x = rs.rand(32, 6).astype(np.float32)
    y = rs.randint(0, 3, 32).astype(np.float32)
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=3, name="fc"), name="softmax")

    np.random.seed(0)
    mx.random.seed(0)
    tr = FusedTrainer(net, optimizer="rmsprop",
                      optimizer_params={"lr": 0.01, "gamma1": 0.9})
    tr.init(data=(32, 6))
    start = {k: np.asarray(v).copy() for k, v in tr.params.items()}
    for _ in range(4):
        tr.step(data=x, softmax_label=y)

    np.random.seed(0)
    mx.random.seed(0)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (32, 6))],
             label_shapes=[("softmax_label", (32,))])
    mod.init_params(arg_params={k: nd.array(v) for k, v in start.items()},
                    aux_params={})
    mod.init_optimizer(optimizer="rmsprop",
                       optimizer_params={"learning_rate": 0.01,
                                         "gamma1": 0.9})
    for _ in range(4):
        mod.forward_backward(mx.io.DataBatch([nd.array(x)], [nd.array(y)]))
        mod.update()
    want, _ = mod.get_params()
    for k, v in tr.params.items():
        np.testing.assert_allclose(np.asarray(v), want[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5, err_msg=k)

def test_step_multi_matches_sequential_steps():
    """step_multi(k stacked batches) must land on exactly the params that
    k sequential step() calls produce — same RNG folds, same lr
    schedule, same optimizer math — so the two are interchangeable
    mid-run (step_multi exists to amortize per-call dispatch latency,
    tools/probe_gap.py)."""
    import jax.numpy as jnp

    from mxnet_tpu.trainer import FusedTrainer

    (xtr, ytr), _ = get_synthetic_mnist(256, 16)
    k, b = 4, 32
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)

    def make():
        mx.random.seed(7)
        tr = FusedTrainer(_conv_sym(), optimizer="sgd",
                          optimizer_params={"lr": 0.1, "momentum": 0.9,
                                            "rescale_grad": 1.0 / b,
                                            "lr_scheduler": sched},
                          initializer=mx.init.Xavier(),
                          dtype=jnp.bfloat16)
        tr.init(data=(b, 1, 28, 28))
        return tr

    batches = [(xtr[i * b:(i + 1) * b], ytr[i * b:(i + 1) * b])
               for i in range(k)]

    seq = make()
    for x, y in batches:
        seq.step(data=x, softmax_label=y)

    multi = make()
    outs = multi.step_multi(
        data=np.stack([x for x, _ in batches]),
        softmax_label=np.stack([y for _, y in batches]))
    assert np.asarray(outs[0]).shape[0] == k
    assert multi._step == seq._step == k

    for name in seq.params:
        np.testing.assert_allclose(np.asarray(seq.params[name]),
                                   np.asarray(multi.params[name]),
                                   rtol=2e-5, atol=2e-5, err_msg=name)
    # and a further plain step() continues cleanly from the scanned state
    multi.step(data=batches[0][0], softmax_label=batches[0][1])
    seq.step(data=batches[0][0], softmax_label=batches[0][1])
    name = sorted(seq.params)[0]
    np.testing.assert_allclose(np.asarray(seq.params[name]),
                               np.asarray(multi.params[name]),
                               rtol=2e-5, atol=2e-5)


def test_hwio_storage_excludes_multi_consumer_weights():
    """A conv weight with ANY consumer besides NHWC convs must stay in
    logical OIHW storage: the second reader (an in-graph weight norm
    here) would silently misread transposed axes otherwise."""
    import jax.numpy as jnp

    from mxnet_tpu.trainer import FusedTrainer

    data = sym.Variable("data")
    w = sym.Variable("c_weight")
    net = sym.Convolution(data, weight=w, kernel=(3, 3), num_filter=4,
                          pad=(1, 1), name="c")
    plain = sym.Convolution(net, kernel=(3, 3), num_filter=4, pad=(1, 1),
                            name="c2")
    pooled = sym.Pooling(plain, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    head = sym.SoftmaxOutput(sym.FullyConnected(sym.Flatten(pooled),
                                                num_hidden=3),
                             name="softmax")
    # second consumer of c_weight: an L2 penalty folded into the outputs
    penalty = sym.sum(sym.square(w))
    grouped = sym.Group([head, penalty])
    tr = FusedTrainer(grouped, optimizer="sgd",
                      optimizer_params={"lr": 0.01})
    tr.init(data=(2, 3, 8, 8))
    assert "c_weight" not in tr._hwio       # tied second use -> OIHW
    assert "c2_weight" in tr._hwio          # single-consumer -> HWIO
    rs = np.random.RandomState(0)
    outs = tr.step(data=rs.rand(2, 3, 8, 8).astype(np.float32),
                   softmax_label=rs.randint(0, 3, 2).astype(np.float32))
    assert all(np.isfinite(np.asarray(o)).all() for o in outs)
    # stored layouts match the discovery decision
    assert tr.params["c_weight"].shape == (4, 3, 3, 3)
    assert tr.params["c2_weight"].shape == (3, 3, 4, 4)


def test_hwio_states_checkpoint_is_layout_portable(tmp_path, monkeypatch):
    """Optimizer-state files are logical OIHW on disk: a checkpoint
    saved by an HWIO-storage trainer must load into a trainer with
    MXTPU_HWIO_STORAGE=0 (and vice versa) with identical slot values."""
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    net = models.get_symbol("resnet-18", num_classes=10,
                            image_shape=(3, 16, 16))

    def make():
        t = FusedTrainer(net, optimizer="sgd",
                         optimizer_params={"lr": 0.1, "momentum": 0.9})
        return t.init(data=(2, 3, 16, 16))

    tr = make()
    assert tr._hwio  # HWIO storage active by default
    rs = np.random.RandomState(0)
    for _ in range(2):
        tr.step(data=rs.rand(2, 3, 16, 16).astype(np.float32),
                softmax_label=rs.randint(0, 10, 2).astype(np.float32))
    prefix = str(tmp_path / "ck")
    tr.save_checkpoint(prefix, 1, save_optimizer_states=True)

    monkeypatch.setenv("MXTPU_HWIO_STORAGE", "0")
    tr2 = make()
    assert not tr2._hwio
    tr2.load_checkpoint(prefix, 1, load_optimizer_states=True)
    name = sorted(tr._hwio)[0]
    # params: tr stores HWIO, tr2 stores OIHW — logically equal
    np.testing.assert_allclose(
        np.transpose(np.asarray(tr.params[name]), (3, 2, 0, 1)),
        np.asarray(tr2.params[name]), rtol=0, atol=0)
    # momentum slots likewise
    np.testing.assert_allclose(
        np.transpose(np.asarray(tr.opt_state[name][0]), (3, 2, 0, 1)),
        np.asarray(tr2.opt_state[name][0]), rtol=0, atol=0)
    # and tr2 keeps training without shape errors
    tr2.step(data=rs.rand(2, 3, 16, 16).astype(np.float32),
             softmax_label=rs.randint(0, 10, 2).astype(np.float32))
