"""Broad operator sweep: numeric-gradient and numpy-oracle checks across
op families (parity model: tests/python/unittest/test_operator.py — the
reference's largest test surface; same two verification tools,
check_numeric_gradient / check_symbolic_forward from test_utils).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.test_utils import (check_numeric_gradient,
                                  check_symbolic_forward)

RS = np.random.RandomState(7)


def _ng(net, loc, **kw):
    kw.setdefault("numeric_eps", 1e-3)
    kw.setdefault("rtol", 0.06)
    kw.setdefault("atol", 0.06)
    check_numeric_gradient(net, loc, **kw)


# ---------------------------------------------------------------- elemwise
@pytest.mark.parametrize("op,ref", [
    ("broadcast_add", np.add), ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply), ("broadcast_maximum", np.maximum),
    ("broadcast_minimum", np.minimum), ("broadcast_hypot", np.hypot),
])
def test_broadcast_binary_grad(op, ref):
    a = RS.uniform(0.5, 2.0, (3, 1, 4)).astype(np.float32)
    b = RS.uniform(0.5, 2.0, (1, 5, 4)).astype(np.float32)
    net = getattr(sym, op)(sym.Variable("a"), sym.Variable("b"))
    check_symbolic_forward(net, {"a": a, "b": b}, [ref(a, b)])
    _ng(net, {"a": a, "b": b})


def test_broadcast_div_power_grad():
    a = RS.uniform(1.0, 2.0, (2, 3)).astype(np.float32)
    b = RS.uniform(1.0, 2.0, (2, 1)).astype(np.float32)
    net = sym.broadcast_div(sym.Variable("a"), sym.Variable("b"))
    check_symbolic_forward(net, {"a": a, "b": b}, [a / b])
    _ng(net, {"a": a, "b": b})
    net = sym.broadcast_power(sym.Variable("a"), sym.Variable("b"))
    check_symbolic_forward(net, {"a": a, "b": b}, [a ** b])
    _ng(net, {"a": a, "b": b})


def test_smooth_l1_grad():
    x = RS.uniform(-3, 3, (4, 5)).astype(np.float32)
    net = sym.smooth_l1(sym.Variable("x"), scalar=1.0)
    expect = np.where(np.abs(x) < 1.0, 0.5 * x * x, np.abs(x) - 0.5)
    check_symbolic_forward(net, {"x": x}, [expect])
    _ng(net, {"x": x})


def test_clip_grad_zero_outside():
    x = np.array([[-2.0, -0.5, 0.5, 2.0]], np.float32)
    net = sym.clip(sym.Variable("x"), a_min=-1.0, a_max=1.0)
    check_symbolic_forward(net, {"x": x}, [np.clip(x, -1, 1)])
    ex = net.simple_bind(ctx=mx.cpu(), x=x.shape)
    ex.arg_dict["x"][:] = x
    ex.forward(is_train=True)
    ex.backward(mx.nd.ones(x.shape))
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               [[0.0, 1.0, 1.0, 0.0]])


# --------------------------------------------------------------- reductions
@pytest.mark.parametrize("op,ref,kw", [
    ("sum", np.sum, {}), ("mean", np.mean, {}),
    ("max", np.max, {}), ("min", np.min, {}),
    ("prod", np.prod, {}),
])
@pytest.mark.parametrize("axis", [None, 0, 1, (0, 2)])
def test_reduce_forward_grad(op, ref, kw, axis):
    x = RS.uniform(0.5, 1.5, (3, 4, 2)).astype(np.float32)
    args = {} if axis is None else {"axis": axis}
    net = getattr(sym, op)(sym.Variable("x"), **args)
    expect = ref(x, axis=axis)
    check_symbolic_forward(net, {"x": x}, [np.asarray(expect, np.float32)])
    if op in ("sum", "mean"):  # smooth everywhere
        _ng(net, {"x": x})


def test_norm_and_argmax_channel():
    x = RS.uniform(-1, 1, (3, 4)).astype(np.float32)
    check_symbolic_forward(sym.norm(sym.Variable("x")), {"x": x},
                           [np.array(np.sqrt((x ** 2).sum()), np.float32)],
                           rtol=1e-3)
    check_symbolic_forward(sym.argmax_channel(sym.Variable("x")), {"x": x},
                           [x.argmax(axis=1).astype(np.float32)])


# ------------------------------------------------------------ layout/shape
def test_pad_modes():
    x = RS.uniform(size=(1, 2, 3, 3)).astype(np.float32)
    net = sym.Pad(sym.Variable("x"), mode="constant",
                  pad_width=(0, 0, 0, 0, 1, 1, 2, 2), constant_value=0.5)
    expect = np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)), constant_values=0.5)
    check_symbolic_forward(net, {"x": x}, [expect])
    net = sym.Pad(sym.Variable("x"), mode="edge",
                  pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    expect = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)), mode="edge")
    check_symbolic_forward(net, {"x": x}, [expect])
    _ng(net, {"x": x})


def test_slice_channel_grads():
    x = RS.uniform(size=(2, 6, 2)).astype(np.float32)
    net = sym.Group(list(sym.SliceChannel(sym.Variable("x"), num_outputs=3,
                                          axis=1)))
    parts = np.split(x, 3, axis=1)
    check_symbolic_forward(net, {"x": x}, parts)
    _ng(net, {"x": x})


def test_swapaxis_flatten_expanddims():
    x = RS.uniform(size=(2, 3, 4)).astype(np.float32)
    check_symbolic_forward(sym.SwapAxis(sym.Variable("x"), dim1=0, dim2=2),
                           {"x": x}, [x.swapaxes(0, 2)])
    check_symbolic_forward(sym.Flatten(sym.Variable("x")), {"x": x},
                           [x.reshape(2, 12)])
    check_symbolic_forward(sym.expand_dims(sym.Variable("x"), axis=1),
                           {"x": x}, [x[:, None]])
    check_symbolic_forward(sym.flip(sym.Variable("x"), axis=2),
                           {"x": x}, [x[:, :, ::-1]])
    check_symbolic_forward(sym.repeat(sym.Variable("x"), repeats=2, axis=1),
                           {"x": x}, [np.repeat(x, 2, axis=1)])
    check_symbolic_forward(sym.tile(sym.Variable("x"), reps=(1, 2, 1)),
                           {"x": x}, [np.tile(x, (1, 2, 1))])


def test_crop_like_and_offset():
    x = RS.uniform(size=(1, 1, 6, 6)).astype(np.float32)
    net = sym.Crop(sym.Variable("x"), offset=(1, 2), h_w=(3, 3))
    check_symbolic_forward(net, {"x": x}, [x[:, :, 1:4, 2:5]])
    _ng(net, {"x": x})


# ------------------------------------------------------------- indexing/dot
def test_take_embedding_grads():
    w = RS.uniform(size=(7, 4)).astype(np.float32)
    idx = np.array([0, 3, 3, 6], np.float32)
    net = sym.take(sym.Variable("w"), sym.Variable("i"))
    check_symbolic_forward(net, {"w": w, "i": idx},
                           [w[idx.astype(int)]])
    ex = net.simple_bind(ctx=mx.cpu(), w=w.shape, i=idx.shape,
                         grad_req={"w": "write", "i": "null"})
    ex.arg_dict["w"][:] = w
    ex.arg_dict["i"][:] = idx
    ex.forward(is_train=True)
    ex.backward(mx.nd.ones((4, 4)))
    gw = ex.grad_dict["w"].asnumpy()
    assert gw[3].sum() == pytest.approx(8.0)  # row 3 taken twice
    assert gw[1].sum() == 0.0


def test_dot_batch_dot_grads():
    a = RS.uniform(size=(3, 4)).astype(np.float32)
    b = RS.uniform(size=(4, 5)).astype(np.float32)
    net = sym.dot(sym.Variable("a"), sym.Variable("b"))
    check_symbolic_forward(net, {"a": a, "b": b}, [a @ b])
    _ng(net, {"a": a, "b": b})
    ba = RS.uniform(size=(2, 3, 4)).astype(np.float32)
    bb = RS.uniform(size=(2, 4, 5)).astype(np.float32)
    net = sym.batch_dot(sym.Variable("a"), sym.Variable("b"))
    check_symbolic_forward(net, {"a": ba, "b": bb}, [ba @ bb])
    _ng(net, {"a": ba, "b": bb})


def test_onehot_and_pick():
    idx = np.array([1, 0, 2], np.float32)
    net = sym.one_hot(sym.Variable("i"), depth=4)
    check_symbolic_forward(net, {"i": idx}, [np.eye(4, dtype=np.float32)[
        idx.astype(int)]])


# ----------------------------------------------------------------- layers
def test_leaky_relu_variants():
    x = RS.uniform(-2, 2, (4, 6)).astype(np.float32)
    net = sym.LeakyReLU(sym.Variable("x"), act_type="leaky", slope=0.1)
    check_symbolic_forward(net, {"x": x},
                           [np.where(x > 0, x, 0.1 * x)])
    _ng(net, {"x": x})
    net = sym.LeakyReLU(sym.Variable("x"), act_type="elu", slope=0.3)
    check_symbolic_forward(net, {"x": x},
                           [np.where(x > 0, x, 0.3 * (np.exp(x) - 1))])
    # prelu carries a learned slope per channel
    net = sym.LeakyReLU(sym.Variable("x"), act_type="prelu", name="pr")
    ex = net.simple_bind(ctx=mx.cpu(), x=(4, 6))
    assert "pr_gamma" in ex.arg_dict
    _ng(net, {"x": x, "pr_gamma": np.full(6, 0.25, np.float32)})


def test_softmax_activation_channel_mode():
    x = RS.uniform(size=(2, 3, 4, 4)).astype(np.float32)
    net = sym.SoftmaxActivation(sym.Variable("x"), mode="channel")
    e = np.exp(x - x.max(axis=1, keepdims=True))
    check_symbolic_forward(net, {"x": x}, [e / e.sum(axis=1, keepdims=True)])


def test_upsampling_nearest():
    x = RS.uniform(size=(1, 2, 3, 3)).astype(np.float32)
    net = sym.UpSampling(sym.Variable("x"), scale=2, sample_type="nearest")
    expect = x.repeat(2, axis=2).repeat(2, axis=3)
    check_symbolic_forward(net, {"x": x}, [expect])
    _ng(net, {"x": x})


def test_svm_output_hinge_grad():
    # SVMOutput backward: reference svm_output-inl.h one-vs-all hinge;
    # sign=+1 at the true class, -1 elsewhere; L2-SVM default:
    # grad = -2*(margin - sign*x)*sign where margin violated
    x = np.array([[0.3, -0.2, 0.1]], np.float32)
    label = np.array([0.0], np.float32)

    def run(**kw):
        net = sym.SVMOutput(sym.Variable("x"), sym.Variable("label"),
                            margin=1.0, name="svm", **kw)
        ex = net.simple_bind(ctx=mx.cpu(), x=x.shape, label=(1,),
                             grad_req={"x": "write", "label": "null"})
        ex.arg_dict["x"][:] = x
        ex.arg_dict["label"][:] = label
        ex.forward(is_train=True)
        np.testing.assert_allclose(ex.outputs[0].asnumpy(), x)
        ex.backward()
        return ex.grad_dict["x"].asnumpy()

    # all three classes violate margin 1: true-class slack 0.7; others 0.8, 1.1
    np.testing.assert_allclose(run(), [[-1.4, 1.6, 2.2]], rtol=1e-5)
    # L1-SVM: constant-magnitude gradient on violators
    np.testing.assert_allclose(run(use_linear=True), [[-1.0, 1.0, 1.0]])


def test_make_loss_and_block_grad():
    x = RS.uniform(0.5, 1.5, (3,)).astype(np.float32)
    v = sym.Variable("x")
    net = sym.MakeLoss(sym.sum(v * v))
    ex = net.simple_bind(ctx=mx.cpu(), x=x.shape)
    ex.arg_dict["x"][:] = x
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), 2 * x, rtol=1e-5)

    net = sym.MakeLoss(sym.sum(sym.BlockGrad(v) * v))
    ex = net.simple_bind(ctx=mx.cpu(), x=x.shape)
    ex.arg_dict["x"][:] = x
    ex.forward(is_train=True)
    ex.backward()
    # BlockGrad stops one factor: d/dx (const * x) = const
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), x, rtol=1e-5)


def test_identity_attach_kl_sparse_reg():
    x = RS.uniform(0.01, 0.2, (4, 5)).astype(np.float32)
    net = sym.IdentityAttachKLSparseReg(sym.Variable("x"), sparseness_target=0.1,
                                        penalty=0.001)
    check_symbolic_forward(net, {"x": x}, [x])


# ---------------------------------------------------------------- sequence
def test_sequence_ops_with_lengths():
    x = RS.uniform(size=(4, 3, 2)).astype(np.float32)  # (T, N, C)
    lens = np.array([2, 4, 1], np.float32)
    net = sym.SequenceLast(sym.Variable("x"), sym.Variable("len"),
                           use_sequence_length=True)
    expect = np.stack([x[1, 0], x[3, 1], x[0, 2]])
    check_symbolic_forward(net, {"x": x, "len": lens}, [expect])

    net = sym.SequenceMask(sym.Variable("x"), sym.Variable("len"),
                           use_sequence_length=True, value=-1.0)
    expect = x.copy()
    expect[2:, 0] = -1.0
    expect[1:, 2] = -1.0
    check_symbolic_forward(net, {"x": x, "len": lens}, [expect])

    net = sym.SequenceReverse(sym.Variable("x"), sym.Variable("len"),
                              use_sequence_length=True)
    expect = x.copy()
    expect[:2, 0] = x[:2, 0][::-1]
    expect[:, 1] = x[:, 1][::-1]
    check_symbolic_forward(net, {"x": x, "len": lens}, [expect])


# ------------------------------------------------------------------ random
def test_sampling_ops_shapes_and_ranges():
    u = mx.nd.uniform(low=2.0, high=3.0, shape=(1000,))
    a = u.asnumpy()
    assert (a >= 2.0).all() and (a < 3.0).all()
    n = mx.nd.normal(loc=5.0, scale=0.1, shape=(2000,)).asnumpy()
    assert abs(n.mean() - 5.0) < 0.05


def test_batchnorm_stats_dtype_flag(monkeypatch):
    """MXTPU_BN_STATS_DTYPE=compute accumulates BN moments in the input
    dtype (the HBM-traffic A/B knob tools/probe_resnet_variants.py
    measures); default stays f32 and the two must agree loosely."""
    import jax.numpy as jnp

    from mxnet_tpu import ops

    rs = np.random.RandomState(0)
    # large |mean| / small std: the regime where naive bf16 squares
    # would cancel catastrophically — the shifted-moments formulation
    # must stay accurate here
    x = jnp.asarray(rs.normal(40.0, 1.0, (8, 4, 5, 5)), jnp.bfloat16)
    gamma = jnp.ones(4)
    beta = jnp.zeros(4)
    mm, mv = jnp.full(4, 40.0), jnp.ones(4)
    octx = ops.OpCtx(is_train=True)
    bn = ops.get("BatchNorm").fn
    monkeypatch.delenv("MXTPU_BN_STATS_DTYPE", raising=False)
    out_f32, _ = bn(octx, x, gamma, beta, mm, mv)
    monkeypatch.setenv("MXTPU_BN_STATS_DTYPE", "compute")
    out_bf16, _ = bn(octx, x, gamma, beta, mm, mv)
    # same math with bf16-rounded squares: close (possibly identical
    # after the output's own bf16 rounding — the flag's effect is HBM
    # traffic, not numerics)
    np.testing.assert_allclose(
        np.asarray(out_f32, np.float32), np.asarray(out_bf16, np.float32),
        atol=0.15)
