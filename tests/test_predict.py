"""Predict-only API + legacy executor manager tests.

Parity model: the reference's c_predict_api usage (predict from a
save_checkpoint checkpoint: MXPredCreate/SetInput/Forward/GetOutput,
tests via amalgamation examples) and executor_manager.py's
DataParallelExecutorManager used by FeedForward.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.predict import Predictor, create as pred_create


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(sym.Flatten(data), name="fc1", num_hidden=16)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=4)
    return sym.SoftmaxOutput(net, name="softmax")


def _trained_checkpoint(tmp_path):
    rs = np.random.RandomState(0)
    x = rs.uniform(size=(64, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd")
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)
    return prefix, x


def test_predictor_from_checkpoint(tmp_path):
    prefix, x = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (16, 8)})
    p.forward(data=x[:16])
    out = p.get_output(0)
    assert out.shape == (16, 4)
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)  # softmax rows

    # parity with the module's own forward
    symbol, arg_params, aux_params = mx.model.load_checkpoint(prefix, 1)
    mod = mx.mod.Module(symbol, context=mx.cpu(), label_names=[])
    mod.bind(data_shapes=[("data", (16, 8))], for_training=False)
    mod.set_params(arg_params, aux_params)
    mod.forward(mx.io.DataBatch(data=[nd.array(x[:16])], label=None))
    ref = mod.get_outputs()[0].asnumpy()
    assert np.allclose(out, ref, atol=1e-5)


def test_predictor_set_input_validation(tmp_path):
    prefix, x = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (4, 8)})
    with pytest.raises(mx.MXNetError):
        p.set_input("nope", x[:4])
    with pytest.raises(mx.MXNetError):
        p.set_input("data", x[:3])  # wrong shape


def test_predictor_reshape(tmp_path):
    prefix, x = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (16, 8)})
    p.forward(data=x[:16])
    first = p.get_output(0)
    p.reshape({"data": (32, 8)})
    p.forward(data=x[:32])
    out = p.get_output(0)
    assert out.shape == (32, 4)
    assert np.allclose(out[:16], first, atol=1e-5)


def test_predictor_partial_forward(tmp_path):
    prefix, x = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (8, 8)})
    p.forward(data=x[:8])
    internals = p.symbol.get_internals().list_outputs()
    outs = p.partial_forward(len(internals) - 1)
    assert np.allclose(outs[0], p.get_output(0), atol=1e-5)


def test_executor_manager_train_step():
    from mxnet_tpu.executor_manager import DataParallelExecutorManager

    rs = np.random.RandomState(0)
    x = rs.uniform(size=(64, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    mgr = DataParallelExecutorManager(_mlp(), [mx.cpu(0), mx.cpu(1)], it)

    arg_params, aux_params = {}, {}
    init = mx.init.Uniform(0.1)
    for name in mgr.param_names:
        shape = dict(zip(mgr.execgrp.arg_names,
                         _mlp().infer_shape(data=(32, 8))[0]))[name]
        arr = nd.zeros(shape)
        init(name, arr)
        arg_params[name] = arr
    mgr.set_params(arg_params, aux_params)

    metric = mx.metric.create("acc")
    it.reset()
    batch = next(it)
    mgr.load_data_batch(batch)
    mgr.forward(is_train=True)
    mgr.backward()
    assert all(g[0] is not None for g in mgr.grad_arrays)
    metric.reset()
    mgr.update_metric(metric, batch.label)
    assert 0.0 <= metric.get()[1] <= 1.0

    out_params, out_aux = {}, {}
    mgr.copy_to(out_params, out_aux)
    assert set(out_params) == set(mgr.param_names)


def test_predictor_bf16_dtype(tmp_path):
    """dtype='bfloat16' casts inside the compiled program: outputs come
    back fp32 and stay within bf16 tolerance of the fp32 predictor."""
    prefix, x = _trained_checkpoint(tmp_path)
    p32 = pred_create(prefix, 1, {"data": (16, 8)})
    p16 = pred_create(prefix, 1, {"data": (16, 8)}, dtype="bfloat16")
    p32.forward(data=x[:16])
    p16.forward(data=x[:16])
    o32 = p32.get_output(0)
    o16 = p16.get_output(0)
    assert o16.dtype == np.float32  # cast back at the program boundary
    assert np.allclose(o16.sum(axis=1), 1.0, atol=1e-2)
    assert np.allclose(o16, o32, atol=0.03)


def test_predictor_set_input_then_parameterless_forward(tmp_path):
    """The C ABI flow (src/c_predict.cc): SetInput -> Forward() with no
    kwargs -> GetOutput must hit the single-dispatch path and agree with
    the kwargs flow."""
    prefix, x = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (16, 8)})
    p.set_input("data", x[:16])
    p.forward()
    via_abi = p.get_output(0)
    p2 = pred_create(prefix, 1, {"data": (16, 8)})
    p2.forward(data=x[:16])
    assert np.allclose(via_abi, p2.get_output(0), atol=1e-6)


def test_predictor_output_shape_before_forward(tmp_path):
    """MXPredGetOutputShape is queried right after MXPredCreate to size
    client buffers (reference c_predict_api flow) — must work with no
    forward run yet."""
    prefix, _ = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (16, 8)})
    assert p.get_output_shape(0) == (16, 4)
    assert p.num_outputs == 1


def test_predictor_forward_async_pipeline(tmp_path):
    """forward_async/get_async: results match forward(), tickets join in
    any order, and a retired ticket raises."""
    prefix, x = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (8, 8)})
    p.forward(data=x[:8])
    want0 = p.get_output(0)
    p.forward(data=x[8:16])
    want1 = p.get_output(0)

    t0 = p.forward_async(data=x[:8])
    t1 = p.forward_async(data=x[8:16])  # two tickets in flight
    out1 = p.get_async(t1)              # out-of-order join
    out0 = p.get_async(t0)
    assert np.allclose(out0, want0, atol=1e-5)
    assert np.allclose(out1, want1, atol=1e-5)
    with pytest.raises(mx.MXNetError):
        p.get_async(t0)  # already retired


def test_predictor_bf16_wire_upload(tmp_path):
    """dtype='bfloat16' uploads inputs already cast on the host (half the
    wire bytes) and still matches the f32 predictor to bf16 tolerance."""
    prefix, x = _trained_checkpoint(tmp_path)
    p32 = pred_create(prefix, 1, {"data": (8, 8)})
    p16 = pred_create(prefix, 1, {"data": (8, 8)}, dtype="bfloat16")
    assert p16._wire_dtype is not None
    p32.forward(data=x[:8])
    p16.forward(data=x[:8])
    a, b = p32.get_output(0), p16.get_output(0)
    assert b.dtype == np.float32  # outputs cast back for the ABI
    assert np.allclose(a, b, atol=2e-2)
    t = p16.forward_async(data=x[:8])
    assert np.allclose(p16.get_async(t), b, atol=2e-2)


def test_predictor_discard_and_inflight_bound(tmp_path):
    """discard_async frees a ticket without fetching; the in-flight map
    stays bounded when a client never fetches."""
    prefix, x = _trained_checkpoint(tmp_path)
    p = pred_create(prefix, 1, {"data": (4, 8)})
    t = p.forward_async(data=x[:4])
    p.discard_async(t)
    with pytest.raises(mx.MXNetError):
        p.get_async(t)
    p.discard_async(12345)  # unknown ticket: no-op
    tickets = [p.forward_async(data=x[:4]) for _ in range(70)]
    assert len(p._inflight) == 64  # exact cap, oldest evicted first
    with pytest.raises(mx.MXNetError):
        p.get_async(tickets[0])    # evicted
    assert p.get_async(tickets[-1]) is not None  # newest survives


def test_predictor_int8_quantize_parity(tmp_path, monkeypatch):
    """quantize='int8' (ISSUE 6): fp matmul weights stored int8 +
    per-channel scales, dequantized inside the compiled program —
    outputs stay within quantization tolerance of the fp32 predictor,
    and MXTPU_PREDICT_INT8=1 enables it for kwarg-less (C-ABI) clients."""
    prefix, x = _trained_checkpoint(tmp_path)
    p32 = pred_create(prefix, 1, {"data": (16, 8)})
    p8 = pred_create(prefix, 1, {"data": (16, 8)}, quantize="int8")
    # int8 storage is real: both fc weights left the fp snapshot
    assert sorted(p8._qparams) == ["fc1_weight", "fc2_weight"]
    assert all(np.dtype(q.dtype) == np.int8
               for q, _ in p8._qparams.values())
    assert not any(k.endswith("weight") for k in p8._param_snapshot)
    p32.forward(data=x[:16])
    p8.forward(data=x[:16])
    o32, o8 = p32.get_output(0), p8.get_output(0)
    assert o8.dtype == np.float32
    assert np.allclose(o8.sum(axis=1), 1.0, atol=1e-3)  # still a softmax
    assert np.allclose(o8, o32, atol=0.02)

    # env-var path for clients that construct without kwargs
    monkeypatch.setenv("MXTPU_PREDICT_INT8", "1")
    penv = pred_create(prefix, 1, {"data": (16, 8)})
    assert penv._quantize == "int8"
    penv.forward(data=x[:16])
    assert np.allclose(penv.get_output(0), o8, atol=1e-6)

    with pytest.raises(mx.MXNetError):
        pred_create(prefix, 1, {"data": (16, 8)}, quantize="int4")
