"""Build and run the C++ unit-test binary for the native runtime
(tests/cpp/native_unit.cc — parity: the reference's gtest C++ suite,
tests/cpp/threaded_engine_test.cc + storage_test.cc)."""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "mxnet_tpu", "lib", "libmxtpu.so")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_native_cpp_unit_suite(tmp_path):
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "-C", os.path.join(REPO, "src")],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
    exe = tmp_path / "native_unit"
    r = subprocess.run(
        ["g++", "-std=c++17", "-O2", "-I", os.path.join(REPO, "src"),
         os.path.join(REPO, "tests", "cpp", "native_unit.cc"), LIB,
         "-o", str(exe), f"-Wl,-rpath,{os.path.dirname(LIB)}", "-pthread"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    r = subprocess.run([str(exe)], capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ALL CPP TESTS OK" in r.stdout
