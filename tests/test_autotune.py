"""Autotuner tests (ISSUE 18): the schedule cache's roundtrip /
corruption / readonly / segregation contracts, the bounded search, the
paged-attention kernel's interpret-mode parity against the PR-15
gather path (prefill + ragged steps + fork-private divergence), the
shape-gate fallback, and zero steady-state recompiles with tuning on.
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autotune as at, models, telemetry as tm
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.decode import KVDecoder
from mxnet_tpu.ops import paged_attention as pa
from mxnet_tpu.ops import residual_epilogue as repi
from mxnet_tpu.serving.paged_kv import PagedSlots
from mxnet_tpu.serving.scheduler import SlotScheduler

L, H, D, T, V = 2, 2, 32, 32, 17


@pytest.fixture(scope="module")
def lm_params():
    net = models.transformer.transformer_lm(
        num_layers=L, num_heads=H, d_model=D, seq_len=T, vocab_size=V)
    ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                         data=(1, T), softmax_label=(1, T))
    rs = np.random.RandomState(0)
    params = {}
    for name, arr in ex.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
        params[name] = arr
    return params


@pytest.fixture(scope="module")
def decoder(lm_params):
    return KVDecoder(lm_params, num_layers=L, num_heads=H, max_len=T)


@pytest.fixture()
def metrics():
    was = tm.enabled()
    tm.enable()
    yield tm.get_registry()
    if not was:
        tm.disable()


@pytest.fixture()
def no_cache(monkeypatch):
    """Autotuning off and in-memory winners forgotten — the default
    regime every non-cache test should run in."""
    monkeypatch.delenv("MXTPU_SCHEDULE_CACHE", raising=False)
    monkeypatch.delenv("MXTPU_PAGED_KERNEL", raising=False)
    at.reset()
    yield
    at.reset()


@pytest.fixture()
def sched_cache(tmp_path, monkeypatch):
    """A private search-mode schedule cache; state reset both sides."""
    path = str(tmp_path / "schedules.json")
    monkeypatch.setenv("MXTPU_SCHEDULE_CACHE", "search:" + path)
    monkeypatch.delenv("MXTPU_PAGED_KERNEL", raising=False)
    monkeypatch.delenv("MXTPU_AUTOTUNE_TRIALS", raising=False)
    at.reset()
    yield path
    at.reset()


def _const_bench(calls=None):
    """A bench_fn whose thunks do trivial device work; optionally
    records which candidates were measured."""
    def bench(cand):
        if calls is not None:
            calls.append(cand)
        return lambda: 0.0
    return bench


# ---------------------------------------------------------------------------
# cache plane
# ---------------------------------------------------------------------------
def test_cache_roundtrip_persists_and_reloads(sched_cache):
    won = at.ensure("k", "sig", {"impl": "a"},
                    [{"impl": "a"}, {"impl": "b"}], _const_bench(),
                    warmup=0, best_of=1)
    assert won["impl"] in ("a", "b")
    doc = json.load(open(sched_cache))
    assert doc["version"] == at.SCHEMA_VERSION
    ent = doc["entries"][at.device_kind()]["k|sig"]
    assert ent["schedule"] == won
    assert ent["trials"] == 2
    assert ent["best_us"] >= 0
    # a fresh process-state must reload the winner from disk with zero
    # new trials: reset the memo, prime through the bind path, look up
    at.reset()
    assert at.schedule_for("k", "sig", "DEFAULT") == "DEFAULT", \
        "unprimed lookup must stay a pure default read"
    at.fingerprint()                       # the executor-bind priming hook
    assert at.schedule_for("k", "sig", "DEFAULT") == won
    calls = []
    again = at.ensure("k", "sig", {"impl": "a"},
                      [{"impl": "a"}, {"impl": "b"}], _const_bench(calls),
                      warmup=0, best_of=1)
    assert again == won and calls == [], \
        "a persisted winner must be reused without re-measuring"


def test_corrupt_and_mismatched_files_fall_back(tmp_path, monkeypatch):
    good = {"version": at.SCHEMA_VERSION,
            "entries": {"cpu": {"k|s": {"schedule": {"impl": "x"}}}}}
    for name, text in [
        ("garbage.json", "{not json"),
        ("wrong_version.json", json.dumps(dict(good, version=999))),
        ("wrong_shape.json", json.dumps([1, 2, 3])),
    ]:
        p = tmp_path / name
        p.write_text(text)
        assert at.load_file(str(p)) == {}, name
    assert at.load_file(str(tmp_path / "missing.json")) == {}
    # end to end: a corrupt cache degrades to defaults, and a search
    # REPLACES it with a valid document instead of crashing
    p = tmp_path / "corrupt.json"
    p.write_text("{not json")
    monkeypatch.setenv("MXTPU_SCHEDULE_CACHE", "search:%s" % p)
    at.reset()
    at.fingerprint()
    assert at.schedule_for("k", "s", "DEFAULT") == "DEFAULT"
    at.ensure("k", "s", {"impl": "a"}, [{"impl": "a"}], _const_bench(),
              warmup=0, best_of=1)
    assert json.load(open(p))["version"] == at.SCHEMA_VERSION
    at.reset()


def test_readonly_never_writes(tmp_path, monkeypatch):
    path = tmp_path / "ro.json"
    monkeypatch.setenv("MXTPU_SCHEDULE_CACHE", "readonly:%s" % path)
    at.reset()
    calls = []
    got = at.ensure("k", "sig", {"impl": "default"},
                    [{"impl": "default"}, {"impl": "other"}],
                    _const_bench(calls), warmup=0, best_of=1)
    assert got == {"impl": "default"}
    assert calls == [], "readonly mode must never measure"
    assert not path.exists(), "readonly mode must never create the file"
    # an explicit record(persist=True) also refuses to touch disk
    at.record("k", "sig", {"impl": "other"}, 1.0, 1)
    assert not path.exists()
    # ...but a pre-existing file IS honored, byte-for-byte untouched
    doc = {"version": at.SCHEMA_VERSION,
           "entries": {at.device_kind(): {
               "k|sig": {"schedule": {"impl": "pinned"},
                         "best_us": 1.0, "trials": 1}}}}
    path.write_text(json.dumps(doc))
    before = path.read_bytes()
    at.reset()
    got = at.ensure("k", "sig", {"impl": "default"},
                    [{"impl": "default"}], _const_bench(calls),
                    warmup=0, best_of=1)
    assert got == {"impl": "pinned"} and calls == []
    assert path.read_bytes() == before
    at.reset()


def test_device_kind_segregation(tmp_path, monkeypatch):
    kind = at.device_kind()
    other = "TPU_v4" if kind != "TPU_v4" else "TPU_v5e"
    path = tmp_path / "mixed.json"
    path.write_text(json.dumps({
        "version": at.SCHEMA_VERSION,
        "entries": {
            kind: {"k|sig": {"schedule": {"impl": "mine"}}},
            other: {"k|sig": {"schedule": {"impl": "theirs"}},
                    "k2|sig": {"schedule": {"impl": "theirs"}}},
        }}))
    monkeypatch.setenv("MXTPU_SCHEDULE_CACHE", "search:%s" % path)
    at.reset()
    at.fingerprint()
    assert at.schedule_for("k", "sig", None) == {"impl": "mine"}
    assert at.schedule_for("k2", "sig", "DEFAULT") == "DEFAULT", \
        "another device kind's winners must not load here"
    # recording here must not clobber the other kind's entries
    at.ensure("k3", "sig", {"impl": "a"}, [{"impl": "a"}], _const_bench(),
              warmup=0, best_of=1)
    entries = json.load(open(path))["entries"]
    assert entries[other]["k|sig"]["schedule"] == {"impl": "theirs"}
    assert "k3|sig" in entries[kind]
    at.reset()


def test_trial_budget_and_telemetry(sched_cache, monkeypatch, metrics):
    monkeypatch.setenv("MXTPU_AUTOTUNE_TRIALS", "2")
    assert at.trials_budget() == 2
    trials = metrics.get("autotune_trials_total")
    cachec = metrics.get("autotune_cache_total")
    t0, h0, m0 = (trials.total(), cachec.value(result="hit"),
                  cachec.value(result="miss"))
    calls = []
    cands = [{"impl": "c%d" % i} for i in range(5)]
    won = at.ensure("budgeted", "sig", cands[0], cands,
                    _const_bench(calls), warmup=0, best_of=1)
    assert len(calls) == 2, "budget must cap measured candidates"
    assert won in cands[:2]
    assert trials.total() - t0 == 2
    assert cachec.value(result="miss") - m0 == 1
    # second call: the recorded winner hits, zero new trials
    calls.clear()
    again = at.ensure("budgeted", "sig", cands[0], cands,
                      _const_bench(calls), warmup=0, best_of=1)
    assert again == won and calls == []
    assert trials.total() - t0 == 2
    assert cachec.value(result="hit") - h0 == 1
    # budget 0: cached winners still honored, new searches disabled
    monkeypatch.setenv("MXTPU_AUTOTUNE_TRIALS", "0")
    assert at.ensure("budgeted", "sig", cands[0], cands,
                     _const_bench(calls), warmup=0, best_of=1) == won
    got = at.ensure("never_searched", "sig", {"impl": "d"}, cands,
                    _const_bench(calls), warmup=0, best_of=1)
    assert got == {"impl": "d"} and calls == []


def test_fingerprint_epoch_invalidates_on_record(sched_cache):
    fp0 = at.fingerprint()
    at.record("k", "sig", {"impl": "a"}, 1.0, 1)
    fp1 = at.fingerprint()
    assert fp0 != fp1, \
        "a new winner must change the executor program-cache key"
    assert fp0[:2] == fp1[:2]              # same mode + path, new epoch


# ---------------------------------------------------------------------------
# paged-attention op parity
# ---------------------------------------------------------------------------
def _op_case(B=3, Hh=2, M=4, block=8, dh=32, Ll=2, seed=3):
    rs = np.random.RandomState(seed)
    P = B * M + 1
    import jax.numpy as jnp
    pool_k = jnp.asarray(rs.normal(size=(P, Ll, Hh, block, dh))
                         .astype(np.float32))
    pool_v = jnp.asarray(rs.normal(size=(P, Ll, Hh, block, dh))
                         .astype(np.float32))
    q = jnp.asarray(rs.normal(size=(B, Hh, 1, dh)).astype(np.float32))
    bt = jnp.asarray(rs.permutation(np.arange(1, P))[:B * M]
                     .reshape(B, M).astype(np.int32))
    # ragged cursors: a nearly-empty, a mid, a nearly-full slot
    cursor = jnp.asarray(
        np.linspace(1, M * block - 1, B).astype(np.int32))
    return q, pool_k, pool_v, bt, cursor


def _run_op(sched, args, layer, block):
    """One jitted attention call — jitted because that is how serving
    invokes it (the bitwise contract is between compiled programs;
    eager dispatch fuses differently and drifts in the last bit)."""
    import jax

    f = jax.jit(lambda *a: pa.paged_attention(
        *a, layer, block=block, schedule=sched))
    return np.asarray(f(*args))


@pytest.mark.parametrize("grid", ["bh", "flat"])
@pytest.mark.parametrize("live_only", [True, False])
def test_pallas_interpret_bitwise_vs_gather(no_cache, grid, live_only):
    """The kernel is BITWISE against the PR-15 gather math on aligned
    shapes, for both grid layouts, with and without live-page DMA
    gating, on ragged block tables."""
    args = _op_case()
    sched = {"impl": "pallas", "grid": grid, "live_only": live_only,
             "interpret": True}
    for layer in range(L):
        ref = _run_op(None, args, layer, 8)
        out = _run_op(sched, args, layer, 8)
        assert np.array_equal(ref, out), (grid, live_only, layer)


def test_pagewalk_allclose_vs_gather(no_cache):
    """The lax pagewalk reassociates the reductions (loop-carried
    accumulation) — allclose, deliberately NOT bitwise, which is why
    only the autotuner or an explicit mode ever installs it."""
    args = _op_case()
    ref = _run_op(None, args, 0, 8)
    for chunk in (1, 2, 4):
        out = _run_op({"impl": "pagewalk", "chunk": chunk}, args, 0, 8)
        np.testing.assert_allclose(ref, out, rtol=2e-5, atol=2e-6)


def test_shape_gate_falls_back_bit_identical(no_cache):
    """A shape the kernel cannot tile (block % 8 != 0) silently takes
    the gather path even when the pallas schedule is forced — same
    array, bit for bit."""
    args = _op_case(block=4, dh=12)
    assert not pa.supports(4, 12, np.float32)
    ref = _run_op(None, args, 0, 4)
    out = _run_op({"impl": "pallas", "grid": "bh", "interpret": True},
                  args, 0, 4)
    assert np.array_equal(ref, out)


def test_candidate_schedules_and_keysig(no_cache):
    cands = pa.candidate_schedules("cpu", 8, 32, 4, np.float32)
    assert {"impl": "gather"} in cands
    assert all(c["impl"] != "pallas" for c in cands), \
        "compiled-pallas candidates are TPU-only"
    assert {"impl": "pagewalk", "chunk": 3} not in cands  # 3 !| M=4
    tpu = pa.candidate_schedules("tpu", 8, 32, 4, np.float32)
    assert any(c["impl"] == "pallas" for c in tpu)
    assert pa.default_schedule("cpu", 8, 32, np.float32) == \
        {"impl": "gather"}
    assert pa.default_schedule("tpu", 8, 32, np.float32)["impl"] == \
        "pallas"
    assert pa.keysig(2, 4, 8, 16, 64, np.float32) == \
        "b2h4m8k16d64_float32"


# ---------------------------------------------------------------------------
# end-to-end serving parity
# ---------------------------------------------------------------------------
def _drive(pg, seed=5):
    """Prefill + ragged steps + a mid-flight fork admission against the
    shared prefix block + dual-slot steps — the full paged life cycle,
    returning every logits array along the way."""
    rs = np.random.RandomState(seed)
    shared = rs.randint(0, V, 8).astype(np.int64)     # one full block
    fa = np.concatenate([shared, rs.randint(0, V, 8)])
    fb = np.concatenate([shared, rs.randint(0, V, 3)])  # ragged tail
    outs = [np.asarray(pg.admit(0, fa), np.float32)]
    occ = np.array([True, False, False])
    tok = np.array([int(outs[-1].argmax()), 0, 0])
    for _ in range(4):
        lg, _ = pg.step(tok, occ)
        outs.append(np.asarray(lg, np.float32))
        tok = np.array([int(outs[-1][0].argmax()), 0, 0])
    outs.append(np.asarray(pg.admit(1, fb), np.float32))
    occ = np.array([True, True, False])
    tok = np.array([tok[0], int(outs[-1].argmax()), 0])
    for _ in range(4):
        lg, _ = pg.step(tok, occ)
        outs.append(np.asarray(lg, np.float32))
        tok = np.array([int(outs[-1][0].argmax()),
                        int(outs[-1][1].argmax()), 0])
    return outs


def test_paged_slots_interpret_kernel_bitwise_end_to_end(decoder,
                                                         no_cache):
    """The interpret-mode kernel drives the REAL serving backend —
    prefill, ragged decode steps, a fork admitting mid-flight behind
    the shared prefix block — bitwise against the gather backend at
    every emission."""
    buckets = (8, 16, 32)
    ref = _drive(PagedSlots(decoder, 3, block=8, prefill_buckets=buckets,
                            kernel="gather"))
    pg = PagedSlots(decoder, 3, block=8, prefill_buckets=buckets,
                    kernel="interpret")
    assert pg.schedule == {"impl": "pallas", "grid": "bh",
                           "live_only": True, "interpret": True}
    assert pg.stats()["kernel"] == "pallas"
    outs = _drive(pg)
    for i, (a, b) in enumerate(zip(ref, outs)):
        assert np.array_equal(a, b), \
            "interpret kernel diverged bitwise at emission %d" % i


def test_paged_slots_pagewalk_and_auto(decoder, no_cache):
    """Pagewalk through the same life cycle stays allclose (its
    documented tier); auto with the cache off resolves to gather on a
    CPU host — bit-identical to MXTPU_PAGED_KERNEL=0."""
    buckets = (8, 16, 32)
    ref = _drive(PagedSlots(decoder, 3, block=8, prefill_buckets=buckets,
                            kernel="gather"))
    pw = PagedSlots(decoder, 3, block=8, prefill_buckets=buckets,
                    kernel="pagewalk")
    assert pw.stats()["kernel"] == "pagewalk"
    for a, b in zip(ref, _drive(pw)):
        scale = max(1.0, float(np.abs(a).max()))
        assert np.abs(a - b).max() < 1e-3 * scale
    auto = PagedSlots(decoder, 3, block=8, prefill_buckets=buckets)
    assert auto.schedule is None and auto.stats()["kernel"] == "gather"
    for a, b in zip(ref, _drive(auto)):
        assert np.array_equal(a, b)


def test_paged_kernel_mode_env(decoder, no_cache, monkeypatch):
    monkeypatch.setenv("MXTPU_PAGED_KERNEL", "0")
    pg = PagedSlots(decoder, 2, block=8, prefill_buckets=(8, 16, 32))
    assert pg.schedule is None
    monkeypatch.setenv("MXTPU_PAGED_KERNEL", "bogus")
    with pytest.raises(MXNetError):
        PagedSlots(decoder, 2, block=8, prefill_buckets=(8, 16, 32))


def test_zero_recompiles_after_warmup_with_tuning_on(decoder, metrics,
                                                     sched_cache,
                                                     monkeypatch):
    """Tuning on (auto kernel, search-mode cache): the admit-time
    search picks a schedule ONCE, and warm serving traffic does zero
    traces per tick — the tuned program is as steady as the gather
    one."""
    monkeypatch.setenv("MXTPU_AUTOTUNE_TRIALS", "3")
    compiles = metrics.get("executor_compile_total")
    trials = metrics.get("autotune_trials_total")
    sched = SlotScheduler(decoder, num_slots=2, queue_size=16,
                          paged=True, kv_block=8)
    try:
        rs = np.random.RandomState(6)
        for plen in (3, 12, 20):           # warm every bucket + search
            sched.generate(rs.randint(0, V, plen), max_new_tokens=2,
                           timeout=120)
        assert os.path.exists(sched_cache), \
            "the admit-time search should have persisted a winner"
        c0, t0 = compiles.total(), trials.total()
        reqs = [sched.submit(rs.randint(0, V, ln), max_new_tokens=4)
                for ln in (3, 7, 5, 9, 4, 18)]
        for r in reqs:
            r.wait(120)
        assert all(r.outcome == "ok" for r in reqs), \
            [(r.outcome, r.error) for r in reqs]
        assert compiles.total() - c0 == 0, \
            "warm tuned serving traffic recompiled"
        assert trials.total() - t0 == 0, \
            "steady-state traffic must never re-search"
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# residual epilogue knob
# ---------------------------------------------------------------------------
def test_epilogue_tune_installs_winner_and_stays_bitwise(sched_cache):
    """tune() records a block_rows winner; the kernel's tiling is
    elementwise so EVERY block size is bitwise-identical — the knob
    can only change speed, never values."""
    import functools

    import jax
    import jax.numpy as jnp

    rows, channels = 64, 128
    won = repi.tune(rows, channels)
    assert won["block_rows"] > 0 and rows % won["block_rows"] == 0
    assert repi._block_rows_for(rows, channels, jnp.float32) == \
        won["block_rows"]
    ent = json.load(open(sched_cache))["entries"][at.device_kind()]
    assert "residual_epilogue|r64c128_float32" in ent
    rs = np.random.RandomState(1)
    x2 = jnp.asarray(rs.normal(size=(rows, channels)).astype(np.float32))
    s2 = jnp.asarray(rs.normal(size=(rows, channels)).astype(np.float32))
    sc = jnp.asarray(rs.normal(size=(channels,)).astype(np.float32))
    b = jnp.asarray(rs.normal(size=(channels,)).astype(np.float32))
    outs = [np.asarray(jax.jit(functools.partial(
        repi._pallas_fwd, interpret=True, block_rows=br))(x2, s2, sc, b))
        for br in (8, 16, 32, 64)]
    for o in outs[1:]:
        assert np.array_equal(outs[0], o)


def test_epilogue_unsupported_shape_keeps_default(no_cache):
    assert repi.tune(60, 100) == \
        {"block_rows": repi._default_block_rows(60)}
