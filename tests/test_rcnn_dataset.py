"""Pascal VOC dataset loader tests (parity:
example/rcnn/rcnn/dataset/pascal_voc.py — the reference parses a
VOCdevkit tree into a roidb; here the writer emits a real devkit and
the parser reads it back, pinning the XML 1-based-coordinate and class
conventions)."""
import os
import sys

import numpy as np
import pytest

RCNN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "rcnn")
sys.path.insert(0, RCNN)

from rcnn import config as cfg_mod  # noqa: E402
from rcnn.dataset import CLASSES, PascalVOC, write_synth_devkit  # noqa: E402
from rcnn.loader import AnchorLoader, synth_image_set  # noqa: E402

pytest.importorskip("PIL")


def test_devkit_roundtrip(tmp_path):
    cfg = cfg_mod.default
    root = write_synth_devkit(str(tmp_path), cfg, 10, seed=3)
    assert os.path.isfile(os.path.join(root, "Annotations", "000000.xml"))
    assert os.path.isfile(os.path.join(root, "JPEGImages", "000000.jpg"))

    train = PascalVOC(str(tmp_path), "trainval", cfg=cfg)
    test = PascalVOC(str(tmp_path), "test", cfg=cfg)
    assert len(train.ids) == 8 and len(test.ids) == 2

    images, gt = train.load()
    src_images, src_gt = synth_image_set(cfg, 10, seed=3)
    assert images.shape == (8, 3, cfg.im_size, cfg.im_size)
    for i in range(8):
        # boxes survive the XML round trip exactly (same-size images:
        # scale 1; VOC 1-based offsets cancel)
        np.testing.assert_allclose(gt[i], src_gt[i], atol=1e-4)
        # jpeg is lossy but close
        assert np.abs(images[i] - src_images[i]).mean() < 0.06


def test_unknown_and_difficult_objects_skipped(tmp_path):
    cfg = cfg_mod.default
    root = write_synth_devkit(str(tmp_path), cfg, 4, seed=0)
    # append an unknown-class and a difficult object to image 0
    import xml.etree.ElementTree as ET

    p = os.path.join(root, "Annotations", "000000.xml")
    tree = ET.parse(p)
    for name, difficult in (("unicorn", "0"), ("wide", "1")):
        obj = ET.SubElement(tree.getroot(), "object")
        ET.SubElement(obj, "name").text = name
        ET.SubElement(obj, "difficult").text = difficult
        bb = ET.SubElement(obj, "bndbox")
        for tag, v in (("xmin", "1"), ("ymin", "1"), ("xmax", "9"),
                       ("ymax", "9")):
            ET.SubElement(bb, tag).text = v
    tree.write(p)

    n_before = len(PascalVOC(str(tmp_path), "trainval", cfg=cfg)
                   .load()[1][0])
    _, src_gt = synth_image_set(cfg, 4, seed=0)
    assert n_before == len(src_gt[0])  # both extras skipped

    keep_difficult = PascalVOC(str(tmp_path), "trainval", cfg=cfg,
                               skip_difficult=False).load()[1][0]
    assert len(keep_difficult) == len(src_gt[0]) + 1


def test_anchor_loader_accepts_preloaded_set(tmp_path):
    cfg = cfg_mod.default
    write_synth_devkit(str(tmp_path), cfg, 10, seed=1)
    images, gt = PascalVOC(str(tmp_path), "trainval", cfg=cfg).load()
    loader = AnchorLoader(cfg, batch_size=4, images=images, gt=gt,
                          shuffle=False)
    batch = next(loader)
    assert batch.data[0].shape == (4, 3, cfg.im_size, cfg.im_size)
    assert len(batch.gt) == 4
    assert CLASSES[int(batch.gt[0][0][4])] in ("wide", "tall")
