"""Perl XS binding over the C predict ABI — a second real external
consumer of libmxtpu_predict.so (parity model: the reference's
language bindings are thin wrappers over the same C API; SURVEY.md
Appendix B calls them proof the C ABI is the real product)."""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "mxnet_tpu", "lib", "libmxtpu_predict.so")

PERL_CLIENT = """
use strict; use warnings;
use lib "%(blib)s/lib", "%(blib)s/arch";
use MXNetTPU;
my $p = MXNetTPU::Predictor->new(
    symbol_file => "%(prefix)s-symbol.json",
    params_file => "%(prefix)s-0000.params",
    input_key   => "data",
    input_shape => [4, 8]);
my @x = map { $_ / 32.0 } 0 .. 31;
my $out = $p->predict([@x]);
my $shape = $p->output_shape;
print "shape: @{$shape}\\n";
printf "%%.6f\\n", $_ for @$out;
"""


def _have_perl_toolchain():
    if shutil.which("perl") is None or shutil.which("make") is None:
        return False
    r = subprocess.run(["perl", "-MExtUtils::MakeMaker", "-e", "1"],
                       capture_output=True)
    return r.returncode == 0


@pytest.mark.skipif(not _have_perl_toolchain(),
                    reason="perl + MakeMaker not available")
def test_perl_binding_matches_python_predictor(tmp_path):
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "-C", os.path.join(REPO, "src"),
                            "predict"], capture_output=True, text=True)
        assert r.returncode == 0, r.stderr

    # build the XS extension out-of-tree
    build = tmp_path / "perl"
    shutil.copytree(os.path.join(REPO, "bindings", "perl"), build)
    env = dict(os.environ, MXTPU_REPO=REPO)
    r = subprocess.run(["perl", "Makefile.PL"], cwd=build, env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(["make"], cwd=build, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr

    # checkpoint + python-side oracle
    import mxnet_tpu as mx
    from mxnet_tpu import predict, sym

    data = sym.Variable("data")
    net = sym.FullyConnected(data, name="fc1", num_hidden=6)
    net = sym.Activation(net, act_type="tanh")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(net, name="fc2", num_hidden=3), name="softmax")
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 8))
    np.random.seed(11)  # initializers draw from numpy's global RNG
    init = mx.init.Xavier()
    arg_params = {}
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(name, arr)
            arg_params[name] = arr
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 0, net, arg_params, {})

    x = (np.arange(32, dtype=np.float32) / 32.0).reshape(4, 8)
    p = predict.create(prefix, 0, {"data": (4, 8)})
    p.set_input("data", x)
    p.forward()
    expected = np.asarray(p.get_output(0))

    script = tmp_path / "client.pl"
    script.write_text(PERL_CLIENT % {"blib": str(build / "blib"),
                                     "prefix": prefix})
    run_env = dict(os.environ)
    run_env["MXTPU_PLATFORM"] = "cpu"
    run_env["JAX_PLATFORMS"] = "cpu"
    run_env["PYTHONPATH"] = REPO + os.pathsep + run_env.get("PYTHONPATH", "")
    # one retry: the client embeds CPython + XLA inside perl, and a
    # heavily loaded machine (full-suite runs) can starve its first
    # compile
    for attempt in (1, 2):
        r = subprocess.run(["perl", str(script)], env=run_env,
                           capture_output=True, text=True, timeout=300)
        if r.returncode == 0:
            break
    assert r.returncode == 0, (
        f"perl client rc={r.returncode}\nstdout: {r.stdout}\n"
        f"stderr: {r.stderr}")
    lines = r.stdout.strip().splitlines()
    assert lines[0] == "shape: 4 3", lines[0]
    got = np.array([float(v) for v in lines[1:]]).reshape(4, 3)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)
