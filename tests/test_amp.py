"""First-class AMP through Module/Executor/KVStore (ISSUE 10).

Contracts pinned here:

- ``MXTPU_AMP`` unset: every path is bit-identical — the amp_cast pass
  returns the SAME symbol object (signatures and program-cache keys
  unchanged), two runs agree bitwise.
- ``MXTPU_AMP=bf16``: amp-vs-fp32 convergence parity on a ResNet-style
  conv net and a transformer LM through the full
  Module/Executor/KVStore path, within bf16 tolerance.
- fp32 master weights: eager ``multi_precision``, fused buckets, the
  8-virtual-device sharded buckets (1/N master bytes per replica), and
  sparse bf16 tables (fp32 master rows) all agree with fp32 math.
- dynamic loss scaling: overflow -> skip-step -> halve -> recovery as
  a device-side lattice, with ZERO per-batch host syncs (counter
  asserted).
- the Pallas residual-epilogue kernel matches the lax lowering fwd AND
  bwd (interpret mode on CPU).
"""
import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import amp, nd, sym
from mxnet_tpu import executor as ex_mod
from mxnet_tpu import models
from mxnet_tpu.module import Module
from mxnet_tpu.io import DataBatch


@pytest.fixture(autouse=True)
def _amp_isolation(monkeypatch):
    """Every test starts AMP-off with a fresh scaler.  The program
    cache is NOT cleared here: AMP binds key on the post-pass
    signature, so amp-on/amp-off entries never collide, and sharing
    compiled programs across tests keeps this file's wall time inside
    the tier-1 budget (tests needing a cold cache clear it
    themselves)."""
    monkeypatch.delenv("MXTPU_AMP", raising=False)
    monkeypatch.delenv("MXTPU_LOSS_SCALE", raising=False)
    monkeypatch.delenv("MXTPU_LOSS_SCALE_WINDOW", raising=False)
    amp.reset_scaler()
    yield
    amp.reset_scaler()


def _fill(ex, seed=7, nclass=4):
    rng = np.random.RandomState(seed)
    for k in sorted(ex.arg_dict):
        v = ex.arg_dict[k]
        if k == "softmax_label":
            v[:] = rng.randint(0, nclass, v.shape).astype(np.float32)
        elif k == "data" and len(v.shape) == 2:
            v[:] = rng.randint(0, 50, v.shape).astype(np.float32)
        else:
            v[:] = rng.uniform(-0.3, 0.3, v.shape).astype(np.float32)
    for k in sorted(ex.aux_dict):
        v = ex.aux_dict[k]
        v[:] = (rng.uniform(0.5, 1.5, v.shape) if "var" in k
                else rng.uniform(-0.1, 0.1, v.shape)).astype(np.float32)


# ---------------------------------------------------------------------------
# policy / pass behavior
# ---------------------------------------------------------------------------
def test_amp_off_is_bit_identical(monkeypatch):
    """AMP unset: the amp_cast pass is the IDENTITY (same symbol
    object, so post-pass signatures — the program-cache keys — cannot
    change), and two runs agree bitwise."""
    from mxnet_tpu.passes.amp_cast import amp_cast

    net, shapes = models.get_symbol(
        "resnet-8", num_classes=4, image_shape=(3, 8, 8)), \
        {"data": (4, 3, 8, 8), "softmax_label": (4,)}
    assert amp_cast(net) is net
    monkeypatch.setenv("MXTPU_AMP", "0")
    assert amp_cast(net) is net

    def run():
        mx.random.seed(0)
        ex = net.simple_bind(mx.cpu(), grad_req="write", **shapes)
        _fill(ex)
        ex.forward(is_train=True)
        ex.backward()
        return ([o.asnumpy() for o in ex.outputs],
                {k: g.asnumpy() for k, g in ex.grad_dict.items()
                 if g is not None})

    a, b = run(), run()
    for x, y in zip(a[0], b[0]):
        np.testing.assert_array_equal(x, y)
    for k in a[1]:
        np.testing.assert_array_equal(a[1][k], b[1][k])


def test_amp_cast_policy_structure(monkeypatch):
    """bf16 policy: MXU op inputs cast to bf16, loss/softmax inputs
    cast back to f32, labels untouched, cast count recorded."""
    from mxnet_tpu import telemetry as tm
    from mxnet_tpu import passes

    monkeypatch.setenv("MXTPU_AMP", "bf16")
    tm.reset()
    tm.enable()
    try:
        d = sym.Variable("data")
        c = sym.Convolution(d, num_filter=8, kernel=(3, 3), name="ac_c")
        b = sym.BatchNorm(c, fix_gamma=False, name="ac_b")
        f = sym.FullyConnected(sym.Flatten(b), num_hidden=4, name="ac_f")
        net = sym.SoftmaxOutput(f, label=sym.Variable("softmax_label"),
                                name="softmax")
        monkeypatch.setenv("MXTPU_GRAPH_PASSES", "amp_cast")
        out = passes.apply_graph_passes(net)
        casts = [n for n in out.nodes if n.op == "Cast"]
        dts = {str(n.attrs["dtype"]) for n in casts}
        assert dts == {"bfloat16", "float32"}
        # conv data+weight and fc data+weight+bias -> bf16 casts
        bf = [n for n in casts if str(n.attrs["dtype"]) == "bfloat16"]
        assert len(bf) >= 4
        # the softmax's DATA input is cast f32; its label variable is not
        soft = [n for n in out.nodes if n.op == "SoftmaxOutput"][0]
        data_src = soft.inputs[0][0]
        assert data_src.op == "Cast" \
            and str(data_src.attrs["dtype"]) == "float32"
        assert soft.inputs[1][0].is_variable
        fam = tm.get_registry().get("amp_cast_nodes_total")
        assert fam is not None and fam.total() >= len(casts)
    finally:
        tm.reset()
        tm.disable()


def test_amp_unknown_policy_raises(monkeypatch):
    monkeypatch.setenv("MXTPU_AMP", "fp8")
    with pytest.raises(mx.MXNetError):
        amp.amp_dtype()


# ---------------------------------------------------------------------------
# convergence parity (the acceptance bar): full Module/Executor/KVStore
# ---------------------------------------------------------------------------
def _train_module(net, data, labels, nclass, steps=8, lr=0.05,
                  optimizer="sgd", data_shape=None):
    mx.random.seed(0)
    mod = Module(net, context=[mx.cpu()])
    dshape = data_shape or data.shape
    mod.bind(data_shapes=[("data", dshape)],
             label_shapes=[("softmax_label", labels.shape)])
    mod.init_params(initializer=mx.init.Xavier(factor_type="in",
                                               magnitude=2.0))
    mod.init_optimizer(kvstore="local", optimizer=optimizer,
                       optimizer_params={"learning_rate": lr})
    batch = DataBatch(data=[nd.array(data)], label=[nd.array(labels)])
    losses = []
    for _ in range(steps):
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        p = mod.get_outputs()[0].asnumpy().astype(np.float64)
        p = p.reshape(len(labels), -1)
        losses.append(float(np.mean(
            -np.log(np.maximum(p[np.arange(len(labels)),
                                 labels.astype(int)], 1e-8)))))
    return losses


def test_amp_vs_fp32_convergence_resnet(monkeypatch):
    """ResNet-style conv net through Module: the bf16 AMP run tracks
    the fp32 run's loss trajectory and learns (loss drops)."""
    rng = np.random.RandomState(0)
    nclass = 4
    labels = rng.randint(0, nclass, 8)
    # separable blobs: per-class channel means + noise
    means = rng.uniform(-1, 1, (nclass, 3))
    data = (means[labels][:, :, None, None]
            + rng.uniform(-0.2, 0.2, (8, 3, 8, 8))).astype(np.float32)
    net = models.get_symbol("resnet-8", num_classes=nclass,
                            image_shape=(3, 8, 8))

    ref = _train_module(net, data, labels, nclass, steps=6)
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    got = _train_module(net, data, labels, nclass, steps=6)
    assert got[-1] < got[0], got  # AMP run learns
    # trajectory parity at bf16 tolerance
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.08)


def test_amp_vs_fp32_convergence_lm(monkeypatch):
    """Tiny transformer LM through Module with Adam: AMP tracks fp32."""
    V, T = 40, 8
    net = models.transformer.transformer_lm(
        num_layers=1, num_heads=2, d_model=16, seq_len=T, vocab_size=V)
    rng = np.random.RandomState(1)
    data = rng.randint(0, V, (4, T)).astype(np.float32)
    labels = np.roll(data, -1, axis=1)

    def run(steps=5):
        mx.random.seed(0)
        mod = Module(net, context=[mx.cpu()])
        mod.bind(data_shapes=[("data", data.shape)],
                 label_shapes=[("softmax_label", labels.shape)])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(kvstore="local", optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3})
        batch = DataBatch(data=[nd.array(data)], label=[nd.array(labels)])
        losses = []
        for _ in range(steps):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            p = mod.get_outputs()[0].asnumpy().astype(np.float64)
            p = p.reshape(-1, V)
            lab = labels.reshape(-1).astype(int)
            losses.append(float(np.mean(-np.log(np.maximum(
                p[np.arange(len(lab)), lab], 1e-8)))))
        return losses

    ref = run()
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    got = run()
    assert got[-1] < got[0]
    np.testing.assert_allclose(got, ref, rtol=0.12, atol=0.1)


# ---------------------------------------------------------------------------
# fp32 master weights
# ---------------------------------------------------------------------------
def test_multi_precision_eager_masters_match_fp32():
    """Optimizer(multi_precision=True): a bf16 weight updated eagerly
    through the master path tracks exact fp32 SGD math."""
    rng = np.random.RandomState(0)
    w0 = rng.uniform(-1, 1, (16, 4)).astype(np.float32)
    g = rng.uniform(-0.1, 0.1, (16, 4)).astype(np.float32)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              multi_precision=True)
    upd = mx.optimizer.get_updater(opt)
    w = nd.array(w0).astype(jnp.bfloat16)
    for _ in range(4):
        upd(0, nd.array(g).astype(jnp.bfloat16), w)
    state = upd.states[0]
    assert isinstance(state, tuple) and len(state) == 2
    assert np.dtype(state[-1].dtype) == np.float32  # the master
    # fp32 reference from the bf16-rounded start
    ref = np.asarray(jnp.asarray(w0).astype(jnp.bfloat16)).astype(np.float32)
    m = np.zeros_like(ref)
    g32 = np.asarray(jnp.asarray(g).astype(jnp.bfloat16)).astype(np.float32)
    for _ in range(4):
        m = 0.9 * m - 0.1 * g32
        ref = ref + m
    np.testing.assert_allclose(state[-1].asnumpy(), ref,
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(w.asnumpy().astype(np.float32), ref,
                               rtol=1e-2, atol=4e-3)


def test_warn_once_without_masters():
    """bf16 weights updating without masters warn exactly once per key."""
    amp.reset_scaler()
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    upd = mx.optimizer.get_updater(opt)
    w = nd.zeros((4, 4), dtype=jnp.bfloat16)
    g = nd.zeros((4, 4), dtype=jnp.bfloat16)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        upd(0, g, w)
        upd(0, g, w)
    msgs = [w_ for w_ in rec if "master" in str(w_.message)]
    assert len(msgs) == 1


def test_fused_bucket_masters_match_fp32(monkeypatch):
    """bf16 params through the fused kvstore buckets: fp32 masters in
    bucket state, update in fp32, bf16 cast emitted in-program."""
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    rng = np.random.RandomState(0)
    shapes = [(8, 4), (6,)]
    ws = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
    gs = [rng.uniform(-0.1, 0.1, s).astype(np.float32) for s in shapes]
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1,
                                         momentum=0.9))
    keys = [0, 1]
    kv.init(keys, [nd.array(w).astype(jnp.bfloat16) for w in ws])
    for _ in range(5):
        kv.push(keys, [[nd.array(g)] for g in gs])
    outs = [nd.zeros(s, dtype=jnp.bfloat16) for s in shapes]
    kv.pull(keys, outs)
    mem = kv._fused.state_memory()
    assert mem["master_bytes"] == sum(int(np.prod(s)) * 4 for s in shapes)
    for i, s in enumerate(shapes):
        ref = np.asarray(jnp.asarray(ws[i]).astype(
            jnp.bfloat16)).astype(np.float32)
        m = np.zeros_like(ref)
        for _ in range(5):
            m = 0.9 * m - 0.1 * gs[i]
            ref = ref + m
        np.testing.assert_allclose(outs[i].asnumpy().astype(np.float32),
                                   ref, rtol=1e-2, atol=4e-3)
        # the Updater's trailing state slot is the fp32 master
        master = kv._updater.states[i][-1]
        assert np.dtype(master.dtype) == np.float32
        np.testing.assert_allclose(master.asnumpy(), ref, rtol=1e-5,
                                   atol=1e-5)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_masters_one_over_n_bytes(monkeypatch):
    """8-replica sharded buckets hold 1/8 of the master bytes per
    replica (ISSUE-10 acceptance) and match the replicated program."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel.mesh import global_mesh

    monkeypatch.setenv("MXTPU_AMP", "bf16")
    mesh = global_mesh()
    repl = NamedSharding(mesh, P())
    rng = np.random.RandomState(3)
    shapes = [(64, 16), (33,), (17, 8)]
    ws = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
    gs = [rng.uniform(-0.1, 0.1, s).astype(np.float32) for s in shapes]
    keys = list(range(len(ws)))

    def run(shard):
        monkeypatch.setenv("MXTPU_SHARD_UPDATE", "1" if shard else "0")
        kv = mx.kv.create("local")
        kv.set_optimizer(mx.optimizer.create("adam", learning_rate=1e-2))
        kv.init(keys, [nd.array(w).astype(jnp.bfloat16) for w in ws])
        grads = [[nd.NDArray(jax.device_put(g, repl))] for g in gs] \
            if shard else [[nd.array(g)] for g in gs]
        for _ in range(4):
            kv.push(keys, grads)
        outs = [nd.zeros(s, dtype=jnp.bfloat16) for s in shapes]
        kv.pull(keys, outs)
        return kv._fused.state_memory(), [o.asnumpy().astype(np.float32)
                                          for o in outs]

    mem, outs = run(True)
    assert mem["sharded_buckets"] >= 1 and mem["replicas"] == 8
    total = sum(int(np.prod(s)) for s in shapes)
    padded = -(-total // 8) * 8
    assert mem["master_bytes"] == padded * 4
    assert mem["master_bytes_per_replica"] == padded * 4 // 8
    _, outs_repl = run(False)
    for a, b in zip(outs, outs_repl):
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=1e-2)


def test_sparse_bf16_table_fp32_master_rows(monkeypatch):
    """A bf16 row-sparse table keeps fp32 master rows: untouched rows
    (table AND master) byte-identical, touched bf16 rows within one
    bf16 ulp of cast(master)."""
    from mxnet_tpu import sparse

    monkeypatch.setenv("MXTPU_AMP", "bf16")
    rows, dim = 50, 8
    rng = np.random.RandomState(1)
    table = rng.uniform(-1, 1, (rows, dim)).astype(np.float32)
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("adam", learning_rate=0.05))
    kv.init(0, sparse.full_row_sparse(nd.array(table).astype(jnp.bfloat16)))
    idx = np.array([3, 7, 3, 20], np.int32)
    vals = rng.uniform(-1, 1, (4, dim)).astype(np.float32)
    g = sparse.RowSparseNDArray(nd.NDArray(jnp.asarray(idx)),
                                nd.NDArray(jnp.asarray(vals)), (rows, dim))
    before = kv._store[0].asnumpy().copy()
    for _ in range(3):
        kv.push([0], [g])
    after = kv._store[0].asnumpy()
    touched = sorted(set(idx.tolist()))
    untouched = [r for r in range(rows) if r not in touched]
    np.testing.assert_array_equal(before[untouched], after[untouched])
    master = kv._updater.states[0][-1]
    assert np.dtype(master.dtype) == np.float32
    mnp = master.asnumpy()
    np.testing.assert_array_equal(
        mnp[untouched],
        np.asarray(jnp.asarray(table[untouched]).astype(
            jnp.bfloat16)).astype(np.float32))
    cast = np.asarray(jnp.asarray(mnp[touched]).astype(
        jnp.bfloat16)).astype(np.float32)
    got = after[touched].astype(np.float32)
    # the delta-scatter re-aims at the master each step, so table rows
    # stay within ~an ulp of cast(master) — the ulp of the UPDATE's
    # magnitude, hence the small absolute slack for near-zero weights
    np.testing.assert_allclose(got, cast, rtol=2 ** -6, atol=2 ** -8)


# ---------------------------------------------------------------------------
# dynamic loss scaling
# ---------------------------------------------------------------------------
def _scaled_push(kv, keys, gs, inf_key=None):
    s = float(np.asarray(amp.global_scaler().scale_raw()))
    vals = []
    for i, g in enumerate(gs):
        arr = np.full(g.shape, np.inf, np.float32) if i == inf_key \
            else g * s
        vals.append([nd.array(arr)])
    kv.push(keys, vals)


def test_loss_scale_overflow_skip_recovery(monkeypatch):
    """The device-side lattice: grow after window clean steps, skip +
    halve on overflow, recover after."""
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    monkeypatch.setenv("MXTPU_LOSS_SCALE", "1024")
    monkeypatch.setenv("MXTPU_LOSS_SCALE_WINDOW", "2")
    amp.reset_scaler()
    rng = np.random.RandomState(0)
    shapes = [(8, 4), (6,)]
    ws = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
    gs = [rng.uniform(-0.1, 0.1, s).astype(np.float32) for s in shapes]
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
    keys = [0, 1]
    kv.init(keys, [nd.array(w).astype(jnp.bfloat16) for w in ws])
    for _ in range(2):
        _scaled_push(kv, keys, gs)
    rep = amp.global_scaler().report()
    assert rep["scale"] == 2048.0  # grew after the 2-step window
    assert rep["overflow_total"] == 0
    outs = [nd.zeros(s, dtype=jnp.bfloat16) for s in shapes]
    kv.pull(keys, outs)
    snap = [o.asnumpy().copy() for o in outs]
    # overflow in ONE bucket's grads
    _scaled_push(kv, keys, gs, inf_key=0)
    rep = amp.global_scaler().report()
    assert rep["scale"] == 1024.0  # halved
    assert rep["overflow_total"] == 1 and rep["skipped_steps_total"] == 1
    kv.pull(keys, outs)
    # the overflowed bucket held its weights (skip-step)
    np.testing.assert_array_equal(snap[0], outs[0].asnumpy())
    # recovery: clean steps keep training and re-grow the scale
    for _ in range(2):
        _scaled_push(kv, keys, gs)
    rep = amp.global_scaler().report()
    assert rep["scale"] == 2048.0
    kv.pull(keys, outs)
    assert not np.array_equal(snap[0], outs[0].asnumpy())


def test_zero_per_batch_host_sync_with_amp(monkeypatch):
    """Steady-state Module training with AMP + dynamic loss scaling
    performs ZERO per-batch host syncs of the scaler state: every
    report()/float() goes through LossScaler._sync_count, which must
    stay 0 across the loop (the acceptance counter)."""
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    monkeypatch.setenv("MXTPU_LOSS_SCALE", "dynamic")
    amp.reset_scaler()
    rng = np.random.RandomState(0)
    nclass = 4
    labels = rng.randint(0, nclass, 8)
    # same net/shapes as the convergence test: the fwd program entry is
    # shared through the program cache (only the loss-scaled fwdbwd
    # traces fresh), keeping this file's wall time down
    data = rng.uniform(-1, 1, (8, 3, 8, 8)).astype(np.float32)
    net = models.get_symbol("resnet-8", num_classes=nclass,
                            image_shape=(3, 8, 8))
    mod = Module(net, context=[mx.cpu()])
    mod.bind(data_shapes=[("data", data.shape)],
             label_shapes=[("softmax_label", labels.shape)])
    mod.init_params(initializer=mx.init.Xavier())
    # an EXPLICIT store: single-device Module defaults to the no-kvstore
    # eager updater (reference _create_kvstore rule) — the fused
    # in-trace scaling lattice is what this test must exercise
    mod.init_optimizer(kvstore=mx.kv.create("device"), optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    batch = DataBatch(data=[nd.array(data)], label=[nd.array(labels)])
    scaler = amp.global_scaler()
    base = scaler._sync_count
    for _ in range(5):
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    assert scaler._sync_count == base  # the whole loop synced NOTHING
    rep = scaler.report()              # the boundary read is explicit
    assert scaler._sync_count == base + 1
    assert rep["overflow_total"] == 0
    # the lattice actually ran: 5 clean steps counted device-side
    assert rep["good_steps"] == 5
    assert rep["scale"] >= 2 ** 15  # still the dynamic default (or grown)


def test_eager_fallback_unscales(monkeypatch):
    """A custom-updater (eager) path still sees UNSCALED gradients:
    Updater.__call__ divides by the live scale."""
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    monkeypatch.setenv("MXTPU_LOSS_SCALE", "512")
    amp.reset_scaler()
    opt = mx.optimizer.create("test")  # weight += grad * rescale
    upd = mx.optimizer.get_updater(opt)
    w = nd.zeros((4,))
    g = nd.array(np.ones(4, np.float32) * 512.0)  # "scaled" grad
    upd(0, g, w)
    np.testing.assert_allclose(w.asnumpy(), np.ones(4), rtol=1e-6)


# ---------------------------------------------------------------------------
# Pallas residual epilogue
# ---------------------------------------------------------------------------
def test_epilogue_pallas_vs_lax_fwd_bwd_parity():
    """The interpreted Pallas kernel and the lax lowering agree on
    forward AND all four gradients."""
    from mxnet_tpu.ops import residual_epilogue as re_mod

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, (2, 4, 4, 128)).astype(np.float32))
    s = jnp.asarray(rng.uniform(-1, 1, (2, 4, 4, 128)).astype(np.float32))
    scale = jnp.asarray(rng.uniform(0.5, 1.5, (128,)).astype(np.float32))
    bias = jnp.asarray(rng.uniform(-0.5, 0.5, (128,)).astype(np.float32))
    assert re_mod.supports(int(np.prod(x.shape[:-1])), x.shape[-1])

    def loss(impl):
        def f(x_, s_, sc_, b_):
            out = re_mod.residual_epilogue(x_, s_, sc_, b_,
                                           channel_axis=-1, impl=impl)
            return jnp.sum(out * jnp.cos(out))

        return f

    for impl in ("lax", "pallas_interpret"):
        outs = re_mod.residual_epilogue(x, s, scale, bias,
                                        channel_axis=-1, impl=impl)
        if impl == "lax":
            ref_out = outs
            ref_g = jax.grad(loss("lax"), argnums=(0, 1, 2, 3))(
                x, s, scale, bias)
        else:
            np.testing.assert_allclose(np.asarray(outs),
                                       np.asarray(ref_out),
                                       rtol=1e-6, atol=1e-6)
            got_g = jax.grad(loss("pallas_interpret"),
                             argnums=(0, 1, 2, 3))(x, s, scale, bias)
            for a, b in zip(ref_g, got_g):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-5)


def test_epilogue_shape_gate_falls_back():
    """Ragged shapes (C not a lane multiple) refuse the kernel even
    when forced, and still compute correctly via lax."""
    from mxnet_tpu.ops import residual_epilogue as re_mod

    assert not re_mod.supports(32, 100)
    x = jnp.ones((2, 3, 3, 100), jnp.float32)
    s = jnp.ones((2, 3, 3, 100), jnp.float32) * -0.5
    out = re_mod.residual_epilogue(x, s, channel_axis=-1, impl="pallas")
    np.testing.assert_allclose(np.asarray(out), np.full(x.shape, 0.5),
                               rtol=1e-6)


def test_epilogue_op_matches_unfused_composite(monkeypatch):
    """The _residual_epilogue_bn op replays the exact add+BN+relu
    composite in train mode: graph-level parity on a residual net (the
    pass's training_safe contract, exercised END to end through the
    executor including NHWC layout)."""
    d = sym.Variable("data")
    c1 = sym.Convolution(d, num_filter=8, kernel=(3, 3), pad=(1, 1),
                         no_bias=True, name="ep_c1")
    c2 = sym.Convolution(d, num_filter=8, kernel=(1, 1), no_bias=True,
                         name="ep_c2")
    added = c1 + c2
    bn = sym.BatchNorm(added, fix_gamma=False, name="ep_bn")
    r = sym.Activation(bn, act_type="relu", name="ep_r")
    # a plain relu(add) tail as well
    r2 = sym.Activation(c1 + c2, act_type="relu", name="ep_r2")
    net = sym.Group([r, r2])
    shapes = {"data": (2, 3, 8, 8)}

    def run(env):
        monkeypatch.setenv("MXTPU_GRAPH_PASSES", env)
        ex_mod.program_cache_clear()
        mx.random.seed(0)
        ex = net.simple_bind(mx.cpu(), grad_req="write", **shapes)
        _fill(ex)
        ex.forward(is_train=True)
        ex.backward([nd.ones(o.shape) for o in ex.outputs])
        return ([o.asnumpy() for o in ex.outputs],
                {k: g.asnumpy() for k, g in ex.grad_dict.items()})

    ref = run("off")
    got = run("residual_epilogue")
    # structural: the rewrite actually fused both patterns
    from mxnet_tpu import passes

    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "residual_epilogue")
    out = passes.apply_graph_passes(net)
    ops_after = [n.op for n in out.nodes if not n.is_variable]
    assert "_residual_epilogue_bn" in ops_after
    assert "_residual_epilogue" in ops_after
    assert "elemwise_add" not in ops_after
    for a, b in zip(ref[0], got[0]):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    for k in ref[1]:
        np.testing.assert_allclose(ref[1][k], got[1][k], rtol=1e-4,
                                   atol=1e-5, err_msg=k)


# ---------------------------------------------------------------------------
# satellites: tolerances / check_consistency threading
# ---------------------------------------------------------------------------
def test_assert_almost_equal_bf16_default_tols():
    from mxnet_tpu.test_utils import assert_almost_equal, default_tols

    a = jnp.asarray(np.linspace(0.1, 1.0, 16), jnp.bfloat16)
    b = jnp.asarray(np.asarray(a).astype(np.float32) * 1.004)
    # fp32-calibrated defaults would flag a 0.4% bf16 difference
    assert_almost_equal(np.asarray(a), np.asarray(b))
    r, t = default_tols(a, b)
    assert r >= 1e-2
    r32, _ = default_tols(np.zeros(2, np.float32))
    assert r32 == 1e-5
    with pytest.raises(AssertionError):
        assert_almost_equal(np.ones(4, np.float32),
                            np.ones(4, np.float32) * 1.004)


def test_check_consistency_threads_amp(monkeypatch):
    from mxnet_tpu.test_utils import check_consistency

    d = sym.Variable("data")
    f = sym.FullyConnected(d, num_hidden=8, name="cc_f")
    net = sym.Activation(f, act_type="tanh")
    seen = {}
    orig = sym.Symbol.simple_bind

    def spy(self, *a, **kw):
        seen["amp"] = os.environ.get("MXTPU_AMP")
        return orig(self, *a, **kw)

    monkeypatch.setattr(sym.Symbol, "simple_bind", spy)
    check_consistency(net, [{"ctx": mx.cpu(), "data": (4, 8)},
                            {"ctx": mx.cpu(), "data": (4, 8)}],
                      amp="bf16")
    assert seen["amp"] == "bf16"
    assert os.environ.get("MXTPU_AMP") is None  # restored
