"""Distributed kvstore tests — multi-process on localhost.

Parity model: tests/nightly/dist_sync_kvstore.py launched via
``tools/launch.py -n 2 --launcher local`` (reference test_all.sh:37):
real worker+server processes, deterministic PS-sync invariant asserted
inside each worker; the test passes iff every worker exits 0.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "launch.py")

SYNC_WORKER = textwrap.dedent("""
    import os
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create('dist_sync')
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw

    shape = (4, 5)
    big = (113, 97)  # > MXNET_KVSTORE_BIGARRAY_BOUND=1000 -> sharded over servers
    kv.init('w', mx.nd.ones(shape))
    kv.init('big', mx.nd.zeros(big))

    # aggregation-only sync mode: pull returns the sum over workers'
    # pushes.  NO per-round barrier + rank-skewed sleeps: a fast worker
    # laps the slow one, exercising the parked-pull round tracking
    # (a naive park-on-any-merge deadlocks here).
    import time
    expect = sum(r + 1 for r in range(nw))
    for i in range(4):
        time.sleep(0.2 * rank)
        kv.push('w', mx.nd.ones(shape) * (rank + 1))
        out = mx.nd.zeros(shape)
        kv.pull('w', out=out)
        assert np.allclose(out.asnumpy(), expect), (i, out.asnumpy()[0, 0], expect)
    kv.barrier()

    # big-array path: slices spread across both servers
    kv.push('big', mx.nd.ones(big) * (rank + 1))
    out = mx.nd.zeros(big)
    kv.pull('big', out=out)
    assert np.allclose(out.asnumpy(), 3.0), out.asnumpy().ravel()[:4]
    kv.barrier()

    # server-side optimizer (update_on_kvstore): weight -= lr * sum(grads)
    kv2_key = 'opt_w'
    kv.init(kv2_key, mx.nd.zeros(shape))
    kv.set_optimizer(mx.optimizer.create('sgd', learning_rate=0.1,
                                         rescale_grad=1.0))
    kv.push(kv2_key, mx.nd.ones(shape))
    out = mx.nd.zeros(shape)
    kv.pull(kv2_key, out=out)
    # one sync update on the merged grad (= nw): w = 0 - 0.1 * nw
    assert np.allclose(out.asnumpy(), -0.1 * nw, atol=1e-6), out.asnumpy()[0, 0]
    print('worker', rank, 'OK')
""")

ASYNC_WORKER = textwrap.dedent("""
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create('dist_async')
    shape = (3, 3)
    if kv.rank == 0:
        pass
    kv.init('a', mx.nd.zeros(shape))
    kv.set_optimizer(mx.optimizer.create('sgd', learning_rate=1.0,
                                         rescale_grad=1.0))
    kv.barrier()
    # async: every push applies immediately; after both workers push once
    # and barrier, the weight reflects both updates
    kv.push('a', mx.nd.ones(shape))
    kv.barrier()
    out = mx.nd.zeros(shape)
    kv.pull('a', out=out)
    assert np.allclose(out.asnumpy(), -2.0), out.asnumpy()[0, 0]
    print('worker', kv.rank, 'OK')
""")


def _launch(script, n=2, s=2, timeout=240, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MXTPU_PLATFORM"] = "cpu"  # keep subprocesses off the accelerator
    env["MXNET_KVSTORE_BIGARRAY_BOUND"] = "1000"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    path = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                        f"dist_worker_{os.getpid()}.py")
    with open(path, "w") as f:
        f.write(script)
    try:
        proc = subprocess.run(
            [sys.executable, LAUNCH, "-n", str(n), "-s", str(s),
             "--launcher", "local", sys.executable, path],
            env=env, timeout=timeout, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
    finally:
        os.unlink(path)


CRASH_WORKER = textwrap.dedent("""
    import os
    import time
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create('dist_async')
    shape = (3, 3)
    kv.init('a', mx.nd.zeros(shape))
    kv.barrier()
    kv.push('a', mx.nd.ones(shape))
    if kv.rank == 1:
        # simulate a crash: no kStopServer, no atexit, sockets just die
        os._exit(0)
    # rank 0: the cluster must keep working without rank 1
    for _ in range(3):
        kv.push('a', mx.nd.ones(shape))
        out = mx.nd.zeros(shape)
        kv.pull('a', out=out)
    # heartbeat staleness must surface the dead worker
    # (MXTPU_PS_DEAD_TIMEOUT_S=3 in the launcher env)
    deadline = time.monotonic() + 30
    n_dead = 0
    while time.monotonic() < deadline:
        n_dead = kv.get_num_dead_node(0, timeout=3)
        if n_dead == 1:
            break
        time.sleep(0.5)
    assert n_dead == 1, n_dead

    # recovery: a restarted worker joins with MXTPU_KV_RECOVERY=1 — init
    # must neither overwrite server state nor wait on the init barrier
    # (parity: kvstore_dist.h:35-39)
    os.environ['MXTPU_KV_RECOVERY'] = '1'
    kv2 = mx.kv.create('dist_async')
    kv2.init('a', mx.nd.zeros(shape))   # would hang/zero the model if not
    out = mx.nd.zeros(shape)
    kv2.pull('a', out=out)
    assert abs(out.asnumpy().sum()) > 0, "recovered init wiped the model"
    print('worker', kv.rank, 'OK')
""")


def test_dist_sync_kvstore():
    _launch(SYNC_WORKER, n=2, s=2)


def test_dist_async_kvstore():
    _launch(ASYNC_WORKER, n=2, s=1)


def test_dist_async_survives_worker_crash():
    """A crashed worker must not wedge the cluster: training continues,
    get_num_dead_node reports it, and servers stop on the survivors'
    request (parity: ps-lite heartbeat dead-node tracking,
    kvstore_dist.h:151-160)."""
    _launch(CRASH_WORKER, n=2, s=1,
            extra_env={"MXTPU_PS_DEAD_TIMEOUT_S": "3",
                       "MXTPU_PS_HEARTBEAT_S": "0.3"})


def test_push_returns_before_server_ack():
    """Comm/compute overlap (SURVEY §3.4): KVStoreDist.push must enqueue
    the RPC on the native host engine and return immediately; the pull's
    result must still be ordered after the push (same key var) and land
    lazily at the out array's next read."""
    import threading
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _native
    from mxnet_tpu.kvstore import KVStoreDist

    if not _native.available():
        pytest.skip("native engine library unavailable")

    class SlowClient:
        """PS client double: acks pushes after a visible delay."""

        def __init__(self):
            self.store = {}
            self.push_acked = threading.Event()

        def push(self, key, arr):
            time.sleep(0.4)
            self.store[key] = self.store.get(key, 0) + arr
            self.push_acked.set()

        def pull(self, key, shape, dtype):
            return np.asarray(self.store[key], dtype)

        def barrier(self):
            pass

    kv = KVStoreDist("dist_sync")  # no MXTPU_PS_SERVERS -> no transport
    kv._client = SlowClient()
    kv._engine = _native.NativeEngine()

    grad = mx.nd.ones((4, 5))
    t0 = time.perf_counter()
    kv.push("w", grad, priority=-1)
    returned = time.perf_counter() - t0
    assert returned < 0.2, f"push blocked for {returned:.3f}s"
    assert not kv._client.push_acked.is_set(), \
        "push must return BEFORE the server ack"

    out = mx.nd.zeros((4, 5))
    kv.pull("w", out=out, priority=-1)
    # value lands at the read (WaitToRead semantics), ordered after push
    np.testing.assert_allclose(out.asnumpy(), 1.0)
    assert kv._client.push_acked.is_set()
    kv._engine.wait_all()


def test_async_comm_emits_profiler_spans():
    """The engine-scheduled push/pull record kvstore spans so traces show
    comm overlapping compute."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _native, profiler
    from mxnet_tpu.kvstore import KVStoreDist

    if not _native.available():
        pytest.skip("native engine library unavailable")

    class Client:
        def __init__(self):
            self.store = {}

        def push(self, key, arr):
            self.store[key] = arr

        def pull(self, key, shape, dtype):
            return np.asarray(self.store[key], dtype)

        def barrier(self):
            pass

    kv = KVStoreDist("dist_sync")
    kv._client = Client()
    kv._engine = _native.NativeEngine()
    profiler.profiler_set_state("run")
    try:
        kv.push("p", mx.nd.ones((2, 2)))
        out = mx.nd.zeros((2, 2))
        kv.pull("p", out=out)
        out.asnumpy()
        kv._engine.wait_all()
        names = [e["name"] for e in profiler._events]
    finally:
        profiler.profiler_set_state("stop")
    assert any("kvstore_push[p]" in n for n in names), names
    assert any("kvstore_pull[p]" in n for n in names), names


def test_async_pull_write_ordering():
    """Engine-scheduled pulls into the SAME out array must land in push
    order even for DIFFERENT keys (per-chunk write-serialization var),
    and a host-side write must not be clobbered by a still-pending pull
    (NDArray._set resolves the chunk's host_waiter first)."""
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _native
    from mxnet_tpu.kvstore import KVStoreDist

    if not _native.available():
        pytest.skip("native engine library unavailable")

    class Client:
        """First key's pull is slow: without per-chunk ordering it would
        land after (and clobber) the second key's value."""

        def __init__(self):
            self.store = {}

        def push(self, key, arr):
            self.store[key] = arr

        def pull(self, key, shape, dtype):
            if key == "slow":
                time.sleep(0.25)
            return np.asarray(self.store[key], dtype)

        def barrier(self):
            pass

    kv = KVStoreDist("dist_sync")
    kv._client = Client()
    kv._engine = _native.NativeEngine()
    kv.push("slow", mx.nd.ones((2, 2)))
    kv.push("fast", mx.nd.ones((2, 2)) * 2)
    kv._engine.wait_all()

    # different keys, same out array: program order must win
    out = mx.nd.zeros((2, 2))
    kv.pull("slow", out=out, priority=-1)
    kv.pull("fast", out=out, priority=-1)
    np.testing.assert_allclose(out.asnumpy(), 2.0)

    # host write while a pull is in flight: the pull lands first, the
    # host write survives
    out2 = mx.nd.zeros((2, 2))
    kv.pull("slow", out=out2, priority=-1)
    out2[:] = 5.0
    np.testing.assert_allclose(out2.asnumpy(), 5.0)
    kv._engine.wait_all()
    np.testing.assert_allclose(out2.asnumpy(), 5.0)


COLLECTIVE_WORKER = textwrap.dedent("""
    import os
    # 4 virtual CPU devices per process -> 8-device global mesh over 2 procs
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.parallel import dist
    dist.init_from_env()          # jax.distributed from launcher env vars
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import sym
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.trainer import FusedTrainer

    net = sym.SoftmaxOutput(
        sym.FullyConnected(
            sym.Activation(sym.FullyConnected(
                sym.Variable("data"), num_hidden=16, name="fc1"),
                act_type="relu"),
            num_hidden=5, name="fc2"),
        sym.Variable("softmax_label"), name="softmax")

    rs = np.random.RandomState(7)
    feeds = [{"data": rs.uniform(-1, 1, (16, 8)).astype(np.float32),
              "softmax_label": rs.randint(0, 5, 16).astype(np.float32)}
             for _ in range(3)]

    def train(mesh):
        np.random.seed(0)
        mx.random.seed(0)
        tr = FusedTrainer(net, optimizer="sgd",
                          optimizer_params={"lr": 0.1, "momentum": 0.9},
                          mesh=mesh)
        tr.init(data=(16, 8), softmax_label=(16,))
        for f in feeds:
            tr.step(**f)
        return tr

    # dist_device_sync path: global data mesh spanning both processes,
    # gradients all-reduced by XLA over the process boundary
    tr_dist = train(create_mesh((8,), ("data",)))
    dist_params = {k: tr_dist._gather(v) for k, v in tr_dist.params.items()}

    # oracle: same batches, single process, no mesh
    tr_one = train(None)
    for k, v in tr_one.params.items():
        np.testing.assert_allclose(dist_params[k], np.asarray(v),
                                   rtol=1e-6, atol=1e-6, err_msg=k)
    dist.barrier()
    print("worker", dist.rank(), "OK")
""")


def test_collective_multiprocess():
    """Collective (dist_device_sync-parity) DP across REAL process
    boundaries: 2 processes x 4 CPU devices, jax.distributed wiring from
    tools/launch.py env, FusedTrainer over the global mesh — params after
    3 steps match a single-process run to 1e-6.  (The 8-CPU dryrun is
    single-process GSPMD; only this catches coordinator/process-group
    bugs.  Parity: tests/nightly/dist_sync_kvstore.py:30-45.)"""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    _launch(COLLECTIVE_WORKER, n=2, s=0, timeout=300,
            extra_env={"MXTPU_COORDINATOR": f"127.0.0.1:{port}",
                       "XLA_FLAGS": ""})


DPTP_WORKER = textwrap.dedent("""
    import os
    # 2 virtual devices per process -> 4-device global (2, 2) mesh: the
    # largest dp x tp layout the CPU gloo collectives run reliably (4
    # devices/process trips a gloo::EnforceNotMet abort in jaxlib
    # 0.4.36; bigger shapes belong to accelerator rigs)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.parallel import dist
    dist.init_from_env()
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, len(jax.devices())

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel.mesh import create_mesh, megatron_rules
    from mxnet_tpu.trainer import FusedTrainer

    lm = models.get_symbol("transformer-lm", num_layers=2, num_heads=2,
                           d_model=32, seq_len=16, num_classes=64)
    rs = np.random.RandomState(11)
    feeds = [{"data": rs.randint(0, 64, (8, 16)).astype(np.float32),
              "softmax_label": rs.randint(0, 64, (8, 16)).astype(np.float32)}
             for _ in range(2)]

    def train(mesh, rules):
        np.random.seed(0)
        mx.random.seed(0)
        # momentum SGD, not adam: the oracle compare needs an update rule
        # LINEAR in the gradients, so cross-process reduction-order float
        # noise stays ~1e-7 instead of being rsqrt-amplified
        tr = FusedTrainer(lm, optimizer="sgd",
                          optimizer_params={"lr": 0.05, "momentum": 0.9},
                          mesh=mesh, sharding_rules=rules)
        tr.init(data=(8, 16), softmax_label=(8, 16))
        for f in feeds:
            tr.step(**f)
        return tr

    # dp x tp across the process boundary: 'data' axis spans both
    # processes (2-way), 'model' axis is 2-way Megatron tensor
    # parallelism — qkv/ffn column-parallel, proj/ffn-out row-parallel,
    # vocab-sharded embed + head.  GSPMD must route grad all-reduces AND
    # tp collectives through the cross-process group correctly.
    mesh = create_mesh((2, 2), ("data", "model"))
    tr_tp = train(mesh, megatron_rules())
    tp_params = {k: tr_tp._gather(v) for k, v in tr_tp.params.items()}

    # dense single-process oracle
    tr_one = train(None, ())
    for k, v in tr_one.params.items():
        np.testing.assert_allclose(tp_params[k], np.asarray(v),
                                   rtol=1e-5, atol=1e-5, err_msg=k)
    dist.barrier()
    print("worker", dist.rank(), "OK")
""")


@pytest.mark.slow  # ~20s of multi-process jax bring-up; the plain DP
# collective test keeps the coordinator/process-group path in tier-1
def test_collective_multiprocess_dp_tp():
    """dp x tp ACROSS a real process boundary: 2 processes x 2 CPU
    devices, mesh (2, 2) ('data', 'model') with Megatron sharding rules
    on a transformer-LM — params after 2 momentum-SGD steps match the
    dense single-process oracle (SGD, not adam: the compare needs an
    update rule linear in the gradients).  Single-process GSPMD (dryrun 2b) cannot catch
    coordinator/process-group interactions with sharded params; this
    does.  Parity: tests/nightly/dist_sync_kvstore.py:30-45."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    _launch(DPTP_WORKER, n=2, s=0, timeout=400,
            extra_env={"MXTPU_COORDINATOR": f"127.0.0.1:{port}",
                       "XLA_FLAGS": ""})


# ---------------------------------------------------------------------------
# ISSUE 13: elastic multi-host runtime
# ---------------------------------------------------------------------------
def test_init_from_env_validation(monkeypatch):
    """A bad rank / coordinator used to surface as an opaque
    jax.distributed hang; now the env contract is validated first."""
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.parallel import dist

    monkeypatch.setenv("MXTPU_COORDINATOR", "127.0.0.1:9999")
    monkeypatch.setenv("MXTPU_NUM_WORKERS", "2")
    monkeypatch.setenv("MXTPU_RANK", "2")
    with pytest.raises(MXNetError, match="MXTPU_RANK=2 out of range"):
        dist.init_from_env()
    monkeypatch.setenv("MXTPU_RANK", "-1")
    with pytest.raises(MXNetError, match="out of range"):
        dist.init_from_env()
    monkeypatch.setenv("MXTPU_RANK", "zero")
    with pytest.raises(MXNetError, match="must be integers"):
        dist.init_from_env()
    monkeypatch.setenv("MXTPU_RANK", "0")
    for bad in ("localhost", "host:notaport", "host:0", ":8476"):
        monkeypatch.setenv("MXTPU_COORDINATOR", bad)
        with pytest.raises(MXNetError, match="host:port"):
            dist.init_from_env()


def test_barrier_watchdog_raises_named_host_lost(monkeypatch):
    """A dead peer parks sync_global_devices forever; the watchdog must
    surface HostLostError naming rank/generation within the timeout
    (the no-hang contract of docs/multihost.md)."""
    import time as _time

    import jax

    from mxnet_tpu.parallel import dist

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(dist, "_sync_global_devices",
                        lambda name: _time.sleep(60))
    monkeypatch.setenv("MXTPU_DIST_GENERATION", "7")
    t0 = _time.monotonic()
    with pytest.raises(dist.HostLostError) as ei:
        dist.barrier("t1_watchdog", timeout=0.3)
    assert _time.monotonic() - t0 < 10
    assert ei.value.site == "barrier"
    assert ei.value.generation == 7
    assert "timed out" in str(ei.value)
    # a healthy barrier under the watchdog passes and is timed
    monkeypatch.setattr(dist, "_sync_global_devices", lambda name: None)
    dist.barrier("t1_ok", timeout=5.0)


def test_barrier_fault_injection_drop(monkeypatch):
    """dist_barrier:drop = simulated dead peer without the wait."""
    import jax

    from mxnet_tpu import faults
    from mxnet_tpu.parallel import dist

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(dist, "_sync_global_devices", lambda name: None)
    monkeypatch.setenv("MXTPU_FAULT_PLAN", "dist_barrier:drop_first:1")
    faults.reset()
    try:
        with pytest.raises(dist.HostLostError, match="injected"):
            dist.barrier("t2")
        dist.barrier("t2")  # fails once, recovers
    finally:
        monkeypatch.delenv("MXTPU_FAULT_PLAN")
        faults.reset()


def test_collective_dist_sync_routes_through_fused_engine():
    """kv_type='dist_sync' WITHOUT MXTPU_PS_SERVERS is the collective
    store: batched push/pull ride the fused bucket engine (the
    cross-host all-reduce and 1/N update live in-trace), not the
    per-key PS priority loop."""
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    assert kv.collective
    assert kv.rank == 0 and kv.num_workers == 1
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1,
                                         rescale_grad=1.0))
    assert kv._fused is not None, \
        "collective dist_sync must build the fused update engine"
    kv.init([0, 1], [mx.nd.ones((4, 5)), mx.nd.ones((8,))])
    kv.push([0, 1], [[mx.nd.ones((4, 5))], [mx.nd.ones((8,))]])
    outs = [mx.nd.zeros((4, 5)), mx.nd.zeros((8,))]
    kv.pull([0, 1], outs)
    np.testing.assert_allclose(outs[0].asnumpy(), 0.9, rtol=1e-6)
    np.testing.assert_allclose(outs[1].asnumpy(), 0.9, rtol=1e-6)
    kv.barrier()  # single-process: no-op, no hang
    assert kv.get_num_dead_node(0) == 0
    # dist_async still needs the PS transport for its semantics
    kva = mx.kv.create("dist_async")
    assert not kva.collective


def test_collective_module_matches_device_store():
    """Module.fit over the collective dist_sync store trains the same
    trajectory as the 'device' store: the batched update path engages
    (one bucketed dispatch per step) and the math is the local fused
    update — cross-host is the same program over a bigger mesh."""
    import mxnet_tpu as mx
    from mxnet_tpu import io as mx_io, sym

    def run(kv_name):
        mx.random.seed(0)
        np.random.seed(0)
        X = np.random.RandomState(5).uniform(-1, 1, (64, 10)).astype(np.float32)
        Y = (X.sum(axis=1) > 0).astype(np.float32)
        train = mx_io.NDArrayIter(X, Y, batch_size=16)
        net = sym.SoftmaxOutput(
            sym.FullyConnected(sym.Variable("data"), num_hidden=4,
                               name="fc1"), name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu(0))
        kv = mx.kv.create(kv_name)
        mod.fit(train, optimizer="sgd", kvstore=kv,
                optimizer_params=(("learning_rate", 0.1),
                                  ("momentum", 0.9)), num_epoch=1)
        args, _ = mod.get_params()
        return kv, {k: v.asnumpy() for k, v in args.items()}

    kv_c, collective = run("dist_sync")
    assert kv_c.collective and kv_c._fused is not None
    _, device = run("device")
    for k in collective:
        np.testing.assert_allclose(collective[k], device[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


def test_kv_recovery_skips_reinit_and_rebarrier(monkeypatch):
    """ISSUE-13 satellite: a worker restarted with MXTPU_KV_RECOVERY=1
    must not re-init keys (the servers hold the model), must not enter
    the long-gone startup/init barriers, and must not re-ship the
    optimizer (parity: kvstore_dist.h:35-39)."""
    import mxnet_tpu as mx
    from mxnet_tpu.kvstore import KVStoreDist

    class RecordingClient:
        def __init__(self):
            self.calls = []

        def init(self, key, value):
            self.calls.append(("init", key))

        def barrier(self):
            self.calls.append(("barrier",))

        def control(self, head, body=None):
            self.calls.append(("control", head))

        def push(self, key, value):
            self.calls.append(("push", key))

        def pull(self, key, shape, dtype):
            self.calls.append(("pull", key))
            return np.zeros(shape, dtype)

    def make(recovery):
        if recovery:
            monkeypatch.setenv("MXTPU_KV_RECOVERY", "1")
        else:
            monkeypatch.delenv("MXTPU_KV_RECOVERY", raising=False)
        kv = KVStoreDist("dist_sync")  # no servers: no real transport
        kv._client = RecordingClient()
        kv._collective = False  # exercise the PS code paths
        return kv

    fresh = make(False)
    fresh.init("w", mx.nd.ones((2, 2)))
    fresh.set_optimizer(mx.optimizer.create("sgd"))
    assert ("init", "w") in fresh._client.calls
    assert ("barrier",) in fresh._client.calls
    assert any(c[0] == "control" for c in fresh._client.calls)

    recovered = make(True)
    recovered.init("w", mx.nd.ones((2, 2)))
    recovered.set_optimizer(mx.optimizer.create("sgd"))
    assert recovered._recovery
    assert recovered._client.calls == [], (
        "a recovered worker re-ran startup RPCs: "
        f"{recovered._client.calls}")
    # recovery still pulls the live model — only startup is skipped
    out = mx.nd.zeros((2, 2))
    recovered.pull("w", out=out)
    assert ("pull", "w") in recovered._client.calls


def test_launch_max_restarts(tmp_path):
    """ISSUE-13 satellite: the local launcher restarts a crashed worker
    with MXTPU_KV_RECOVERY=1 up to --max-restarts times, logging rank
    and exit code."""
    marker = tmp_path / "crashed_once"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, pathlib, sys
        marker = pathlib.Path({str(marker)!r})
        if os.environ.get("MXTPU_RANK") == "1" and not marker.exists():
            marker.write_text("x")
            sys.exit(9)          # first life crashes
        if marker.exists() and os.environ.get("MXTPU_RANK") == "1":
            # second life must carry the recovery flag
            assert os.environ.get("MXTPU_KV_RECOVERY") == "1", os.environ
        sys.exit(0)
    """))
    proc = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "-s", "0",
         "--max-restarts", "1", "--launcher", "local",
         sys.executable, str(script)],
        timeout=120, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    log = proc.stdout + proc.stderr
    assert "worker 1 exited with code 9" in log
    assert "MXTPU_KV_RECOVERY=1" in log


ELASTIC_WORKER = textwrap.dedent("""
    import os, sys
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2")
    slot = int(os.environ["MXTPU_ELASTIC_SLOT"])
    gen = int(os.environ["MXTPU_DIST_GENERATION"])
    if slot == 1 and gen == 0:
        # the victim: a SIGKILL-shaped death fired from the per-step
        # membership poll a few batches into the first generation
        os.environ["MXTPU_FAULT_PLAN"] = "host_crash:crash_after:6"
    os.environ["MXTPU_ASYNC_DEPTH"] = "1"  # deterministic window
    import jax
    jax.config.update("jax_platforms", "cpu")

    # NB: each "host" trains on its LOCAL 2-device mesh over the SAME
    # replicated global batch schedule — mathematically identical to
    # the cross-host collective run (pinned separately by
    # test_collective_multiprocess*), without riding the CPU gloo
    # fabric, whose context races (see docs/multihost.md, launch.py
    # --fabric-retries) would make a chaos test nondeterministic.
    # The ELASTIC machinery under test — coordinator leases,
    # generation epochs, kill detection, boundary checkpoints,
    # shrink/rejoin relaunch, resume re-bind — is fully real.
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import io as mx_io, sym
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.trainer import FusedTrainer

    OUT = os.environ["ELASTIC_OUT"]
    net = sym.SoftmaxOutput(
        sym.FullyConnected(
            sym.Activation(sym.FullyConnected(
                sym.Variable("data"), num_hidden=16, name="fc1"),
                act_type="relu"),
            num_hidden=5, name="fc2"),
        sym.Variable("softmax_label"), name="softmax")

    rs = np.random.RandomState(11)
    X = rs.uniform(-1, 1, (192, 8)).astype(np.float32)
    Y = rs.randint(0, 5, 192).astype(np.float32)

    def main():
        np.random.seed(0)
        mx.random.seed(0)
        mesh = create_mesh((2,), ("data",))
        tr = FusedTrainer(net, optimizer="sgd",
                          optimizer_params={"lr": 0.1, "momentum": 0.9},
                          mesh=mesh)
        train = mx_io.NDArrayIter(X, Y, batch_size=8)
        tr.fit(train, num_epoch=40, resume=True)
        host = {k: np.asarray(v) for k, v in tr.params.items()}
        np.savez(os.path.join(OUT, f"params_slot{slot}.npz"), **host)

    dist.elastic_main(main)
    print("worker", slot, "generation", gen, "DONE", flush=True)
""")


@pytest.mark.slow  # 3 process generations + lease/watchdog waits (~1-2 min)
def test_elastic_generation_cycle(tmp_path):
    """ISSUE-13 acceptance: 2 hosts x 2 devices, SIGKILL-shaped death
    mid-epoch -> the coordinator's lease expires, the survivor leaves at
    a checkpoint boundary (or via the wedge watchdog), the launcher
    relaunches the SHRUNK world which resumes and keeps training, the
    killed slot rejoins at the next generation re-expanding the world,
    and the final params match an uninterrupted single-process run of
    the same global batch schedule to collective-reduction tolerance."""
    out = tmp_path / "out"
    ckpt = tmp_path / "ckpt"
    out.mkdir()
    ckpt.mkdir()
    script = tmp_path / "elastic_worker.py"
    script.write_text(ELASTIC_WORKER)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MXTPU_PLATFORM": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "ELASTIC_OUT": str(out),
        "MXTPU_CKPT_DIR": str(ckpt),
        "MXTPU_CKPT_EVERY": "2",
        "MXTPU_COORD_LEASE_S": "1.0",
        "MXTPU_DIST_BARRIER_TIMEOUT_S": "8",
        "XLA_FLAGS": "",
    })
    proc = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--max-restarts", "1",
         "--launcher", "elastic", "--rejoin-progress", "3",
         "--exit-grace", "60", sys.executable, str(script)],
        env=env, timeout=600, capture_output=True, text=True)
    log = proc.stdout + proc.stderr
    assert proc.returncode == 0, log[-4000:]
    # the lifecycle actually happened: crash -> shrunk world -> rejoin
    assert "slot 1 crashed with exit code 137" in log, log[-4000:]
    assert "generation 1: world=[0]" in log, log[-4000:]
    assert "announced rejoin of slot 1" in log, log[-4000:]
    assert "generation 2: world=[0, 1]" in log, log[-4000:]

    # oracle: uninterrupted run of the same schedule, single process
    oracle_env = dict(os.environ)
    oracle_out = tmp_path / "oracle"
    oracle_out.mkdir()
    oracle_env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep
        + oracle_env.get("PYTHONPATH", ""),
        "ELASTIC_OUT": str(oracle_out),
        "MXTPU_ELASTIC_SLOT": "0",
        "MXTPU_DIST_GENERATION": "0",
        "MXTPU_CKPT_DIR": str(tmp_path / "oracle_ckpt"),
        "XLA_FLAGS": "",
    })
    oproc = subprocess.run([sys.executable, str(script)], env=oracle_env,
                           timeout=300, capture_output=True, text=True)
    assert oproc.returncode == 0, oproc.stdout + oproc.stderr

    final = np.load(out / "params_slot0.npz")
    oracle = np.load(oracle_out / "params_slot0.npz")
    assert set(final.files) == set(oracle.files)
    for k in final.files:
        np.testing.assert_allclose(final[k], oracle[k], rtol=1e-5,
                                   atol=1e-5, err_msg=k)


def test_collective_steady_loop_zero_per_batch_syncs(monkeypatch):
    """ISSUE-13 acceptance: the collective dist_sync steady loop keeps
    the zero-per-batch-host-sync property — with fused metrics, host
    syncs do NOT grow with batch count (the bucketed update dispatch,
    in-trace all-reduce, and the coordinator poll are all sync-free;
    the static half of this guarantee is tools/lint.py over
    analysis/config.py:ENTRY_POINTS)."""
    import mxnet_tpu as mx
    from mxnet_tpu import engine
    from mxnet_tpu import io as mx_io, nd, sym

    counts = {"n": 0}
    orig_asnumpy = nd.NDArray.asnumpy
    orig_wait = engine.wait_for_var

    def counted_asnumpy(self):
        counts["n"] += 1
        return orig_asnumpy(self)

    def counted_wait(arr):
        counts["n"] += 1
        return orig_wait(arr)

    def run(nbatch):
        counts["n"] = 0
        rs = np.random.RandomState(9)
        X = rs.uniform(-1, 1, (16 * nbatch, 10)).astype(np.float32)
        Y = (X.sum(axis=1) > 0).astype(np.float32)
        train = mx_io.NDArrayIter(X, Y, batch_size=16, shuffle=False)
        net = sym.SoftmaxOutput(
            sym.FullyConnected(sym.Variable("data"), num_hidden=4,
                               name="zfc"), name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu(0))
        kv = mx.kv.create("dist_sync")
        assert kv.collective
        mod.fit(train, optimizer="sgd", kvstore=kv,
                optimizer_params=(("learning_rate", 0.1),), num_epoch=1)
        return counts["n"]

    monkeypatch.setattr(nd.NDArray, "asnumpy", counted_asnumpy)
    monkeypatch.setattr(engine, "wait_for_var", counted_wait)
    small = run(4)
    large = run(16)
    assert large == small, (small, large)
