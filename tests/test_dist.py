"""Distributed kvstore tests — multi-process on localhost.

Parity model: tests/nightly/dist_sync_kvstore.py launched via
``tools/launch.py -n 2 --launcher local`` (reference test_all.sh:37):
real worker+server processes, deterministic PS-sync invariant asserted
inside each worker; the test passes iff every worker exits 0.
"""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "launch.py")

SYNC_WORKER = textwrap.dedent("""
    import os
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create('dist_sync')
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw

    shape = (4, 5)
    big = (113, 97)  # > MXNET_KVSTORE_BIGARRAY_BOUND=1000 -> sharded over servers
    kv.init('w', mx.nd.ones(shape))
    kv.init('big', mx.nd.zeros(big))

    # aggregation-only sync mode: pull returns the sum over workers'
    # pushes.  NO per-round barrier + rank-skewed sleeps: a fast worker
    # laps the slow one, exercising the parked-pull round tracking
    # (a naive park-on-any-merge deadlocks here).
    import time
    expect = sum(r + 1 for r in range(nw))
    for i in range(4):
        time.sleep(0.2 * rank)
        kv.push('w', mx.nd.ones(shape) * (rank + 1))
        out = mx.nd.zeros(shape)
        kv.pull('w', out=out)
        assert np.allclose(out.asnumpy(), expect), (i, out.asnumpy()[0, 0], expect)
    kv.barrier()

    # big-array path: slices spread across both servers
    kv.push('big', mx.nd.ones(big) * (rank + 1))
    out = mx.nd.zeros(big)
    kv.pull('big', out=out)
    assert np.allclose(out.asnumpy(), 3.0), out.asnumpy().ravel()[:4]
    kv.barrier()

    # server-side optimizer (update_on_kvstore): weight -= lr * sum(grads)
    kv2_key = 'opt_w'
    kv.init(kv2_key, mx.nd.zeros(shape))
    kv.set_optimizer(mx.optimizer.create('sgd', learning_rate=0.1,
                                         rescale_grad=1.0))
    kv.push(kv2_key, mx.nd.ones(shape))
    out = mx.nd.zeros(shape)
    kv.pull(kv2_key, out=out)
    # one sync update on the merged grad (= nw): w = 0 - 0.1 * nw
    assert np.allclose(out.asnumpy(), -0.1 * nw, atol=1e-6), out.asnumpy()[0, 0]
    print('worker', rank, 'OK')
""")

ASYNC_WORKER = textwrap.dedent("""
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create('dist_async')
    shape = (3, 3)
    if kv.rank == 0:
        pass
    kv.init('a', mx.nd.zeros(shape))
    kv.set_optimizer(mx.optimizer.create('sgd', learning_rate=1.0,
                                         rescale_grad=1.0))
    kv.barrier()
    # async: every push applies immediately; after both workers push once
    # and barrier, the weight reflects both updates
    kv.push('a', mx.nd.ones(shape))
    kv.barrier()
    out = mx.nd.zeros(shape)
    kv.pull('a', out=out)
    assert np.allclose(out.asnumpy(), -2.0), out.asnumpy()[0, 0]
    print('worker', kv.rank, 'OK')
""")


def _launch(script, n=2, s=2, timeout=240, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MXTPU_PLATFORM"] = "cpu"  # keep subprocesses off the accelerator
    env["MXNET_KVSTORE_BIGARRAY_BOUND"] = "1000"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    path = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                        f"dist_worker_{os.getpid()}.py")
    with open(path, "w") as f:
        f.write(script)
    try:
        proc = subprocess.run(
            [sys.executable, LAUNCH, "-n", str(n), "-s", str(s),
             "--launcher", "local", sys.executable, path],
            env=env, timeout=timeout, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
    finally:
        os.unlink(path)


CRASH_WORKER = textwrap.dedent("""
    import os
    import time
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create('dist_async')
    shape = (3, 3)
    kv.init('a', mx.nd.zeros(shape))
    kv.barrier()
    kv.push('a', mx.nd.ones(shape))
    if kv.rank == 1:
        # simulate a crash: no kStopServer, no atexit, sockets just die
        os._exit(0)
    # rank 0: the cluster must keep working without rank 1
    for _ in range(3):
        kv.push('a', mx.nd.ones(shape))
        out = mx.nd.zeros(shape)
        kv.pull('a', out=out)
    # heartbeat staleness must surface the dead worker
    # (MXTPU_PS_DEAD_TIMEOUT_S=3 in the launcher env)
    deadline = time.monotonic() + 30
    n_dead = 0
    while time.monotonic() < deadline:
        n_dead = kv.get_num_dead_node(0, timeout=3)
        if n_dead == 1:
            break
        time.sleep(0.5)
    assert n_dead == 1, n_dead

    # recovery: a restarted worker joins with MXTPU_KV_RECOVERY=1 — init
    # must neither overwrite server state nor wait on the init barrier
    # (parity: kvstore_dist.h:35-39)
    os.environ['MXTPU_KV_RECOVERY'] = '1'
    kv2 = mx.kv.create('dist_async')
    kv2.init('a', mx.nd.zeros(shape))   # would hang/zero the model if not
    out = mx.nd.zeros(shape)
    kv2.pull('a', out=out)
    assert abs(out.asnumpy().sum()) > 0, "recovered init wiped the model"
    print('worker', kv.rank, 'OK')
""")


def test_dist_sync_kvstore():
    _launch(SYNC_WORKER, n=2, s=2)


def test_dist_async_kvstore():
    _launch(ASYNC_WORKER, n=2, s=1)


def test_dist_async_survives_worker_crash():
    """A crashed worker must not wedge the cluster: training continues,
    get_num_dead_node reports it, and servers stop on the survivors'
    request (parity: ps-lite heartbeat dead-node tracking,
    kvstore_dist.h:151-160)."""
    _launch(CRASH_WORKER, n=2, s=1,
            extra_env={"MXTPU_PS_DEAD_TIMEOUT_S": "3",
                       "MXTPU_PS_HEARTBEAT_S": "0.3"})


def test_push_returns_before_server_ack():
    """Comm/compute overlap (SURVEY §3.4): KVStoreDist.push must enqueue
    the RPC on the native host engine and return immediately; the pull's
    result must still be ordered after the push (same key var) and land
    lazily at the out array's next read."""
    import threading
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _native
    from mxnet_tpu.kvstore import KVStoreDist

    if not _native.available():
        pytest.skip("native engine library unavailable")

    class SlowClient:
        """PS client double: acks pushes after a visible delay."""

        def __init__(self):
            self.store = {}
            self.push_acked = threading.Event()

        def push(self, key, arr):
            time.sleep(0.4)
            self.store[key] = self.store.get(key, 0) + arr
            self.push_acked.set()

        def pull(self, key, shape, dtype):
            return np.asarray(self.store[key], dtype)

        def barrier(self):
            pass

    kv = KVStoreDist("dist_sync")  # no MXTPU_PS_SERVERS -> no transport
    kv._client = SlowClient()
    kv._engine = _native.NativeEngine()

    grad = mx.nd.ones((4, 5))
    t0 = time.perf_counter()
    kv.push("w", grad, priority=-1)
    returned = time.perf_counter() - t0
    assert returned < 0.2, f"push blocked for {returned:.3f}s"
    assert not kv._client.push_acked.is_set(), \
        "push must return BEFORE the server ack"

    out = mx.nd.zeros((4, 5))
    kv.pull("w", out=out, priority=-1)
    # value lands at the read (WaitToRead semantics), ordered after push
    np.testing.assert_allclose(out.asnumpy(), 1.0)
    assert kv._client.push_acked.is_set()
    kv._engine.wait_all()


def test_async_comm_emits_profiler_spans():
    """The engine-scheduled push/pull record kvstore spans so traces show
    comm overlapping compute."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _native, profiler
    from mxnet_tpu.kvstore import KVStoreDist

    if not _native.available():
        pytest.skip("native engine library unavailable")

    class Client:
        def __init__(self):
            self.store = {}

        def push(self, key, arr):
            self.store[key] = arr

        def pull(self, key, shape, dtype):
            return np.asarray(self.store[key], dtype)

        def barrier(self):
            pass

    kv = KVStoreDist("dist_sync")
    kv._client = Client()
    kv._engine = _native.NativeEngine()
    profiler.profiler_set_state("run")
    try:
        kv.push("p", mx.nd.ones((2, 2)))
        out = mx.nd.zeros((2, 2))
        kv.pull("p", out=out)
        out.asnumpy()
        kv._engine.wait_all()
        names = [e["name"] for e in profiler._events]
    finally:
        profiler.profiler_set_state("stop")
    assert any("kvstore_push[p]" in n for n in names), names
    assert any("kvstore_pull[p]" in n for n in names), names


def test_async_pull_write_ordering():
    """Engine-scheduled pulls into the SAME out array must land in push
    order even for DIFFERENT keys (per-chunk write-serialization var),
    and a host-side write must not be clobbered by a still-pending pull
    (NDArray._set resolves the chunk's host_waiter first)."""
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _native
    from mxnet_tpu.kvstore import KVStoreDist

    if not _native.available():
        pytest.skip("native engine library unavailable")

    class Client:
        """First key's pull is slow: without per-chunk ordering it would
        land after (and clobber) the second key's value."""

        def __init__(self):
            self.store = {}

        def push(self, key, arr):
            self.store[key] = arr

        def pull(self, key, shape, dtype):
            if key == "slow":
                time.sleep(0.25)
            return np.asarray(self.store[key], dtype)

        def barrier(self):
            pass

    kv = KVStoreDist("dist_sync")
    kv._client = Client()
    kv._engine = _native.NativeEngine()
    kv.push("slow", mx.nd.ones((2, 2)))
    kv.push("fast", mx.nd.ones((2, 2)) * 2)
    kv._engine.wait_all()

    # different keys, same out array: program order must win
    out = mx.nd.zeros((2, 2))
    kv.pull("slow", out=out, priority=-1)
    kv.pull("fast", out=out, priority=-1)
    np.testing.assert_allclose(out.asnumpy(), 2.0)

    # host write while a pull is in flight: the pull lands first, the
    # host write survives
    out2 = mx.nd.zeros((2, 2))
    kv.pull("slow", out=out2, priority=-1)
    out2[:] = 5.0
    np.testing.assert_allclose(out2.asnumpy(), 5.0)
    kv._engine.wait_all()
    np.testing.assert_allclose(out2.asnumpy(), 5.0)


COLLECTIVE_WORKER = textwrap.dedent("""
    import os
    # 4 virtual CPU devices per process -> 8-device global mesh over 2 procs
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.parallel import dist
    dist.init_from_env()          # jax.distributed from launcher env vars
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import sym
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.trainer import FusedTrainer

    net = sym.SoftmaxOutput(
        sym.FullyConnected(
            sym.Activation(sym.FullyConnected(
                sym.Variable("data"), num_hidden=16, name="fc1"),
                act_type="relu"),
            num_hidden=5, name="fc2"),
        sym.Variable("softmax_label"), name="softmax")

    rs = np.random.RandomState(7)
    feeds = [{"data": rs.uniform(-1, 1, (16, 8)).astype(np.float32),
              "softmax_label": rs.randint(0, 5, 16).astype(np.float32)}
             for _ in range(3)]

    def train(mesh):
        np.random.seed(0)
        mx.random.seed(0)
        tr = FusedTrainer(net, optimizer="sgd",
                          optimizer_params={"lr": 0.1, "momentum": 0.9},
                          mesh=mesh)
        tr.init(data=(16, 8), softmax_label=(16,))
        for f in feeds:
            tr.step(**f)
        return tr

    # dist_device_sync path: global data mesh spanning both processes,
    # gradients all-reduced by XLA over the process boundary
    tr_dist = train(create_mesh((8,), ("data",)))
    dist_params = {k: tr_dist._gather(v) for k, v in tr_dist.params.items()}

    # oracle: same batches, single process, no mesh
    tr_one = train(None)
    for k, v in tr_one.params.items():
        np.testing.assert_allclose(dist_params[k], np.asarray(v),
                                   rtol=1e-6, atol=1e-6, err_msg=k)
    dist.barrier()
    print("worker", dist.rank(), "OK")
""")


def test_collective_multiprocess():
    """Collective (dist_device_sync-parity) DP across REAL process
    boundaries: 2 processes x 4 CPU devices, jax.distributed wiring from
    tools/launch.py env, FusedTrainer over the global mesh — params after
    3 steps match a single-process run to 1e-6.  (The 8-CPU dryrun is
    single-process GSPMD; only this catches coordinator/process-group
    bugs.  Parity: tests/nightly/dist_sync_kvstore.py:30-45.)"""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    _launch(COLLECTIVE_WORKER, n=2, s=0, timeout=300,
            extra_env={"MXTPU_COORDINATOR": f"127.0.0.1:{port}",
                       "XLA_FLAGS": ""})


DPTP_WORKER = textwrap.dedent("""
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.parallel import dist
    dist.init_from_env()
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel.mesh import create_mesh, megatron_rules
    from mxnet_tpu.trainer import FusedTrainer

    lm = models.get_symbol("transformer-lm", num_layers=2, num_heads=2,
                           d_model=32, seq_len=16, num_classes=64)
    rs = np.random.RandomState(11)
    feeds = [{"data": rs.randint(0, 64, (8, 16)).astype(np.float32),
              "softmax_label": rs.randint(0, 64, (8, 16)).astype(np.float32)}
             for _ in range(2)]

    def train(mesh, rules):
        np.random.seed(0)
        mx.random.seed(0)
        # momentum SGD, not adam: the oracle compare needs an update rule
        # LINEAR in the gradients, so cross-process reduction-order float
        # noise stays ~1e-7 instead of being rsqrt-amplified
        tr = FusedTrainer(lm, optimizer="sgd",
                          optimizer_params={"lr": 0.05, "momentum": 0.9},
                          mesh=mesh, sharding_rules=rules)
        tr.init(data=(8, 16), softmax_label=(8, 16))
        for f in feeds:
            tr.step(**f)
        return tr

    # dp x tp across the process boundary: 'data' axis spans both
    # processes (4-way), 'model' axis is 2-way Megatron tensor
    # parallelism — qkv/ffn column-parallel, proj/ffn-out row-parallel,
    # vocab-sharded embed + head.  GSPMD must route grad all-reduces AND
    # tp collectives through the cross-process group correctly.
    mesh = create_mesh((4, 2), ("data", "model"))
    tr_tp = train(mesh, megatron_rules())
    tp_params = {k: tr_tp._gather(v) for k, v in tr_tp.params.items()}

    # dense single-process oracle
    tr_one = train(None, ())
    for k, v in tr_one.params.items():
        np.testing.assert_allclose(tp_params[k], np.asarray(v),
                                   rtol=1e-5, atol=1e-5, err_msg=k)
    dist.barrier()
    print("worker", dist.rank(), "OK")
""")


def test_collective_multiprocess_dp_tp():
    """dp x tp ACROSS a real process boundary: 2 processes x 4 CPU
    devices, mesh (4, 2) ('data', 'model') with Megatron sharding rules
    on a transformer-LM — params after 2 momentum-SGD steps match the
    dense single-process oracle (SGD, not adam: the compare needs an
    update rule linear in the gradients).  Single-process GSPMD (dryrun 2b) cannot catch
    coordinator/process-group interactions with sharded params; this
    does.  Parity: tests/nightly/dist_sync_kvstore.py:30-45."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    _launch(DPTP_WORKER, n=2, s=0, timeout=400,
            extra_env={"MXTPU_COORDINATOR": f"127.0.0.1:{port}",
                       "XLA_FLAGS": ""})
