// C++ unit tests for the native host runtime (parity: the reference's
// tests/cpp/threaded_engine_test.cc + storage_test.cc, SURVEY.md §4.1).
//
// Plain-assert binary (no gtest in the image) driving libmxtpu.so
// directly:
//  - engine: writer serialization per var, reader parallelism, priority
//    acceptance, dependency-ordering stress over random var sets,
//    CheckDuplicate rejection, wait_for_var/wait_all semantics
//  - storage arena: pow2 size-class recycling, pool accounting,
//    direct-free bypass, release_all
//
// Built+run by tests/test_native_cpp.py.
#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <algorithm>
#include <random>
#include <vector>

#include "mxtpu.h"

namespace {

struct SeqCtx {
  std::atomic<int64_t> *order;
  int64_t id;
};

void record_order(void *raw) {
  SeqCtx *c = static_cast<SeqCtx *>(raw);
  // writers on one var must observe strictly increasing ids
  int64_t prev = c->order->load();
  assert(prev == c->id - 1);
  c->order->store(c->id);
}

void count_up(void *raw) {
  static_cast<std::atomic<int64_t> *>(raw)->fetch_add(1);
}

void engine_writer_serialization() {
  void *eng = mxe_create(4);
  int64_t var = mxe_new_var(eng);
  std::atomic<int64_t> order{0};
  std::vector<SeqCtx> ctxs(200);
  for (int64_t i = 0; i < 200; ++i) {
    ctxs[i] = {&order, i + 1};
    int rc = mxe_push(eng, record_order, &ctxs[i], nullptr, 0, &var, 1, 0);
    assert(rc == 0);
  }
  mxe_wait_for_var(eng, var);
  assert(order.load() == 200);
  mxe_destroy(eng);
  std::printf("engine_writer_serialization OK\n");
}

void engine_reader_parallel_and_priority() {
  void *eng = mxe_create(4);
  int64_t var = mxe_new_var(eng);
  std::atomic<int64_t> done{0};
  // readers share the var concurrently; priority values must be accepted
  for (int i = 0; i < 64; ++i) {
    int rc = mxe_push(eng, count_up, &done, &var, 1, nullptr, 0, -i);
    assert(rc == 0);
  }
  mxe_wait_all(eng);
  assert(done.load() == 64);
  assert(mxe_pending(eng) == 0);
  mxe_destroy(eng);
  std::printf("engine_reader_parallel_and_priority OK\n");
}

void engine_duplicate_vars_rejected() {
  void *eng = mxe_create(2);
  int64_t var = mxe_new_var(eng);
  std::atomic<int64_t> done{0};
  int64_t both[1] = {var};
  // same var as const AND mutable: CheckDuplicate parity -> error
  int rc = mxe_push(eng, count_up, &done, both, 1, both, 1, 0);
  assert(rc != 0);
  mxe_destroy(eng);
  std::printf("engine_duplicate_vars_rejected OK\n");
}

struct StressCtx {
  std::vector<std::atomic<int64_t>> *vals;
  std::vector<int> reads, writes;
};

void stress_fn(void *raw) {
  StressCtx *c = static_cast<StressCtx *>(raw);
  int64_t sum = 0;
  for (int r : c->reads) sum += (*c->vals)[r].load();
  for (int w : c->writes) (*c->vals)[w].fetch_add(1 + (sum & 1));
}

void engine_dependency_stress() {
  // random const/mutable var sets (the reference's de-facto race test):
  // per-var write counts must equal the number of ops that mutated it.
  void *eng = mxe_create(8);
  const int kVars = 16, kOps = 2000;
  std::vector<int64_t> vars(kVars);
  for (auto &v : vars) v = mxe_new_var(eng);
  std::vector<std::atomic<int64_t>> vals(kVars);
  for (auto &v : vals) v.store(0);
  std::vector<int64_t> expected(kVars, 0);

  std::mt19937 rng(7);
  std::vector<StressCtx> ctxs(kOps);
  for (int i = 0; i < kOps; ++i) {
    StressCtx &c = ctxs[i];
    c.vals = &vals;
    std::vector<int> perm(kVars);
    for (int j = 0; j < kVars; ++j) perm[j] = j;
    std::shuffle(perm.begin(), perm.end(), rng);
    int nr = rng() % 3, nw = 1 + rng() % 2;
    c.reads.assign(perm.begin(), perm.begin() + nr);
    c.writes.assign(perm.begin() + nr, perm.begin() + nr + nw);
    std::vector<int64_t> rv, wv;
    for (int r : c.reads) rv.push_back(vars[r]);
    for (int w : c.writes) { wv.push_back(vars[w]); }
    int rc = mxe_push(eng, stress_fn, &c, rv.data(), (int)rv.size(),
                      wv.data(), (int)wv.size(), (int)(rng() % 7) - 3);
    assert(rc == 0);
  }
  mxe_wait_all(eng);
  // every op's writes landed exactly once: vals[w] counts its mutators
  int64_t total = 0;
  for (auto &v : vals) total += v.load();
  int64_t min_expected = 0;
  for (auto &c : ctxs) min_expected += (int64_t)c.writes.size();
  assert(total >= min_expected);  // each write adds 1 or 2
  assert(total <= 2 * min_expected);
  mxe_destroy(eng);
  std::printf("engine_dependency_stress OK (total=%lld)\n",
              (long long)total);
}

void storage_pool_recycling() {
  mxs_release_all();
  void *a = mxs_alloc(1000);          // class 1024
  std::memset(a, 0xAB, 1000);
  mxs_free(a);
  uint64_t pooled = mxs_pool_bytes();
  assert(pooled >= 1000);
  void *b = mxs_alloc(900);           // same class -> recycled block
  assert(b == a);
  assert(mxs_pool_bytes() < pooled);
  mxs_free(b);

  void *c = mxs_alloc(4096);
  mxs_direct_free(c);                  // bypass: pool must not grow
  uint64_t after_direct = mxs_pool_bytes();
  assert(after_direct == mxs_pool_bytes());

  mxs_release_all();
  assert(mxs_pool_bytes() == 0);
  std::printf("storage_pool_recycling OK\n");
}

}  // namespace

int main() {
  engine_writer_serialization();
  engine_reader_parallel_and_priority();
  engine_duplicate_vars_rejected();
  engine_dependency_stress();
  storage_pool_recycling();
  std::printf("ALL CPP TESTS OK\n");
  return 0;
}
