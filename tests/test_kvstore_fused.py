"""Bucketed jit-fused KVStore update path (kvstore_fused.py).

Numerical-parity suite: the fused bucketed engine must reproduce the
eager per-key push/pull loops across stores, optimizers, grad dtypes,
per-device value lists, and bucket-boundary layouts — plus the engine's
caching/fallback contracts and the kvstore arg-validation bugfixes.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.context import Context
from mxnet_tpu.ndarray import NDArray

SHAPES = [(4, 5), (16,), (3, 2, 2), (32, 8), (7,)]


def _make_data(seed, n_dev, steps, shapes):
    rng = np.random.RandomState(seed)
    weights = [rng.uniform(-1, 1, s).astype(np.float32) for s in shapes]
    grads = [[[rng.uniform(-1, 1, s).astype(np.float32)
               for _ in range(n_dev)] for s in shapes]
             for _ in range(steps)]
    return weights, grads


def _run(kv_type, opt_name, opt_kwargs, fused, monkeypatch, n_dev=1,
         grad_dtype="float32", steps=4, bucket_mb=None, shapes=SHAPES):
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1" if fused else "0")
    if bucket_mb is None:
        monkeypatch.delenv("MXTPU_KV_BUCKET_MB", raising=False)
    else:
        monkeypatch.setenv("MXTPU_KV_BUCKET_MB", str(bucket_mb))
    weights, grads = _make_data(0, n_dev, steps, shapes)
    kv = mx.kv.create(kv_type)
    kv.set_optimizer(mx.optimizer.create(opt_name, **dict(opt_kwargs)))
    keys = list(range(len(shapes)))
    kv.init(keys, [nd.array(w) for w in weights])
    outs = [nd.zeros(s) for s in shapes]
    for t in range(steps):
        vals = []
        for i in range(len(shapes)):
            vals.append([
                NDArray(jnp.asarray(grads[t][i][d]).astype(
                    jnp.dtype(grad_dtype)), ctx=Context("cpu", d))
                for d in range(n_dev)
            ])
        kv.push(keys, vals)
        kv.pull(keys, outs)
    return kv, [o.asnumpy().astype(np.float32) for o in outs]


OPTIMIZERS = [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4,
             "rescale_grad": 1.0 / 8}),
    ("sgd", {"learning_rate": 0.05, "clip_gradient": 0.5,
             "rescale_grad": 1.0 / 8}),
    ("adam", {"learning_rate": 0.01, "rescale_grad": 1.0 / 8}),
    ("rmsprop", {"learning_rate": 0.01, "rescale_grad": 1.0 / 8}),
]


@pytest.mark.parametrize("kv_type", ["local", "device"])
@pytest.mark.parametrize("opt_name,opt_kwargs", OPTIMIZERS)
def test_fused_matches_eager(kv_type, opt_name, opt_kwargs, monkeypatch):
    kvf, fused = _run(kv_type, opt_name, opt_kwargs, True, monkeypatch)
    assert kvf._fused is not None and kvf._fused.num_buckets >= 1
    kve, eager = _run(kv_type, opt_name, opt_kwargs, False, monkeypatch)
    assert kve._fused is None
    for f, e in zip(fused, eager):
        np.testing.assert_allclose(f, e, rtol=2e-6, atol=2e-7)


@pytest.mark.parametrize("kv_type", ["local", "device"])
@pytest.mark.parametrize("grad_dtype,rtol", [("float32", 2e-6),
                                             ("bfloat16", 2e-2)])
def test_fused_multi_device_value_lists(kv_type, grad_dtype, rtol,
                                        monkeypatch):
    """Per-device gradient copies reduce through the bucket path (one
    concat per source device + one flat add) identically to the eager
    per-key merge loop, for fp32 and bf16 grads."""
    args = (kv_type, "sgd",
            {"learning_rate": 0.1, "momentum": 0.9, "rescale_grad": 1.0 / 3})
    kvf, fused = _run(*args, True, monkeypatch, n_dev=3,
                      grad_dtype=grad_dtype)
    assert kvf._fused is not None and kvf._fused._plan_keys is not None
    _, eager = _run(*args, False, monkeypatch, n_dev=3,
                    grad_dtype=grad_dtype)
    for f, e in zip(fused, eager):
        np.testing.assert_allclose(f, e, rtol=rtol, atol=rtol)


def test_fused_bucket_boundary_straddle(monkeypatch):
    """A param larger than MXTPU_KV_BUCKET_MB gets its own bucket and
    the split layout still matches eager bit-for-bit-in-tolerance."""
    shapes = [(8, 8)] * 3 + [(100000,)] + [(4,)] * 3  # 400KB param, 100KB cap
    args = ("local", "adam", {"learning_rate": 0.01, "rescale_grad": 0.1})
    kvf, fused = _run(*args, True, monkeypatch, bucket_mb=0.1, shapes=shapes)
    assert kvf._fused.num_buckets >= 3
    big_bucket = [b for b in kvf._fused._buckets if 3 in b.keys]
    assert len(big_bucket) == 1 and big_bucket[0].keys == [3]
    _, eager = _run(*args, False, monkeypatch, bucket_mb=0.1, shapes=shapes)
    for f, e in zip(fused, eager):
        np.testing.assert_allclose(f, e, rtol=2e-6, atol=2e-7)


def test_fused_no_retrace_after_warmup_and_cache_hits(monkeypatch):
    """After the warmup step: zero kv_update retraces across repeated
    steps AND lr changes (lr is traced), with the bucket programs served
    from the process-wide LRU (executor_graph_cache_total hits)."""
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1")
    tm = mx.telemetry
    was = tm.enabled()
    tm.enable()
    try:
        kv = mx.kv.create("local")
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        kv.set_optimizer(opt)
        keys = [0, 1, 2]
        kv.init(keys, [nd.ones((4, 4)) for _ in keys])
        g = [[nd.ones((4, 4))] for _ in keys]
        outs = [nd.zeros((4, 4)) for _ in keys]
        kv.push(keys, g)
        kv.pull(keys, outs)
        reg = tm.get_registry()
        compiles = reg.get("executor_compile_total")
        cache = reg.get("executor_graph_cache_total")
        c0 = compiles.value(kind="kv_update")
        h0 = cache.value(result="hit")
        assert c0 >= 1
        opt.lr = 0.01  # lr is a traced scalar: must NOT retrace
        for _ in range(5):
            kv.push(keys, g)
            kv.pull(keys, outs)
        assert compiles.value(kind="kv_update") == c0
        assert cache.value(result="hit") >= h0 + 5
        # a fresh engine with the same layout+config reuses the programs
        kv2 = mx.kv.create("local")
        kv2.set_optimizer(
            mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9))
        kv2.init(keys, [nd.ones((4, 4)) for _ in keys])
        kv2.push(keys, g)
        kv2.pull(keys, outs)
        assert compiles.value(kind="kv_update") == c0
    finally:
        if not was:
            tm.disable()


def test_fused_telemetry_families(monkeypatch):
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1")
    tm = mx.telemetry
    was = tm.enabled()
    tm.enable()
    try:
        reg = tm.get_registry()

        def count(name):
            fam = reg.get(name)
            return fam.count(store="local") if fam is not None else 0

        f0, b0, p0 = (count("kvstore_fused_update_seconds"),
                      count("kvstore_bucket_bytes"),
                      count("kvstore_pull_seconds"))
        kv = mx.kv.create("local")
        kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
        keys = [0, 1]
        kv.init(keys, [nd.ones((8,)) for _ in keys])
        kv.push(keys, [[nd.ones((8,))] for _ in keys])
        kv.pull(keys, [nd.zeros((8,)) for _ in keys])
        assert count("kvstore_fused_update_seconds") == f0 + 1
        assert reg.get("kvstore_bucket_count").value(store="local") == 1
        assert count("kvstore_bucket_bytes") == b0 + 1
        assert count("kvstore_pull_seconds") == p0 + 1
    finally:
        if not was:
            tm.disable()


def test_fused_fallbacks(monkeypatch):
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1")
    # NAG subclasses SGD with different math: no fused rule
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("nag", momentum=0.9))
    assert kv._fused is None
    # centered RMSProp: 3-slot state, different math
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("rmsprop", centered=True))
    assert kv._fused is None
    # custom Python updater clears the engine
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd"))
    assert kv._fused is not None
    kv._set_updater(lambda k, g, w: None)
    assert kv._fused is None
    # env opt-out
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "0")
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd"))
    assert kv._fused is None


def test_fused_eager_interleave_consistent(monkeypatch):
    """Single-key (eager) pushes interleaved with batched (fused) pushes
    share the Updater's state store — the sequence matches an all-eager
    run."""
    def run(fused_mid):
        monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1" if fused_mid else "0")
        kv = mx.kv.create("local")
        kv.set_optimizer(mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9))
        keys = [0, 1]
        kv.init(keys, [nd.ones((4,)) for _ in keys])
        outs = [nd.zeros((4,)) for _ in keys]
        for k in keys:  # per-key (always eager) step
            kv.push(k, [nd.ones((4,))])
        kv.push(keys, [[nd.ones((4,))] for _ in keys])  # batched step
        kv.pull(keys, outs)
        return [o.asnumpy() for o in outs]

    mixed = run(True)
    eager = run(False)
    for m, e in zip(mixed, eager):
        np.testing.assert_allclose(m, e, rtol=2e-6, atol=2e-7)


def test_fused_optimizer_states_roundtrip(tmp_path, monkeypatch):
    """save/load_optimizer_states works mid-run under the fused engine
    (state NDArrays are shared with the Updater)."""
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1")
    fname = str(tmp_path / "kv.states")
    keys = [0, 1]
    g = [[nd.ones((4,))] for _ in keys]

    def fresh():
        kv = mx.kv.create("local")
        kv.set_optimizer(mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9))
        kv.init(keys, [nd.ones((4,)) for _ in keys])
        return kv

    kv = fresh()
    kv.push(keys, g)
    kv.save_optimizer_states(fname)
    kv.push(keys, g)  # one more step after the save
    expect = [kv._store[k].asnumpy() for k in keys]

    kv2 = fresh()
    kv2.push(keys, g)  # reach the same weights as the save point
    kv2.load_optimizer_states(fname)
    kv2.push(keys, g)
    got = [kv2._store[k].asnumpy() for k in keys]
    for a, b in zip(got, expect):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-7)


def test_module_fused_matches_eager(monkeypatch):
    """End-to-end Module.fit through the batched update path: fused vs
    eager training trajectories agree."""
    from mxnet_tpu import io as mx_io, sym

    def run(fused):
        monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1" if fused else "0")
        mx.random.seed(0)
        np.random.seed(0)
        X = np.random.RandomState(3).uniform(-1, 1, (64, 10)).astype(np.float32)
        Y = (X.sum(axis=1) > 0).astype(np.float32)
        train = mx_io.NDArrayIter(X, Y, batch_size=16)
        net = sym.SoftmaxOutput(
            sym.FullyConnected(
                sym.Activation(
                    sym.FullyConnected(sym.Variable("data"), num_hidden=8,
                                       name="fc1"), act_type="relu"),
                num_hidden=2, name="fc2"),
            name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu(0))
        mod.fit(train, optimizer="sgd", kvstore=mx.kv.create("local"),
                optimizer_params=(("learning_rate", 0.1),
                                  ("momentum", 0.9)), num_epoch=2)
        used_fused = (mod._kvstore._fused is not None
                      and mod._kvstore._fused._plan_keys is not None)
        args, _ = mod.get_params()
        return used_fused, {k: v.asnumpy() for k, v in args.items()}

    used, fused = run(True)
    assert used
    _, eager = run(False)
    for k in fused:
        np.testing.assert_allclose(fused[k], eager[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


# ----------------------------- arg-validation bugfixes ---------------------
def test_push_pull_init_length_mismatch_raises():
    kv = mx.kv.create("local")
    with pytest.raises(MXNetError, match="3 keys but 1"):
        kv.init([3, 4, 5], [nd.ones((2,))])
    kv.init([0, 1], [nd.ones((2,)), nd.ones((2,))])
    with pytest.raises(MXNetError, match="2 keys but 1"):
        kv.push([0, 1], [nd.ones((2,))])
    with pytest.raises(MXNetError, match="2 keys but 1"):
        kv.pull([0, 1], out=[nd.zeros((2,))])
    with pytest.raises(MXNetError, match="2 keys but None"):
        kv.pull([0, 1], out=None)


def test_pull_single_key_fanout_records_seconds():
    """The single-key/multi-out fast path must observe
    kvstore_pull_seconds like the main loop (it used to skip it)."""
    tm = mx.telemetry
    was = tm.enabled()
    tm.enable()
    try:
        before = tm.get_registry().get("kvstore_pull_seconds")
        n0 = before.count(store="local") if before is not None else 0
        kv = mx.kv.create("local")
        kv.init(0, nd.ones((2, 2)))
        kv.pull(0, out=[nd.zeros((2, 2)) for _ in range(3)])
        hist = tm.get_registry().get("kvstore_pull_seconds")
        assert hist.count(store="local") == n0 + 1
        assert tm.get_registry().get("kvstore_pull_total").value(
            store="local") >= 1
    finally:
        if not was:
            tm.disable()
