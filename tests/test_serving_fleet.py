"""Serving-fleet tests (ISSUE 15): the replica router (least-loaded
balancing, idempotent retries, draining rolling upgrades, SIGKILL'd
replica survival), the paged KV cache (bitwise parity vs contiguous,
prefix reuse with fork isolation, pool accounting), and the graceful
SIGTERM drain of tools/serve.py.
"""
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models, telemetry as tm
from mxnet_tpu.models.decode import KVDecoder
from mxnet_tpu.serving import (NoReplicaAvailable, ReplicaDied,
                               ReplicaRouter, ReplicaTimeout,
                               RouterRetriesExhausted, SlotScheduler,
                               register_replica, serve_decoder,
                               start_router)
from mxnet_tpu.serving.paged_kv import PagedSlots, PoolExhausted
from mxnet_tpu.serving.scheduler import _ContiguousSlots

L, H, D, T, V = 2, 2, 32, 32, 17
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def lm_params():
    net = models.transformer.transformer_lm(
        num_layers=L, num_heads=H, d_model=D, seq_len=T, vocab_size=V)
    ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                         data=(1, T), softmax_label=(1, T))
    rs = np.random.RandomState(0)
    params = {}
    for name, arr in ex.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
        params[name] = arr
    return params


@pytest.fixture(scope="module")
def decoder(lm_params):
    return KVDecoder(lm_params, num_layers=L, num_heads=H, max_len=T)


@pytest.fixture()
def metrics():
    was = tm.enabled()
    tm.enable()
    yield tm.get_registry()
    if not was:
        tm.disable()


def _post(port, body, path="/generate", timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _get(port, path, timeout=30):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


def _fleet(decoder, n=2, **kw):
    """n in-process replicas + a started router; caller cleans up."""
    servers, scheds = [], []
    for _ in range(n):
        s, sch = serve_decoder(decoder, port=0, num_slots=2,
                               queue_size=16)
        servers.append(s)
        scheds.append(sch)
    addrs = ["127.0.0.1:%d" % s.server_address[1] for s in servers]
    kw.setdefault("scrape_s", 0.1)
    router = ReplicaRouter(replicas=addrs, **kw)
    rsrv = start_router(router, port=0)
    return servers, scheds, addrs, router, rsrv


def _teardown(servers, scheds, router, rsrv):
    rsrv.shutdown()
    router.stop()
    for s in servers:
        s.shutdown()
    for sch in scheds:
        sch.close()


# ---------------------------------------------------------------------------
# router core
# ---------------------------------------------------------------------------
def test_router_relays_and_balances(decoder, metrics):
    """Requests through the router complete with decode parity, the
    answering replica is named in the header, load spreads over both
    replicas, and the router metric families are live."""
    servers, scheds, addrs, router, rsrv = _fleet(decoder)
    rport = rsrv.server_address[1]
    try:
        rs = np.random.RandomState(1)
        used = set()
        for i in range(8):
            prompt = rs.randint(0, V, 4 + i % 5).tolist()
            st, out, hdr = _post(rport, {"prompt": prompt,
                                         "max_tokens": 5})
            assert st == 200 and out["outcome"] == "ok"
            ref = decoder.generate(np.array(prompt)[None], 5,
                                   temperature=0)
            assert out["tokens"] == ref[0].tolist()
            used.add(hdr.get("X-MXTPU-Replica"))
        assert used <= set(addrs)
        hz = _get(rport, "/healthz")
        assert hz["status"] == "ok" and hz["healthy"] == 2
        assert set(hz["replicas"]) == set(addrs)
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{rport}/metrics",
            timeout=30).read().decode()
        for fam in ("router_requests_total", "router_replicas",
                    "router_request_seconds"):
            assert fam in text
        fl = _get(rport, "/fleet")
        assert fl["healthy"] == 2 and len(fl["replicas"]) == 2
        # federation: replica metric families arrive host-labeled
        assert "serve_requests_total" in fl["metrics"]
        labels = {s["labels"].get("host")
                  for s in fl["metrics"]["serve_requests_total"]["samples"]}
        assert labels <= set(addrs) and labels
    finally:
        _teardown(servers, scheds, router, rsrv)


def test_router_retries_connect_failures(decoder, metrics):
    """A replica that looks healthy in the cache but is gone re-routes
    idempotently: the request succeeds on the next replica and the
    retry is counted with reason=connect; the dead row is marked.
    (No background scrape here — the test owns the cache so the forged
    healthy-but-gone row survives until routing.)"""
    server, sched = serve_decoder(decoder, port=0, num_slots=2,
                                  queue_size=8)
    live = "127.0.0.1:%d" % server.server_address[1]
    dead = "127.0.0.1:1"
    router = ReplicaRouter(replicas=[dead, live], scrape_s=30,
                           retries=2)
    try:
        router.scrape_once()
        retr = metrics.get("router_retries_total")
        r0 = retr.value(reason="connect")
        # forge a fresh-looking healthy row so pick() prefers the dead
        # addr (tie on load, first insertion wins)
        router._replicas[dead].update(
            ok=True, health={"slots": 8, "occupied": 0,
                             "queue_depth": 0, "queue_size": 16})
        status, data, addr = router.route_generate(
            json.dumps({"prompt": [1, 2, 3], "max_tokens": 3}).encode())
        assert status == 200 and addr == live
        assert json.loads(data)["outcome"] == "ok"
        assert retr.value(reason="connect") - r0 >= 1
        assert router.replicas()[dead]["ok"] is False
    finally:
        router.stop()
        server.shutdown()
        sched.close()


def test_router_all_draining_returns_503(decoder):
    """503 + Retry-After ONLY when every replica is draining; undrain
    restores service."""
    servers, scheds, addrs, router, rsrv = _fleet(decoder)
    rport = rsrv.server_address[1]
    try:
        st, out, _ = _post(rport, {}, path="/admin/drain")
        assert st == 200 and set(out["replicas"]) == set(addrs)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(rport, {"prompt": [1], "max_tokens": 2})
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
        st, out, _ = _post(rport, {}, path="/admin/undrain")
        assert st == 200
        router.scrape_once()
        st, out, _ = _post(rport, {"prompt": [1], "max_tokens": 2})
        assert st == 200 and out["outcome"] == "ok"
    finally:
        _teardown(servers, scheds, router, rsrv)


def test_router_exhaustion_is_named(decoder):
    """When every candidate was tried and failed, the router raises the
    named RouterRetriesExhausted (502 over HTTP), not a generic 500."""
    router = ReplicaRouter(replicas=["127.0.0.1:1"], scrape_s=30,
                           retries=1)
    router._replicas["127.0.0.1:1"].update(
        ok=True, health={"slots": 2, "occupied": 0, "queue_depth": 0,
                         "queue_size": 4})
    with pytest.raises(RouterRetriesExhausted, match="127.0.0.1:1"):
        router.route_generate(b'{"prompt": [1]}')
    # nothing routable at all -> the named unavailable error
    with pytest.raises(NoReplicaAvailable):
        router.route_generate(b'{"prompt": [1]}')


def _stub_replica(post_handler):
    """A bare HTTP server whose POST /generate is ``post_handler``;
    returns (server, "host:port")."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _H(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0") or 0)
            self.rfile.read(n)
            post_handler(self)

        def log_message(self, *args):
            pass

    class _S(ThreadingHTTPServer):
        daemon_threads = True

    srv = _S(("127.0.0.1", 0), _H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, "127.0.0.1:%d" % srv.server_address[1]


def _routable(router, addr):
    router._replicas[addr].update(
        ok=True, health={"slots": 2, "occupied": 0, "queue_depth": 0,
                         "queue_size": 4})


def test_router_all_shed_keeps_backpressure_503():
    """When EVERY attempted replica answers a live 429/503 admission
    shed, the fleet is saturated, not broken: the router keeps the
    documented backpressure contract (NoReplicaAvailable -> 503 +
    Retry-After), not RouterRetriesExhausted's 502."""
    def shed(h):
        h.send_response(429)
        h.send_header("Content-Length", "0")
        h.end_headers()

    srvs, addrs = zip(*(_stub_replica(shed) for _ in range(2)))
    try:
        router = ReplicaRouter(replicas=list(addrs), scrape_s=30,
                               retries=2)
        for a in addrs:
            _routable(router, a)
        with pytest.raises(NoReplicaAvailable, match="429/503"):
            router.route_generate(b'{"prompt": [1]}')
        # a shed reply is not a death: both replicas stay routable
        assert all(r["ok"] for r in router.replicas().values())
    finally:
        for s in srvs:
            s.shutdown()


def test_router_slow_replica_is_timeout_not_dead():
    """A replica that merely exceeds generate_timeout_s raises the
    named ReplicaTimeout (504) and is NOT marked dead — a slow, healthy
    replica must not be reported as died mid-request nor dropped from
    routing."""
    def slow(h):
        time.sleep(3.0)
        h.send_response(200)
        h.send_header("Content-Length", "0")
        h.end_headers()

    srv, addr = _stub_replica(slow)
    try:
        router = ReplicaRouter(replicas=[addr], scrape_s=30, retries=1,
                               generate_timeout_s=0.3)
        _routable(router, addr)
        with pytest.raises(ReplicaTimeout, match="did not answer"):
            router.route_generate(b'{"prompt": [1]}')
        assert router.replicas()[addr]["ok"], \
            "slow replica was wrongly marked dead"
    finally:
        srv.shutdown()


def test_rolling_upgrade_under_live_traffic(decoder, metrics):
    """The acceptance bar: a full rolling upgrade (drain each replica,
    wait drained, undrain) completes under continuous client traffic
    with ZERO failed (non-retried) requests."""
    servers, scheds, addrs, router, rsrv = _fleet(decoder)
    rport = rsrv.server_address[1]
    try:
        rs = np.random.RandomState(3)
        stop = threading.Event()
        results, errors = [], []

        def client(i):
            r2 = np.random.RandomState(100 + i)
            while not stop.is_set():
                try:
                    st, out, _ = _post(
                        rport, {"prompt": r2.randint(0, V, 1 + i % 6)
                                .tolist(), "max_tokens": 4})
                    results.append((st, out["outcome"]))
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return
                time.sleep(0.01)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        while not results and time.monotonic() < deadline:
            time.sleep(0.01)     # traffic is flowing before we upgrade
        upgraded = router.rolling_upgrade(drain_timeout=60)
        stop.set()
        for t in threads:
            t.join(120)
        assert [u["replica"] for u in upgraded] == sorted(addrs)
        assert not errors, errors[:3]
        assert results
        bad = [r for r in results if r != (200, "ok")]
        assert not bad, f"{len(bad)} failed requests during upgrade"
    finally:
        _teardown(servers, scheds, router, rsrv)


# ---------------------------------------------------------------------------
# coordinator self-registration
# ---------------------------------------------------------------------------
def test_replica_self_registration_via_coordinator(decoder, metrics):
    """A replica that register_replica()s with the PR-13 coordinator
    (role=serve) appears in the router's registry without any static
    list; leaving removes it at the next sweep."""
    from mxnet_tpu.parallel.coordinator import CoordinatorService

    svc = CoordinatorService(port=0, lease_s=2.0).start()
    server, sched = serve_decoder(decoder, port=0, num_slots=2,
                                  queue_size=8)
    addr = "127.0.0.1:%d" % server.server_address[1]
    client = None
    router = None
    try:
        client = register_replica(addr, coordinator=svc.address)
        cl = svc.cluster()
        assert client.member in cl["members"]
        assert cl["members"][client.member]["role"] == "serve"
        router = ReplicaRouter(replicas=[], coordinator=svc.address,
                               scrape_s=0.1)
        router.scrape_once()
        rows = router.replicas()
        assert addr in rows and rows[addr]["ok"]
        assert rows[addr]["source"] == "coordinator"
        status, data, via = router.route_generate(
            json.dumps({"prompt": [2, 4], "max_tokens": 3}).encode())
        assert status == 200 and via == addr
        client.leave()
        client = None
        router.scrape_once()
        assert addr not in router.replicas()
    finally:
        if client is not None:
            client.leave()
        if router is not None:
            router.stop()
        svc.stop()
        server.shutdown()
        sched.close()


# ---------------------------------------------------------------------------
# subprocess chaos: SIGKILL'd replica, SIGTERM graceful drain
# ---------------------------------------------------------------------------
def _spawn_replica(extra_env=None, extra_flags=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MXTPU_TELEMETRY_HTTP_PORT", None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "--demo", "--port", "0", "--num-layers", "1", "--num-heads",
         "1", "--d-model", "16", "--vocab-size", "32", "--max-len",
         "32", *extra_flags],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)
    addr, deadline = None, time.time() + 180
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        m = re.search(r"serving on http://([0-9.]+:[0-9]+)", line)
        if m:
            addr = m.group(1)
            break
    if addr is None:
        proc.kill()
        raise AssertionError("replica never came up:\n" + "".join(lines))
    return proc, addr


def test_router_survives_replica_sigkill_mid_request(decoder, metrics):
    """Fault site replica_kill (crash_after = a SIGKILL-shaped death
    mid-decode): the in-flight request gets the named 502, new work
    re-routes to the surviving replica, and the fleet converges (the
    dead replica is marked in the registry)."""
    proc, faulty = _spawn_replica(
        extra_env={"MXTPU_FAULT_PLAN": "replica_kill:crash_after:3"})
    server, sched = serve_decoder(decoder, port=0, num_slots=2,
                                  queue_size=8)
    live = "127.0.0.1:%d" % server.server_address[1]
    router = ReplicaRouter(replicas=[faulty, live], scrape_s=0.1,
                           retries=2)
    rsrv = start_router(router, port=0)
    rport = rsrv.server_address[1]
    try:
        # force the doomed replica to take the request: drain the
        # healthy one, so the router's only candidate is the fault rig
        router.drain(live)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(rport, {"prompt": [1, 2, 3], "max_tokens": 20})
        assert ei.value.code == 502
        body = json.loads(ei.value.read())
        assert body["router_error"] == "ReplicaDied"
        assert faulty in body["error"]
        assert proc.wait(timeout=60) == 137   # the crash_after exit
        # queued/new work re-routes: reopen the survivor and serve
        router.undrain(live)
        router.scrape_once()
        st, out, hdr = _post(rport, {"prompt": [4, 5], "max_tokens": 3})
        assert st == 200 and out["outcome"] == "ok"
        assert hdr.get("X-MXTPU-Replica") == live
        # convergence: the registry names the dead replica dead
        rows = router.replicas()
        assert rows[faulty]["ok"] is False
        assert rows[live]["ok"] is True
        hz = _get(rport, "/healthz")
        assert hz["healthy"] == 1
    finally:
        if proc.poll() is None:
            proc.kill()
        _teardown([server], [sched], router, rsrv)


def test_serve_sigterm_drains_then_exits(decoder):
    """ISSUE-15 satellite: SIGTERM on tools/serve.py == graceful
    rolling-restart step — the in-flight request finishes (not killed)
    and the process exits 0 after 'drained'."""
    proc, addr = _spawn_replica()
    port = int(addr.rsplit(":", 1)[1])
    try:
        result = {}

        def client():
            try:
                # 24 tokens fits the replica's cache window (max_len 32,
                # prompt bucket 8 -> 25 steps available): truncation can
                # never explain a short answer, only a broken drain can
                result["resp"] = _post(port, {"prompt": [1, 2],
                                              "max_tokens": 24})
            except Exception as exc:  # noqa: BLE001
                result["error"] = exc

        t = threading.Thread(target=client)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if _get(port, "/healthz", timeout=10)["occupied"] > 0:
                    break
            except OSError:
                pass
            time.sleep(0.01)
        proc.send_signal(signal.SIGTERM)
        t.join(120)
        assert proc.wait(timeout=120) == 0, "drain exit must be clean"
        assert "error" not in result, result.get("error")
        st, out, _ = result["resp"]
        assert st == 200 and out["outcome"] == "ok"
        assert out["n_tokens"] == 24   # the request was NOT cut short
    finally:
        if proc.poll() is None:
            proc.kill()


# ---------------------------------------------------------------------------
# paged KV cache
# ---------------------------------------------------------------------------
def test_paged_vs_contiguous_bitwise(decoder):
    """On a block-aligned prompt the paged gather reconstructs exactly
    the contiguous layout: prefill logits and every step's logits are
    BITWISE equal between the two backends."""
    buckets = (8, 16, 32)
    cont = _ContiguousSlots(decoder, 2, buckets)
    paged = PagedSlots(decoder, 2, block=8, prefill_buckets=buckets)
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, V, 8).astype(np.int64)   # == bucket: start 0
    lc = np.asarray(cont.admit(0, prompt), np.float32)
    lp = np.asarray(paged.admit(0, prompt), np.float32)
    assert np.array_equal(lc, lp), "prefill logits diverged bitwise"
    tok = np.array([int(lc.argmax()), 0])
    occ = np.array([True, False])
    for _ in range(6):
        slc, _n = cont.step(tok, occ)
        slp, _m = paged.step(tok, occ)
        slc = np.asarray(slc, np.float32)
        slp = np.asarray(slp, np.float32)
        assert np.array_equal(slc[0], slp[0]), "step logits diverged"
        tok = np.array([int(slc[0].argmax()), 0])


def test_paged_scheduler_parity_and_zero_recompiles(decoder, metrics):
    """Mixed prompt lengths through the paged scheduler: every request
    matches its per-request greedy decode exactly, slots are reused
    mid-flight, and a WARM paged server does zero traces per tick."""
    reuse = metrics.get("serve_slot_reuse_total")
    compiles = metrics.get("executor_compile_total")
    sched = SlotScheduler(decoder, num_slots=2, queue_size=16,
                          paged=True, kv_block=8)
    try:
        rs = np.random.RandomState(6)
        # warmup: one request per tail bucket this traffic hits
        for plen in (3, 12, 20):
            sched.generate(rs.randint(0, V, plen), max_new_tokens=2,
                           timeout=120)
        c0, r0 = compiles.total(), reuse.total()
        prompts = [rs.randint(0, V, ln) for ln in (3, 7, 5, 9, 4, 18)]
        reqs = [sched.submit(p, max_new_tokens=5) for p in prompts]
        for r in reqs:
            r.wait(120)
        assert all(r.outcome == "ok" for r in reqs), \
            [(r.outcome, r.error) for r in reqs]
        for p, r in zip(prompts, reqs):
            ref = decoder.generate(p[None], 5, temperature=0)
            assert r.tokens == ref[0].tolist(), (
                f"paged co-batched decode diverged for len {len(p)}")
        assert compiles.total() - c0 == 0, \
            "warm paged serving traffic recompiled"
        assert reuse.total() - r0 > 0, "no mid-flight slot reuse"
    finally:
        sched.close()


def test_prefix_reuse_and_fork_isolation(decoder, metrics):
    """The prefix-cache correctness pin, driven at the backend level so
    the check is immune to greedy-argmax tie noise between different
    program structures: fork A decodes (mutating pages PAST the shared
    block), then fork B admits against the reused shared block — if
    A's writes corrupted the shared page, B's logits would be wrong by
    O(1); the legitimate full-prefill vs tail-reuse rounding difference
    is bounded at ~1e-5.  Steps feed both backends IDENTICAL forced
    tokens, so trajectories cannot drift apart."""
    hits = metrics.get("serve_prefix_hits_total")
    buckets = (8, 16, 32)
    cont = _ContiguousSlots(decoder, 3, buckets)
    pg = PagedSlots(decoder, 3, block=8, prefill_buckets=buckets)
    rs = np.random.RandomState(7)
    shared = rs.randint(0, V, 8).astype(np.int64)    # one full block
    fa = np.concatenate([shared, rs.randint(0, V, 8)])   # aligned p=16
    fb = np.concatenate([shared, rs.randint(0, V, 8)])
    tol = 1e-3

    h0 = hits.total()
    la_c = np.asarray(cont.admit(0, fa), np.float32)
    la_p = np.asarray(pg.admit(0, fa), np.float32)
    assert np.array_equal(la_c, la_p)      # aligned: bitwise regime
    assert hits.total() - h0 == 0          # nothing cached yet
    # mutate fork A: 6 decode steps writing K/V past the shared block
    occ = np.array([True, False, False])
    tok = np.array([int(la_c.argmax()), 0, 0])
    for _ in range(6):
        lc, _ = cont.step(tok, occ)
        lp, _ = pg.step(tok, occ)
        lc = np.asarray(lc, np.float32)
        lp = np.asarray(lp, np.float32)
        assert np.array_equal(lc[0], lp[0])
        tok = np.array([int(lc[0].argmax()), 0, 0])
    # fork B admits: the paged side prefills ONLY its tail behind the
    # reused shared page; corruption would blow past tol by orders of
    # magnitude
    lb_c = np.asarray(cont.admit(1, fb), np.float32)
    lb_p = np.asarray(pg.admit(1, fb), np.float32)
    assert hits.total() - h0 >= 1, "the shared block was not reused"
    scale = max(1.0, float(np.abs(lb_c).max()))
    assert np.abs(lb_c - lb_p).max() < tol * scale, \
        "fork B diverged — fork A's writes corrupted the shared prefix"
    occ2 = np.array([False, True, False])
    tok2 = np.array([0, int(lb_c.argmax()), 0])
    for _ in range(6):
        lc, _ = cont.step(tok2, occ2)
        lp, _ = pg.step(tok2, occ2)
        lc = np.asarray(lc, np.float32)
        lp = np.asarray(lp, np.float32)
        assert np.abs(lc[1] - lp[1]).max() < tol * scale
        tok2 = np.array([0, int(lc[1].argmax()), 0])
    # release both forks: private pages return to the pool, the shared
    # block stays pinned by the prefix index, and a third admission
    # still reuses the INTACT prefix
    pg.release(0)
    pg.release(1)
    st = pg.stats()
    assert st["prefix_pages"] >= 1
    assert st["pages_free"] == st["pages_total"] - st["prefix_pages"]
    h1 = hits.total()
    cont.release(0)
    lc3 = np.asarray(cont.admit(0, fa), np.float32)
    lp3 = np.asarray(pg.admit(0, fa), np.float32)
    assert hits.total() - h1 >= 1
    assert np.abs(lc3 - lp3).max() < tol * scale


def test_paged_healthz_and_env_selection(decoder, monkeypatch):
    """/healthz gains the paged pool block plus queue/drain signals;
    MXTPU_KV_BLOCK alone selects the paged backend."""
    monkeypatch.setenv("MXTPU_KV_BLOCK", "8")
    server, sched = serve_decoder(decoder, port=0, num_slots=2,
                                  queue_size=8)
    port = server.server_address[1]
    try:
        assert sched.paged and sched.backend.block == 8
        hz = _get(port, "/healthz")
        assert hz["paged"]["pages_total"] == 2 * (T // 8)
        assert hz["paged"]["block"] == 8
        assert hz["queue_size"] == 8 and hz["draining"] is False
        sched.drain()
        hz = _get(port, "/healthz")
        assert hz["draining"] is True
        assert hz["status"] in ("draining", "drained")
    finally:
        server.shutdown()
        sched.close()


def test_paged_pool_exhaustion_truncates(decoder):
    """Two slots contending for a pool smaller than their combined
    appetite: nobody hangs or errors — the starved request is delivered
    truncated with outcome ok (the paged cache-window analog)."""
    sched = SlotScheduler(decoder, num_slots=2, queue_size=4,
                          paged=True, kv_block=8, num_pages=4,
                          prefix_cache=False)
    try:
        rs = np.random.RandomState(8)
        a = sched.submit(rs.randint(0, V, 8), max_new_tokens=25)
        b = sched.submit(rs.randint(0, V, 8), max_new_tokens=25)
        a.wait(120)
        b.wait(120)
        assert a.outcome == "ok" and b.outcome == "ok"
        # 4 pages = 32 cache positions for 16 prompt tokens + budget 50:
        # at least one request must have been truncated by the pool
        assert len(a.tokens) + len(b.tokens) < 50
        assert min(len(a.tokens), len(b.tokens)) >= 1
        # the pool fully recovers for the next request
        c = sched.generate(rs.randint(0, V, 4), max_new_tokens=3,
                           timeout=120)
        assert c.outcome == "ok" and len(c.tokens) == 3
        assert sched.paged_stats()["pages_free"] == 4
    finally:
        sched.close()


def test_prefix_chain_pinned_against_own_eviction(decoder, metrics):
    """Admit-order regression pin: the shared chain must be pinned
    BEFORE tail allocation.  Unpinned, _alloc's LRU eviction reclaims
    this request's own ref==1 prefix page and hands it back as an owned
    tail page — one physical page mapped to two logical blocks, the
    tail prefill overwriting the shared prefix it is reusing.  Pinned,
    a pool that cannot feed the tail fails CLEANLY: PoolExhausted with
    refcounts and the prefix index intact, and the same admission
    succeeds uncorrupted once pages free up."""
    hits = metrics.get("serve_prefix_hits_total")
    buckets = (8, 16, 32)
    cont = _ContiguousSlots(decoder, 1, buckets)
    pg = PagedSlots(decoder, 3, block=8, num_pages=4,
                    prefix_cache=True, prefill_buckets=buckets)
    rs = np.random.RandomState(11)
    block_a = rs.randint(0, V, 8).astype(np.int64)
    pg.admit(0, block_a)                 # seed + promote chain A
    pg.release(0)
    pga = next(iter(pg._prefix.values()))
    pg.admit(1, rs.randint(0, V, 4))     # a live slot: 2 pages free
    # slot 0 matches chain A and needs 3 tail pages with 2 free; the
    # ONLY eviction candidate is chain A itself — unpinned, it would be
    # evicted into the owned tail (the page aliased to two blocks)
    long = np.concatenate([block_a, rs.randint(0, V, 24)])
    with pytest.raises(PoolExhausted):
        pg.admit(0, long)
    assert int(pg._ref[pga]) == 1          # the pin rolled back
    assert pga in pg._prefix.values()      # chain A survived
    assert pg._slot_pages[0] == []
    assert pg.stats()["pages_free"] == 2
    # release the contending slot: the SAME admission now succeeds,
    # reusing the intact chain behind a duplicate-free page row
    pg.release(1)
    h0 = hits.total()
    lp = np.asarray(pg.admit(0, long), np.float32)
    assert hits.total() - h0 >= 1, "chain A was not reused"
    row = pg._slot_pages[0]
    assert row[0] == pga and len(set(row)) == len(row) == 4
    lc = np.asarray(cont.admit(0, long), np.float32)
    scale = max(1.0, float(np.abs(lc).max()))
    assert np.abs(lc - lp).max() < 1e-3 * scale, \
        "tail prefill corrupted the shared prefix"


def test_paged_composes_with_int8(lm_params):
    """quantize='int8' weights decode through the paged programs too —
    the _DequantView dequantize-in-compute is backend-agnostic.  Parity
    is pinned in the bitwise regime (block-aligned prompt, paged vs
    contiguous scheduler over the SAME int8 decoder): comparing two
    structurally different programs on near-tie int8 logits would pin
    floating-point rounding, not the quantize/paging contract."""
    dec8 = KVDecoder(lm_params, num_layers=L, num_heads=H, max_len=T,
                     quantize="int8")
    prompt = np.arange(1, 9)                   # len 8 == kv_block
    cont = SlotScheduler(dec8, num_slots=2, queue_size=4, paged=False)
    try:
        ref = cont.generate(prompt, max_new_tokens=5, timeout=120)
        assert ref.outcome == "ok"
    finally:
        cont.close()
    sched = SlotScheduler(dec8, num_slots=2, queue_size=4, paged=True,
                          kv_block=8)
    try:
        req = sched.generate(prompt, max_new_tokens=5, timeout=120)
        assert req.outcome == "ok"
        assert req.tokens == ref.tokens
    finally:
        sched.close()


def test_paged_validation():
    class _FakeDec:
        mesh = None
        max_len = 30

    with pytest.raises(mx.MXNetError, match="divide"):
        PagedSlots(_FakeDec(), 2, block=8)


# ---------------------------------------------------------------------------
# tooling satellites: fleetstat rows, bench_trend directions
# ---------------------------------------------------------------------------
def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxtpu_" + name, os.path.join(REPO, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleetstat_router_rows_show_drain_and_paged():
    fleetstat = _load_tool("fleetstat")
    fleet = {
        "healthy": 1, "scrape_interval_s": 1.0,
        "replicas": {
            "10.0.0.1:9200": {"ok": True, "draining": True,
                              "health": {"status": "draining",
                                         "slots": 4, "occupied": 2,
                                         "queue_depth": 1, "ticks": 9,
                                         "paged": {"pages_total": 32,
                                                   "pages_free": 20,
                                                   "prefix_pages": 5}}},
            "10.0.0.2:9200": {"ok": False, "draining": False,
                              "health": None,
                              "error": "ConnectionRefusedError(61)"}},
        "metrics": {"serve_requests_total": {}},
    }
    out = fleetstat.render_router(fleet)
    assert "draining" in out                  # upgrade progress visible
    assert "DEAD" in out                      # dead replica named
    assert "20/32, 5 prefix" in out           # paged occupancy rendered
    assert "ConnectionRefused" in out


def test_bench_trend_directions_for_serve_metrics():
    """Round-19 direction table: retries/unavailable regress UP,
    throughput and the paged ratio regress DOWN."""
    bt = _load_tool("bench_trend")
    assert bt.lower_is_better("router_retry_total")
    assert bt.lower_is_better("router_retries_total")
    assert bt.lower_is_better("serve_fleet_ttft_p99_ms")
    assert not bt.lower_is_better("serve_fleet_tokens_per_sec")
    assert not bt.lower_is_better("paged_vs_contiguous_tokens_per_sec")
    assert not bt.lower_is_better("serve_paged_tokens_per_sec")


def test_bench_trend_directions_for_autotune_metrics():
    """Round-21 direction table: search wall cost and per-step kernel
    microseconds regress UP; the kernel speedup ratio regresses DOWN."""
    bt = _load_tool("bench_trend")
    assert bt.lower_is_better("autotune_search_ms")
    assert bt.lower_is_better("paged_attn_kernel_us_per_step")
    assert bt.lower_is_better("paged_attn_gather_us_per_step")
    assert bt.lower_is_better("epilogue_tuned_vs_default_us")
    assert not bt.lower_is_better("paged_attn_kernel_speedup")
    assert not bt.lower_is_better("autotune_cache_hit")
