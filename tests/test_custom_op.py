"""Custom-op frontend (parity pattern: example/numpy-ops/custom_softmax.py
and tests for python/mxnet/operator.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd, symbol as sym


class _Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(int)
        y = out_data[0].asnumpy().copy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], y)
        self.assign(in_grad[1], "null", None)


@mx.operator.register("test_softmax")
class _SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = [in_shape[0][0]]
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _Softmax()


def test_custom_op_imperative():
    rs = np.random.RandomState(0)
    x = rs.uniform(-1, 1, size=(4, 5)).astype(np.float32)
    lbl = np.array([0, 1, 2, 3], np.float32)
    out = nd.Custom(nd.array(x), nd.array(lbl), op_type="test_softmax")
    ref = np.exp(x - x.max(1, keepdims=True))
    ref /= ref.sum(1, keepdims=True)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)


def test_custom_op_symbolic_forward_backward():
    data = sym.Variable("data")
    label = sym.Variable("label")
    net = sym.Custom(data, label, op_type="test_softmax", name="csm")
    exe = net.simple_bind(ctx=mx.context.cpu(), data=(4, 5), label=(4,),
                          grad_req={"data": "write", "label": "null"})
    rs = np.random.RandomState(1)
    x = rs.uniform(-1, 1, size=(4, 5)).astype(np.float32)
    exe.arg_dict["data"][:] = x
    exe.arg_dict["label"][:] = np.array([1, 0, 3, 2], np.float32)
    out = exe.forward(is_train=True)[0].asnumpy()
    ref = np.exp(x - x.max(1, keepdims=True))
    ref /= ref.sum(1, keepdims=True)
    np.testing.assert_allclose(out, ref, rtol=1e-5)

    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    expect = ref.copy()
    expect[np.arange(4), [1, 0, 3, 2]] -= 1.0
    np.testing.assert_allclose(g, expect, rtol=1e-5, atol=1e-6)


def test_custom_op_in_module_fit():
    """A custom loss layer trains through Module.fit."""
    from mxnet_tpu import module, io as mio

    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc")
    net = sym.Custom(fc, sym.Variable("softmax_label"),
                     op_type="test_softmax", name="loss")
    rs = np.random.RandomState(2)
    X = rs.uniform(size=(32, 6)).astype(np.float32)
    Y = (X[:, 0] > 0.5).astype(np.float32) + (X[:, 1] > 0.5)
    it = mio.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    m = module.Module(net, context=mx.context.cpu(),
                      label_names=("softmax_label",))
    m.fit(it, num_epoch=3, optimizer="sgd",
          optimizer_params={"learning_rate": 0.5})
    acc = mx.metric.Accuracy()
    m.score(it, acc)
    assert acc.get()[1] > 0.4  # learns something


def test_custom_op_need_top_grad():
    """need_top_grad=True ops receive the true head gradient."""

    class _Scale(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], in_data[0].asnumpy() * 2.0)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0], out_grad[0].asnumpy() * 2.0)

    @mx.operator.register("test_scale2")
    class _ScaleProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return _Scale()

    data = sym.Variable("data")
    net = sym.sum(sym.Custom(data, op_type="test_scale2") * 3.0)
    exe = net.simple_bind(ctx=mx.context.cpu(), data=(2, 3))
    exe.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    exe.forward(is_train=True)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               np.full((2, 3), 6.0), rtol=1e-6)


def test_numpy_op_shim():
    class _Sq(mx.operator.NumpyOp):
        def forward(self, in_data, out_data):
            out_data[0][...] = in_data[0] ** 2

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][...] = 2.0 * in_data[0] * out_grad[0]

    op = _Sq()
    net = op(sym.Variable("data"), name="sq")
    exe = net.simple_bind(ctx=mx.context.cpu(), data=(3,))
    exe.arg_dict["data"][:] = np.array([1.0, 2.0, 3.0], np.float32)
    out = exe.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, [1, 4, 9], rtol=1e-6)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               [2, 4, 6], rtol=1e-6)


def test_unregistered_op_type_errors():
    import pytest
    with pytest.raises(mx.MXNetError):
        nd.Custom(nd.array(np.zeros((2, 2), np.float32)), op_type="nope")


def test_custom_prop_receives_symbol_kwargs_as_strings():
    """Reference parity (custom-inl.h): the sym.Custom call's extra
    kwargs reach the CustomOpProp constructor AS STRINGS; framework
    attrs (op_type/num_args/name) never do."""
    seen = {}

    @mx.operator.register("kwarg_probe_op")
    class KwargProbeProp(mx.operator.CustomOpProp):
        def __init__(self, alpha, mode="x"):
            seen["alpha"] = alpha
            seen["mode"] = mode
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return [in_shape[0]], [in_shape[0]]  # 2-tuple form is legal

        def create_operator(self, ctx, shapes, dtypes):
            class _Op(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0],
                                mx.nd.array(in_data[0].asnumpy() * 2.0))

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0],
                                mx.nd.array(out_grad[0].asnumpy() * 2.0))

            return _Op()

    data = mx.sym.Variable("data")
    net = mx.sym.Custom(data, alpha=1.5, mode="fast",
                        op_type="kwarg_probe_op")
    exe = net.simple_bind(mx.cpu(), data=(2, 3))
    exe.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    out = exe.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, 2.0 * np.ones((2, 3)))
    assert seen["alpha"] == "1.5" and seen["mode"] == "fast", seen
