"""Speech-demo subsystem tests: feature container round-trips (HTK,
Kaldi ark/scp, text ark), CMVN, delta/splice transforms, the LSTMP cell,
the scheduled-momentum optimizer, and the utterance bucketing iterator.

Parity model: the reference ships io_func/feat_readers with
tests/test_system.py reading prepared feature files
(example/speech-demo/tests/test_system.py); here the files are written
by our own writers first, so both directions are pinned.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym

SPEECH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "speech-demo")
sys.path.insert(0, SPEECH)

from io_util import (  # noqa: E402
    UtteranceIter, add_deltas, apply_cmvn, compute_cmvn_stats,
    compute_cmvn_stats_scp, read_ark, read_ark_entry, read_htk, read_scp,
    read_text_ark, splice_frames, write_ark, write_htk, write_text_ark)


def _feats(rs, t, d=8):
    return rs.randn(t, d).astype(np.float32)


def test_htk_roundtrip(tmp_path):
    rs = np.random.RandomState(0)
    x = _feats(rs, 17, 13)
    path = str(tmp_path / "a.fea")
    write_htk(path, x, samp_period=100000, parm_kind=9)
    y, period, kind = read_htk(path)
    np.testing.assert_array_equal(x, y)
    assert period == 100000 and kind == 9
    # header is genuinely big-endian HTK: first int32 BE == nSamples
    raw = open(path, "rb").read()
    assert int.from_bytes(raw[:4], "big") == 17


def test_kaldi_binary_ark_roundtrip(tmp_path):
    rs = np.random.RandomState(1)
    utts = {f"u{i}": _feats(rs, 5 + i) for i in range(4)}
    ark = str(tmp_path / "f.ark")
    scp = str(tmp_path / "f.scp")
    write_ark(ark, utts, scp)
    back = dict(read_ark(ark))
    assert list(back) == list(utts)
    for u in utts:
        np.testing.assert_array_equal(utts[u], back[u])
    # random access through the scp index
    entries = read_scp(scp)
    assert [u for u, _, _ in entries] == list(utts)
    for u, path, off in entries:
        np.testing.assert_array_equal(read_ark_entry(path, off), utts[u])


def test_kaldi_text_ark_roundtrip(tmp_path):
    rs = np.random.RandomState(2)
    utts = {"a": _feats(rs, 3, 4), "empty": np.zeros((0, 4), np.float32),
            "b": _feats(rs, 6, 4)}
    path = str(tmp_path / "t.ark")
    write_text_ark(path, utts)
    back = dict(read_text_ark(path))
    assert list(back) == list(utts)
    for u in ("a", "b"):
        np.testing.assert_allclose(utts[u], back[u], rtol=1e-5)
    assert back["empty"].size == 0


def test_kaldi_scp_streaming_matches_random_access(tmp_path):
    from io_util import read_scp_matrices

    rs = np.random.RandomState(8)
    utts = {f"u{i}": _feats(rs, 4 + i) for i in range(5)}
    ark, scp = str(tmp_path / "s.ark"), str(tmp_path / "s.scp")
    write_ark(ark, utts, scp)
    streamed = dict(read_scp_matrices(scp))
    assert list(streamed) == list(utts)
    for u in utts:
        np.testing.assert_array_equal(streamed[u], utts[u])


def test_cmvn(tmp_path):
    rs = np.random.RandomState(3)
    utts = {f"u{i}": _feats(rs, 50, 6) * 3.0 + 5.0 for i in range(3)}
    stats = compute_cmvn_stats(utts)
    assert stats.shape == (2, 7) and stats[0, -1] == 150
    allf = np.concatenate([apply_cmvn(f, stats) for f in utts.values()])
    np.testing.assert_allclose(allf.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(allf.std(axis=0), 1.0, atol=1e-3)
    # scp-driven accumulation matches in-memory accumulation
    ark, scp = str(tmp_path / "c.ark"), str(tmp_path / "c.scp")
    write_ark(ark, utts, scp)
    np.testing.assert_allclose(stats, compute_cmvn_stats_scp(scp), rtol=1e-6)


def test_deltas_and_splice():
    rs = np.random.RandomState(4)
    x = _feats(rs, 12, 5)
    d = add_deltas(x, order=2)
    assert d.shape == (12, 15)
    np.testing.assert_array_equal(d[:, :5], x)
    # constant signal -> zero deltas
    const = np.ones((8, 3), np.float32)
    np.testing.assert_allclose(add_deltas(const)[:, 3:], 0.0, atol=1e-7)
    # ramp -> constant first delta in the interior
    ramp = np.arange(20, dtype=np.float32)[:, None]
    dd = add_deltas(ramp, order=1, window=2)
    np.testing.assert_allclose(dd[4:-4, 1], 1.0, atol=1e-5)
    s = splice_frames(x, left=2, right=2)
    assert s.shape == (12, 25)
    np.testing.assert_array_equal(s[3, 10:15], x[3])  # center block
    np.testing.assert_array_equal(s[0, 0:5], x[0])    # edge-padded


def test_utterance_iter_buckets_and_masking():
    rs = np.random.RandomState(5)
    utts = [(f"u{i}", _feats(rs, int(rs.randint(8, 25)), 6))
            for i in range(40)]
    labels = [rs.randint(0, 3, len(f)).astype(np.float32)
              for _, f in utts]
    it = UtteranceIter(utts, labels, batch_size=4, buckets=[10, 25],
                       ignore_label=-1, shuffle=False)
    seen = 0
    for batch in it:
        t = batch.bucket_key
        data = batch.data[0].asnumpy()
        lab = batch.label[0].asnumpy()
        assert data.shape == (4, t, 6) and lab.shape == (4, t)
        # padding frames are ignore-labeled and zero-featured
        for r in range(4):
            pad = lab[r] == -1
            assert np.all(data[r][pad] == 0)
        seen += 1
    assert seen == it.curr_idx and seen > 0


def test_lstmp_cell_projection_shapes_and_grads():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMPCell(16, 6, prefix="l0_"))
    outputs, states = stack.unroll(4, inputs=sym.Variable("data"),
                                   layout="NTC", merge_outputs=True)
    net = sym.MakeLoss(sym.sum(outputs))
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 4, 5),
                         l0_begin_state_0=(2, 6), l0_begin_state_1=(2, 16))
    assert ex.arg_dict["l0_h2h_weight"].shape == (64, 6)   # 4H x P
    assert ex.arg_dict["l0_proj_weight"].shape == (6, 16)  # P x H
    rs = np.random.RandomState(6)
    for k, v in ex.arg_dict.items():
        if "state" not in k:
            v[:] = rs.uniform(-0.3, 0.3, v.shape)
    ex.forward(is_train=True)
    ex.backward()
    for k in ("l0_i2h_weight", "l0_h2h_weight", "l0_proj_weight"):
        assert float(np.abs(ex.grad_dict[k].asnumpy()).sum()) > 0, k
    # the output is the projection: last dim P, not H
    assert ex.outputs[0].shape == ()


def test_speech_sgd_matches_sgd_without_schedule():
    import speech_sgd  # noqa: F401 — registers

    rs = np.random.RandomState(7)
    w0 = rs.uniform(-1, 1, (5, 3)).astype(np.float32)
    grads = [rs.uniform(-1, 1, (5, 3)).astype(np.float32) for _ in range(4)]

    def run(name):
        o = mx.optimizer.create(name, learning_rate=0.1, momentum=0.9)
        w = mx.nd.array(w0.copy())
        state = o.create_state(0, w)
        for g in grads:
            o.update(0, w, mx.nd.array(g), state)
        return w.asnumpy()

    np.testing.assert_allclose(run("speechsgd"), run("sgd"), rtol=1e-6)


def test_speech_sgd_scheduled_momentum():
    from speech_sgd import EpochScheduler

    o = mx.optimizer.create("speechsgd", learning_rate=0.1,
                            lr_scheduler=EpochScheduler(momentum=0.9, ramp=3))
    w = mx.nd.array(np.zeros((2,), np.float32))
    state = o.create_state(0, w)
    g = mx.nd.array(np.ones((2,), np.float32))
    # num_update counts 1-based: updates 1,2 < ramp -> momentum off,
    # plain sgd steps of -0.1 (the momentum buffer still accumulates)
    o.update(0, w, g, state)
    o.update(0, w, g, state)
    np.testing.assert_allclose(w.asnumpy(), -0.2, rtol=1e-6)
    # update 3: momentum on -> mom = 0.9*prev(=1.0) + grad
    o.update(0, w, g, state)
    np.testing.assert_allclose(w.asnumpy(), -0.2 - 0.1 * (0.9 + 1.0),
                               rtol=1e-5)
