"""KV-cache incremental decoding tests (models/decode.py): the cached
step-by-step forward must reproduce the training symbol's full forward
exactly — prefill+steps vs one dense causal pass over the same tokens.
"""
import numpy as np

import jax
import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.models.decode import KVDecoder

L, H, D, T, V = 2, 2, 32, 12, 17


def _bound_model():
    net = models.transformer.transformer_lm(
        num_layers=L, num_heads=H, d_model=D, seq_len=T, vocab_size=V)
    ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                         data=(2, T), softmax_label=(2, T))
    rs = np.random.RandomState(0)
    params = {}
    for name, arr in ex.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
        params[name] = arr
    return ex, params, rs


def _symbol_probs(ex, tokens):
    ex.forward(is_train=False, data=tokens.astype(np.float32),
               softmax_label=np.zeros_like(tokens, dtype=np.float32))
    return ex.outputs[0].asnumpy().reshape(tokens.shape[0], T, V)


def test_prefill_matches_symbol_forward():
    ex, params, rs = _bound_model()
    tokens = rs.randint(0, V, (2, T))
    ref = _symbol_probs(ex, tokens)

    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    _, logits = dec.prefill(tokens)
    got = np.asarray(jax.nn.softmax(logits, axis=-1))
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_incremental_steps_match_symbol_forward():
    ex, params, rs = _bound_model()
    tokens = rs.randint(0, V, (2, T))
    ref = _symbol_probs(ex, tokens)

    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    # prefill 4 tokens, then feed the rest ONE at a time
    state, logits = dec.prefill(tokens[:, :4])
    probs = [np.asarray(jax.nn.softmax(logits, axis=-1))]
    for t in range(4, T):
        state, lg = dec.step(state, tokens[:, t])
        probs.append(np.asarray(jax.nn.softmax(lg, axis=-1))[:, None])
    got = np.concatenate(probs, axis=1)
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_generate_shapes_and_determinism():
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    prompt = rs.randint(0, V, (2, 4))
    a = dec.generate(prompt, 6, temperature=0,
                     rng=np.random.RandomState(1))
    b = dec.generate(prompt, 6, temperature=0,
                     rng=np.random.RandomState(2))
    assert a.shape == (2, 6)
    np.testing.assert_array_equal(a, b)  # greedy is rng-independent
    c = dec.generate(prompt, 6, temperature=0.8, top_k=5,
                     rng=np.random.RandomState(1))
    assert c.shape == (2, 6) and (c < V).all()


def test_beam_search_beam1_matches_greedy():
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    prompt = rs.randint(0, V, (2, 4))
    greedy = dec.generate(prompt, 6, temperature=0)
    beam, scores = dec.beam_search(prompt, 6, beam_size=1)
    assert beam.shape == (2, 1, 6) and scores.shape == (2, 1)
    np.testing.assert_array_equal(beam[:, 0], greedy)


def test_beam_search_widening_never_hurts_best_score():
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    prompt = rs.randint(0, V, (1, 3))
    _, s1 = dec.beam_search(prompt, 5, beam_size=1)
    _, s4 = dec.beam_search(prompt, 5, beam_size=4)
    # a wider beam can only find an equal-or-better best sequence
    assert s4[0, 0] >= s1[0, 0] - 1e-5
    # per-beam scores come back sorted best-first
    assert (np.diff(s4[0]) <= 1e-6).all()


def test_beam_search_eos_finishes_and_pads():
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    prompt = rs.randint(0, V, (1, 3))
    # find the greedy first token, then make IT the eos: the best beam
    # finishes immediately and must come back fully eos-padded with its
    # single-token score frozen
    base, base_scores = dec.beam_search(prompt, 5, beam_size=2)
    eos = int(base[0, 0, 0])
    toks, scores = dec.beam_search(prompt, 5, beam_size=2, eos_id=eos,
                                   length_penalty=1.0)
    assert (toks[0, 0] == eos).all()
    # the finished beam froze after ONE token: its length-normalized
    # score is that single logprob, strictly better than any 5-token
    # accumulation (logprobs only subtract)
    assert scores[0, 0] > base_scores[0, 0]
    assert toks.shape == (1, 2, 5)


def test_beam_search_rejects_oversized_beam():
    import pytest

    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    with pytest.raises(ValueError, match="beam_size"):
        dec.beam_search(rs.randint(0, V, (1, 2)), 3, beam_size=V + 1)


def test_tensor_parallel_decode_matches_dense():
    """KVDecoder over a 2-way 'model' mesh (Megatron-sharded weights,
    head-sharded cache) must reproduce the single-device decode."""
    from jax.sharding import Mesh

    _, params, rs = _bound_model()
    dense = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    devs = np.array(jax.devices("cpu")[:2])  # H=2 heads -> tp=2
    mesh = Mesh(devs, ("model",))
    tp = KVDecoder(params, num_layers=L, num_heads=H, max_len=T,
                   mesh=mesh)
    tokens = rs.randint(0, V, (2, 8))
    _, ref = dense.prefill(tokens)
    _, got = tp.prefill(tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5)
    # and step-by-step
    sd, ld = dense.prefill(tokens[:, :3])
    st, lt = tp.prefill(tokens[:, :3])
    for t in range(3, 8):
        sd, ld = dense.step(sd, tokens[:, t])
        st, lt = tp.step(st, tokens[:, t])
        np.testing.assert_allclose(np.asarray(lt), np.asarray(ld),
                                   atol=2e-5)
    # the cache is genuinely sharded on the head axis
    k_shard = st[0].sharding
    assert "model" in str(k_shard.spec)


def test_generate_scan_matches_generate_greedy():
    """The one-dispatch scan loop must emit token-for-token what the
    per-token generate() loop emits in greedy mode, and continue to a
    valid state (same cache semantics)."""
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    prompt = rs.randint(0, V, (2, 4))
    n = 6
    ref = dec.generate(prompt, n, temperature=0.0)
    got = dec.generate_scan(prompt, n, temperature=0.0)
    np.testing.assert_array_equal(got, ref)
    # sampled mode: right shape/range, deterministic per seed
    s1 = dec.generate_scan(prompt, n, temperature=1.0, top_k=5, seed=3)
    s2 = dec.generate_scan(prompt, n, temperature=1.0, top_k=5, seed=3)
    assert s1.shape == (2, n) and (s1 >= 0).all() and (s1 < V).all()
    np.testing.assert_array_equal(s1, s2)
    # single-token edge: no scan iterations at all
    one = dec.generate_scan(prompt, 1, temperature=0.0)
    np.testing.assert_array_equal(one, ref[:, :1])


def test_generate_scan_eos_early_exit():
    """eos rows freeze to eos-padding (beam_search's convention) and the
    device while_loop exits once every row finished: the no-eos scan
    output must agree with the eos run up to each row's first eos."""
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    prompt = rs.randint(0, V, (3, 3))
    n = 8
    free = dec.generate_scan(prompt, n, temperature=0.0)
    # choose an eos the greedy run actually emits mid-sequence
    eos = int(free[0, 2])
    got = dec.generate_scan(prompt, n, temperature=0.0, eos_id=eos)
    assert got.shape == free.shape
    for r in range(free.shape[0]):
        hits = np.where(free[r] == eos)[0]
        cut = (hits[0] + 1) if len(hits) else n
        np.testing.assert_array_equal(got[r, :cut], free[r, :cut])
        assert (got[r, cut:] == eos).all()
    # sampling path of the eos loop: same prefix property vs the
    # identically-seeded no-eos sampled run (rng key handling must not
    # diverge between the scan and while_loop bodies)
    s_free = dec.generate_scan(prompt, n, temperature=0.8, seed=5)
    s_eos = int(s_free[1, 1])
    s_got = dec.generate_scan(prompt, n, temperature=0.8, seed=5,
                              eos_id=s_eos)
    for r in range(s_free.shape[0]):
        hits = np.where(s_free[r] == s_eos)[0]
        cut = (hits[0] + 1) if len(hits) else n
        np.testing.assert_array_equal(s_got[r, :cut], s_free[r, :cut])
        assert (s_got[r, cut:] == s_eos).all()


def test_slot_pool_variable_length_parity():
    """ISSUE 6 satellite: variable-length prompts co-batched in ONE
    slot-pool batch (left-padded prefill + per-slot [start, cursor]
    windows) must reproduce per-request single-batch decode exactly —
    prefill next-token logits AND every subsequent step_slots tick."""
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    lengths = [3, 7, 5]
    B, P = len(lengths), 8
    prompts = [rs.randint(0, V, ln) for ln in lengths]
    padded = np.zeros((B, P), np.int64)
    for b, p in enumerate(prompts):
        padded[b, P - len(p):] = p
    cache, logits = dec.prefill_padded(padded, lengths)
    logits = np.asarray(logits)
    start = (P - np.asarray(lengths)).astype(np.int32)
    cursor = np.full(B, P, np.int32)

    # reference: each request prefilled alone at its own length
    refs = [dec.prefill(p[None]) for p in prompts]
    for b in range(B):
        np.testing.assert_allclose(
            logits[b, -1], np.asarray(refs[b][1])[0, -1], atol=2e-5)

    # co-batched greedy steps, every row at a DIFFERENT cache position
    ref_states = [r[0] for r in refs]
    toks = np.array([np.asarray(r[1])[0, -1].argmax() for r in refs])
    for _ in range(4):
        cache, lg = dec.step_slots(cache, toks, start, cursor)
        cursor += 1
        lg = np.asarray(lg)
        nxt = []
        for b in range(B):
            ref_states[b], rlg = dec.step(ref_states[b], toks[b:b + 1])
            rlg = np.asarray(rlg)[0]
            np.testing.assert_allclose(lg[b], rlg, atol=2e-5)
            nxt.append(rlg.argmax())
        toks = np.array(nxt)


def test_slot_pool_adopt_row_mid_flight():
    """adopt_row replaces ONE slot's cache without perturbing the other
    slots: a row admitted mid-flight decodes exactly like a fresh
    single-request decode while its neighbor's stream continues
    unchanged."""
    _, params, rs = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    P = 8
    stay, newcomer = rs.randint(0, V, 6), rs.randint(0, V, 4)

    # slot 0: 'stay', slot 1: garbage that a finished request left behind
    padded = np.zeros((2, P), np.int64)
    padded[0, P - 6:] = stay
    padded[1, :] = rs.randint(0, V, P)
    cache, logits = dec.prefill_padded(padded, [6, P])
    start = np.array([P - 6, 0], np.int32)
    cursor = np.array([P, P], np.int32)
    tok_stay = int(np.asarray(logits)[0, -1].argmax())

    # admit 'newcomer' into slot 1 via the scheduler's admission path
    row, row_logits = dec.prefill_padded(
        np.concatenate([np.zeros(P - 4, np.int64), newcomer])[None], [4])
    cache = dec.adopt_row(cache, row, 1)
    start[1], cursor[1] = P - 4, P
    tok_new = int(np.asarray(row_logits)[0, -1].argmax())

    # references decoded alone
    st_stay, lg = dec.prefill(stay[None])
    assert int(np.asarray(lg)[0, -1].argmax()) == tok_stay
    st_new, lg = dec.prefill(newcomer[None])
    assert int(np.asarray(lg)[0, -1].argmax()) == tok_new

    toks = np.array([tok_stay, tok_new])
    for _ in range(3):
        cache, lg = dec.step_slots(cache, toks, start, cursor)
        cursor += 1
        lg = np.asarray(lg)
        st_stay, r0 = dec.step(st_stay, toks[0:1])
        st_new, r1 = dec.step(st_new, toks[1:2])
        np.testing.assert_allclose(lg[0], np.asarray(r0)[0], atol=2e-5)
        np.testing.assert_allclose(lg[1], np.asarray(r1)[0], atol=2e-5)
        toks = np.array([np.asarray(r0)[0].argmax(),
                         np.asarray(r1)[0].argmax()])


def test_slot_pool_validation():
    _, params, _ = _bound_model()
    dec = KVDecoder(params, num_layers=L, num_heads=H, max_len=T)
    padded = np.zeros((1, 8), np.int64)
    for bad in ([0], [9], [4, 4]):
        try:
            dec.prefill_padded(padded, bad)
            assert False, f"lengths {bad} should have been rejected"
        except ValueError:
            pass
    try:
        dec.prefill_padded(np.zeros((1, T + 1), np.int64), [1])
        assert False, "padded width beyond max_len should be rejected"
    except ValueError:
        pass
    cache = dec.init_slot_state(2)
    try:
        dec.step_slots(cache, np.zeros(2, np.int64),
                       np.zeros(2, np.int32), np.array([0, T], np.int32))
        assert False, "cursor at max_len should be rejected"
    except ValueError:
        pass
    try:
        dec.adopt_row(cache, dec.init_slot_state(2), 0)
        assert False, "non-batch-1 row cache should be rejected"
    except ValueError:
        pass
