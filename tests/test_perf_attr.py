"""Per-program performance attribution plane (ISSUE 20).

Covers the acceptance criteria: guarded cost capture (backends without
``cost_analysis`` yield an "unknown" row, never a raise), non-null CPU
MFU for the fwd+bwd program, step buckets summing to the step wall on
a live fit loop, the ``GET /profile`` shape, explain.py render + diff,
zero per-batch host syncs with the plane ARMED, the Speedometer's
sync-free ``mfu=`` suffix, and the bench-trend direction pins for the
new metric names.
"""
import importlib.util
import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu import telemetry as tm
from mxnet_tpu.telemetry import perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _load_tool(name):
    """Import a tools/ script by path (tools/ is not a package)."""
    spec = importlib.util.spec_from_file_location(
        "perf_test_" + name, os.path.join(TOOLS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def plane():
    """Armed plane + live registry, fully reset around each test."""
    tm.enable()
    tm.reset()
    perf.reset()
    perf.enable()
    yield perf
    perf.disable()
    perf.reset()
    tm.reset()
    tm.disable()


def _mlp():
    data = sym.Variable("data")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=16, name="fc1"),
                       act_type="relu")
    return sym.SoftmaxOutput(sym.FullyConnected(h, num_hidden=10,
                                                name="fc2"), name="softmax")


def _fit(n_batches=6, num_epoch=1):
    rs = np.random.RandomState(7)
    x = rs.randn(16 * n_batches, 8).astype(np.float32)
    y = rs.randint(0, 10, (16 * n_batches,)).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16, shuffle=False)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(it, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.1),),
            num_epoch=num_epoch)
    return mod


# ---------------------------------------------------------------------------
# peak table + derivation
# ---------------------------------------------------------------------------

def test_peak_table_matching_rules():
    # v5p must win over the v5 substring; cpu is a nominal reference
    assert perf.peak_flops("TPU v5p") == 459.0e12
    assert perf.peak_flops("TPU v5 lite") == 197.0e12
    assert perf.peak_flops("cpu") == 0.1e12
    assert perf.peak_flops("quantum9000") is None
    assert perf.peak_bytes_per_sec("TPU v4") == 1228.0e9
    # machine balance = peak flops / peak bytes; None off-table
    assert perf.machine_balance("cpu") == pytest.approx(2.0)
    assert perf.machine_balance("quantum9000") is None


def test_bench_peak_table_is_the_shared_one():
    """Satellite: bench.py must report against the SAME peaks the live
    plane derives MFU from — the table lives in perf.py only."""
    spec = importlib.util.spec_from_file_location(
        "perf_test_bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert not hasattr(bench, "_PEAK_TFLOPS")
    assert bench._peak_flops("TPU v5p") == perf.peak_flops("TPU v5p")
    assert bench._peak_flops("nope") is None


# ---------------------------------------------------------------------------
# cost capture (guarded)
# ---------------------------------------------------------------------------

class _FakeCompiled:
    def __init__(self, cost):
        self._cost = cost

    def cost_analysis(self):
        if isinstance(self._cost, Exception):
            raise self._cost
        return self._cost


class _FakeLowered:
    def __init__(self, cost):
        self._cost = cost

    def compile(self):
        return _FakeCompiled(self._cost)


class _FakeJitted:
    def __init__(self, cost):
        self._cost = cost

    def lower(self, *a, **k):
        return _FakeLowered(self._cost)


def test_attach_cost_analysis_backend_without_support(plane):
    """A backend whose executable has no usable cost_analysis must
    yield an 'unknown' row and never raise."""
    class NoCost:
        def lower(self, *a, **k):
            raise AttributeError("no lower on this backend")

    assert plane.attach_cost_analysis("progA", NoCost()) is False
    assert plane.attach_cost_analysis(
        "progB", _FakeJitted(RuntimeError("unimplemented"))) is False
    rows = {r["program"]: r for r in plane.cost_table()}
    assert rows["progA"]["source"] == "unknown"
    assert rows["progA"]["flops"] is None
    assert rows["progB"]["source"] == "unknown"


def test_attach_cost_analysis_real_row_and_list_shape(plane):
    # newer jax returns a dict; older returned [dict] — both accepted
    assert plane.attach_cost_analysis(
        "progC", _FakeJitted({"flops": 1200.0, "bytes accessed": 600.0}))
    assert plane.attach_cost_analysis(
        "progD", _FakeJitted([{"flops": 7.0, "bytes accessed": 14.0}]))
    rows = {r["program"]: r for r in plane.cost_table()}
    assert rows["progC"] == {
        "program": "progC", "flops": 1200.0, "bytes_accessed": 600.0,
        "peak_memory": None, "source": "cost_analysis"}
    assert rows["progD"]["flops"] == 7.0


def test_attach_disarmed_records_nothing():
    perf.disable()
    try:
        assert perf.attach_cost_analysis(
            "progE", _FakeJitted({"flops": 1.0})) is False
        assert perf.cost_table() == []
    finally:
        perf.reset()


def test_cpu_executor_gets_real_cost_row_and_mfu(plane):
    """Acceptance: on CPU the fwd+bwd program's MFU is non-null — the
    capture must NOT skip the cpu backend (the memory plane does)."""
    net = _mlp()
    ex = net.simple_bind(mx.cpu(), data=(8, 8))
    for _ in range(3):
        ex.forward(is_train=True)
        ex.backward()
    payload = plane.profile_payload()
    assert payload["device_kind"] == "cpu"
    row = payload["programs"][0]
    assert row["cost_source"] == "cost_analysis"
    assert row["flops"] and row["flops"] > 0
    assert row["mfu"] is not None and row["mfu"] > 0
    assert row["roofline"] in ("compute_bound", "memory_bound")
    assert row["dispatches"] == 3


# ---------------------------------------------------------------------------
# runtime ledger + step decomposition
# ---------------------------------------------------------------------------

def test_fit_buckets_sum_to_step_wall(plane):
    """Acceptance: in-step buckets partition each step's wall, so their
    ledger sums match the accumulated step wall within 10% on a live
    CPU fit loop (exact by construction up to float rounding)."""
    _fit(n_batches=6, num_epoch=2)
    payload = plane.profile_payload()
    steps = payload["steps"]
    assert steps["count"] == 12
    in_sum = sum(b["seconds"] for b in payload["buckets"].values()
                 if b["in_step"])
    assert steps["wall_s"] > 0
    assert abs(in_sum - steps["wall_s"]) <= 0.10 * steps["wall_s"]
    assert {"data_wait", "dispatch", "window_stall"} <= \
        set(payload["buckets"])
    # the epoch drain is outside the identity but on the ledger
    assert payload["buckets"]["boundary_sync"]["in_step"] is False
    # and the fwd+bwd program carried per-dispatch wall + a cost row
    prog = payload["programs"][0]
    assert prog["dispatches"] >= 12
    assert prog["mfu"] is not None


def test_fit_publishes_metric_families(plane):
    _fit(n_batches=4)
    plane.publish_gauges()
    reg = tm.get_registry()
    assert reg.get("program_wall_seconds").total() > 0
    assert reg.get("step_time_seconds").total() > 0
    assert reg.get("program_mfu") is not None
    assert reg.get("program_mfu").samples()
    assert reg.get("program_cost").samples()


def test_disarmed_records_nothing():
    perf.reset()
    perf.disable()
    perf.record_dispatch("p", 0.5)
    perf.record_step_buckets(1.0, dispatch=1.0)
    perf.record_bucket("boundary_sync", 0.1)
    assert perf.runtime_table() == []
    assert perf.bucket_table() == {}
    assert perf.speedometer_suffix() == ""


# ---------------------------------------------------------------------------
# surfaces: /profile, flight dump, Speedometer
# ---------------------------------------------------------------------------

def test_profile_endpoint_shape(plane):
    plane.record_cost("p1", flops=100.0, bytes_accessed=50.0,
                      source="cost_analysis")
    plane.record_dispatch("p1", 0.25)
    plane.record_step_buckets(0.3, data_wait=0.05, dispatch=0.25)
    srv = tm.start_http_server(0)
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/profile", timeout=10).read()
        doc = json.loads(body)
        assert doc["version"] == 1 and doc["armed"] is True
        assert doc["device_kind"] == "cpu"
        assert doc["programs_total"] == 1
        p = doc["programs"][0]
        assert p["program"] == "p1" and p["dispatches"] == 1
        assert p["mfu"] == pytest.approx(100.0 / (0.25 * 0.1e12))
        assert p["roofline"] == "compute_bound"  # 2.0 intensity on cpu
        assert doc["buckets"]["dispatch"]["seconds"] == pytest.approx(0.25)
        assert doc["steps"] == {"count": 1, "wall_s": pytest.approx(0.3)}
        # the scrape also derived the gauges for /metrics.json
        jbody = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10).read()
        fams = json.loads(jbody)["metrics"]
        assert fams["program_mfu"]["samples"]
        assert fams["program_roofline"]["samples"]
    finally:
        srv.shutdown()


def test_profile_topn_truncates_but_counts_all(plane, monkeypatch):
    for i in range(5):
        plane.record_dispatch("prog%d" % i, 0.1 * (i + 1))
    monkeypatch.setenv("MXTPU_PROFILE_TOPN", "2")
    doc = plane.profile_payload()
    assert doc["programs_total"] == 5 and len(doc["programs"]) == 2
    assert doc["programs"][0]["program"] == "prog4"  # ranked by wall
    assert len(plane.profile_payload(topn=0)["programs"]) == 5


def test_flight_dump_embeds_untruncated_profile(plane, tmp_path):
    from mxnet_tpu.telemetry import health

    for i in range(3):
        plane.record_dispatch("prog%d" % i, 0.1)
    path = health.dump_flight_record(str(tmp_path / "dump.json"),
                                     trigger="test")
    with open(path) as f:
        dump = json.load(f)
    assert dump["perf"]["programs_total"] == 3
    assert len(dump["perf"]["programs"]) == 3


def test_speedometer_suffix_rides_log_line_with_zero_syncs(
        plane, caplog, monkeypatch):
    """Satellite: the armed Speedometer line carries mfu + dominant
    bucket from pure ledger reads — zero device syncs added."""
    import logging

    from mxnet_tpu.callback import Speedometer

    plane.record_cost("p1", flops=1e9, bytes_accessed=1e9,
                      source="cost_analysis")
    plane.record_dispatch("p1", 0.1)
    plane.record_step_buckets(0.12, data_wait=0.02, dispatch=0.1)

    counts = {"n": 0}
    orig_asnumpy = nd.NDArray.asnumpy
    orig_wait = nd.NDArray.wait_to_read

    def counted_asnumpy(self):
        counts["n"] += 1
        return orig_asnumpy(self)

    def counted_wait(self):
        counts["n"] += 1
        return orig_wait(self)

    monkeypatch.setattr(nd.NDArray, "asnumpy", counted_asnumpy)
    monkeypatch.setattr(nd.NDArray, "wait_to_read", counted_wait)

    class P:
        epoch, nbatch, eval_metric = 0, 2, None

    speedo = Speedometer(batch_size=16, frequent=2)
    with caplog.at_level(logging.INFO):
        speedo(type("P0", (), {"epoch": 0, "nbatch": 0,
                               "eval_metric": None})())
        speedo(P())
    line = "\n".join(r.getMessage() for r in caplog.records)
    assert "mfu=0.10" in line and "top=dispatch" in line
    assert counts["n"] == 0  # the suffix added no host syncs

    # disarmed: the suffix vanishes, the line survives
    perf.disable()
    caplog.clear()
    with caplog.at_level(logging.INFO):
        speedo(type("P1", (), {"epoch": 0, "nbatch": 4,
                               "eval_metric": None})())
    line = "\n".join(r.getMessage() for r in caplog.records)
    assert "Speed:" in line and "mfu=" not in line


def test_perf_armed_fit_keeps_zero_per_batch_syncs(plane, monkeypatch):
    """Acceptance: arming the plane must not add per-batch host syncs —
    sync counts stay flat as the batch count quadruples."""
    from mxnet_tpu import engine

    counts = {"n": 0}
    orig_asnumpy = nd.NDArray.asnumpy
    orig_wait = engine.wait_for_var

    def counted_asnumpy(self):
        counts["n"] += 1
        return orig_asnumpy(self)

    def counted_wait(arr):
        counts["n"] += 1
        return orig_wait(arr)

    def run(nbatch):
        counts["n"] = 0
        rs = np.random.RandomState(3)
        x = rs.randn(16 * nbatch, 8).astype(np.float32)
        y = rs.randint(0, 10, (16 * nbatch,)).astype(np.float32)
        it = mx.io.NDArrayIter(x, y, batch_size=16, shuffle=False)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.fit(it, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.1),), num_epoch=1)
        return counts["n"]

    monkeypatch.setattr(nd.NDArray, "asnumpy", counted_asnumpy)
    monkeypatch.setattr(engine, "wait_for_var", counted_wait)
    small = run(4)
    large = run(16)
    assert large == small, (small, large)


# ---------------------------------------------------------------------------
# explain.py
# ---------------------------------------------------------------------------

def _synthetic_profile(wall=0.5, mfu_flops=2.5e10, steps=10,
                       dispatch=0.45, data_wait=0.04, stall=0.01):
    total = dispatch + data_wait + stall
    return {
        "version": 1, "armed": True, "device_kind": "cpu",
        "peak_flops": 0.1e12, "peak_bytes_per_sec": 50.0e9,
        "machine_balance": 2.0,
        "programs": [{
            "program": "fused_step[net]", "wall_s": wall,
            "dispatches": steps, "flops": mfu_flops / steps,
            "bytes_accessed": 1e9, "peak_memory": 1 << 20,
            "cost_source": "cost_analysis",
            "mfu": mfu_flops / (wall * 0.1e12),
            "intensity": mfu_flops / steps / 1e9,
            "roofline_ratio": 1.2, "roofline": "compute_bound"}],
        "programs_total": 1,
        "buckets": {
            "dispatch": {"seconds": dispatch, "count": steps,
                         "in_step": True},
            "data_wait": {"seconds": data_wait, "count": steps,
                          "in_step": True},
            "window_stall": {"seconds": stall, "count": steps,
                             "in_step": True},
            "boundary_sync": {"seconds": 0.002, "count": 1,
                              "in_step": False}},
        "steps": {"count": steps, "wall_s": total},
    }


def _run_explain(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(TOOLS, "explain.py"), *argv],
        capture_output=True, text=True, timeout=60)


def test_explain_renders_profile_and_flight_dump(tmp_path):
    prof = tmp_path / "prof.json"
    prof.write_text(json.dumps(_synthetic_profile()))
    r = _run_explain(str(prof))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fused_step[net]" in r.stdout
    assert "compute_bound" in r.stdout
    assert "sanity:" in r.stdout and "DIVERGED" not in r.stdout
    assert "boundary_sync" in r.stdout and "(outside steps)" in r.stdout

    # a flight dump carries the same document under "perf"
    dump = tmp_path / "dump.json"
    dump.write_text(json.dumps({"reason": "oom",
                                "perf": _synthetic_profile()}))
    r = _run_explain(str(dump))
    assert r.returncode == 0 and "fused_step[net]" in r.stdout


def test_explain_sanity_line_flags_divergence(tmp_path):
    prof = _synthetic_profile()
    prof["steps"]["wall_s"] *= 1.5  # a stamp went missing
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(prof))
    r = _run_explain(str(p))
    assert r.returncode == 0
    assert "DIVERGED" in r.stdout


def test_explain_diff_directions(tmp_path):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_synthetic_profile(wall=0.5, dispatch=0.45)))
    b.write_text(json.dumps(_synthetic_profile(wall=0.6, dispatch=0.55,
                                               mfu_flops=2.0e10)))
    r = _run_explain("diff", str(a), str(b))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fused_step[net]" in r.stdout
    assert "+20.0%" in r.stdout          # wall moved up
    assert "ms/step" in r.stdout         # per-step bucket normalization
    assert "step wall:" in r.stdout


def test_explain_rejects_non_profile_json(tmp_path):
    p = tmp_path / "junk.json"
    p.write_text(json.dumps({"hello": 1}))
    r = _run_explain(str(p))
    assert r.returncode == 1
    assert "neither" in r.stderr


# ---------------------------------------------------------------------------
# bench_trend direction pins (satellite)
# ---------------------------------------------------------------------------

def test_bench_trend_directions_for_perf_metrics():
    trend = _load_tool("bench_trend")
    # higher-is-better: utilization + throughput regress DOWN
    assert not trend.lower_is_better("mfu")
    assert not trend.lower_is_better("dispatch_program_mfu")
    assert not trend.lower_is_better("decode_tokens_per_sec")
    # the override wins even when a lower-is-better token rides along
    assert not trend.lower_is_better("mfu_stall_adjusted")
    # lower-is-better: waiting regresses UP
    assert trend.lower_is_better("data_wait_ms_per_step")
    assert trend.lower_is_better("window_stall_seconds")


# ---------------------------------------------------------------------------
# bench agreement (acceptance: within 5% in _dispatch_micro)
# ---------------------------------------------------------------------------

def test_dispatch_micro_mfu_agreement():
    spec = importlib.util.spec_from_file_location(
        "perf_test_bench2", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    try:
        out = bench._dispatch_micro()
    finally:
        perf.reset()
        tm.reset()
        tm.disable()
    assert out["recompiles"] == 0  # the cost capture must not count
    a = out["dispatch_bench_mfu"]
    b = out["dispatch_program_mfu"]
    assert a and b
    assert abs(a - b) / max(a, b) <= 0.05, out
