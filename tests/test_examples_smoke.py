"""Example-workload smoke harness (parity: the reference's
tests/nightly + example CI — run real example scripts end-to-end at
reduced sizes and require their success markers).

Gated behind MXTPU_EXAMPLE_TESTS=1: each script costs minutes on a
small box, so the default CI run skips them; the nightly/judge run
flips the flag.  Scripts already self-assert (TRAIN OK / STYLE OK /
...); this harness pins that they KEEP doing so after framework
changes.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EX = os.path.join(REPO, "examples")

CASES = [
    ("speech-demo", "train_lstm_proj.py",
     ["--train_num_epochs=2", "--train_min_frame_acc=0.0"], "TRAIN OK"),
    ("neural-style", "neural_style.py", ["--steps", "25"], "STYLE OK"),
    ("warpctc", "ocr_toy.py", ["--num-steps", "10"], "done"),
    ("kaggle-ndsb2", "train.py",
     ["--epochs", "1", "--max-crps", "1.0", "--work", "/tmp/smoke_ndsb2"],
     "NDSB2 OK"),
    ("rcnn", "train_end2end.py", ["--steps", "15", "--log-interval", "15"],
     "VOC07_mAP"),
    ("image-classification", "score.py", [], "SCORE OK"),
    ("gan", "cgan.py", ["--num-batches", "400"], "CGAN OK"),
    ("bayesian-methods", "bdk_toy.py",
     ["--burn-in", "400", "--samples", "100", "--thin", "8",
      "--student-epochs", "200"], "BDK OK"),
    ("recommenders", "implicit.py", ["--epochs", "8"], "IMPLICIT OK"),
    ("adversary", "adversary_generation.py", [], "ADVERSARY OK"),
    ("adversary", "adversarial_training.py", [], "ADVTRAIN OK"),
    ("autoencoder", "mnist_sae.py", [], "SAE OK"),
    ("dec", "dec_cluster.py", [], "DEC OK"),
    ("nce-loss", "toy_softmax.py", [], "SOFTMAX OK"),
    ("nce-loss", "toy_nce.py", [], "NCE OK"),
    ("nce-loss", "wordvec.py", ["--steps", "350"], "WORDVEC OK"),
    ("cnn_text_classification", "text_cnn.py", [], "TRAIN OK"),
    ("fcn-xs", "fcn_xs.py", ["--work", "/tmp/smoke_fcnxs"], "FCNXS OK"),
    ("fcn-xs", "image_segmentaion.py", ["--work", "/tmp/smoke_fcnxs_seg"],
     "SEG OK"),  # own dir: self-trains, no ordering coupling
    ("bi-lstm-sort", "lstm_sort.py",
     ["--impl", "fused", "--work", "/tmp/smoke_bilstm"], "SORT OK"),
    ("stochastic-depth", "sd_mnist.py", [], "SD OK"),
    ("numpy-ops", "numpy_softmax.py", [], "NUMPYOP OK"),
    ("numpy-ops", "weighted_logistic_regression.py", [], "WLR OK"),
    ("profiler", "profiler_matmul.py", [], "PROF OK"),
    ("profiler", "profiler_ndarray.py", [], "PROF OK"),
    ("profiler", "profiler_imageiter.py", [], "PROF OK"),
    ("bi-lstm-sort", "infer_sort.py",
     ["--impl", "cells", "--epochs", "14", "--work", "/tmp/smoke_bilstm_c"],
     "INFER OK"),  # own dir; covers the cell-API path end to end
]


@pytest.mark.parametrize("dirname,script,args,marker",
                         CASES, ids=[c[0] + "/" + c[1] for c in CASES])
def test_example_smoke(dirname, script, args, marker):
    if os.environ.get("MXTPU_EXAMPLE_TESTS") != "1":
        pytest.skip("example smokes disabled; set MXTPU_EXAMPLE_TESTS=1")
    env = dict(os.environ, MXTPU_PLATFORM="cpu", PYTHONUNBUFFERED="1")
    r = subprocess.run(
        [sys.executable, script] + args,
        cwd=os.path.join(EX, dirname), env=env,
        capture_output=True, text=True, timeout=1800)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert marker in out, out[-3000:]


def test_notebooks_reexecute():
    """Re-build + re-execute every tutorial notebook (the committed
    .ipynb carry executed outputs; this pins that their assertions stay
    true).  Same gate as the script smokes."""
    if os.environ.get("MXTPU_EXAMPLE_TESTS") != "1":
        pytest.skip("example smokes disabled; set MXTPU_EXAMPLE_TESTS=1")
    import tempfile

    env = dict(os.environ, MXTPU_PLATFORM="cpu", PYTHONUNBUFFERED="1")
    with tempfile.TemporaryDirectory() as tmp:
        # write into a scratch tree: executed outputs carry timings and
        # temp paths, so re-running in place would dirty the committed
        # notebooks on every gated test run
        env["MXTPU_NOTEBOOK_OUT"] = tmp
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "make_notebooks.py")],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=1200)
        assert r.returncode == 0, (r.stdout + r.stderr)[-3000:]
        assert r.stdout.count("wrote ") == 4, r.stdout
