"""Custom-tail vision ops + CTC vs numpy references.

Mirrors the reference's test pattern (tests/python/unittest/test_operator.py:
numpy forward references + finite-difference gradients)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu.ops.registry import invoke


def _np(x):
    return np.asarray(x)


def test_grid_generator_affine():
    # identity affine -> grid equals normalized meshgrid
    theta = np.array([[1, 0, 0, 0, 1, 0]], np.float32)
    out = _np(invoke("GridGenerator", [theta],
                     {"transform_type": "affine", "target_shape": (4, 5)}))
    assert out.shape == (1, 2, 4, 5)
    np.testing.assert_allclose(out[0, 0, 0], np.linspace(-1, 1, 5), rtol=1e-5)
    np.testing.assert_allclose(out[0, 1, :, 0], np.linspace(-1, 1, 4), rtol=1e-5)
    # translation shifts the grid
    theta_t = np.array([[1, 0, 0.5, 0, 1, -0.25]], np.float32)
    out_t = _np(invoke("GridGenerator", [theta_t],
                       {"transform_type": "affine", "target_shape": (4, 5)}))
    np.testing.assert_allclose(out_t[0, 0], out[0, 0] + 0.5, rtol=1e-5)
    np.testing.assert_allclose(out_t[0, 1], out[0, 1] - 0.25, rtol=1e-5)


def test_grid_generator_warp_zero_flow_identity():
    flow = np.zeros((2, 2, 3, 4), np.float32)
    out = _np(invoke("GridGenerator", [flow], {"transform_type": "warp"}))
    np.testing.assert_allclose(out[0, 0, 0], np.linspace(-1, 1, 4), atol=1e-6)
    np.testing.assert_allclose(out[0, 1, :, 0], np.linspace(-1, 1, 3), atol=1e-6)


def test_bilinear_sampler_identity():
    rs = np.random.RandomState(0)
    data = rs.uniform(size=(2, 3, 5, 6)).astype(np.float32)
    theta = np.tile(np.array([[1, 0, 0, 0, 1, 0]], np.float32), (2, 1))
    grid = _np(invoke("GridGenerator", [theta],
                      {"transform_type": "affine", "target_shape": (5, 6)}))
    out = _np(invoke("BilinearSampler", [data, grid]))
    np.testing.assert_allclose(out, data, rtol=1e-4, atol=1e-5)


def test_bilinear_sampler_numpy_reference():
    rs = np.random.RandomState(1)
    data = rs.uniform(size=(1, 2, 4, 4)).astype(np.float32)
    grid = rs.uniform(-1.2, 1.2, size=(1, 2, 3, 3)).astype(np.float32)
    out = _np(invoke("BilinearSampler", [data, grid]))

    # scalar numpy reference
    n, c, h, w = data.shape
    ref = np.zeros((1, 2, 3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            x = (grid[0, 0, i, j] + 1) * (w - 1) / 2
            y = (grid[0, 1, i, j] + 1) * (h - 1) / 2
            x0, y0 = int(np.floor(x)), int(np.floor(y))
            for dy in (0, 1):
                for dx in (0, 1):
                    xi, yi = x0 + dx, y0 + dy
                    if 0 <= xi < w and 0 <= yi < h:
                        wgt = (1 - abs(x - xi)) * (1 - abs(y - yi))
                        ref[0, :, i, j] += wgt * data[0, :, yi, xi]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_spatial_transformer_identity():
    rs = np.random.RandomState(2)
    data = rs.uniform(size=(2, 3, 6, 6)).astype(np.float32)
    loc = np.tile(np.array([[1, 0, 0, 0, 1, 0]], np.float32), (2, 1))
    out = _np(invoke("SpatialTransformer", [data, loc],
                     {"target_shape": (6, 6), "transform_type": "affine",
                      "sampler_type": "bilinear"}))
    np.testing.assert_allclose(out, data, rtol=1e-4, atol=1e-5)


def test_roi_pooling_reference():
    rs = np.random.RandomState(3)
    data = rs.uniform(size=(2, 2, 8, 8)).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 7],
                     [1, 2, 2, 6, 6],
                     [0, 4, 4, 4, 4]], np.float32)
    out = _np(invoke("ROIPooling", [data, rois],
                     {"pooled_size": (2, 2), "spatial_scale": 1.0}))
    assert out.shape == (3, 2, 2, 2)

    def ref_roi(b, x1, y1, x2, y2, ph, pw):
        rw = max(x2 - x1 + 1, 1)
        rh = max(y2 - y1 + 1, 1)
        res = np.zeros((data.shape[1], ph, pw), np.float32)
        for i in range(ph):
            for j in range(pw):
                hs = int(np.floor(i * rh / ph)) + y1
                he = int(np.ceil((i + 1) * rh / ph)) + y1
                ws = int(np.floor(j * rw / pw)) + x1
                we = int(np.ceil((j + 1) * rw / pw)) + x1
                hs, he = max(hs, 0), min(he, 8)
                ws, we = max(ws, 0), min(we, 8)
                if he > hs and we > ws:
                    res[:, i, j] = data[b, :, hs:he, ws:we].max(axis=(1, 2))
        return res

    np.testing.assert_allclose(out[0], ref_roi(0, 0, 0, 7, 7, 2, 2), rtol=1e-5)
    np.testing.assert_allclose(out[1], ref_roi(1, 2, 2, 6, 6, 2, 2), rtol=1e-5)
    np.testing.assert_allclose(out[2], ref_roi(0, 4, 4, 4, 4, 2, 2), rtol=1e-5)


def test_correlation_self_kernel1():
    # correlating a map with itself at zero displacement = mean of squares
    rs = np.random.RandomState(4)
    data = rs.uniform(size=(1, 4, 6, 6)).astype(np.float32)
    out = _np(invoke("Correlation", [data, data],
                     {"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                      "stride2": 1, "pad_size": 1, "is_multiply": True}))
    # grid 3x3 -> 9 channels; center channel (index 4) is zero displacement
    assert out.shape[1] == 9
    border = 1
    center = out[0, 4]
    expect = (data[0] ** 2).mean(axis=0)
    h = center.shape[0]
    np.testing.assert_allclose(
        center, np.pad(expect, 1)[border:border + h, border:border + h],
        rtol=1e-4, atol=1e-5)


def test_multibox_prior_counts_and_centers():
    data = np.zeros((1, 3, 4, 4), np.float32)
    out = _np(invoke("MultiBoxPrior", [data],
                     {"sizes": (0.5, 0.25), "ratios": (1.0, 2.0)}))
    # |sizes| + |ratios| - 1 = 3 anchors per cell
    assert out.shape == (1, 4 * 4 * 3, 4)
    first = out[0, 0]
    cx, cy = (first[0] + first[2]) / 2, (first[1] + first[3]) / 2
    np.testing.assert_allclose([cx, cy], [0.5 / 4, 0.5 / 4], atol=1e-6)
    np.testing.assert_allclose(first[2] - first[0], 0.5, atol=1e-6)


def test_multibox_target_matching():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0]]], np.float32)
    # one gt box of class 2 overlapping anchor 0 exactly
    label = np.array([[[2, 0.0, 0.0, 0.5, 0.5],
                       [-1, 0, 0, 0, 0]]], np.float32)
    cls_pred = np.zeros((1, 4, 3), np.float32)
    loc_t, loc_m, cls_t = invoke(
        "MultiBoxTarget", [anchors, label, cls_pred], {})
    cls_t = _np(cls_t)
    assert cls_t.shape == (1, 3)
    assert cls_t[0, 0] == 3.0  # cls 2 -> target 3 (background=0 offset)
    assert cls_t[0, 1] == 0.0
    loc_m = _np(loc_m).reshape(1, 3, 4)
    assert loc_m[0, 0].sum() == 4.0 and loc_m[0, 1].sum() == 0.0
    # perfectly matched anchor -> zero loc target
    np.testing.assert_allclose(_np(loc_t).reshape(1, 3, 4)[0, 0], 0.0,
                               atol=1e-5)


def test_multibox_target_padded_labels_dont_corrupt_matching():
    """Padded (cls=-1) label rows must not steal/unclaim valid gts' anchors
    (regression: scatter race between padding rows and valid rows)."""
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0]]], np.float32)
    # gt IoU with anchor 0 is ~0.49 < threshold: only bipartite stage matches
    gt_row = [1, 0.0, 0.0, 0.35, 0.35]
    for npad in (0, 1, 3):
        label = np.array([[gt_row] + [[-1, 0, 0, 0, 0]] * npad], np.float32)
        cls_pred = np.zeros((1, 4, 3), np.float32)
        _, _, cls_t = invoke("MultiBoxTarget", [anchors, label, cls_pred], {})
        assert _np(cls_t)[0, 0] == 2.0, f"npad={npad}: gt lost its anchor"


def test_roi_pooling_half_rounding():
    """ROI coords scale-round like C round() (half away from zero), not
    banker's rounding: x=40 * 1/16 = 2.5 -> 3."""
    data = np.arange(16 * 16, dtype=np.float32).reshape(1, 1, 16, 16)
    rois = np.array([[0, 40, 40, 80, 80]], np.float32)  # /16 -> 2.5..5.0
    out = _np(invoke("ROIPooling", [data, rois],
                     {"pooled_size": (1, 1), "spatial_scale": 1.0 / 16}))
    # rounds to [3,3]..[5,5] -> max = data[5,5]; banker's would give [2..5]
    assert out[0, 0, 0, 0] == data[0, 0, 5, 5]


def test_ctc_loss_op_returns_loss_vector():
    """_contrib_CTCLoss contract: (T,N,C) data -> (N,) loss."""
    rs = np.random.RandomState(8)
    data = rs.uniform(-1, 1, size=(5, 3, 7)).astype(np.float32)
    label = np.array([[1, 2], [3, 0], [0, 0]], np.float32)
    out = _np(invoke("_contrib_CTCLoss", [data, label], {}))
    assert out.shape == (3,)
    assert (out > 0).all()


def test_multibox_detection_nms_topk():
    """nms_topk statically bounds the suppression loop but must keep
    suppression semantics within the top-k."""
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.01, 0.01, 0.51, 0.51],
                         [0.5, 0.5, 1.0, 1.0]]], np.float32)
    cls_prob = np.array([[[0.1, 0.1, 0.1],
                          [0.9, 0.8, 0.1],
                          [0.0, 0.1, 0.8]]], np.float32)
    loc_pred = np.zeros((1, 12), np.float32)
    out = _np(invoke("MultiBoxDetection", [cls_prob, loc_pred, anchors],
                     {"nms_threshold": 0.5, "nms_topk": 2}))
    kept = out[0][out[0, :, 0] >= 0]
    # anchor 1 still suppressed by anchor 0; anchor 2 past topk -> dropped
    assert len(kept) == 1 and kept[0, 0] == 0.0


def test_multibox_detection_nms():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.01, 0.01, 0.51, 0.51],
                         [0.5, 0.5, 1.0, 1.0]]], np.float32)
    # class probs: (N, num_cls+1, A); anchors 0/1 confident class 1,
    # anchor 2 confident class 2
    cls_prob = np.array([[[0.1, 0.1, 0.1],
                          [0.9, 0.8, 0.1],
                          [0.0, 0.1, 0.8]]], np.float32)
    loc_pred = np.zeros((1, 12), np.float32)
    out = _np(invoke("MultiBoxDetection", [cls_prob, loc_pred, anchors],
                     {"nms_threshold": 0.5}))
    assert out.shape == (1, 3, 6)
    kept = out[0][out[0, :, 0] >= 0]
    # anchor 1 suppressed by anchor 0 (same class, IoU > 0.5)
    assert len(kept) == 2
    classes = sorted(kept[:, 0].tolist())
    assert classes == [0.0, 1.0]


def test_ctc_loss_simple():
    from mxnet_tpu.ops.ctc import ctc_loss
    # T=1, single label: loss = -log softmax(label)
    logits = np.array([[[0.0, 2.0, 0.0]]], np.float32)  # (T=1, N=1, C=3)
    labels = np.array([[1]], np.int32)
    loss = _np(ctc_loss(logits, labels))
    p = np.exp(2.0) / (2 + np.exp(2.0))
    np.testing.assert_allclose(loss[0], -np.log(p), rtol=1e-5)


def test_ctc_loss_two_frames():
    from mxnet_tpu.ops.ctc import ctc_loss
    # T=2, label "a": paths = {a a, blank a, a blank}
    rs = np.random.RandomState(5)
    logits = rs.uniform(-1, 1, size=(2, 1, 3)).astype(np.float32)
    labels = np.array([[1]], np.int32)
    loss = _np(ctc_loss(logits, labels))
    p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    prob = (p[0, 0, 1] * p[1, 0, 1] + p[0, 0, 0] * p[1, 0, 1]
            + p[0, 0, 1] * p[1, 0, 0])
    np.testing.assert_allclose(loss[0], -np.log(prob), rtol=1e-4)


def test_warpctc_op_backward_ignores_head_grad():
    import jax
    import jax.numpy as jnp
    rs = np.random.RandomState(6)
    data = jnp.asarray(rs.uniform(-1, 1, size=(4, 2, 5)).astype(np.float32))
    label = jnp.asarray(np.array([[1, 2], [3, 0]], np.int32))

    out = invoke("WarpCTC", [data, label], {"label_length": 2})
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, rtol=1e-5)

    def f(d):
        return invoke("WarpCTC", [d, label], {"label_length": 2}).sum()

    g = jax.grad(f)(data)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0

    # gradient equals d(ctc)/d(data) regardless of head grad scaling
    def f2(d):
        return (invoke("WarpCTC", [d, label], {"label_length": 2}) * 7.0).sum()

    g2 = jax.grad(f2)(data)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g2), rtol=1e-5)


def test_vision_ops_in_symbol_graph():
    """Vision ops compose through the symbolic executor."""
    from mxnet_tpu import symbol as sym
    data = sym.Variable("data")
    rois = sym.Variable("rois")
    pooled = sym.ROIPooling(data=data, rois=rois, pooled_size=(2, 2),
                            spatial_scale=1.0, name="roi")
    exe = pooled.simple_bind(ctx=mx.context.cpu(),
                             data=(1, 2, 8, 8), rois=(2, 5), grad_req="null")
    rs = np.random.RandomState(7)
    exe.arg_dict["data"][:] = rs.uniform(size=(1, 2, 8, 8)).astype(np.float32)
    exe.arg_dict["rois"][:] = np.array([[0, 0, 0, 7, 7], [0, 1, 1, 5, 5]],
                                       np.float32)
    out = exe.forward()[0].asnumpy()
    assert out.shape == (2, 2, 2, 2)


def test_deconvolution_matches_conv_transpose():
    """Deconvolution must be the exact adjoint of Convolution: its output
    equals the input-gradient of the matching conv (the reference
    implements it that way, deconvolution-inl.h), and its shape follows
    (in-1)*s - 2p + k (regression: an extra stride-1 inflated outputs)."""
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 5, 5).astype(np.float32)
    w = rs.rand(3, 4, 3, 3).astype(np.float32)  # (in_ch, out_ch/g, kh, kw)
    stride, pad = (2, 2), (1, 1)

    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                              stride=stride, pad=pad, num_filter=4,
                              no_bias=True)
    assert out.shape == (2, 4, 9, 9)  # (5-1)*2 - 2 + 3

    # adjoint reference: vjp of the forward conv whose weight is w
    # transposed to OIHW (out=3 filters taking 4 channels)
    def conv(y):
        # forward conv 4ch -> 3ch; its OIHW weight (3,4,3,3) IS w
        return jax.lax.conv_general_dilated(
            y, jnp.asarray(w),
            window_strides=stride, padding=[pad, pad],
            dimension_numbers=jax.lax.conv_dimension_numbers(
                (2, 4, 9, 9), (3, 4, 3, 3), ("NCHW", "OIHW", "NCHW")))

    y0 = jnp.zeros((2, 4, 9, 9), jnp.float32)
    _, vjp = jax.vjp(conv, y0)
    (adjoint,) = vjp(jnp.asarray(x))
    np.testing.assert_allclose(out.asnumpy(), np.asarray(adjoint),
                               rtol=1e-4, atol=1e-4)


def test_voc_map_metric_math():
    """VOC mAP metrics (examples/ssd/eval_metric.py): perfect detections
    score 1.0; a known mixed ranking gives the hand-computed AP."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "ssd"))
    from eval_metric import MApMetric, VOC07MApMetric

    gts = np.array([[[0, 0.1, 0.1, 0.4, 0.4],
                     [1, 0.5, 0.5, 0.9, 0.9]]], np.float32)
    perfect = np.array([[[0, 0.9, 0.1, 0.1, 0.4, 0.4],
                         [1, 0.8, 0.5, 0.5, 0.9, 0.9],
                         [-1, 0, 0, 0, 0, 0]]], np.float32)
    m = MApMetric()
    m.update([gts], [perfect])
    assert abs(m.get()[1] - 1.0) < 1e-6
    m07 = VOC07MApMetric()
    m07.update([gts], [perfect])
    assert abs(m07.get()[1] - 1.0) < 1e-6

    # one class, 1 gt, two detections: rank1 false (IoU 0), rank2 true ->
    # precision at the hit = 1/2, continuous AP = 0.5
    mixed = np.array([[[0, 0.9, 0.6, 0.6, 0.9, 0.9],
                       [0, 0.8, 0.1, 0.1, 0.4, 0.4]]], np.float32)
    gts1 = np.array([[[0, 0.1, 0.1, 0.4, 0.4]]], np.float32)
    m2 = MApMetric()
    m2.update([gts1], [mixed])
    assert abs(m2.get()[1] - 0.5) < 1e-6


def test_ssd_example_eval_runs():
    """The SSD-VGG16 graph end-to-end: train steps + deploy-graph mAP
    eval (parity: example/ssd train + evaluate)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, MXTPU_PLATFORM="cpu", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "ssd", "train.py"),
         "--data-size", "64", "--num-steps", "2", "--batch-size", "4",
         "--eval"],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "mAP:" in r.stdout


def test_ssd_trains_to_above_floor_map():
    """SSD train->eval with an asserted mAP floor and a perf line: the
    tiny from-scratch backbone reaches VOC07 mAP well above chance in a
    short run (the VGG16 config matches the reference, which fine-tunes
    pretrained weights; random-init VGG cannot learn in minutes)."""
    import os
    import re
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, MXTPU_PLATFORM="cpu", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "ssd", "train.py"),
         "--backbone", "tiny", "--data-size", "128", "--num-steps", "250",
         "--lr", "0.01", "--assert-map", "0.15"],
        capture_output=True, text=True, timeout=580, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MAP_FLOOR_OK" in r.stdout
    assert re.search(r"train_perf: [0-9.]+ img/s", r.stdout), r.stdout


def test_frcnn_example_trains_to_nonzero_map():
    """The Faster R-CNN workload end-to-end (parity: example/rcnn): RPN
    with sampled anchor batches, gt-augmented proposal targets, detection
    mAP well above chance after a short training run."""
    import os
    import re
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, MXTPU_PLATFORM="cpu", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable,
         os.path.join(repo, "examples", "rcnn", "train_frcnn.py"),
         "--steps", "120", "--batch", "8", "--lr", "0.1", "--eval"],
        capture_output=True, text=True, timeout=580, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    m = re.search(r"mAP: ([0-9.]+)", r.stdout)
    assert m, r.stdout
    assert float(m.group(1)) > 0.15, r.stdout


def test_frcnn_end2end_system(tmp_path):
    """The FULL Faster R-CNN system (examples/rcnn/rcnn/ package):
    AnchorLoader -> proposal_target sampling -> joint 4-loss training
    with the reference's four metrics -> per-class bbox decode + NMS ->
    held-out VOC07 mAP above floor -> checkpoint -> demo detection."""
    import os
    import re
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, MXTPU_PLATFORM="cpu", JAX_PLATFORMS="cpu")
    prefix = str(tmp_path / "frcnn")
    r = subprocess.run(
        [sys.executable,
         os.path.join(repo, "examples", "rcnn", "train_end2end.py"),
         "--steps", "200", "--assert-map", "0.3",
         "--save-prefix", prefix],
        capture_output=True, text=True, timeout=580, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MAP_FLOOR_OK" in r.stdout
    m = re.search(r"VOC07_mAP: ([0-9.]+)", r.stdout)
    assert m and float(m.group(1)) > 0.3, r.stdout

    r = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "rcnn", "demo.py"),
         "--prefix", prefix],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DEMO OK" in r.stdout
