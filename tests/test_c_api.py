"""General C API test: compile the pure-C LeNet training client
(tests/c/train_lenet.c) against libmxtpu_capi.so and require the loss to
drop — the training analogue of test_c_predict.py (parity model: the
reference bindings' train loops over include/mxnet/c_api.h)."""
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "mxnet_tpu", "lib", "libmxtpu_capi.so")
CLIENT = os.path.join(REPO, "tests", "c", "train_lenet.c")


@pytest.fixture(scope="module")
def capi_lib():
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "capi"],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
    return LIB


def test_c_train_lenet(capi_lib, tmp_path):
    exe = tmp_path / "train_lenet"
    r = subprocess.run(
        ["gcc", CLIENT, "-I", os.path.join(REPO, "src"), str(capi_lib),
         "-lm", "-o", str(exe), f"-Wl,-rpath,{os.path.dirname(capi_lib)}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["MXTPU_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([str(exe)], env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "TRAIN OK" in r.stdout
    # the composed graph must expose the expected parameter surface
    assert "conv1_weight" in r.stdout and "fc1_weight" in r.stdout


def test_c_iter_invoke(capi_lib, tmp_path):
    """Data-iterator + imperative-invoke ABI from pure C."""
    exe = tmp_path / "iter_invoke"
    src = os.path.join(REPO, "tests", "c", "iter_invoke.c")
    r = subprocess.run(
        ["gcc", src, "-I", os.path.join(REPO, "src"), str(capi_lib),
         "-lm", "-o", str(exe), f"-Wl,-rpath,{os.path.dirname(capi_lib)}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["MXTPU_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([str(exe)], env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ITER INVOKE OK" in r.stdout
