"""Torch plugin tests (parity model: plugin/torch in the reference —
here verified against torch autograd as the oracle)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import mxnet_tpu as mx
from mxnet_tpu import plugins
from mxnet_tpu.plugins import torch_plugin as tp


def test_torch_module_forward_backward():
    lin = torch.nn.Linear(4, 3)
    mid = tp.register_module(lin)
    rs = np.random.RandomState(0)
    x = rs.normal(size=(5, 4)).astype(np.float32)
    w = lin.weight.detach().numpy().copy()
    b = lin.bias.detach().numpy().copy()

    out = mx.nd.TorchModule(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                            module_id=mid)
    assert np.allclose(out.asnumpy(), x @ w.T + b, atol=1e-6)

    net = mx.sym.MakeLoss(mx.sym.sum(
        mx.sym.TorchModule(mx.sym.Variable("x"), mx.sym.Variable("w"),
                           mx.sym.Variable("b"), module_id=mid) ** 2))
    ex = net.simple_bind(ctx=mx.cpu(), x=(5, 4), w=(3, 4), b=(3,))
    ex.arg_dict["x"][:] = x
    ex.arg_dict["w"][:] = w
    ex.arg_dict["b"][:] = b
    ex.forward(is_train=True)
    ex.backward()

    xt = torch.tensor(x, requires_grad=True)
    lin.zero_grad()
    (lin(xt) ** 2).sum().backward()
    assert np.allclose(ex.grad_dict["x"].asnumpy(), xt.grad.numpy(), atol=1e-5)
    assert np.allclose(ex.grad_dict["w"].asnumpy(), lin.weight.grad.numpy(),
                       atol=1e-5)
    assert np.allclose(ex.grad_dict["b"].asnumpy(), lin.bias.grad.numpy(),
                       atol=1e-5)


def test_torch_module_stochastic_consistency():
    # dropout: backward recompute must use the SAME mask as forward
    drop = torch.nn.Sequential(torch.nn.Dropout(0.5), torch.nn.Linear(4, 4))
    mid = tp.register_module(drop)
    params = [p.detach().numpy().copy() for p in drop.parameters()]
    rs = np.random.RandomState(2)
    x = rs.normal(size=(64, 4)).astype(np.float32)

    net = mx.sym.MakeLoss(mx.sym.sum(
        mx.sym.TorchModule(mx.sym.Variable("x"), mx.sym.Variable("w"),
                           mx.sym.Variable("b"), module_id=mid)))
    ex = net.simple_bind(ctx=mx.cpu(), x=(64, 4), w=(4, 4), b=(4,))
    ex.arg_dict["x"][:] = x
    ex.arg_dict["w"][:] = params[0]
    ex.arg_dict["b"][:] = params[1]
    ex.forward(is_train=True)
    ex.backward()
    # with matching masks, rows dropped in forward get zero input-grad
    # columns in dw: check grads are at least finite and mask-consistent
    dx = ex.grad_dict["x"].asnumpy()
    assert np.isfinite(dx).all()
    # a dropped input element contributes no gradient: the fraction of
    # exact zeros in dx should be ~0.5 (identical masks), not ~0.25
    # (independent fwd/bwd masks would rarely zero the same entries)
    zero_frac = float((dx == 0).mean())
    assert 0.3 < zero_frac < 0.7, zero_frac


def test_torch_module_eval_mode_in_cached_executable():
    bn = torch.nn.BatchNorm1d(4)
    mid = tp.register_module(bn)
    x = np.random.RandomState(3).normal(size=(8, 4)).astype(np.float32)
    args = [p.detach().numpy().copy() for p in bn.parameters()]
    net = mx.sym.TorchModule(mx.sym.Variable("x"), mx.sym.Variable("w"),
                             mx.sym.Variable("b"), module_id=mid)
    ex = net.simple_bind(ctx=mx.cpu(), grad_req="null", x=(8, 4),
                         w=(4,), b=(4,))
    ex.arg_dict["x"][:] = x
    ex.arg_dict["w"][:] = args[0]
    ex.arg_dict["b"][:] = args[1]
    before = [b.detach().numpy().copy() for b in bn.buffers()]
    ex.forward(is_train=False)
    ex.outputs[0].asnumpy()
    after = [b.detach().numpy().copy() for b in bn.buffers()]
    # inference invocation (is_train=False) must not advance BN stats
    for b1, b2 in zip(before, after):
        assert np.allclose(b1, b2)


def test_torch_criterion():
    cid = tp.register_criterion(torch.nn.MSELoss())
    rs = np.random.RandomState(1)
    pred = rs.normal(size=(6, 3)).astype(np.float32)
    target = rs.normal(size=(6, 3)).astype(np.float32)
    loss = mx.nd.TorchCriterion(mx.nd.array(pred), mx.nd.array(target),
                                criterion_id=cid)
    assert np.isclose(float(loss.asnumpy()),
                      float(((pred - target) ** 2).mean()), atol=1e-6)


def test_plugin_flag():
    assert plugins.torch_available


# --------------------------------------------------------------------------
# opencv plugin (parity: plugin/opencv — PIL/native-backed here)
# --------------------------------------------------------------------------
def test_opencv_imdecode_resize_border():
    from PIL import Image
    import io as _io

    from mxnet_tpu.plugins import opencv_plugin as cv

    rs = np.random.RandomState(0)
    img = rs.randint(0, 255, (24, 32, 3), dtype=np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")

    dec = cv.imdecode(buf.getvalue())
    assert dec.shape == (24, 32, 3)
    assert np.array_equal(dec.asnumpy(), img)  # png is lossless

    gray = cv.imdecode(buf.getvalue(), flag=0)
    assert gray.shape == (24, 32, 1)

    small = cv.resize(dec, (16, 12))
    assert small.shape == (12, 16, 3)

    padded = cv.copyMakeBorder(dec, 2, 3, 4, 5, value=7)
    assert padded.shape == (24 + 5, 32 + 9, 3)
    assert (padded.asnumpy()[:2] == 7).all()

    rep = cv.copyMakeBorder(dec, 1, 0, 0, 0, border_type=cv.BORDER_REPLICATE)
    assert np.array_equal(rep.asnumpy()[0], img[0])


def test_opencv_crops_and_normalize():
    from mxnet_tpu.plugins import opencv_plugin as cv

    rs = np.random.RandomState(1)
    img = mx.nd.array(rs.randint(0, 255, (40, 50, 3)).astype(np.uint8))
    crop = cv.fixed_crop(img, 5, 3, 20, 30)
    assert crop.shape == (30, 20, 3)
    out, (x0, y0, w, h) = cv.random_crop(img, (16, 16),
                                         rng=np.random.RandomState(2))
    assert out.shape == (16, 16, 3)
    out2, roi = cv.random_size_crop(img, (16, 16),
                                    rng=np.random.RandomState(3))
    assert out2.shape == (16, 16, 3)
    norm = cv.color_normalize(img, mean=(1.0, 2.0, 3.0), std=(2.0, 2.0, 2.0))
    expect = (img.asnumpy().astype(np.float32) - [1, 2, 3]) / 2.0
    assert np.allclose(norm.asnumpy(), expect)


def test_opencv_cv2_and_fallback_agree():
    """With real cv2 present (this image ships it), the cv2-backed
    kernels and the PIL/native fallback must agree: exactly for
    lossless decode and constant-pad, and in shape for resize (cv2 and
    PIL nearest use different sampling grids, so pixel-exact resize
    agreement is not a contract) — scripts keep working when the
    plugin's backend changes."""
    import io as _io

    from PIL import Image

    from mxnet_tpu.plugins import opencv_plugin as cv

    if cv._cv2 is None:
        import pytest

        pytest.skip("cv2 not in this image")

    rs = np.random.RandomState(4)
    img = rs.randint(0, 255, (21, 17, 3), dtype=np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    raw = buf.getvalue()

    via_cv2 = cv.imdecode(raw).asnumpy()
    real_cv2, cv._cv2 = cv._cv2, None
    try:
        via_pil = cv.imdecode(raw).asnumpy()
        small_pil = cv.resize(mx.nd.array(img), (8, 10),
                              cv.INTER_NEAREST).asnumpy()
        pad_pil = cv.copyMakeBorder(mx.nd.array(img), 1, 2, 3, 4,
                                    value=9).asnumpy()
    finally:
        cv._cv2 = real_cv2
    assert np.array_equal(via_cv2, via_pil)  # both lossless RGB

    small_cv2 = cv.resize(mx.nd.array(img), (8, 10),
                          cv.INTER_NEAREST).asnumpy()
    assert small_cv2.shape == small_pil.shape == (10, 8, 3)

    pad_cv2 = cv.copyMakeBorder(mx.nd.array(img), 1, 2, 3, 4,
                                value=9).asnumpy()
    assert np.array_equal(pad_cv2, pad_pil)
