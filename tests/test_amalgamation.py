"""Amalgamation build test (parity model: the reference's amalgamation
smoke builds): fuse the runtime into one translation unit, compile it
with a bare g++ line, and drive recordio + the engine through it."""
import ctypes
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="g++ unavailable")


def test_amalgamation_builds_and_runs(tmp_path):
    src = tmp_path / "mxtpu-all.cc"
    lib = tmp_path / "libmxtpu-amal.so"
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "amalgamation", "amalgamate.py"),
                        "-o", str(src)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    r = subprocess.run(["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
                        str(src), "-o", str(lib), "-pthread"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    m = ctypes.CDLL(str(lib))
    # recordio roundtrip through the amalgamated runtime
    m.mxr_writer_open.restype = ctypes.c_void_p
    m.mxr_writer_open.argtypes = [ctypes.c_char_p]
    m.mxr_write.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
    m.mxr_writer_close.argtypes = [ctypes.c_void_p]
    m.mxr_open.restype = ctypes.c_void_p
    m.mxr_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    m.mxr_next.restype = ctypes.POINTER(ctypes.c_uint8)
    m.mxr_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    m.mxr_close.argtypes = [ctypes.c_void_p]

    rec = str(tmp_path / "t.rec").encode()
    w = m.mxr_writer_open(rec)
    payloads = [bytes([i]) * (5 + i) for i in range(8)]
    for p in payloads:
        buf = (ctypes.c_uint8 * len(p)).from_buffer_copy(p)
        m.mxr_write(w, buf, len(p))
    m.mxr_writer_close(w)

    rd = m.mxr_open(rec, 0, 1)
    n = ctypes.c_uint64()
    got = []
    while True:
        ptr = m.mxr_next(rd, ctypes.byref(n))
        if not ptr:
            break
        got.append(bytes(ctypes.cast(
            ptr, ctypes.POINTER(ctypes.c_uint8 * n.value)).contents))
    m.mxr_close(rd)
    assert got == payloads

    # the engine symbols must be present too
    m.mxe_create.restype = ctypes.c_void_p
    eng = m.mxe_create(2)
    assert eng
    m.mxe_destroy.argtypes = [ctypes.c_void_p]
    m.mxe_destroy(eng)
