"""Multi-process input pipeline tests (mp_io.MultiProcessImageRecordIter).

Parity model: the reference's sharded threaded ImageRecordIter
(src/io/iter_image_recordio.cc:150-368) — here the fan-out is across
worker processes writing into a shared-memory ring."""
import numpy as np
import pytest

from mxnet_tpu import recordio
from mxnet_tpu.image import MultiProcessImageRecordIter, imencode
from mxnet_tpu.recordio import IRHeader, MXRecordIO


def _write_labeled_rec(tmp_path, n=24, size=16):
    """PNG records (lossless) where pixel value encodes the label: sample
    with label i is a constant image of value (i * 7) % 256."""
    rec = str(tmp_path / "mp.rec")
    w = MXRecordIO(rec, "w")
    for i in range(n):
        img = np.full((size, size, 3), (i * 7) % 256, np.uint8)
        w.write(recordio.pack(IRHeader(0, float(i), i, 0),
                              imencode(img, img_fmt=".png")))
    w.close()
    return rec


@pytest.mark.parametrize("workers", [1, 2])
def test_mp_iter_covers_every_record(tmp_path, workers):
    rec = _write_labeled_rec(tmp_path, n=24)
    it = MultiProcessImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        num_workers=workers, stall_timeout=120)
    try:
        seen = []
        total, pads = 0, 0
        for batch in it:
            data = batch.data[0].asnumpy()
            labels = batch.label[0].asnumpy()
            assert data.shape == (4, 3, 16, 16)
            # zero-copy ring correctness: each sample's pixels must match
            # ITS OWN label (a swapped/corrupted slot breaks this)
            for s in range(4):
                want = (int(labels[s]) * 7) % 256
                np.testing.assert_array_equal(
                    data[s], np.full((3, 16, 16), want, np.float32))
            seen.extend(labels.astype(int).tolist())
            total += data.shape[0]
            pads += batch.pad
        # byte-range InputSplit shards need not be record-even; the
        # invariants are exact coverage net of per-shard wrap padding
        assert total - pads == 24
        assert set(seen) == set(range(24))
        epoch1 = total

        # epoch 2: the barrier opens the next pass with the same count
        it.reset()
        assert sum(b.data[0].shape[0] for b in it) == epoch1
    finally:
        it.close()


def test_mp_iter_uneven_shards_pad(tmp_path):
    # 10 records, 2 workers, batch 4: shards of 5 -> 2 padded batches each
    rec = _write_labeled_rec(tmp_path, n=10)
    it = MultiProcessImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        num_workers=2, stall_timeout=120)
    try:
        batches = list(it)
        total = sum(b.data[0].shape[0] for b in batches)
        assert total - sum(b.pad for b in batches) == 10
        labels = {int(v) for b in batches
                  for v in b.label[0].asnumpy().astype(int)}
        assert labels == set(range(10))
    finally:
        it.close()


def test_mp_iter_close_midway_no_hang(tmp_path):
    rec = _write_labeled_rec(tmp_path, n=24)
    it = MultiProcessImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        num_workers=2, stall_timeout=120)
    next(iter(it))
    it.close()  # must not deadlock with workers mid-ring
    with pytest.raises(Exception):
        it.next()


def test_mp_iter_under_device_prefetch(tmp_path):
    from mxnet_tpu import io as mio

    rec = _write_labeled_rec(tmp_path, n=24)
    base = MultiProcessImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
        num_workers=2, stall_timeout=120)
    try:
        it = mio.DevicePrefetchIter(base, depth=2)
        total, pads = 0, 0
        for b in it:
            total += b.data[0].shape[0]
            pads += b.pad
        assert total - pads == 24
    finally:
        base.close()


def test_mp_iter_shard_smaller_than_batch(tmp_path):
    """Per-process shards smaller than one batch must loop-fill the wrap
    padding — every row of every ring slot carries real decoded pixels."""
    rec = _write_labeled_rec(tmp_path, n=6)
    it = MultiProcessImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=8,
        num_workers=2, stall_timeout=120)
    try:
        for batch in it:
            data = batch.data[0].asnumpy()
            labels = batch.label[0].asnumpy()
            for s in range(8):  # pad rows included: all must be coherent
                want = (int(labels[s]) * 7) % 256
                np.testing.assert_array_equal(
                    data[s], np.full((3, 16, 16), want, np.float32))
    finally:
        it.close()


def test_mp_iter_worker_decode_error_surfaces(tmp_path):
    """A corrupt record must raise in the CONSUMER promptly (not stall)."""
    rec = str(tmp_path / "bad.rec")
    w = MXRecordIO(rec, "w")
    img = np.full((16, 16, 3), 9, np.uint8)
    w.write(recordio.pack(IRHeader(0, 1.0, 0, 0),
                          imencode(img, img_fmt=".png")))
    w.write(recordio.pack(IRHeader(0, 2.0, 1, 0), b"\x89PNG-not-really"))
    w.close()
    it = MultiProcessImageRecordIter(
        path_imgrec=rec, data_shape=(3, 16, 16), batch_size=2,
        num_workers=1, stall_timeout=120)
    try:
        with pytest.raises(Exception, match="worker 0 failed"):
            while True:
                it.next()
    finally:
        it.close()


def test_mp_iter_host_sharding_composes(tmp_path):
    """part_index/num_parts (the distributed host contract) compose with
    the worker fan-out: two 'hosts' x two workers cover the dataset in
    four disjoint shards."""
    rec = _write_labeled_rec(tmp_path, n=32)
    seen = {}
    for host in range(2):
        it = MultiProcessImageRecordIter(
            path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
            num_workers=2, part_index=host, num_parts=2,
            stall_timeout=120)
        try:
            labels = []
            pads = 0
            for b in it:
                labels.extend(b.label[0].asnumpy().astype(int).tolist())
                pads += b.pad
            seen[host] = (labels, pads)
        finally:
            it.close()
    l0, p0 = seen[0]
    l1, p1 = seen[1]
    # disjoint between hosts (net of wrap padding), union = everything
    assert set(l0) | set(l1) == set(range(32))
    assert (len(l0) - p0) + (len(l1) - p1) == 32
