"""Executor tests (parity model: tests/python/unittest/test_executor.py +
operator gradient checks from test_operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import (
    check_numeric_gradient,
    check_symbolic_backward,
    check_symbolic_forward,
    check_consistency,
)


def test_bind_forward_backward():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a * b
    a_nd = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b_nd = nd.array([[5.0, 6.0], [7.0, 8.0]])
    ga = nd.zeros((2, 2))
    gb = nd.zeros((2, 2))
    ex = c.bind(mx.cpu(), args=[a_nd, b_nd], args_grad=[ga, gb])
    out = ex.forward(is_train=True)[0]
    np.testing.assert_allclose(out.asnumpy(), a_nd.asnumpy() * b_nd.asnumpy())
    ex.backward([nd.ones((2, 2))])
    np.testing.assert_allclose(ga.asnumpy(), b_nd.asnumpy())
    np.testing.assert_allclose(gb.asnumpy(), a_nd.asnumpy())


def test_grad_req_add():
    a = sym.Variable("a")
    c = a * 2.0
    a_nd = nd.ones((3,))
    ga = nd.zeros((3,))
    ex = c.bind(mx.cpu(), args=[a_nd], args_grad=[ga], grad_req="add")
    for i in range(3):
        ex.forward(is_train=True)
        ex.backward([nd.ones((3,))])
    np.testing.assert_allclose(ga.asnumpy(), 6.0 * np.ones(3))


def test_grad_req_null():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    ex = c.simple_bind(mx.cpu(), grad_req={"a": "write", "b": "null"}, a=(2,), b=(2,))
    ex.forward(is_train=True)
    ex.backward([nd.ones((2,))])
    assert "b" not in ex.grad_dict
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), [1, 1])


def test_softmax_output_grad():
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(data, name="softmax")
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    labels = np.array([0, 1, 2, 3], dtype=np.float32)
    ex = net.simple_bind(mx.cpu(), data=(4, 5))
    ex.arg_dict["data"][:] = x
    ex.arg_dict["softmax_label"][:] = labels
    ex.forward(is_train=True)
    ex.backward()
    p = ex.outputs[0].asnumpy()
    onehot = np.eye(5, dtype=np.float32)[labels.astype(int)]
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), p - onehot, rtol=1e-5)


def test_linear_regression_grad():
    data = sym.Variable("data")
    net = sym.LinearRegressionOutput(data, name="lro")
    x = np.random.RandomState(1).randn(6, 3).astype(np.float32)
    y = np.random.RandomState(2).randn(6, 3).astype(np.float32)
    ex = net.simple_bind(mx.cpu(), data=(6, 3))
    ex.arg_dict["data"][:] = x
    ex.arg_dict["lro_label"][:] = y
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(
        ex.grad_dict["data"].asnumpy(), (x - y) / 3.0, rtol=1e-5
    )


def test_check_numeric_gradient_fc():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=4)
    rs = np.random.RandomState(3)
    loc = {
        "data": rs.randn(3, 5).astype(np.float32),
        "fc_weight": rs.randn(4, 5).astype(np.float32),
        "fc_bias": rs.randn(4).astype(np.float32),
    }
    check_numeric_gradient(fc, loc, numeric_eps=1e-2, rtol=5e-2)


def test_check_numeric_gradient_tanh():
    data = sym.Variable("data")
    net = sym.Activation(data, act_type="tanh")
    loc = {"data": np.random.RandomState(4).randn(4, 4).astype(np.float32)}
    check_numeric_gradient(net, loc, numeric_eps=1e-2, rtol=5e-2)


def test_symbolic_forward_backward_helpers():
    a = sym.Variable("a")
    net = sym.exp(a)
    x = np.random.RandomState(5).rand(3, 3).astype(np.float32)
    check_symbolic_forward(net, {"a": x}, np.exp(x), rtol=1e-5)
    check_symbolic_backward(net, {"a": x}, [np.ones_like(x)], {"a": np.exp(x)}, rtol=1e-5)


def test_conv_forward_matches_numpy():
    # 1x1 conv == per-pixel matmul
    data = sym.Variable("data")
    conv = sym.Convolution(data, name="c", kernel=(1, 1), num_filter=4, no_bias=True)
    rs = np.random.RandomState(6)
    x = rs.randn(2, 3, 5, 5).astype(np.float32)
    w = rs.randn(4, 3, 1, 1).astype(np.float32)
    expect = np.einsum("nchw,fc->nfhw", x, w[:, :, 0, 0])
    check_symbolic_forward(conv, {"data": x, "c_weight": w}, expect, rtol=1e-4)


def test_pooling_forward():
    data = sym.Variable("data")
    pool = sym.Pooling(data, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    expect = np.array([[[[5, 7], [13, 15]]]], dtype=np.float32)
    check_symbolic_forward(pool, {"data": x}, expect)
    avg = sym.Pooling(data, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    expect_avg = np.array([[[[2.5, 4.5], [10.5, 12.5]]]], dtype=np.float32)
    check_symbolic_forward(avg, {"data": x}, expect_avg)


def test_batchnorm_train_stats():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn", fix_gamma=True, eps=1e-5)
    x = np.random.RandomState(7).randn(8, 3, 4, 4).astype(np.float32) * 3 + 1
    ex = bn.simple_bind(mx.cpu(), data=x.shape)
    ex.arg_dict["data"][:] = x
    ex.aux_dict["bn_moving_var"][:] = 1.0
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    # normalized output: per-channel mean ~0, var ~1
    assert abs(out.mean(axis=(0, 2, 3))).max() < 1e-4
    np.testing.assert_allclose(out.var(axis=(0, 2, 3)), np.ones(3), rtol=1e-2)


def test_dropout_train_vs_eval():
    data = sym.Variable("data")
    net = sym.Dropout(data, p=0.5)
    x = np.ones((100, 100), dtype=np.float32)
    ex = net.simple_bind(mx.cpu(), grad_req="null", data=x.shape)
    ex.arg_dict["data"][:] = x
    eval_out = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(eval_out, x)
    train_out = ex.forward(is_train=True)
    train_np = ex.outputs[0].asnumpy()
    frac_zero = (train_np == 0).mean()
    assert 0.4 < frac_zero < 0.6
    # kept entries scaled by 1/keep
    assert np.allclose(train_np[train_np > 0], 2.0)


def test_executor_reshape():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=4)
    ex = fc.simple_bind(mx.cpu(), data=(8, 6))
    ex2 = ex.reshape(data=(2, 6))
    ex2.arg_dict["data"][:] = np.ones((2, 6), dtype=np.float32)
    out = ex2.forward()[0]
    assert out.shape == (2, 4)


def test_shared_exec_bucketing_cache():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=4)
    ex1 = fc.simple_bind(mx.cpu(), data=(8, 6))
    ex2 = fc.simple_bind(mx.cpu(), shared_exec=ex1, data=(4, 6))
    assert ex2._jit_fwd is ex1._jit_fwd  # compilation cache shared


# ---------------------------------------------------------------------------
# process-wide program cache + in-jit gradient accumulation (ISSUE 2)
# ---------------------------------------------------------------------------
def _uniquely_named_net(tag, num_hidden=4):
    """A small train graph rebuilt from scratch per call.  Explicit names
    keyed on ``tag`` make the structure unique per test (the program
    cache is process-wide) while two calls with the SAME tag hash equal."""
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name=f"{tag}_fc", num_hidden=num_hidden)
    return sym.SoftmaxOutput(fc, name=f"{tag}_softmax")


@pytest.fixture
def _telemetry():
    from mxnet_tpu import telemetry as tm

    tm.reset()
    tm.enable()
    yield tm.get_registry()
    tm.reset()
    tm.disable()


def test_program_cache_rebind_zero_retraces(_telemetry):
    """Binding a structurally-identical symbol twice reuses the jitted
    programs: graph-cache hit recorded, compile counter stays flat."""
    reg = _telemetry
    ex1 = _uniquely_named_net("pc0").simple_bind(mx.cpu(), data=(4, 6))
    ex1.forward(is_train=True)
    ex1.backward()
    compiles = reg.get("executor_compile_total").total()
    hits = reg.get("executor_graph_cache_total").value(result="hit")
    # a FRESH symbol object with the same structure — object-identity
    # shared_exec cannot help here, only the program cache can
    ex2 = _uniquely_named_net("pc0").simple_bind(mx.cpu(), data=(4, 6))
    assert ex2._jit_fwd is ex1._jit_fwd
    assert ex2._jit_fwdbwd is ex1._jit_fwdbwd
    ex2.forward(is_train=True)
    ex2.backward()
    assert reg.get("executor_graph_cache_total").value(result="hit") == hits + 1
    assert reg.get("executor_compile_total").total() == compiles


def test_program_cache_alpha_renamed_graphs_share_entry(_telemetry):
    """ISSUE-8 satellite: internal op-node names are NOT part of
    structural_signature — two gensym-renamed copies of the same net
    (fresh NameManager counters, as across processes or re-generated
    bucket symbols) share ONE program-cache entry.  Variable names stay
    in the key: they are the bind interface."""
    reg = _telemetry

    def build():
        data = sym.Variable("data")
        w, b = sym.Variable("ar_weight"), sym.Variable("ar_bias")
        fc = sym.FullyConnected(data, weight=w, bias=b, num_hidden=4)
        return sym.SoftmaxOutput(fc, label=sym.Variable("ar_label"),
                                 name="ar_softmax")

    s1, s2 = build(), build()
    fc1 = next(n.name for n in s1.nodes if n.op == "FullyConnected")
    fc2 = next(n.name for n in s2.nodes if n.op == "FullyConnected")
    assert fc1 != fc2  # genuinely alpha-renamed op nodes...
    assert s1.structural_signature() == s2.structural_signature()

    ex1 = s1.simple_bind(mx.cpu(), data=(4, 6))
    ex1.forward(is_train=True)
    ex1.backward()
    compiles = reg.get("executor_compile_total").total()
    hits = reg.get("executor_graph_cache_total").value(result="hit")
    ex2 = s2.simple_bind(mx.cpu(), data=(4, 6))
    assert ex2._jit_fwd is ex1._jit_fwd  # ...one cache entry
    ex2.forward(is_train=True)
    ex2.backward()
    assert reg.get("executor_graph_cache_total").value(result="hit") == hits + 1
    assert reg.get("executor_compile_total").total() == compiles

    # variable renames still miss: the bind interface is the key
    data = sym.Variable("data")
    s3 = sym.SoftmaxOutput(
        sym.FullyConnected(data, weight=sym.Variable("other_weight"),
                           bias=sym.Variable("ar_bias"), num_hidden=4),
        label=sym.Variable("ar_label"), name="ar_softmax")
    assert s3.structural_signature() != s1.structural_signature()


def test_program_cache_disable_knob(monkeypatch):
    from mxnet_tpu.executor import program_cache_clear

    monkeypatch.setenv("MXTPU_PROGRAM_CACHE", "off")
    program_cache_clear()
    ex1 = _uniquely_named_net("pc1").simple_bind(mx.cpu(), data=(2, 3))
    ex2 = _uniquely_named_net("pc1").simple_bind(mx.cpu(), data=(2, 3))
    assert ex2._jit_fwd is not ex1._jit_fwd  # cache off: fresh jits


def test_program_cache_lru_bound(monkeypatch):
    from mxnet_tpu.executor import program_cache_clear

    monkeypatch.setenv("MXTPU_PROGRAM_CACHE", "1")  # capacity 1
    program_cache_clear()
    ex_a = _uniquely_named_net("pc2a").simple_bind(mx.cpu(), data=(2, 3))
    ex_b = _uniquely_named_net("pc2b").simple_bind(mx.cpu(), data=(2, 3))
    assert ex_b._jit_fwd is not ex_a._jit_fwd
    # binding A's structure again must MISS: B evicted it (capacity 1)
    ex_a2 = _uniquely_named_net("pc2a").simple_bind(mx.cpu(), data=(2, 3))
    assert ex_a2._jit_fwd is not ex_a._jit_fwd
    # ... and A, now resident again, hits
    ex_a3 = _uniquely_named_net("pc2a").simple_bind(mx.cpu(), data=(2, 3))
    assert ex_a3._jit_fwd is ex_a2._jit_fwd


def test_grad_req_add_accumulates_inside_jit():
    """grad_req="add" must land through the fused fwd+bwd program (no
    eager per-param add): the grad buffer receives EXACTLY the program's
    returned grad, and accumulation matches eager float32 bitwise."""
    rs = np.random.RandomState(11)
    a_val = rs.randn(3, 4).astype(np.float32)
    b_val = rs.randn(3, 4).astype(np.float32)
    a, b = sym.Variable("a"), sym.Variable("b")
    net = a * b
    ex = net.simple_bind(mx.cpu(), grad_req="add", a=(3, 4), b=(3, 4))
    ex.arg_dict["a"][:] = a_val
    ex.arg_dict["b"][:] = b_val

    calls = []
    orig = ex._jit_fwdbwd

    def spy(*args, **kwargs):
        res = orig(*args, **kwargs)
        calls.append((args, kwargs, res))
        return res

    ex._jit_fwdbwd = spy
    head = nd.ones((3, 4))
    expected = np.zeros((3, 4), np.float32)
    for _ in range(3):
        ex.forward(is_train=True)
        ex.backward([head])
        expected = expected + b_val  # eager float32 reference, in order
    assert len(calls) == 3
    _, kwargs, res = calls[-1]
    assert set(kwargs["add_names"]) == {"a", "b"}
    # the written grad IS the program output — no eager post-add happened
    np.testing.assert_array_equal(
        np.asarray(res[2]["a"]), ex.grad_dict["a"].asnumpy())
    # and the fused accumulation is bitwise-equal to the eager path
    np.testing.assert_array_equal(ex.grad_dict["a"].asnumpy(), expected)


def test_backward_without_head_grads_single_jit_call():
    """The ones-seed backward builds cotangents in-trace: no separate
    eval_shape / ones dispatch per step, and repeat steps never retrace."""
    from mxnet_tpu import telemetry as tm

    tm.reset()
    tm.enable()
    try:
        reg = tm.get_registry()
        net = _uniquely_named_net("pc3")
        ex = net.simple_bind(mx.cpu(), data=(4, 6))
        ex.forward(is_train=True)
        ex.backward()
        compiles = reg.get("executor_compile_total").total()
        for _ in range(5):
            ex.forward(is_train=True)
            ex.backward()
        assert reg.get("executor_compile_total").total() == compiles
    finally:
        tm.reset()
        tm.disable()


def test_input_gather_cache_sees_updates():
    """The per-step input-dict cache must never serve stale values: an
    in-place write (version bump) and a wholesale NDArray replacement
    both invalidate the cached entry."""
    a = sym.Variable("a")
    net = a * 2.0
    ex = net.simple_bind(mx.cpu(), grad_req="null", a=(2,))
    ex.arg_dict["a"][:] = 1.0
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [2, 2])
    ex.arg_dict["a"][:] = 3.0  # same chunk, bumped version
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [6, 6])
    ex.arg_dict["a"] = nd.array([5.0, 5.0])  # replaced NDArray object
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [10, 10])


def test_simple_bind_honors_type_dict():
    data = sym.Variable("data")
    w = sym.Variable("w")
    net = data * w
    ex = net.simple_bind(mx.cpu(), type_dict={"data": np.int32},
                         data=(2, 2), w=(2, 2))
    assert ex.arg_dict["data"].dtype == np.int32
    assert ex.arg_dict["w"].dtype == np.float32  # undeclared stays fp32
    assert ex.grad_dict["w"].dtype == np.float32
    # grads allocate in their arg's dtype
    ex16 = net.simple_bind(mx.cpu(), type_dict={"w": np.float16},
                           data=(2, 2), w=(2, 2))
    assert ex16.arg_dict["w"].dtype == np.float16
    assert ex16.grad_dict["w"].dtype == np.float16


def test_simple_bind_variable_dtype_attr():
    data = sym.Variable("data", dtype=np.int32)
    net = sym.BlockGrad(data)
    ex = net.simple_bind(mx.cpu(), grad_req="null", data=(3,))
    assert ex.arg_dict["data"].dtype == np.int32
    # explicit type_dict overrides the Variable annotation
    ex2 = net.simple_bind(mx.cpu(), grad_req="null",
                          type_dict={"data": np.float32}, data=(3,))
    assert ex2.arg_dict["data"].dtype == np.float32


def test_forward_kwargs_preserve_dtype():
    """Executor.forward(**kwargs) must not force-cast typed inputs to
    fp32 — integer labels keep an integer dtype; plain Python floats
    still default to fp32."""
    data = sym.Variable("data")
    net = sym.BlockGrad(data)
    ex = net.simple_bind(mx.cpu(), grad_req="null", data=(3,))
    out = ex.forward(data=np.array([1, 2, 3], dtype=np.int32))[0]
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out.asnumpy(), [1, 2, 3])
    out = ex.forward(data=[1.0, 2.0, 3.0])[0]
    assert out.dtype == np.float32
    out = ex.forward(data=np.array([1, 2, 3], dtype=np.float16))[0]
    assert out.dtype == np.float16


def test_check_consistency_multi_ctx():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=4)
    net = sym.Activation(fc, act_type="relu")
    check_consistency(net, [{"ctx": mx.cpu(0), "data": (4, 7)},
                            {"ctx": mx.cpu(1), "data": (4, 7)}])


def test_multi_output_executor():
    data = sym.Variable("data")
    parts = sym.SliceChannel(data, num_outputs=2, axis=1, name="sl")
    ex = parts.simple_bind(mx.cpu(), grad_req="null", data=(2, 4, 3))
    x = np.random.RandomState(8).randn(2, 4, 3).astype(np.float32)
    ex.arg_dict["data"][:] = x
    outs = ex.forward()
    assert len(outs) == 2
    np.testing.assert_allclose(outs[0].asnumpy(), x[:, :2])
    np.testing.assert_allclose(outs[1].asnumpy(), x[:, 2:])


def test_monitor_callback():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=2)
    ex = fc.simple_bind(mx.cpu(), grad_req="null", data=(2, 3))
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.forward(is_train=False)
    assert any("fc_output" in s for s in seen)


def test_channels_last_pass_matches_nchw():
    """The NHWC execution pass (default) and the legacy NCHW lowering
    (MXTPU_CONV_LAYOUT=NCHW escape hatch) must agree: same graph, same
    inputs, outputs + gradients equal to float tolerance."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.executor import _build_graph_fn

    data = sym.Variable("data")
    net = sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                          name="c1")
    net = sym.BatchNorm(net, name="bn1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, num_filter=4, kernel=(1, 1), name="c2")
    net = (net * 0.5) + (net * 0.5)  # elementwise chain stays NHWC
    net = sym.Concat(net, net, dim=1)
    net = sym.Flatten(net)
    net = sym.SoftmaxOutput(sym.FullyConnected(net, num_hidden=3, name="fc"),
                            sym.Variable("softmax_label"), name="softmax")

    shapes = {"data": (2, 3, 8, 8)}
    arg_shapes, _, aux_shapes = net.infer_shape(**shapes)
    rs = np.random.RandomState(3)
    args = {n: jnp.asarray(rs.normal(0, 0.5, s).astype(np.float32))
            for n, s in zip(net.list_arguments(), arg_shapes)}
    args["softmax_label"] = jnp.asarray(rs.randint(0, 3, 2).astype(np.float32))
    aux = {n: jnp.asarray((np.ones if n.endswith("_var") else np.zeros)(s, np.float32))
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)}
    key = jax.random.PRNGKey(0)

    def run(channels_last):
        fn = _build_graph_fn(net, channels_last=channels_last)
        grad_names = [n for n in net.list_arguments()
                      if n not in ("data", "softmax_label")]

        def loss(ga):
            merged = dict(args); merged.update(ga)
            outs, new_aux = fn(merged, aux, key, True)
            return jnp.sum(outs[0] * outs[0]), (outs[0], new_aux)

        (l, (out, new_aux)), grads = jax.value_and_grad(
            loss, has_aux=True)({k: args[k] for k in grad_names})
        return out, grads, new_aux

    out_cl, g_cl, aux_cl = run(True)
    out_ref, g_ref, aux_ref = run(False)
    np.testing.assert_allclose(np.asarray(out_cl), np.asarray(out_ref),
                               rtol=1e-5, atol=1e-6)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_cl[k]), np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)
    for k in aux_ref:
        np.testing.assert_allclose(np.asarray(aux_cl[k]), np.asarray(aux_ref[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_channels_last_resnet_has_two_activation_transposes():
    """Static guarantee of the NHWC pass on the flagship graph: every
    conv runs channels-last and the activation flow converts layout
    exactly twice (graph input, global-pool exit) — a fallback regression
    (an op dropping out of the NHWC chain) would add transposes here."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.executor import _build_graph_fn

    net = models.get_symbol("resnet-18", num_classes=10,
                            image_shape=(3, 32, 32))
    fn = _build_graph_fn(net, channels_last=True)
    arg_shapes, _, aux_shapes = net.infer_shape(data=(2, 3, 32, 32))
    args = {n: jnp.zeros(s, jnp.float32)
            for n, s in zip(net.list_arguments(), arg_shapes)}
    aux = {n: jnp.zeros(s, jnp.float32)
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)}
    jaxpr = jax.make_jaxpr(
        lambda a, x: fn(a, x, jax.random.PRNGKey(0), True))(args, aux)
    eqns = jaxpr.jaxpr.eqns
    convs = [e for e in eqns if e.primitive.name == "conv_general_dilated"]
    assert convs and all(
        e.params["dimension_numbers"].lhs_spec[1] == 3 for e in convs)
    act_transposes = [
        e for e in eqns if e.primitive.name == "transpose"
        and tuple(e.params["permutation"]) in ((0, 2, 3, 1), (0, 3, 1, 2))]
    assert len(act_transposes) == 2, (
        f"{len(act_transposes)} activation-layout transposes; an op fell "
        "out of the channels-last chain")
    # conv weights must enter via OIHW dimension numbers, NOT a
    # materialized OIHW->HWIO transpose — the transpose form measurably
    # copied ~116 MB/step of weights (fwd + vjp mirror) on ResNet-50
    w_transposes = [
        e for e in eqns if e.primitive.name == "transpose"
        and tuple(e.params["permutation"]) == (2, 3, 1, 0)]
    assert not w_transposes, (
        f"{len(w_transposes)} materialized conv-weight transposes")
