"""Graph-rewrite pass pipeline (ISSUE 8).

Parity contract: every pass is semantics-preserving — with the pass on,
forward outputs (and, for training-safe passes, backward gradients)
match the pass-off graph on real model-zoo symbols.  Plus the pass-
safety lint: a pass cannot be registered without declaring
``training_safe`` and appearing by name in this file's parity tests.
"""
import pathlib
import re

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import passes, sym
from mxnet_tpu import executor as ex_mod
from mxnet_tpu.base import MXNetError

ALL_GRAPH_PASSES = ["constant_fold", "cse", "dce", "residual_epilogue",
                    "amp_cast", "prefuse"]


@pytest.fixture
def _telemetry():
    from mxnet_tpu import telemetry as tm

    tm.reset()
    tm.enable()
    yield tm.get_registry()
    tm.reset()
    tm.disable()


@pytest.fixture(autouse=True)
def _fresh_cache():
    ex_mod.program_cache_clear()
    yield
    ex_mod.program_cache_clear()


# ---------------------------------------------------------------------------
# graph builders
# ---------------------------------------------------------------------------
def _mixed_net():
    """A net exercising every graph pass at once: conv stack (layout
    pass composition), duplicated subexpression (cse), no-op
    reshape/transpose-pair/identity (dce), elementwise chain (prefuse),
    and a constant subgraph (constant_fold)."""
    d = sym.Variable("data")
    c1 = sym.Convolution(d, num_filter=4, kernel=(3, 3), pad=(1, 1),
                         name="px_c1")
    a1 = sym.Activation(c1, act_type="relu", name="px_r1")
    # transpose pair that cancels + an identity copy
    t = sym.transpose(a1, axes=(0, 2, 3, 1))
    t = sym.transpose(t, axes=(0, 3, 1, 2))
    t = sym.identity(t)
    # duplicated subexpression for cse
    dup = t * t + t * t
    # elementwise chain for prefuse
    chain = sym.exp(sym.tanh(dup * 0.5 + 1.0))
    # constant subgraph folded at bind
    const = sym.ones((2, 4, 8, 8)) * 0.25 + sym.zeros((2, 4, 8, 8))
    f = sym.Flatten(chain + const, name="px_fl")
    fc = sym.FullyConnected(f, num_hidden=3, name="px_fc")
    return sym.SoftmaxOutput(fc, label=sym.Variable("softmax_label"),
                             name="softmax"), {"data": (2, 3, 8, 8),
                                               "softmax_label": (2,)}


def _model_zoo(name):
    from mxnet_tpu import models

    if name == "resnet":
        net = models.get_symbol("resnet-18", num_classes=10,
                                image_shape=(3, 32, 32))
        return net, {"data": (1, 3, 32, 32), "softmax_label": (1,)}
    if name == "inception_bn":
        net = models.get_symbol("inception-bn", num_classes=10,
                                image_shape=(3, 32, 32))
        return net, {"data": (1, 3, 32, 32), "softmax_label": (1,)}
    if name == "lstm":
        from mxnet_tpu.models.lstm import lstm_unroll

        net = lstm_unroll(1, 4, 30, 8, 8, 30, dropout=0.0)
        return net, {"data": (2, 4), "softmax_label": (2, 4),
                     "l0_init_c": (2, 8), "l0_init_h": (2, 8)}
    raise AssertionError(name)


def _fill(ex, shapes, seed=7):
    """Deterministic by-name fill so pass-on and pass-off binds see the
    same values."""
    rng = np.random.RandomState(seed)
    for k in sorted(ex.arg_dict):
        v = ex.arg_dict[k]
        if k == "data" and len(v.shape) == 2:  # token ids (lstm)
            v[:] = rng.randint(0, 30, v.shape).astype(np.float32)
        elif k == "softmax_label":
            v[:] = rng.randint(0, 3, v.shape).astype(np.float32)
        else:
            v[:] = rng.uniform(-0.5, 0.5, v.shape).astype(np.float32)
    for k in sorted(ex.aux_dict):
        v = ex.aux_dict[k]
        if "var" in k:
            v[:] = rng.uniform(0.5, 1.5, v.shape).astype(np.float32)
        else:
            v[:] = rng.uniform(-0.2, 0.2, v.shape).astype(np.float32)


def _run(net, shapes, passes_env, monkeypatch, train=True, seed=7):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", passes_env)
    ex_mod.program_cache_clear()
    ex = net.simple_bind(mx.cpu(), grad_req="write" if train else "null",
                         **shapes)
    _fill(ex, shapes, seed)
    out = ex.forward(is_train=train)
    if not train:
        return [o.asnumpy() for o in out], {}
    ex.backward()
    outs = [o.asnumpy() for o in ex.outputs]
    grads = {k: g.asnumpy() for k, g in ex.grad_dict.items()
             if g is not None and k not in ("data", "softmax_label")}
    return outs, grads


def _assert_parity(ref, got, atol=2e-4):
    ro, rg = ref
    go, gg = got
    assert len(ro) == len(go)
    for a, b in zip(ro, go):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=atol)
    assert sorted(rg) == sorted(gg)
    for k in rg:
        np.testing.assert_allclose(rg[k], gg[k], rtol=1e-3, atol=atol,
                                   err_msg=f"grad {k}")


# ---------------------------------------------------------------------------
# per-pass parity (fwd AND bwd — all four graph passes are training-safe)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("pass_name", ALL_GRAPH_PASSES)
def test_single_pass_parity_fwd_bwd(pass_name, monkeypatch):
    net, shapes = _mixed_net()
    ref = _run(net, shapes, "off", monkeypatch)
    got = _run(net, shapes, pass_name, monkeypatch)
    _assert_parity(ref, got)


@pytest.mark.parametrize("model", ["resnet", "inception_bn", "lstm"])
def test_full_pipeline_parity_model_zoo(model, monkeypatch):
    """Whole default pipeline vs pass-off on model-zoo symbols: forward
    outputs and parameter gradients agree."""
    net, shapes = _model_zoo(model)
    ref = _run(net, shapes, "off", monkeypatch)
    got = _run(net, shapes, "default", monkeypatch)
    _assert_parity(ref, got, atol=5e-4)


def test_passes_off_bit_identical(monkeypatch):
    """MXTPU_GRAPH_PASSES=0 restores pass-off numerics bit-identically:
    two pass-off binds agree bitwise (the rewrite layer is fully out of
    the path, not merely approximately disabled)."""
    net, shapes = _mixed_net()
    a = _run(net, shapes, "0", monkeypatch)
    b = _run(net, shapes, "0", monkeypatch)
    for x, y in zip(a[0], b[0]):
        np.testing.assert_array_equal(x, y)
    for k in a[1]:
        np.testing.assert_array_equal(a[1][k], b[1][k])


# ---------------------------------------------------------------------------
# structural effects
# ---------------------------------------------------------------------------
def test_constant_fold_bakes_literal(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "constant_fold")
    net, _ = _mixed_net()
    before = passes.op_node_count(net)
    out = passes.apply_graph_passes(net)
    ops_after = [n.op for n in out.nodes if not n.is_variable]
    assert "_literal" in ops_after
    assert "_zeros" not in ops_after and "_ones" not in ops_after
    assert passes.op_node_count(out) < before


def test_prefuse_collapses_chain(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "prefuse")
    d = sym.Variable("data")
    chain = sym.exp(sym.tanh(sym.sqrt(d * 2.0) + 1.0))
    out = passes.apply_graph_passes(chain)
    ops_after = [n.op for n in out.nodes if not n.is_variable]
    assert ops_after == ["_fused_elemwise"]


def test_dce_cancels_transpose_pair_and_identity(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "dce")
    d = sym.Variable("data")
    t = sym.transpose(sym.transpose(d, axes=(0, 2, 3, 1)),
                      axes=(0, 3, 1, 2))
    out = passes.apply_graph_passes(sym.identity(t) + d)
    ops_after = [n.op for n in out.nodes if not n.is_variable]
    assert "transpose" not in ops_after and "_copy" not in ops_after


def test_cse_merges_duplicate_subexpression(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "cse")
    a, b = sym.Variable("a"), sym.Variable("b")
    out = passes.apply_graph_passes(a * b + a * b)
    muls = [n for n in out.nodes if n.op == "elemwise_mul"]
    assert len(muls) == 1


def test_residual_epilogue_fuses_resnet_tails(monkeypatch):
    """The "residual_epilogue" pass collapses every relu(BN(add))
    residual tail of a model-zoo resnet into one fused node; parity of
    the rewrite is pinned by test_single_pass_parity_fwd_bwd (this
    file) and end-to-end in tests/test_amp.py."""
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "residual_epilogue")
    net, _ = _model_zoo("resnet")
    before = passes.op_node_count(net)
    out = passes.apply_graph_passes(net)
    ops_after = [n.op for n in out.nodes if not n.is_variable]
    assert "_residual_epilogue_bn" in ops_after
    assert passes.op_node_count(out) < before


def test_amp_cast_is_identity_without_policy(monkeypatch):
    """The "amp_cast" pass with MXTPU_AMP unset returns the SAME
    symbol object — signatures and program-cache keys untouched (the
    AMP-off bit-identity contract; the armed-policy behavior is pinned
    in tests/test_amp.py)."""
    monkeypatch.delenv("MXTPU_AMP", raising=False)
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "amp_cast")
    net, _ = _mixed_net()
    assert passes.apply_graph_passes(net) is net
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    out = passes.apply_graph_passes(net)
    assert out is not net
    assert any(n.op == "Cast" for n in out.nodes if not n.is_variable)
    assert out.structural_signature() != net.structural_signature()


def test_cse_never_merges_rng_ops(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "cse")
    d = sym.Variable("data")
    net = sym.Dropout(d, p=0.5) + sym.Dropout(d, p=0.5)
    out = passes.apply_graph_passes(net)
    drops = [n for n in out.nodes if n.op == "Dropout"]
    assert len(drops) == 2  # two independent masks must stay independent


def test_env_selection_and_unknown_name(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "cse,dce")
    assert passes.enabled_passes() == ["cse", "dce"]
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "0")
    assert passes.enabled_passes() == []
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "nope")
    with pytest.raises(MXNetError):
        passes.enabled_passes()


# ---------------------------------------------------------------------------
# program-cache interaction (cache keys on the POST-pass signature)
# ---------------------------------------------------------------------------
def test_equivalent_graphs_share_one_cache_entry(_telemetry, monkeypatch):
    """Differently-written but equivalent graphs converge: a duplicated
    subexpression (CSE-able) and its shared-subexpression form rewrite
    to the same structure, so the second bind is a cache hit with zero
    fresh traces."""
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "default")
    reg = _telemetry
    a, b = sym.Variable("a"), sym.Variable("b")
    g1 = sym.identity(a * b) + (a * b)   # duplicated + a no-op identity
    m = a * b
    g2 = m + m                           # shared subexpression
    ex1 = g1.simple_bind(mx.cpu(), grad_req="null", a=(2, 3), b=(2, 3))
    ex1.forward(is_train=False)
    compiles = reg.get("executor_compile_total").total()
    hits = reg.get("executor_graph_cache_total").value(result="hit")
    ex2 = g2.simple_bind(mx.cpu(), grad_req="null", a=(2, 3), b=(2, 3))
    assert ex2._jit_fwd is ex1._jit_fwd
    ex2.forward(is_train=False)
    assert reg.get("executor_graph_cache_total").value(result="hit") == hits + 1
    assert reg.get("executor_compile_total").total() == compiles


def test_zero_recompiles_after_warmup_with_passes(_telemetry, monkeypatch):
    """Equal-structure rebinds of pass-rewritten graphs still do zero
    retraces (ISSUE 8 acceptance): warm bind+forward, rebind a fresh
    equal-structure symbol, compile counter stays flat."""
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "default")
    reg = _telemetry
    net1, shapes = _mixed_net()
    ex1 = net1.simple_bind(mx.cpu(), grad_req="write", **shapes)
    _fill(ex1, shapes)
    ex1.forward(is_train=True)
    ex1.backward()
    compiles = reg.get("executor_compile_total").total()
    net2, _ = _mixed_net()  # fresh gensym names, equal structure
    ex2 = net2.simple_bind(mx.cpu(), grad_req="write", **shapes)
    _fill(ex2, shapes)
    ex2.forward(is_train=True)
    ex2.backward()
    assert reg.get("executor_compile_total").total() == compiles


# ---------------------------------------------------------------------------
# inference-mode Conv+BN folding ("convbn_fold")
# ---------------------------------------------------------------------------
def _convbn_net():
    d = sym.Variable("data")
    c1 = sym.Convolution(d, num_filter=6, kernel=(3, 3), pad=(1, 1),
                         name="q_c1")
    b1 = sym.BatchNorm(c1, fix_gamma=False, eps=2e-5, name="q_b1")
    a1 = sym.Activation(b1, act_type="relu", name="q_r1")
    c2 = sym.Convolution(a1, num_filter=4, kernel=(3, 3), pad=(1, 1),
                         no_bias=True, name="q_c2")
    b2 = sym.BatchNorm(c2, name="q_b2")  # fix_gamma default True
    f = sym.Flatten(b2, name="q_fl")
    fc = sym.FullyConnected(f, num_hidden=3, name="q_fc")
    return sym.SoftmaxOutput(fc, label=sym.Variable("softmax_label"),
                             name="softmax")


def _convbn_params(net, seed=3):
    rng = np.random.RandomState(seed)
    ex = net.simple_bind(mx.cpu(), grad_req="null", data=(2, 3, 8, 8))
    args, auxs = {}, {}
    for k, v in ex.arg_dict.items():
        if k in ("data", "softmax_label"):
            continue
        args[k] = mx.nd.array(
            rng.uniform(-0.5, 0.5, v.shape).astype(np.float32))
    for k, v in ex.aux_dict.items():
        if "var" in k:
            auxs[k] = mx.nd.array(
                rng.uniform(0.5, 1.5, v.shape).astype(np.float32))
        else:
            auxs[k] = mx.nd.array(
                rng.uniform(-0.2, 0.2, v.shape).astype(np.float32))
    return args, auxs


def test_convbn_fold_predictor_parity(_telemetry, monkeypatch):
    """convbn_fold parity: the folded Predictor matches the unfolded
    (MXTPU_GRAPH_PASSES=0) float path, both BatchNorms leave the graph
    (a no_bias conv gains a bias), and the telemetry counter records
    the folds."""
    from mxnet_tpu.predict import Predictor

    net = _convbn_net()
    args, auxs = _convbn_params(net)
    x = np.random.RandomState(11).uniform(
        -1, 1, (2, 3, 8, 8)).astype(np.float32)

    reg = _telemetry
    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "default")
    p_fold = Predictor(symbol=net, arg_params=dict(args),
                       aux_params=dict(auxs),
                       input_shapes={"data": (2, 3, 8, 8)})
    assert p_fold._n_bn_folded == 2
    assert reg.get("graph_pass_convbn_folded_total").total() == 2
    folded_ops = [n.op for n in p_fold.symbol.nodes if not n.is_variable]
    assert "BatchNorm" not in folded_ops
    assert "q_c2_bias" in p_fold.symbol.list_arguments()
    p_fold.forward(data=x)
    out_fold = p_fold.get_output(0)

    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "0")
    ex_mod.program_cache_clear()
    p_raw = Predictor(symbol=net, arg_params=dict(args),
                      aux_params=dict(auxs),
                      input_shapes={"data": (2, 3, 8, 8)})
    assert p_raw._n_bn_folded == 0
    p_raw.forward(data=x)
    out_raw = p_raw.get_output(0)
    np.testing.assert_allclose(out_fold, out_raw, rtol=1e-4, atol=1e-4)


def test_convbn_fold_skips_shared_activations():
    """A conv whose output feeds MORE than the BN must not fold — the
    other consumer observes pre-BN activations."""
    d = sym.Variable("data")
    c = sym.Convolution(d, num_filter=4, kernel=(1, 1), name="s_c")
    b = sym.BatchNorm(c, name="s_b")
    net = sym.Group([b, sym.Activation(c, act_type="relu")])
    ex = net.simple_bind(mx.cpu(), grad_req="null", data=(1, 3, 4, 4))
    args = {k: mx.nd.array(np.ones(v.shape, np.float32))
            for k, v in ex.arg_dict.items() if k != "data"}
    auxs = {k: mx.nd.array(np.ones(v.shape, np.float32))
            for k, v in ex.aux_dict.items()}
    out, new_args, new_auxs, n = passes.fold_conv_bn(net, args, auxs)
    assert n == 0
    assert sorted(new_args) == sorted(args)


def test_convbn_fold_runs_before_int8_scales(monkeypatch):
    """serving e2e ordering: prepare_inference_params quantizes the
    FOLDED weights — the dequantized conv kernel reconstructs W*scale
    (not the raw checkpoint W), and the per-channel scales differ from
    scales of the unfolded weight wherever BN rescales a channel."""
    from mxnet_tpu.serving.quantize import (QuantizedTensor,
                                            prepare_inference_params,
                                            quantize_per_channel)

    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "default")
    net = _convbn_net()
    args, auxs = _convbn_params(net)
    fsym, fargs, faux, n = passes.fold_conv_bn(net, args, auxs)
    assert n == 2
    qsym, qparams, qaux, qn = prepare_inference_params(
        net, args, auxs, quantize="int8", device_put=False)
    assert qn == 2
    qt = qparams["q_c1_weight"]
    assert isinstance(qt, QuantizedTensor)
    folded_w = fargs["q_c1_weight"].asnumpy()
    deq = np.asarray(qt.q, np.float32) * np.asarray(qt.scale, np.float32)
    np.testing.assert_allclose(deq, folded_w,
                               atol=np.abs(folded_w).max() / 127 + 1e-7)
    _, raw_scale = quantize_per_channel(args["q_c1_weight"].asnumpy())
    assert not np.allclose(np.asarray(qt.scale), raw_scale)


def test_int8_of_folded_net_matches_unfolded_float(monkeypatch):
    """serving e2e: int8 quantization of a BN-folded net stays within
    the established int8 tolerance (test_predict uses 0.02) of the
    UNFOLDED float path."""
    from mxnet_tpu.predict import Predictor

    net = _convbn_net()
    args, auxs = _convbn_params(net)
    x = np.random.RandomState(5).uniform(
        -1, 1, (2, 3, 8, 8)).astype(np.float32)

    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "0")
    p_float = Predictor(symbol=net, arg_params=dict(args),
                        aux_params=dict(auxs),
                        input_shapes={"data": (2, 3, 8, 8)})
    p_float.forward(data=x)
    out_float = p_float.get_output(0)

    monkeypatch.setenv("MXTPU_GRAPH_PASSES", "default")
    ex_mod.program_cache_clear()
    p8 = Predictor(symbol=net, arg_params=dict(args), aux_params=dict(auxs),
                   input_shapes={"data": (2, 3, 8, 8)}, quantize="int8")
    assert p8._n_bn_folded == 2
    assert any(k.endswith("weight") for k in p8._qparams)
    p8.forward(data=x)
    out8 = p8.get_output(0)
    np.testing.assert_allclose(out8.sum(axis=1), 1.0, atol=1e-3)
    np.testing.assert_allclose(out8, out_float, atol=0.02)


def test_convbn_fold_model_zoo_counts(monkeypatch):
    """Acceptance: on ResNet-50 / inception_bn inference binds the fold
    actually fires (counter > 0), and the folded inception predictor
    matches the unfolded float path."""
    from mxnet_tpu import models, telemetry as tm
    from mxnet_tpu.predict import Predictor

    tm.reset()
    tm.enable()
    try:
        # resnet-50: pre-activation units still contain interior
        # conv->bn pairs (bn2(conv1), bn3(conv2)); fold without a
        # forward (structure + values only)
        rnet = models.get_symbol("resnet-50", num_classes=10,
                                 image_shape=(3, 32, 32))
        rex = rnet.simple_bind(mx.cpu(), grad_req="null",
                               data=(1, 3, 32, 32))
        rng = np.random.RandomState(1)
        rargs = {k: mx.nd.array(rng.uniform(-0.1, 0.1, v.shape)
                                .astype(np.float32))
                 for k, v in rex.arg_dict.items()
                 if k not in ("data", "softmax_label")}
        rauxs = {k: mx.nd.array(
                    (rng.uniform(0.5, 1.5, v.shape) if "var" in k
                     else rng.uniform(-0.1, 0.1, v.shape))
                    .astype(np.float32))
                 for k, v in rex.aux_dict.items()}
        _, _, _, n_res = passes.fold_conv_bn(rnet, rargs, rauxs)
        assert n_res > 0

        inet = models.get_symbol("inception-bn", num_classes=10,
                                 image_shape=(3, 32, 32))
        iex = inet.simple_bind(mx.cpu(), grad_req="null",
                               data=(1, 3, 32, 32))
        iargs = {k: mx.nd.array(rng.uniform(-0.1, 0.1, v.shape)
                                .astype(np.float32))
                 for k, v in iex.arg_dict.items()
                 if k not in ("data", "softmax_label")}
        iauxs = {k: mx.nd.array(
                    (rng.uniform(0.5, 1.5, v.shape) if "var" in k
                     else rng.uniform(-0.1, 0.1, v.shape))
                    .astype(np.float32))
                 for k, v in iex.aux_dict.items()}
        x = rng.uniform(-1, 1, (1, 3, 32, 32)).astype(np.float32)

        monkeypatch.setenv("MXTPU_GRAPH_PASSES", "default")
        ex_mod.program_cache_clear()
        reg = tm.get_registry()
        before = reg.get("graph_pass_convbn_folded_total").total()
        p_fold = Predictor(symbol=inet, arg_params=dict(iargs),
                           aux_params=dict(iauxs),
                           input_shapes={"data": (1, 3, 32, 32)})
        assert p_fold._n_bn_folded > 0
        assert reg.get("graph_pass_convbn_folded_total").total() > before
        p_fold.forward(data=x)
        out_fold = p_fold.get_output(0)

        monkeypatch.setenv("MXTPU_GRAPH_PASSES", "0")
        ex_mod.program_cache_clear()
        p_raw = Predictor(symbol=inet, arg_params=dict(iargs),
                          aux_params=dict(iauxs),
                          input_shapes={"data": (1, 3, 32, 32)})
        p_raw.forward(data=x)
        np.testing.assert_allclose(out_fold, p_raw.get_output(0),
                                   rtol=1e-3, atol=2e-4)
    finally:
        tm.reset()
        tm.disable()


# ---------------------------------------------------------------------------
# pass-safety lint (ISSUE 8 satellite): no pass lands unverified
# ---------------------------------------------------------------------------
def test_pass_safety_lint():
    """Every registered pass declares training_safe as a real bool and
    is referenced by name in this parity suite, so a future pass
    cannot land without a parity test."""
    src = pathlib.Path(__file__).read_text()
    assert passes.PASSES, "pass registry is empty"
    for name, p in passes.PASSES.items():
        assert isinstance(p.training_safe, bool), (
            f"pass {name!r} must declare training_safe as a bool")
        refs = re.findall(rf'"{re.escape(name)}"', src)
        assert refs, (
            f"pass {name!r} has no parity test referencing it by name "
            f"in tests/test_passes.py")
    # the pipeline entry point skips inference-only passes on training
    # binds: convbn_fold is registered training-unsafe
    assert passes.PASSES["convbn_fold"].training_safe is False
    for name in ALL_GRAPH_PASSES:
        assert passes.PASSES[name].training_safe is True
