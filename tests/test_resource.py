"""Resource manager tests (parity model: include/mxnet/resource.h +
attach_op_resource_pass.cc — kRandom / kTempSpace semantics)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.resource import Resource, ResourceManager, ResourceRequest


def test_temp_space_reuse_and_growth():
    rm = ResourceManager.get()
    res = rm.request(mx.cpu(), ResourceRequest.kTempSpace)
    a = res.get_space((16,), np.float32)
    a[:] = 7.0
    b = res.get_space((8,), np.float32)
    # same backing block reused (contents undefined but address shared)
    assert b.ctypes.data == a.ctypes.data
    big = res.get_space((64, 64), np.float64)
    assert big.shape == (64, 64)
    assert big.nbytes >= 64 * 64 * 8


def test_temp_space_round_robin_bounded(monkeypatch):
    monkeypatch.setenv("MXNET_EXEC_NUM_TEMP", "2")
    rm = ResourceManager()
    r1 = rm.request(mx.cpu(), ResourceRequest.kTempSpace)
    r2 = rm.request(mx.cpu(), ResourceRequest.kTempSpace)
    r3 = rm.request(mx.cpu(), ResourceRequest.kTempSpace)
    r4 = rm.request(mx.cpu(), ResourceRequest.kTempSpace)
    assert r1 is not r2
    # only MXNET_EXEC_NUM_TEMP distinct spaces exist; further requests cycle
    assert {id(r3), id(r4)} <= {id(r1), id(r2)}


def test_random_resource_seeding():
    rm = ResourceManager.get()
    res = rm.request(mx.cpu(), ResourceRequest.kRandom)
    mx.random.seed(42)
    x = res.generator().normal(size=4)
    mx.random.seed(42)
    y = res.generator().normal(size=4)
    assert np.allclose(x, y)
    assert rm.request(mx.cpu(), ResourceRequest.kRandom) is res


def test_request_accepts_strings_and_rejects_junk():
    rm = ResourceManager()
    res = rm.request(mx.cpu(), "temp_space")
    assert isinstance(res, Resource)
    try:
        rm.request(mx.cpu(), "workspace")
    except mx.MXNetError:
        pass
    else:
        raise AssertionError("bad resource type accepted")
