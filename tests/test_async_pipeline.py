"""Async training-loop pipeline (round 8): device-resident fused
metrics, the bounded in-flight step window, and the device-side
step_multi feed.

Covers the ISSUE-4 acceptance criteria: fused metric values match the
eager numpy path, fit results are identical across MXTPU_ASYNC_DEPTH
settings, the steady-state Module.fit loop performs zero per-batch
host syncs with fused metrics on, and step_multi consumes per-step
device feeds without host re-stacking.
"""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import get_synthetic_mnist


# ---------------------------------------------------------------------------
# fused metric parity
# ---------------------------------------------------------------------------

def _classification_batches(n_batches=3, b=16, c=10, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        logits = rs.uniform(0.01, 1.0, (b, c)).astype(np.float32)
        pred = logits / logits.sum(axis=1, keepdims=True)
        label = rs.randint(0, c, b).astype(np.float32)
        out.append((label, pred))
    return out


def _regression_batches(n_batches=3, b=16, seed=1):
    rs = np.random.RandomState(seed)
    return [(rs.uniform(-1, 1, (b, 4)).astype(np.float32),
             rs.uniform(-1, 1, (b, 4)).astype(np.float32))
            for _ in range(n_batches)]


_METRIC_CASES = [
    ("acc", lambda: mx.metric.Accuracy(), _classification_batches),
    ("acc-ignore", lambda: mx.metric.Accuracy(ignore_label=0),
     _classification_batches),
    ("top3", lambda: mx.metric.TopKAccuracy(top_k=3),
     _classification_batches),
    ("ce", lambda: mx.metric.CrossEntropy(), _classification_batches),
    ("perplexity", lambda: mx.metric.Perplexity(ignore_label=1),
     _classification_batches),
    ("mae", lambda: mx.metric.MAE(), _regression_batches),
    ("mse", lambda: mx.metric.MSE(), _regression_batches),
    ("rmse", lambda: mx.metric.RMSE(), _regression_batches),
    ("loss", lambda: mx.metric.Loss(), _regression_batches),
]


@pytest.mark.parametrize("name,make,data", _METRIC_CASES,
                         ids=[c[0] for c in _METRIC_CASES])
def test_fused_metric_matches_eager(name, make, data, monkeypatch):
    """Device-accumulated values must match the host-numpy path."""
    batches = data()

    fused = make()
    assert fused._fused_delta is not None  # the case list is fused-capable
    for label, pred in batches:
        fused.update([nd.array(label)], [nd.array(pred)])
    # nothing synced yet: the device window is still pending
    assert fused._dev_sum is not None
    fname, fval = fused.get()
    assert fused._dev_sum is None  # get() drained

    monkeypatch.setenv("MXTPU_FUSED_METRICS", "0")
    eager = make()
    for label, pred in batches:
        eager.update([nd.array(label)], [nd.array(pred)])
    assert eager._dev_sum is None  # opt-out really took the eager path
    ename, eval_ = eager.get()

    assert fname == ename
    np.testing.assert_allclose(fval, eval_, rtol=1e-5, atol=1e-7)
    assert fused.num_inst == eager.num_inst


def test_fused_and_eager_updates_interleave(monkeypatch):
    """The two paths share accumulators: flipping the gate mid-stream
    (or a non-device input) must not lose either window."""
    batches = _classification_batches(4)
    m = mx.metric.Accuracy()
    for i, (label, pred) in enumerate(batches):
        if i % 2:
            monkeypatch.setenv("MXTPU_FUSED_METRICS", "0")
        else:
            monkeypatch.delenv("MXTPU_FUSED_METRICS", raising=False)
        m.update([nd.array(label)], [nd.array(pred)])
    monkeypatch.setenv("MXTPU_FUSED_METRICS", "0")
    ref = mx.metric.Accuracy()
    for label, pred in batches:
        ref.update([nd.array(label)], [nd.array(pred)])
    assert m.get() == ref.get()
    assert m.num_inst == ref.num_inst


def test_fused_metric_local_global_split():
    """reset_local folds the pending device window into the carried
    totals (Speedometer auto_reset interval semantics)."""
    batches = _classification_batches(4)
    m = mx.metric.Accuracy()
    m.update([nd.array(batches[0][0])], [nd.array(batches[0][1])])
    m.update([nd.array(batches[1][0])], [nd.array(batches[1][1])])
    first_window = m.get()[1]
    m.reset_local()
    m.update([nd.array(batches[2][0])], [nd.array(batches[2][1])])
    second_window = m.get()[1]
    g = m.get_global()[1]
    exp = (first_window * 32 + second_window * 16) / 48
    np.testing.assert_allclose(g, exp, rtol=1e-6)


def test_custom_and_f1_metrics_stay_eager():
    label = nd.array(np.array([1.0, 0.0]))
    pred = nd.array(np.array([[0.2, 0.8], [0.3, 0.7]]))
    cm = mx.metric.np(lambda l, p: float((p.argmax(1) == l).mean()))
    cm.update([label], [pred])
    assert cm._dev_sum is None
    f1 = mx.metric.F1()
    f1.update([label], [pred])
    assert f1._dev_sum is None
    assert mx.metric.create("loss").name == "loss"


# ---------------------------------------------------------------------------
# bounded in-flight window
# ---------------------------------------------------------------------------

def test_async_window_bounds_in_flight(monkeypatch):
    import jax.numpy as jnp

    from mxnet_tpu import engine

    monkeypatch.setenv("MXTPU_ASYNC_DEPTH", "3")
    assert engine.async_depth() == 3
    w = engine.AsyncWindow()
    for i in range(8):
        w.push(jnp.ones((4,)) * i)
        assert len(w) <= 3
    w.drain()
    assert len(w) == 0
    # explicit depth overrides the env; NDArray handles are unwrapped
    w2 = engine.AsyncWindow(depth=1)
    w2.push([nd.array([1.0]), nd.array([2.0])])
    w2.push(nd.array([3.0]))
    assert len(w2) == 1
    w2.drain()


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(sym.Flatten(data), name="fc1", num_hidden=16)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=10)
    return sym.SoftmaxOutput(net, name="softmax")


def _fixed_params():
    rs = np.random.RandomState(3)
    return {
        "fc1_weight": nd.array(rs.uniform(-0.05, 0.05, (16, 784))),
        "fc1_bias": nd.array(np.zeros(16)),
        "fc2_weight": nd.array(rs.uniform(-0.05, 0.05, (10, 16))),
        "fc2_bias": nd.array(np.zeros(10)),
    }


def _fit_once(depth, monkeypatch, nbatch=8):
    monkeypatch.setenv("MXTPU_ASYNC_DEPTH", str(depth))
    (xtr, ytr), _ = get_synthetic_mnist(64 * nbatch, 16)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=64, shuffle=False)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    metric = mx.metric.create("acc")
    mod.fit(train, eval_metric=metric, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.5),), num_epoch=2,
            arg_params=_fixed_params())
    args, _ = mod.get_params()
    return ({k: v.asnumpy() for k, v in args.items()},
            metric.get_global()[1])


def test_fit_identical_across_async_depths(monkeypatch):
    """MXTPU_ASYNC_DEPTH only changes WHEN the host waits, never the
    math: same seed/params/data must produce bit-identical results."""
    params1, acc1 = _fit_once(1, monkeypatch)
    params4, acc4 = _fit_once(4, monkeypatch)
    assert params1.keys() == params4.keys()
    for k in params1:
        np.testing.assert_array_equal(params1[k], params4[k], err_msg=k)
    assert acc1 == acc4


def test_steady_state_fit_has_zero_per_batch_syncs(monkeypatch):
    """ISSUE-4 acceptance: with fused metrics the epoch loop performs no
    per-batch asnumpy/wait — host syncs must NOT grow with batch count."""
    from mxnet_tpu import engine

    counts = {"asnumpy": 0, "wait": 0}
    orig_asnumpy = nd.NDArray.asnumpy
    orig_wait = engine.wait_for_var

    def counted_asnumpy(self):
        counts["asnumpy"] += 1
        return orig_asnumpy(self)

    def counted_wait(arr):
        counts["wait"] += 1
        return orig_wait(arr)

    def run(nbatch):
        counts["asnumpy"] = counts["wait"] = 0
        (xtr, ytr), _ = get_synthetic_mnist(64 * nbatch, 16)
        train = mx.io.NDArrayIter(xtr, ytr, batch_size=64, shuffle=False)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.5),), num_epoch=1,
                arg_params=_fixed_params())
        return counts["asnumpy"] + counts["wait"]

    monkeypatch.setattr(nd.NDArray, "asnumpy", counted_asnumpy)
    monkeypatch.setattr(engine, "wait_for_var", counted_wait)

    small = run(4)
    large = run(16)
    # fused: whatever boundary syncs exist are per-EPOCH, not per-batch
    assert large == small, (small, large)

    monkeypatch.setenv("MXTPU_FUSED_METRICS", "0")
    small_eager = run(4)
    large_eager = run(16)
    # eager: every batch pays at least one device->host metric sync
    assert large_eager - small_eager >= 12
    assert large_eager > large


def test_monitor_does_not_serialize_async_window(monkeypatch):
    """ISSUE-5 satellite: Monitor.tic used to wait_to_read every arg
    array each interval, pinning the in-flight window at 0.  Stat
    dispatch is async (the sync lives in toc's _render), so an
    installed Monitor must keep engine_pipeline_depth > 0."""
    from mxnet_tpu import telemetry as tm

    monkeypatch.setenv("MXTPU_ASYNC_DEPTH", "2")
    # tic's old blocking loop is only observable as wait_to_read calls:
    # count them (the deque-length gauge alone stays full either way)
    waits = {"n": 0}
    orig_wait = nd.NDArray.wait_to_read

    def counted_wait(self):
        waits["n"] += 1
        return orig_wait(self)

    monkeypatch.setattr(nd.NDArray, "wait_to_read", counted_wait)
    tm.reset()
    tm.enable()
    try:
        (xtr, ytr), _ = get_synthetic_mnist(64 * 8, 16)
        train = mx.io.NDArrayIter(xtr, ytr, batch_size=64, shuffle=False)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mon = mx.Monitor(interval=1, pattern=".*fc1.*")
        depth = tm.get_registry().get("engine_pipeline_depth")
        seen = []

        def watch(_param):
            seen.append(depth.value())

        mod.fit(train, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.5),), num_epoch=1,
                arg_params=_fixed_params(), monitor=mon,
                batch_end_callback=watch)
        # the monitor still produced stats (toc_print consumed them)...
        assert mon.step > 0
        # ...without the per-interval wait_to_read sweep over every arg
        # array (8 batches x 8 arrays would be >= 64 calls)
        assert waits["n"] == 0, waits
        # ...and the window stayed pipelined under it
        assert max(seen) > 0, seen
    finally:
        tm.reset()
        tm.disable()


def test_fused_metrics_with_data_parallel_module():
    """Sharded outputs (4-device data-parallel group) accumulate device-
    side too: replicated scalars + replicated host labels."""
    (xtr, ytr), (xte, yte) = get_synthetic_mnist(512, 128)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(xte, yte, batch_size=64)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(4)])
    metric = mx.metric.create("acc")
    mod.fit(train, eval_metric=metric, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.5),), num_epoch=3)
    assert mod.score(val, "acc")[0][1] > 0.9


# ---------------------------------------------------------------------------
# step_multi device feed
# ---------------------------------------------------------------------------

def _fc_sym():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, name="fc", num_hidden=10)
    return sym.SoftmaxOutput(net, name="softmax")


def _make_trainer(b):
    from mxnet_tpu.trainer import FusedTrainer

    mx.random.seed(11)
    tr = FusedTrainer(_fc_sym(), optimizer="sgd",
                      optimizer_params={"lr": 0.1, "momentum": 0.9,
                                        "rescale_grad": 1.0 / b},
                      initializer=mx.init.Xavier())
    tr.init(data=(b, 32))
    return tr


def test_step_multi_tuple_feed_matches_sequential():
    """Per-step tuple feeds (the DevicePrefetchIter path) are stacked
    inside the compiled program and land on the same params as k
    sequential step() calls."""
    import jax

    rs = np.random.RandomState(5)
    k, b = 4, 8
    batches = [(rs.uniform(-1, 1, (b, 32)).astype(np.float32),
                rs.randint(0, 10, b).astype(np.float32))
               for _ in range(k)]

    seq = _make_trainer(b)
    for x, y in batches:
        seq.step(data=x, softmax_label=y)

    multi = _make_trainer(b)
    # device-resident per-step arrays, fed WITHOUT host re-stacking
    feed = {
        "data": tuple(jax.device_put(x) for x, _ in batches),
        "softmax_label": tuple(jax.device_put(y) for _, y in batches),
    }
    outs = multi.step_multi(_donate=True, **feed)
    assert np.asarray(outs[0]).shape[0] == k
    assert multi._step == seq._step == k
    for name in seq.params:
        np.testing.assert_allclose(np.asarray(seq.params[name]),
                                   np.asarray(multi.params[name]),
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_step_multi_prestacked_jax_array_not_donated_by_default():
    """A caller-held pre-stacked device batch survives step_multi (the
    bench replays one stack), while _donate=True consumes it."""
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(6)
    k, b = 3, 8
    tr = _make_trainer(b)
    stacked = {
        "data": jax.device_put(
            rs.uniform(-1, 1, (k, b, 32)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 10, (k, b)).astype(np.float32)),
    }
    tr.step_multi(**stacked)
    # default: owned-by-caller arrays are NOT donated — still readable
    assert float(jnp.sum(stacked["data"])) == pytest.approx(
        float(np.sum(np.asarray(stacked["data"]))))
    tr.step_multi(**stacked)  # and replayable


def test_io_step_multi_feeds_groups_batches():
    from mxnet_tpu import io as io_mod

    rs = np.random.RandomState(9)
    x = rs.uniform(-1, 1, (64, 32)).astype(np.float32)
    y = rs.randint(0, 10, 64).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=8)
    feeds = list(io_mod.step_multi_feeds(it, 3))
    # 8 batches -> groups of 3, 3, 2 (short tail kept)
    assert [len(f["data"]) for f in feeds] == [3, 3, 2]
    assert set(feeds[0]) == {"data", "softmax_label"}
    assert feeds[0]["data"][0].shape == (8, 32)

    it.reset()
    tr = _make_trainer(8)
    for feed in io_mod.step_multi_feeds(it, 3):
        tr.step_multi(_donate=True, **feed)
    assert tr._step == 8

    it.reset()
    dropped = list(io_mod.step_multi_feeds(it, 3, drop_remainder=True))
    assert [len(f["data"]) for f in dropped] == [3, 3]


# ---------------------------------------------------------------------------
# Speedometer "values needed" guard
# ---------------------------------------------------------------------------

def test_speedometer_skips_sync_without_new_values(caplog):
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.module.base_module import BatchEndParam

    metric = mx.metric.Accuracy()
    reads = {"n": 0}
    orig = metric.get_name_value

    def counted():
        reads["n"] += 1
        return orig()

    metric.get_name_value = counted
    spd = Speedometer(batch_size=4, frequent=1, auto_reset=False)
    lab = nd.array(np.array([1.0, 1.0]))
    pred = nd.array(np.array([[0.1, 0.9], [0.1, 0.9]]))

    import time

    with caplog.at_level(logging.INFO):
        metric.update([lab], [pred])
        spd(BatchEndParam(epoch=0, nbatch=0, eval_metric=metric,
                          locals=None))  # opens the window, no report
        time.sleep(0.01)  # non-degenerate window (elapsed > 0)
        spd(BatchEndParam(epoch=0, nbatch=1, eval_metric=metric,
                          locals=None))
        assert reads["n"] == 1  # new values -> synced and printed
        assert "Train-accuracy" in caplog.text
        caplog.clear()
        time.sleep(0.01)
        spd(BatchEndParam(epoch=0, nbatch=2, eval_metric=metric,
                          locals=None))
        assert reads["n"] == 1  # nothing new -> NO device->host sync
        assert "Speed" in caplog.text  # speed line still emitted
        assert "Train-accuracy" not in caplog.text
        metric.update([lab], [pred])
        time.sleep(0.01)
        spd(BatchEndParam(epoch=0, nbatch=3, eval_metric=metric,
                          locals=None))
        assert reads["n"] == 2  # new values -> synced again
        assert "Train-accuracy" in caplog.text


# ---------------------------------------------------------------------------
# CustomOpProp sequence-kwarg canonicalization
# ---------------------------------------------------------------------------

def test_custom_op_sequence_kwargs_stringify_as_tuples():
    from mxnet_tpu.base import frozen_attrs

    seen = []

    @mx.operator.register("attr_echo_r8")
    class _EchoProp(mx.operator.CustomOpProp):  # noqa: F841
        def __init__(self, kernel="()", scale="1"):
            seen.append((kernel, scale))
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

    # both sequence spellings canonicalize to the reference's tuple text
    mx.operator.get_prop("attr_echo_r8", {"kernel": [3, 3], "scale": 2})
    mx.operator.get_prop("attr_echo_r8", {"kernel": (3, 3), "scale": 2})
    assert seen == [("(3, 3)", "2"), ("(3, 3)", "2")]
    # frozen_attrs round-trips both to the SAME tuple form, so the
    # imperative jit cache and the symbolic frontend agree
    assert frozen_attrs({"kernel": [3, 3]}) == frozen_attrs(
        {"kernel": (3, 3)})


# ---------------------------------------------------------------------------
# telemetry families
# ---------------------------------------------------------------------------

def test_pipeline_telemetry_families(monkeypatch):
    from mxnet_tpu import telemetry as tm

    tm.enable()
    try:
        tm.reset()
        (xtr, ytr), _ = get_synthetic_mnist(256, 16)
        train = mx.io.NDArrayIter(xtr, ytr, batch_size=64, shuffle=False)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.5),), num_epoch=1,
                arg_params=_fixed_params())
        reg = tm.get_registry()
        fused = reg.get("metric_fused_update_total")
        assert fused is not None and fused.total() == 4  # one per batch
        syncs = reg.get("metric_host_sync_total")
        assert syncs is not None and syncs.total() >= 1  # epoch boundary
        stall = reg.get("trainer_host_stall_seconds")
        assert stall is not None and stall.count(site="window") >= 1
        text = tm.generate_text()
        assert "engine_pipeline_depth" in text
    finally:
        tm.reset()
        tm.disable()
